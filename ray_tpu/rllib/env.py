"""Vectorized environments for rollout workers.

Equivalent of the reference's env layer (`rllib/env/vector_env.py`) reduced
to the batch-first protocol the sampler needs:

    reset() -> obs [n_envs, obs_dim]
    step(actions [n_envs]) -> (obs, rewards, dones, infos)

with auto-reset on termination (done envs restart; the returned obs is the
fresh episode's first observation, reference `VectorEnv` semantics).

`CartPoleVectorEnv` is a pure-numpy vectorized CartPole (dynamics per the
classic Barto-Sutton-Anderson formulation) — the sampler hot loop stays in
numpy instead of stepping n Python envs. `GymnasiumVectorEnv` adapts any
gymnasium env id.
"""

from __future__ import annotations

import math
from typing import Any, Dict, Optional, Tuple

import numpy as np


class VectorEnv:
    n_envs: int
    obs_dim: int
    n_actions: int
    max_episode_steps: int = 500

    @property
    def obs_shape(self) -> Tuple[int, ...]:
        """Per-env observation shape; (obs_dim,) for flat envs, [H, W] or
        [H, W, C] for image envs."""
        return (self.obs_dim,)

    @property
    def obs_dtype(self):
        return np.float32

    def reset(self) -> np.ndarray:
        raise NotImplementedError

    def step(self, actions: np.ndarray
             ) -> Tuple[np.ndarray, np.ndarray, np.ndarray, Dict[str, Any]]:
        raise NotImplementedError


class CartPoleVectorEnv(VectorEnv):
    """Numpy-vectorized CartPole-v1 (same constants as gymnasium's)."""

    GRAVITY = 9.8
    MASSCART = 1.0
    MASSPOLE = 0.1
    LENGTH = 0.5           # half pole length
    FORCE_MAG = 10.0
    TAU = 0.02
    THETA_LIMIT = 12 * 2 * math.pi / 360
    X_LIMIT = 2.4

    def __init__(self, n_envs: int = 8, seed: int = 0,
                 max_episode_steps: int = 500):
        self.n_envs = n_envs
        self.obs_dim = 4
        self.n_actions = 2
        self.max_episode_steps = max_episode_steps
        self._rng = np.random.default_rng(seed)
        self._state = np.zeros((n_envs, 4), dtype=np.float64)
        self._steps = np.zeros(n_envs, dtype=np.int64)
        self._total_mass = self.MASSPOLE + self.MASSCART
        self._polemass_length = self.MASSPOLE * self.LENGTH

    def reset(self) -> np.ndarray:
        self._state = self._rng.uniform(-0.05, 0.05, size=(self.n_envs, 4))
        self._steps[:] = 0
        return self._state.astype(np.float32)

    def _reset_envs(self, mask: np.ndarray):
        n = int(mask.sum())
        if n:
            self._state[mask] = self._rng.uniform(-0.05, 0.05, size=(n, 4))
            self._steps[mask] = 0

    def step(self, actions: np.ndarray):
        x, x_dot, theta, theta_dot = self._state.T
        force = np.where(actions == 1, self.FORCE_MAG, -self.FORCE_MAG)
        costheta = np.cos(theta)
        sintheta = np.sin(theta)
        temp = (force + self._polemass_length * theta_dot ** 2 * sintheta
                ) / self._total_mass
        theta_acc = (self.GRAVITY * sintheta - costheta * temp) / (
            self.LENGTH * (4.0 / 3.0
                           - self.MASSPOLE * costheta ** 2 / self._total_mass))
        x_acc = temp - self._polemass_length * theta_acc * costheta \
            / self._total_mass
        x = x + self.TAU * x_dot
        x_dot = x_dot + self.TAU * x_acc
        theta = theta + self.TAU * theta_dot
        theta_dot = theta_dot + self.TAU * theta_acc
        self._state = np.stack([x, x_dot, theta, theta_dot], axis=1)
        self._steps += 1

        terminated = (np.abs(x) > self.X_LIMIT) | \
            (np.abs(theta) > self.THETA_LIMIT)
        truncated = (self._steps >= self.max_episode_steps) & ~terminated
        dones = terminated | truncated
        rewards = np.ones(self.n_envs, dtype=np.float32)
        # Auto-reset finished episodes; the truncated flag marks boundaries
        # where GAE should bootstrap V(next). Termination takes precedence
        # when both land on the same step (gymnasium/RLlib semantics).
        # final_obs carries the TRUE pre-reset state at done rows so value
        # bootstrapping at truncation uses the right state. Only built when
        # an episode actually ended — the hot loop stays allocation-lean.
        infos = {"truncated": truncated.copy()}
        if dones.any():
            infos["final_obs"] = self._state.astype(np.float32)
        self._reset_envs(dones)
        return (self._state.astype(np.float32), rewards, dones, infos)


class GymnasiumVectorEnv(VectorEnv):
    """Adapter over `gymnasium.make_vec` for arbitrary env ids."""

    def __init__(self, env_id: str, n_envs: int = 8, seed: int = 0, **kw):
        import gymnasium as gym

        # SAME_STEP autoreset so the obs returned at a done step is the new
        # episode's first observation (gymnasium 1.x defaults to NEXT_STEP,
        # which would inject a bogus no-op transition after every episode).
        # Native vector entry points reject vector_kwargs, so pin the sync
        # vectorizer, which honors autoreset_mode.
        try:
            kw.setdefault("vectorization_mode", "sync")
            kw.setdefault("vector_kwargs",
                          {"autoreset_mode": gym.vector.AutoresetMode.SAME_STEP})
        except AttributeError:
            pass  # older gymnasium: same-step is already the behavior
        self._env = gym.make_vec(env_id, num_envs=n_envs, **kw)
        self.n_envs = n_envs
        space = self._env.single_observation_space
        self.obs_dim = int(np.prod(space.shape))
        # Image spaces (rank >= 2, e.g. Atari [210, 160, 3] uint8) keep
        # their native shape and dtype for the connector pipeline; flat
        # spaces normalize to [n, obs_dim] float32.
        self._image = len(space.shape) >= 2
        self._shape = tuple(space.shape) if self._image else (self.obs_dim,)
        self._dtype = np.uint8 if (self._image
                                   and space.dtype == np.uint8) else np.float32
        self.n_actions = int(self._env.single_action_space.n)
        self._seed = seed
        spec = getattr(self._env, "spec", None)
        self.max_episode_steps = getattr(spec, "max_episode_steps", 500) or 500

    @property
    def obs_shape(self):
        return self._shape

    @property
    def obs_dtype(self):
        return self._dtype

    def _cast(self, obs: np.ndarray) -> np.ndarray:
        if self._image:
            return np.asarray(obs, dtype=self._dtype)
        return obs.reshape(self.n_envs, -1).astype(np.float32)

    def reset(self) -> np.ndarray:
        obs, _ = self._env.reset(seed=self._seed)
        return self._cast(obs)

    def step(self, actions: np.ndarray):
        obs, rewards, terminated, truncated, infos = self._env.step(actions)
        terminated = np.asarray(terminated)
        truncated = np.asarray(truncated) & ~terminated  # termination wins
        dones = terminated | truncated
        obs = self._cast(obs)
        out_infos = {"truncated": truncated}
        if dones.any():
            # Gymnasium SAME_STEP autoreset reports the pre-reset
            # observation per done env (key name varies across versions);
            # default to the returned obs where absent. Built only on steps
            # with an episode end — the hot loop stays allocation-lean.
            final_obs = obs.copy()
            raw_final = infos.get("final_obs",
                                  infos.get("final_observation"))
            if raw_final is not None:
                for i in np.nonzero(dones)[0]:
                    fo = raw_final[i]
                    if fo is not None:
                        final_obs[i] = np.asarray(
                            fo, final_obs.dtype).reshape(self._shape)
            out_infos["final_obs"] = final_obs
        return (obs, np.asarray(rewards, dtype=np.float32), dones, out_infos)


class CatchVectorEnv(VectorEnv):
    """Synthetic image env (uint8 [H, W] frames): a pellet falls from a
    random column; the agent moves a paddle along the bottom row
    (left/stay/right) and gets +1 for catching it, -1 for missing.

    Serves as the Atari-shaped workload for the image pipeline (CNN module
    + connectors) in environments without ale_py — same dtype, obs rank,
    and reward sparsity class as Pong-like games, but cheap enough for CI.
    """

    def __init__(self, n_envs: int = 8, seed: int = 0, size: int = 21,
                 shaped: bool = False):
        self.n_envs = n_envs
        self.size = size
        self.obs_dim = size * size
        self.n_actions = 3
        self.max_episode_steps = size  # one drop per episode
        # shaped=True adds a small per-step reward for closing the
        # paddle-ball gap — turns the sparse terminal signal into a dense
        # one for quick CI-scale learning checks.
        self.shaped = shaped
        self._rng = np.random.default_rng(seed)
        self._ball_col = np.zeros(n_envs, dtype=np.int64)
        self._ball_row = np.zeros(n_envs, dtype=np.int64)
        self._paddle = np.zeros(n_envs, dtype=np.int64)

    @property
    def obs_shape(self):
        return (self.size, self.size)

    @property
    def obs_dtype(self):
        return np.uint8

    def _render(self) -> np.ndarray:
        frames = np.zeros((self.n_envs, self.size, self.size), dtype=np.uint8)
        idx = np.arange(self.n_envs)
        frames[idx, self._ball_row, self._ball_col] = 255
        frames[idx, self.size - 1, self._paddle] = 128
        return frames

    def _spawn(self, mask: np.ndarray):
        n = int(mask.sum())
        if n:
            self._ball_col[mask] = self._rng.integers(0, self.size, n)
            self._ball_row[mask] = 0
            self._paddle[mask] = self._rng.integers(0, self.size, n)

    def reset(self) -> np.ndarray:
        self._spawn(np.ones(self.n_envs, dtype=bool))
        return self._render()

    def step(self, actions: np.ndarray):
        gap_before = np.abs(self._paddle - self._ball_col)
        self._paddle = np.clip(self._paddle + (actions - 1), 0, self.size - 1)
        self._ball_row += 1
        landed = self._ball_row >= self.size - 1
        caught = landed & (self._paddle == self._ball_col)
        rewards = np.where(caught, 1.0, np.where(landed, -1.0, 0.0)
                           ).astype(np.float32)
        if self.shaped:
            gap_after = np.abs(self._paddle - self._ball_col)
            rewards += 0.1 * np.sign(gap_before - gap_after).astype(np.float32)
        dones = landed.copy()
        infos: Dict[str, Any] = {"truncated": np.zeros(self.n_envs, bool)}
        if dones.any():
            infos["final_obs"] = self._render()
        self._spawn(dones)
        return self._render(), rewards, dones, infos


class ConnectorVectorEnv(VectorEnv):
    """Wraps a VectorEnv with an observation connector pipeline (reference
    agent connectors run in this position inside the rollout worker).

    Handles the stateful FrameStack correctly across auto-resets: done rows
    restart their stacks from the new episode's first frame, and the
    true-final-obs bootstrap gets the stack as it WOULD have continued.
    """

    def __init__(self, inner: VectorEnv, pipeline):
        from ray_tpu.rllib.connectors import FrameStack

        self.inner = inner
        self.pipeline = pipeline
        self._stateless = [c for c in pipeline.connectors
                           if not isinstance(c, FrameStack)]
        stacks = [c for c in pipeline.connectors if isinstance(c, FrameStack)]
        assert len(stacks) <= 1, "at most one FrameStack per pipeline"
        if stacks:
            # Application order is stateless-then-stack; a FrameStack
            # anywhere but last would make the declared output_shape
            # contradict what step() actually emits.
            assert isinstance(pipeline.connectors[-1], FrameStack), \
                "FrameStack must be the LAST connector in the pipeline"
        self._stack = stacks[0] if stacks else None
        self.n_envs = inner.n_envs
        self.n_actions = inner.n_actions
        self.max_episode_steps = inner.max_episode_steps
        self._shape = tuple(pipeline.output_shape(inner.obs_shape))
        self._dtype = pipeline.output_dtype(inner.obs_dtype)
        self.obs_dim = int(np.prod(self._shape))

    @property
    def obs_shape(self):
        return self._shape

    @property
    def obs_dtype(self):
        return self._dtype

    def _pre(self, obs: np.ndarray) -> np.ndarray:
        for c in self._stateless:
            obs = c(obs)
        return obs

    def reset(self) -> np.ndarray:
        x = self._pre(self.inner.reset())
        if self._stack is not None:
            self._stack._stack = None  # fresh episodes everywhere
            x = self._stack(x)
        return x

    def step(self, actions: np.ndarray):
        raw, rewards, dones, infos = self.inner.step(actions)
        x = self._pre(raw)
        out_infos: Dict[str, Any] = {
            "truncated": infos.get("truncated",
                                   np.zeros(self.n_envs, bool))}
        done_rows = np.nonzero(dones)[0]
        raw_final = infos.get("final_obs")
        if self._stack is None:
            if done_rows.size and raw_final is not None:
                out_infos["final_obs"] = self._pre(raw_final)
            return x, rewards, dones, out_infos
        # Stack the final obs BEFORE committing this step's frame: the
        # bootstrap sees frames [..t-k+2, final], not the reset frame.
        if done_rows.size and raw_final is not None:
            out_infos["final_obs"] = self._stack.peek(self._pre(raw_final))
        obs = self._stack(x)
        if done_rows.size:
            # Done rows' x is already the NEW episode's first frame
            # (auto-reset); their stacks restart from it.
            self._stack.reset_rows(done_rows, x)
            obs[done_rows] = self._stack._stack[done_rows]
        return obs, rewards, dones, out_infos


def make_env(env: Any, n_envs: int, seed: int = 0,
             connectors: Any = None) -> VectorEnv:
    """env may be a VectorEnv factory, a VectorEnv, or a gymnasium id
    (Atari "ALE/..." ids get the standard preprocessing pipeline)."""
    if isinstance(env, VectorEnv):
        out = env
    elif callable(env):
        out = env(n_envs=n_envs, seed=seed)
        assert isinstance(out, VectorEnv)
    elif env in ("CartPole-v1", "CartPole"):
        out = CartPoleVectorEnv(n_envs=n_envs, seed=seed)
    elif env in ("Catch-v0", "Catch"):
        out = CatchVectorEnv(n_envs=n_envs, seed=seed)
    else:
        out = GymnasiumVectorEnv(env, n_envs=n_envs, seed=seed)
        if connectors is None and isinstance(env, str) and \
                (env.startswith("ALE/") or "NoFrameskip" in env):
            from ray_tpu.rllib.connectors import atari_connectors

            connectors = atari_connectors()
    if connectors is not None:
        from ray_tpu.rllib.connectors import ConnectorPipeline

        if not isinstance(connectors, ConnectorPipeline):
            connectors = ConnectorPipeline(list(connectors))
        out = ConnectorVectorEnv(out, connectors)
    return out
