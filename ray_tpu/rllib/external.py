"""External env support: policy server + client.

Equivalent of the reference's external-agent API
(`rllib/env/policy_server_input.py`, `rllib/env/policy_client.py`,
`rllib/env/external_env.py`): a simulator that CANNOT be stepped by the
framework (a game server, a hardware rig, a browser session) connects
over HTTP, asks the current policy for actions, and logs rewards; the
server assembles complete episodes into SampleBatch-shaped transition
batches that feed replay-based training (DQN) or, with the logged
logp/value heads, on-policy postprocessing.

TPU-first notes: inference runs through the module's jitted sample
function (pinned to host CPU — external-env action rates never justify
chip occupancy; SURVEY.md §7 one-JAX-process-per-chip model), and the
wire protocol is plain JSON over stdlib HTTP, so clients need nothing
from this framework beyond `PolicyClient`.
"""

from __future__ import annotations

import json
import logging
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, Dict, List, Optional

import numpy as np

logger = logging.getLogger(__name__)


class _EpisodeState:
    __slots__ = ("obs", "action", "logp", "value", "transitions", "total",
                 "pending_reward", "last_active")

    def __init__(self):
        self.obs = None
        self.action = None
        self.logp = 0.0
        self.value = 0.0
        self.transitions: List[Dict[str, Any]] = []
        self.total = 0.0
        # Rewards logged after an action but before the NEXT observation
        # arrives: held here until the transition they belong to is
        # created (at the next get_action / end_episode).
        self.pending_reward = 0.0
        self.last_active = time.monotonic()


class PolicyServer:
    """Serves get_action/log_returns over HTTP; collects episodes.

    `module` is an RLModule (DiscretePolicyModule etc.); weights refresh
    via `set_weights` (e.g. from a learner between iterations). Complete
    episodes accumulate until `sample_batch()` drains them.
    """

    # Episodes with no traffic for this long are abandoned (crashed
    # simulator) and evicted; returns history is ring-bounded.
    EPISODE_TTL_S = 600.0
    MAX_RETURNS_KEPT = 1000

    def __init__(self, module, host: str = "127.0.0.1", port: int = 0,
                 explore: bool = True, seed: int = 0):
        from collections import deque

        from ray_tpu._jax_env import apply_jax_platform_env

        apply_jax_platform_env()
        import jax

        self.module = module
        self.params = module.init_params(jax.random.PRNGKey(seed))
        self._rng = jax.random.PRNGKey(seed + 17)
        self._explore = explore
        self._lock = threading.Lock()
        self._episodes: Dict[str, _EpisodeState] = {}
        self._complete: List[Dict[str, Any]] = []
        self._episode_returns = deque(maxlen=self.MAX_RETURNS_KEPT)
        self._eid = 0
        server = self

        class Handler(BaseHTTPRequestHandler):
            def do_POST(self):  # noqa: N802 — http.server API
                length = int(self.headers.get("Content-Length", 0))
                try:
                    req = json.loads(self.rfile.read(length) or b"{}")
                    resp = server._dispatch(req)
                    code = 200
                except Exception as e:  # noqa: BLE001 — surface to client
                    resp = {"error": f"{type(e).__name__}: {e}"}
                    code = 400
                body = json.dumps(resp).encode()
                self.send_response(code)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def log_message(self, *a):  # silence per-request stderr spam
                pass

        self._httpd = ThreadingHTTPServer((host, port), Handler)
        self.address = (f"http://{self._httpd.server_address[0]}:"
                        f"{self._httpd.server_address[1]}")
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, name="policy-server",
            daemon=True)
        self._thread.start()

    # ------------------------------------------------------------ protocol

    def _dispatch(self, req: Dict[str, Any]) -> Dict[str, Any]:
        cmd = req.get("command")
        if cmd == "start_episode":
            with self._lock:
                self._evict_stale_locked()
                self._eid += 1
                eid = f"ep{self._eid}"
                self._episodes[eid] = _EpisodeState()
            return {"episode_id": eid}
        if cmd == "get_action":
            return self._get_action(req["episode_id"],
                                    np.asarray(req["observation"],
                                               np.float32))
        if cmd == "log_returns":
            with self._lock:
                ep = self._episodes[req["episode_id"]]
                ep.last_active = time.monotonic()  # still alive: no TTL
                ep.total += float(req["reward"])
                ep.pending_reward += float(req["reward"])
            return {}
        if cmd == "end_episode":
            return self._end_episode(
                req["episode_id"],
                np.asarray(req["observation"], np.float32),
                bool(req.get("terminated", True)))
        raise ValueError(f"unknown command {cmd!r}")

    def _evict_stale_locked(self):
        now = time.monotonic()
        for eid, ep in list(self._episodes.items()):
            if now - ep.last_active > self.EPISODE_TTL_S:
                del self._episodes[eid]  # abandoned simulator

    def _get_action(self, eid: str, obs: np.ndarray) -> Dict[str, Any]:
        import jax

        with self._lock:
            ep = self._episodes[eid]
            ep.last_active = time.monotonic()
            self._rng, key = jax.random.split(self._rng)
            params = self.params
        batch_obs = obs[None, ...]
        if self._explore:
            out = self.module.forward_exploration(params, batch_obs, key)
            action, logp, value = out["actions"], out["logp"], out["vf"]
        else:
            out = self.module.forward_inference(params, batch_obs)
            action, value = out["actions"], out["vf"]
            logp = np.zeros(1, np.float32)
        action = int(np.asarray(action)[0])
        with self._lock:
            # The lock was released for inference: a concurrent
            # end_episode may have finalized this episode — appending to
            # the popped object would silently drop the step.
            if self._episodes.get(eid) is not ep:
                raise KeyError(
                    f"episode {eid} ended while an action request was "
                    f"in flight")
            if ep.obs is not None:
                # The previous step's transition completes now that we
                # know its successor observation and the rewards logged
                # in between.
                ep.transitions.append({
                    "obs": ep.obs, "action": ep.action, "logp": ep.logp,
                    "vf": ep.value, "reward": ep.pending_reward,
                    "next_obs": obs, "done": False})
                ep.pending_reward = 0.0
            ep.obs = obs
            ep.action = action
            ep.logp = float(np.asarray(logp)[0])
            ep.value = float(np.asarray(value)[0])
        return {"action": action}

    def _end_episode(self, eid: str, final_obs: np.ndarray,
                     terminated: bool) -> Dict[str, Any]:
        with self._lock:
            ep = self._episodes.pop(eid)
            if ep.obs is not None:
                ep.transitions.append({
                    "obs": ep.obs, "action": ep.action, "logp": ep.logp,
                    "vf": ep.value, "reward": ep.pending_reward,
                    "next_obs": final_obs, "done": terminated})
            if ep.transitions:
                self._complete.append(self._episode_to_batch(ep))
                self._episode_returns.append(ep.total)
        return {"episodes_collected": len(self._complete)}

    @staticmethod
    def _episode_to_batch(ep: _EpisodeState) -> Dict[str, np.ndarray]:
        from ray_tpu.rllib import sample_batch as sb

        t = ep.transitions
        return {
            sb.OBS: np.stack([x["obs"] for x in t]),
            sb.ACTIONS: np.asarray([x["action"] for x in t], np.int32),
            sb.REWARDS: np.asarray([x["reward"] for x in t], np.float32),
            sb.LOGP: np.asarray([x["logp"] for x in t], np.float32),
            sb.VF_PREDS: np.asarray([x["vf"] for x in t], np.float32),
            "next_obs": np.stack([x["next_obs"] for x in t]),
            sb.DONES: np.asarray([x["done"] for x in t], np.float32),
        }

    # ------------------------------------------------------------- training

    def set_weights(self, params) -> None:
        with self._lock:
            self.params = params

    def sample_batch(self) -> Optional[Dict[str, np.ndarray]]:
        """Drain collected episodes into one concatenated batch (None if
        nothing complete yet)."""
        with self._lock:
            eps, self._complete = self._complete, []
        if not eps:
            return None
        return {k: np.concatenate([e[k] for e in eps]) for k in eps[0]}

    def episode_returns(self) -> List[float]:
        with self._lock:
            return list(self._episode_returns)

    def stop(self):
        self._httpd.shutdown()
        self._httpd.server_close()


class PolicyClient:
    """External-simulator side (reference `policy_client.py`): no
    framework dependencies beyond stdlib — a simulator anywhere on the
    network drives episodes against the server's current policy."""

    def __init__(self, address: str, timeout_s: float = 30.0):
        self.address = address.rstrip("/")
        self.timeout_s = timeout_s

    def _call(self, payload: Dict[str, Any]) -> Dict[str, Any]:
        import urllib.error
        import urllib.request

        req = urllib.request.Request(
            self.address, data=json.dumps(payload).encode(),
            headers={"Content-Type": "application/json"})
        try:
            with urllib.request.urlopen(req,
                                        timeout=self.timeout_s) as resp:
                out = json.loads(resp.read())
        except urllib.error.HTTPError as e:
            # The server's diagnostic rides the error body; surface it
            # instead of a bare "HTTP Error 400".
            try:
                detail = json.loads(e.read()).get("error", str(e))
            except Exception:  # noqa: BLE001
                detail = str(e)
            raise RuntimeError(f"policy server error: {detail}") from None
        if "error" in out:
            raise RuntimeError(out["error"])
        return out

    def start_episode(self) -> str:
        return self._call({"command": "start_episode"})["episode_id"]

    def get_action(self, episode_id: str, observation) -> int:
        return self._call({
            "command": "get_action", "episode_id": episode_id,
            "observation": np.asarray(observation).tolist()})["action"]

    def log_returns(self, episode_id: str, reward: float) -> None:
        self._call({"command": "log_returns", "episode_id": episode_id,
                    "reward": float(reward)})

    def end_episode(self, episode_id: str, observation,
                    terminated: bool = True) -> None:
        self._call({"command": "end_episode", "episode_id": episode_id,
                    "observation": np.asarray(observation).tolist(),
                    "terminated": terminated})
