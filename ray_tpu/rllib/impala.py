"""IMPALA: async sampling + V-trace off-policy correction.

Equivalent of the reference's `rllib/algorithms/impala/impala.py:65,677`
(async request pipeline, mixin replay, periodic weight broadcast) and
`vtrace_torch.py` (reimplemented in JAX with a reverse `lax.scan` — the
whole loss+vtrace+optimizer step is one jitted XLA program on the learner
chip).

Design differences from PPO (the on-policy path): rollout workers sample
continuously with up to `max_requests_in_flight_per_worker` outstanding
tasks each; the driver harvests whichever fragment finishes first
(`ray_tpu.wait(num_returns=1)`), assembles fixed-shape train batches
(fresh fragments + mixin replay, constant fragment count so XLA compiles
the update exactly once), updates the learner, and broadcasts weights
every `broadcast_interval` updates without blocking on the workers.
Workers are therefore a bounded number of policy versions stale — exactly
the off-policyness V-trace corrects.
"""

from __future__ import annotations

import logging
import time
from collections import deque
from dataclasses import dataclass
from typing import Any, Callable, Dict, List, Optional

import numpy as np

from ray_tpu.rllib import sample_batch as sb
from ray_tpu.rllib.learner import Learner, LearnerGroup
from ray_tpu.rllib.rl_module import build_module_from_env_spec
from ray_tpu.rllib.rollout import WorkerSet

logger = logging.getLogger(__name__)


# --------------------------------------------------------------------------- #
# V-trace (jit-safe)
# --------------------------------------------------------------------------- #


def vtrace_returns(behavior_logp, target_logp, rewards, terminateds, dones,
                   values, next_values, gamma: float,
                   clip_rho_threshold: float = 1.0,
                   clip_c_threshold: float = 1.0):
    """V-trace targets and policy-gradient advantages.

    All inputs [T, B] (time-major). `terminateds` zeroes the bootstrap
    (true episode end); `dones` (terminated | truncated) cuts the trace so
    corrections never leak across auto-reset boundaries. `next_values[t]`
    is V(x_{t+1}) as seen by the behavior worker.

    Returns (vs, pg_advantages), both stop-gradient'd [T, B].
    """
    import jax
    import jax.numpy as jnp

    rho = jnp.exp(target_logp - behavior_logp)
    clipped_rho = jnp.minimum(rho, clip_rho_threshold)
    clipped_c = jnp.minimum(rho, clip_c_threshold)

    bootstrap_gamma = gamma * (1.0 - terminateds)      # [T, B]
    trace_cont = 1.0 - dones                           # [T, B]
    deltas = clipped_rho * (rewards + bootstrap_gamma * next_values - values)

    def backward(acc, xs):
        delta, cont, c = xs
        acc = delta + gamma * cont * c * acc
        return acc, acc

    _, acc = jax.lax.scan(
        backward, jnp.zeros_like(deltas[0]),
        (deltas, trace_cont, clipped_c), reverse=True)
    vs = values + acc

    # vs_{t+1} for the pg advantage: within-fragment shift; at episode ends
    # (and the fragment tail) the future is just the bootstrap value.
    vs_next = jnp.concatenate([vs[1:], next_values[-1:]], axis=0)
    vs_next = jnp.where(dones > 0, next_values, vs_next)
    pg_adv = clipped_rho * (rewards + bootstrap_gamma * vs_next - values)
    return jax.lax.stop_gradient(vs), jax.lax.stop_gradient(pg_adv)


# --------------------------------------------------------------------------- #
# Config / Learner / Algorithm
# --------------------------------------------------------------------------- #


@dataclass
class IMPALAConfig:
    env: Any = "CartPole-v1"
    num_rollout_workers: int = 2
    num_envs_per_worker: int = 8
    rollout_fragment_length: int = 64
    fragments_per_batch: int = 2       # fresh fragments per train batch
    replay_fragments: int = 0          # mixin-replayed fragments per batch
    replay_buffer_num_slots: int = 16
    max_requests_in_flight_per_worker: int = 2
    updates_per_iteration: int = 8     # learner updates per train() call
    broadcast_interval: int = 1        # weight push every N updates
    lr: float = 5e-4
    gamma: float = 0.99
    vtrace_clip_rho_threshold: float = 1.0
    vtrace_clip_c_threshold: float = 1.0
    vf_loss_coeff: float = 0.5
    entropy_coeff: float = 0.01
    grad_clip: float = 40.0
    # Standardize pg advantages per batch. The reference leaves vtrace
    # advantages raw; with small per-update batches the raw scale is
    # dominated by critic error early on, so normalization buys stable
    # small-batch learning. Set False for paper-faithful behavior.
    standardize_advantages: bool = True
    hidden: tuple = (64, 64)
    seed: int = 0
    learner_mode: str = "local"        # local | remote
    num_learners: int = 1              # dp-sharded update (see LearnerGroup)
    learner_resources: Optional[Dict[str, float]] = None
    num_cpus_per_worker: float = 0.4
    rollout_platform: Optional[str] = "cpu"
    connectors: Any = None  # observation connector pipeline

    def environment(self, env) -> "IMPALAConfig":
        self.env = env
        return self

    def rollouts(self, *, num_rollout_workers: Optional[int] = None,
                 num_envs_per_worker: Optional[int] = None,
                 rollout_fragment_length: Optional[int] = None
                 ) -> "IMPALAConfig":
        if num_rollout_workers is not None:
            self.num_rollout_workers = num_rollout_workers
        if num_envs_per_worker is not None:
            self.num_envs_per_worker = num_envs_per_worker
        if rollout_fragment_length is not None:
            self.rollout_fragment_length = rollout_fragment_length
        return self

    def training(self, **kwargs) -> "IMPALAConfig":
        for k, v in kwargs.items():
            if not hasattr(self, k):
                raise ValueError(f"unknown IMPALA option {k}")
            setattr(self, k, v)
        return self

    def build(self) -> "IMPALA":
        return IMPALA(self)


class IMPALALearner(Learner):
    # Batches are time-major [T, n_envs, ...]: dp shards envs so the
    # V-trace scan over T never crosses devices.
    dp_axis = 1

    def _fragment_forward(self, params, batch):
        """One forward over the fragment obs plus the tail obs [T+1, B]:
        the learner computes its OWN values everywhere (reference vtrace
        uses learner-side values for both v_t and the bootstrap — mixing
        the behavior worker's stale value head in poisons the targets).
        Returns time-major [T, B] heads plus the extended value column
        (shared by IMPALA's loss and APPO's target-anchored variant)."""
        import jax.numpy as jnp

        T, B = batch[sb.ACTIONS].shape
        obs_ext = jnp.concatenate([batch[sb.OBS], batch["last_obs"]],
                                  axis=0)
        flat = {
            "obs": obs_ext.reshape(((T + 1) * B,) + obs_ext.shape[2:]),
            "actions": jnp.concatenate(
                [batch[sb.ACTIONS],
                 jnp.zeros((1, B), batch[sb.ACTIONS].dtype)],
                axis=0).reshape((T + 1) * B),
        }
        out = self.module.forward_train(params, flat)
        vf_ext = out["vf"].reshape(T + 1, B)
        heads = {
            "logp": out["logp"].reshape(T + 1, B)[:T],
            "vf": vf_ext[:T],
            "vf_ext": vf_ext,
            "entropy": out["entropy"].reshape(T + 1, B)[:T],
        }
        if "logits" in out:
            heads["logits"] = out["logits"].reshape(
                (T + 1, B) + out["logits"].shape[1:])[:T]
        return heads

    def _vtrace_advantages(self, target_logp, batch, vf, vf_ext):
        """V-trace targets + pg advantages for a fragment, with the
        done-row bootstrap substitution and optional standardization."""
        import jax
        import jax.numpy as jnp

        cfg = self.config
        # V(x_{t+1}) under current params: within-fragment shift. At done
        # rows the shifted value belongs to the next episode's reset obs,
        # so substitute the behavior worker's value of the TRUE final obs
        # (terminated rows are zeroed by bootstrap_gamma; truncated rows
        # genuinely need it).
        next_vf = jnp.where(batch[sb.DONES] > 0,
                            batch["behavior_next_vf"], vf_ext[1:])
        vs, pg_adv = vtrace_returns(
            behavior_logp=batch[sb.LOGP],
            target_logp=target_logp,
            rewards=batch[sb.REWARDS],
            terminateds=batch["terminateds"],
            dones=batch[sb.DONES],
            values=vf,
            next_values=jax.lax.stop_gradient(next_vf),
            gamma=cfg.gamma,
            clip_rho_threshold=cfg.vtrace_clip_rho_threshold,
            clip_c_threshold=cfg.vtrace_clip_c_threshold,
        )
        if cfg.standardize_advantages:
            pg_adv = (pg_adv - jnp.mean(pg_adv)) / (jnp.std(pg_adv) + 1e-8)
        return vs, pg_adv

    def compute_loss(self, params, batch):
        import jax.numpy as jnp

        cfg = self.config
        heads = self._fragment_forward(params, batch)
        target_logp = heads["logp"]
        vf, entropy = heads["vf"], heads["entropy"]
        vs, pg_adv = self._vtrace_advantages(target_logp, batch, vf,
                                             heads["vf_ext"])
        policy_loss = -jnp.mean(pg_adv * target_logp)
        vf_loss = 0.5 * jnp.mean((vs - vf) ** 2)
        mean_entropy = jnp.mean(entropy)
        loss = policy_loss + cfg.vf_loss_coeff * vf_loss \
            - cfg.entropy_coeff * mean_entropy
        return loss, {"policy_loss": policy_loss, "vf_loss": vf_loss,
                      "entropy": mean_entropy,
                      "mean_vtrace_rho":
                          jnp.mean(jnp.exp(target_logp - batch[sb.LOGP]))}


class IMPALA:
    """Async-sampling algorithm (reference `impala.py:677` training_step)."""

    # Subclasses on the same async machinery (APPO) swap the learner.
    learner_cls = None  # default: IMPALALearner

    def __init__(self, config: IMPALAConfig):
        import ray_tpu

        self.config = config
        self.workers = WorkerSet(
            config.env, num_workers=config.num_rollout_workers,
            n_envs=config.num_envs_per_worker, hidden=config.hidden,
            seed=config.seed,
            num_cpus_per_worker=config.num_cpus_per_worker,
            jax_platform=config.rollout_platform,
            connectors=config.connectors)
        module = build_module_from_env_spec(self.workers.env_spec(),
                                            hidden=config.hidden)
        learner_cls = type(self).learner_cls or IMPALALearner
        self.learner_group = LearnerGroup(
            lambda **kw: learner_cls(module, config, seed=config.seed, **kw),
            mode=config.learner_mode,
            resources=config.learner_resources,
            num_learners=config.num_learners)
        self.workers.sync_weights(self.learner_group.get_weights())

        self.iteration = 0
        self._timesteps = 0
        self._updates = 0
        self._rng = np.random.default_rng(config.seed)
        self._worker_failures = 0
        self._replay: deque = deque(maxlen=config.replay_buffer_num_slots)
        self._fresh_queue: deque = deque()
        # ref -> worker index, for resubmission on completion.
        self._inflight: Dict[Any, int] = {}
        self._ray = ray_tpu

    # ------------------------------------------------------------- sampling

    def _pump_sampling(self):
        """Keep every worker loaded with outstanding sample tasks.
        Submission to a dead actor raises — replace the worker and retry
        (same fault path as a failed harvest)."""
        per_worker: Dict[int, int] = {}
        for idx in self._inflight.values():
            per_worker[idx] = per_worker.get(idx, 0) + 1
        for idx in range(len(self.workers.workers)):
            while per_worker.get(idx, 0) < \
                    self.config.max_requests_in_flight_per_worker:
                try:
                    ref = self.workers.workers[idx].sample.remote(
                        self.config.rollout_fragment_length)
                except Exception:  # noqa: BLE001 — dead actor
                    if not self._replace_worker(idx):
                        break
                    continue
                self._inflight[ref] = idx
                per_worker[idx] = per_worker.get(idx, 0) + 1

    def _replace_worker(self, idx: int) -> bool:
        """Restart worker `idx`; False once the failure budget is spent."""
        self._worker_failures += 1
        if self._worker_failures > 3 * max(
                1, self.config.num_rollout_workers):
            raise RuntimeError(
                "impala: rollout workers keep dying "
                f"({self._worker_failures} failures)")
        logger.warning("impala: restarting rollout worker %d", idx)
        for r, i in list(self._inflight.items()):
            if i == idx:
                self._inflight.pop(r, None)
        try:
            worker = self.workers.restart_worker(idx)
            worker.set_weights.remote(self._ray.put(
                self.learner_group.get_weights()))
        except Exception:  # noqa: BLE001
            logger.exception("impala: worker %d restart failed", idx)
            return False
        return True

    def _harvest(self, block: bool) -> int:
        """Collect finished fragments into the fresh queue."""
        if not self._inflight:
            # Nothing outstanding (every worker dead with failed restarts,
            # or first call): re-pump rather than letting a blocking caller
            # spin; if pumping can't put anything in flight either, the
            # sampler is wedged — surface it instead of hanging.
            self._pump_sampling()
            if not self._inflight:
                if block:
                    raise RuntimeError(
                        "impala: no rollout tasks in flight and no worker "
                        f"accepts new ones ({self._worker_failures} worker "
                        "failures)")
                return 0
        refs = list(self._inflight.keys())
        ready, rest = self._ray.wait(
            refs, num_returns=1, timeout=None if block else 0.0)
        if rest:
            # Drain everything else already finished too — in particular
            # error-resolved refs from a dead worker (submission to a dead
            # actor returns errored refs rather than raising), so the
            # replacement path always runs even when a live worker's
            # fragment came back first.
            more, _ = self._ray.wait(rest, num_returns=len(rest),
                                     timeout=0.0)
            ready = list(ready) + list(more)
        got = 0
        for ref in ready:
            idx = self._inflight.pop(ref, None)
            try:
                frag = self._ray.get(ref)
            except Exception:  # noqa: BLE001 — worker died: replace it
                if idx is not None:
                    self._replace_worker(idx)
                continue
            self._fresh_queue.append(self._to_time_major(frag))
            got += 1
        self._pump_sampling()
        return got

    def _to_time_major(self, frag: Dict[str, np.ndarray]
                       ) -> Dict[str, np.ndarray]:
        T, n = frag.pop("_shape")
        obs_shape = frag[sb.OBS].shape[1:]  # (obs_dim,) or image dims
        dones = frag[sb.DONES].reshape(T, n).astype(np.float32)
        truncs = frag[sb.TRUNCATEDS].reshape(T, n).astype(np.float32)
        return {
            sb.OBS: frag[sb.OBS].reshape((T, n) + obs_shape),
            "last_obs": frag["_last_obs"].reshape((1, n) + obs_shape),
            sb.ACTIONS: frag[sb.ACTIONS].reshape(T, n),
            sb.REWARDS: frag[sb.REWARDS].reshape(T, n),
            sb.LOGP: frag[sb.LOGP].reshape(T, n),
            sb.DONES: dones,
            "terminateds": np.maximum(dones - truncs, 0.0),
            # Behavior-side V(x_{t+1}) with the TRUE final obs at done rows
            # (rollout patches them); used only at episode boundaries.
            "behavior_next_vf": frag["_next_vf"].reshape(T, n),
        }

    def _assemble_batch(self) -> Dict[str, np.ndarray]:
        cfg = self.config
        fresh = [self._fresh_queue.popleft()
                 for _ in range(cfg.fragments_per_batch)]
        for frag in fresh:
            self._replay.append(frag)
        frags = list(fresh)
        for _ in range(cfg.replay_fragments):
            # Mixin replay (reference replay_proportion): sample a stored
            # fragment; until the buffer warms up this re-reads fresh ones,
            # keeping the batch shape (and the XLA program) constant.
            frags.append(self._replay[self._rng.integers(len(self._replay))])
        # Every array is [T, n, ...] except last_obs's leading dim of 1 —
        # both concatenate along the env axis (axis 1).
        return {k: np.concatenate([f[k] for f in frags], axis=1)
                for k in frags[0]}

    # ------------------------------------------------------------- training

    def training_step(self) -> Dict[str, Any]:
        cfg = self.config
        metrics: Dict[str, float] = {}
        frames_per_batch = (cfg.fragments_per_batch
                            * cfg.rollout_fragment_length
                            * cfg.num_envs_per_worker)
        sample_s = 0.0
        learn_s = 0.0
        self._pump_sampling()
        for _ in range(cfg.updates_per_iteration):
            t0 = time.perf_counter()
            while len(self._fresh_queue) < cfg.fragments_per_batch:
                self._harvest(block=True)
            batch = self._assemble_batch()
            sample_s += time.perf_counter() - t0

            t1 = time.perf_counter()
            metrics = self.learner_group.update(batch) or metrics
            learn_s += time.perf_counter() - t1
            self._updates += 1
            self._timesteps += frames_per_batch
            # Opportunistically drain finished fragments (non-blocking) so
            # workers never stall on a full in-flight budget.
            self._harvest(block=False)

            if self._updates % cfg.broadcast_interval == 0:
                weights_ref = self._ray.put(
                    self.learner_group.get_weights())
                for w in self.workers.workers:
                    w.set_weights.remote(weights_ref)

        total = cfg.updates_per_iteration * frames_per_batch
        return {
            "sample_wait_s": sample_s,
            "learn_s": learn_s,
            "learner_sps": total / learn_s if learn_s else 0.0,
            "updates": self._updates,
            **metrics,
        }

    def train(self) -> Dict[str, Any]:
        self.iteration += 1
        t0 = time.perf_counter()
        step_metrics = self.training_step()
        wall = time.perf_counter() - t0
        stats = self.workers.episode_stats()
        rewards = [s["episode_reward_mean"] for s in stats
                   if s["episode_reward_mean"] is not None]
        lens = [s["episode_len_mean"] for s in stats
                if s["episode_len_mean"] is not None]
        frames = (self.config.updates_per_iteration
                  * self.config.fragments_per_batch
                  * self.config.rollout_fragment_length
                  * self.config.num_envs_per_worker)
        return {
            "training_iteration": self.iteration,
            "timesteps_total": self._timesteps,
            "env_steps_per_s": frames / wall,
            "episode_reward_mean": float(np.mean(rewards)) if rewards
            else None,
            "episode_len_mean": float(np.mean(lens)) if lens else None,
            **step_metrics,
        }

    # --------------------------------------------------------- checkpointing

    def save(self, path: str) -> str:
        import os
        import pickle

        os.makedirs(path, exist_ok=True)
        with open(os.path.join(path, "algorithm.pkl"), "wb") as f:
            pickle.dump({"learner": self.learner_group.get_state(),
                         "iteration": self.iteration,
                         "timesteps": self._timesteps}, f)
        return path

    def restore(self, path: str):
        import os
        import pickle

        with open(os.path.join(path, "algorithm.pkl"), "rb") as f:
            state = pickle.load(f)
        self.learner_group.set_state(state["learner"])
        self.iteration = state["iteration"]
        self._timesteps = state["timesteps"]
        self.workers.sync_weights(self.learner_group.get_weights())

    def get_weights(self):
        return self.learner_group.get_weights()

    def stop(self):
        # Drop in-flight refs before killing workers.
        self._inflight.clear()
        self.workers.shutdown()
        self.learner_group.shutdown()

    @staticmethod
    def as_trainable(base_config: "IMPALAConfig") -> Callable:
        def trainable(config: Dict[str, Any]):
            import copy

            from ray_tpu import tune

            cfg = copy.deepcopy(base_config)
            for k, v in (config or {}).items():
                if hasattr(cfg, k):
                    setattr(cfg, k, v)
            algo = IMPALA(cfg)
            try:
                while True:
                    tune.report(algo.train())
            finally:
                algo.stop()

        trainable.__name__ = "IMPALA"
        return trainable
