"""Learner / LearnerGroup: the gradient-update half of the new stack.

Equivalent of the reference's `Learner.{compute_loss,update}`
(`rllib/core/learner/learner.py:111,645,805`) and `LearnerGroup`
(`learner_group.py:61`) — TPU-first: the update is one jitted function
(loss + grad + optimizer apply fused by XLA onto the chip); a distributed
LearnerGroup shards the batch over a dp mesh axis inside jit instead of
DDP-allreducing torch gradients.
"""

from __future__ import annotations

import logging
from typing import Any, Callable, Dict, List, Optional

import numpy as np

logger = logging.getLogger(__name__)


def host_local_numpy(arr) -> np.ndarray:
    """Materialize this process's rows of a (possibly multi-host sharded)
    jax array: np.asarray on a non-fully-addressable array raises, so
    concatenate the addressable shards in index order instead."""
    if getattr(arr, "is_fully_addressable", True):
        return np.asarray(arr)
    shards = sorted(arr.addressable_shards,
                    key=lambda s: tuple(sl.start or 0 for sl in s.index))
    return np.concatenate([np.asarray(s.data) for s in shards])


class Learner:
    """Owns params + optimizer state; `update` is the jitted hot path.

    num_devices > 1 turns the learner into a data-parallel SPMD program:
    the update is jitted over a `Mesh` with a "dp" axis, the batch sharded
    along its leading axis and params/opt-state replicated — XLA's
    partitioner inserts the gradient all-reduce (psum over dp) that the
    reference obtains from torch DDP hooks
    (`rllib/core/learner/torch/torch_learner.py`). One jitted program, N
    chips, no per-gradient host traffic.
    """

    # Which batch axis data-parallelism shards: 0 for flat [B, ...]
    # batches (PPO/DQN); time-major learners ([T, n_envs, ...], IMPALA)
    # override to 1 so the V-trace time scan stays device-local.
    dp_axis: int = 0
    # Methods whose first argument is a batch to dp-split across learner
    # processes (subclasses with extra update entry points extend this —
    # DQN adds "update_dqn").
    batch_update_methods: tuple = ("update", "update_many")

    def __init__(self, module, config, seed: int = 0,
                 num_devices: int = 1, devices: Optional[List] = None):
        from ray_tpu._jax_env import apply_jax_platform_env

        apply_jax_platform_env()
        import jax
        import optax

        self.module = module
        self.config = config
        self.num_devices = max(1, int(num_devices))
        self.params = module.init_params(jax.random.PRNGKey(seed))
        lr = getattr(config, "lr", 3e-4)
        clip = getattr(config, "grad_clip", 0.5)
        self.optimizer = optax.chain(
            optax.clip_by_global_norm(clip), optax.adam(lr))
        self.opt_state = self.optimizer.init(self.params)
        if self.num_devices > 1:
            self._init_sharded(devices)
        else:
            self._rep_sharding = None
            self._batch_sharding = None
            self._stacked_sharding = None
            self._update = jax.jit(self._update_impl)
            self._update_many = jax.jit(self._update_many_impl)

    def _init_sharded(self, devices: Optional[List] = None):
        import jax
        from jax.sharding import Mesh, NamedSharding
        from jax.sharding import PartitionSpec as P

        devs = list(devices) if devices is not None else list(jax.devices())
        if len(devs) < self.num_devices:
            raise ValueError(
                f"num_learners={self.num_devices} but only {len(devs)} "
                f"devices visible ({jax.default_backend()})")
        self.mesh = Mesh(np.asarray(devs[: self.num_devices]), ("dp",))
        rep = NamedSharding(self.mesh, P())
        self._rep_sharding = rep
        self._batch_sharding = NamedSharding(
            self.mesh, P(*([None] * self.dp_axis), "dp"))
        self._stacked_sharding = NamedSharding(
            self.mesh, P(*([None] * (self.dp_axis + 1)), "dp"))
        self.params = jax.device_put(self.params, rep)
        self.opt_state = jax.device_put(self.opt_state, rep)
        self._update = jax.jit(
            self._update_impl,
            in_shardings=(rep, rep, self._batch_sharding),
            out_shardings=(rep, rep, rep))
        self._update_many = jax.jit(
            self._update_many_impl,
            in_shardings=(rep, rep, self._stacked_sharding),
            out_shardings=(rep, rep, rep))

    def _prepare_batch(self, batch: Dict[str, Any], axis: int
                       ) -> Optional[Dict[str, Any]]:
        """dp-shard a host batch: trim the batch axis to a multiple of dp
        (DDP drop-last semantics) and, under multi-host SPMD, assemble
        global arrays from this process's local rows. Returns None when
        trimming leaves nothing to train on."""
        if self.num_devices <= 1:
            return batch
        import jax

        world = jax.process_count()
        # Multi-host: this process holds 1/world of the global batch; its
        # rows need only cover the local device share of the dp axis.
        n = self.num_devices // world if world > 1 else self.num_devices
        n = max(1, n)

        def trim(x):
            x = np.asarray(x)
            keep = (x.shape[axis] // n) * n
            if keep == x.shape[axis]:
                return x
            sl = [slice(None)] * x.ndim
            sl[axis] = slice(0, keep)
            return x[tuple(sl)]

        out = {k: trim(v) for k, v in batch.items()}
        if any(v.shape[axis] == 0 for v in out.values()):
            return None
        if world > 1:
            sh = self._batch_sharding if axis == 0 else self._stacked_sharding
            out = {k: jax.make_array_from_process_local_data(sh, v)
                   for k, v in out.items()}
        return out

    # -- override point -------------------------------------------------------

    def compute_loss(self, params, batch: Dict[str, Any]):
        """Return (loss, metrics). Overridden per algorithm (PPO below)."""
        raise NotImplementedError

    # -- update ---------------------------------------------------------------

    def _update_impl(self, params, opt_state, batch):
        import jax
        import optax

        (loss, metrics), grads = jax.value_and_grad(
            self.compute_loss, has_aux=True)(params, batch)
        updates, opt_state = self.optimizer.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        metrics["total_loss"] = loss
        metrics["grad_norm"] = optax.global_norm(grads)
        return params, opt_state, metrics

    def _update_many_impl(self, params, opt_state, stacked):
        """One SGD epoch as a single XLA program: lax.scan over the
        leading minibatch axis. TPU-first — a per-minibatch Python loop
        pays one host->device dispatch per step (hundreds of ms through a
        remote-chip tunnel); the scan pays one for the whole epoch."""
        import jax

        def step(carry, mb):
            p, o = carry
            p, o, metrics = self._update_impl(p, o, mb)
            return (p, o), metrics

        (params, opt_state), metrics = jax.lax.scan(
            step, (params, opt_state), stacked)
        # Epoch means for reporting — except KL, where the guard needs the
        # END-of-epoch divergence (the mean is diluted by the first
        # minibatch's near-zero KL and would fire the early stop too late).
        out = jax.tree_util.tree_map(lambda m: m.mean(), metrics)
        if "kl" in metrics:
            out["kl"] = metrics["kl"][-1]
        return params, opt_state, out

    def update(self, batch: Dict[str, np.ndarray]) -> Dict[str, float]:
        batch = self._prepare_batch(batch, axis=self.dp_axis)
        if batch is None:
            return {}
        self.params, self.opt_state, metrics = self._update(
            self.params, self.opt_state, batch)
        return {k: float(v) for k, v in metrics.items()}

    def update_many(self, stacked: Dict[str, np.ndarray]) -> Dict[str, float]:
        """Run one update per row of the leading minibatch axis."""
        stacked = self._prepare_batch(stacked, axis=self.dp_axis + 1)
        if stacked is None:
            return {}
        self.params, self.opt_state, metrics = self._update_many(
            self.params, self.opt_state, stacked)
        return {k: float(v) for k, v in metrics.items()}

    def get_weights(self) -> Any:
        import jax

        return jax.device_get(self.params)

    def set_weights(self, weights: Any):
        self.params = weights

    def get_state(self) -> Dict[str, Any]:
        import jax

        return {"params": jax.device_get(self.params),
                "opt_state": jax.device_get(self.opt_state)}

    def set_state(self, state: Dict[str, Any]):
        self.params = state["params"]
        self.opt_state = state["opt_state"]


class LearnerGroup:
    """Local or remote learner execution (reference `learner_group.py:61`).

    mode="local": the learner lives in the calling process (drives the
    local chip directly — the default for 1-host training).
    mode="remote": the learner runs in a dedicated actor (optionally with
    TPU resources) so rollout workers and the driver stay off the chip.

    num_learners > 1 scales the update the TPU way (reference
    `learner_group.py:114-126` scales via N DDP torch workers):
      * local — one SPMD program dp-sharded over num_learners local chips
        (the single-host multi-chip case; see `Learner._init_sharded`).
      * remote — num_learners actors form a `jax.distributed` process
        group (multi-host); every actor runs the same dp-sharded update
        over the global mesh on its local slice of the batch.
    """

    def __init__(self, learner_factory: Callable[..., Learner],
                 mode: str = "local",
                 resources: Optional[Dict[str, float]] = None,
                 num_learners: int = 1):
        self.mode = mode
        self.num_learners = max(1, int(num_learners))
        self._sharded_group = None
        if mode != "local" and self.num_learners > 1:
            self._learner = None
            self._actor = None
            self._sharded_group = _ShardedLearnerGroup(
                learner_factory, self.num_learners, resources)
        elif mode == "local":
            self._learner = (learner_factory(num_devices=self.num_learners)
                             if self.num_learners > 1 else learner_factory())
            self._actor = None
        else:
            import ray_tpu

            opts: Dict[str, Any] = {}
            if resources:
                res = dict(resources)
                if "CPU" in res:
                    opts["num_cpus"] = res.pop("CPU")
                if "TPU" in res:
                    opts["num_tpus"] = res.pop("TPU")
                if res:
                    opts["resources"] = res
            actor_cls = ray_tpu.remote(_LearnerActor)
            self._actor = (actor_cls.options(**opts) if opts else actor_cls
                           ).remote(learner_factory)
            self._learner = None
            ray_tpu.get(self._actor.ping.remote())

    def update(self, batch) -> Dict[str, float]:
        if self._learner is not None:
            return self._learner.update(batch)
        if self._sharded_group is not None:
            return self._sharded_group.update("update", batch)
        import ray_tpu

        return ray_tpu.get(self._actor.update.remote(batch))

    def update_many(self, stacked) -> Dict[str, float]:
        if self._learner is not None:
            return self._learner.update_many(stacked)
        if self._sharded_group is not None:
            return self._sharded_group.update("update_many", stacked)
        import ray_tpu

        return ray_tpu.get(self._actor.update_many.remote(stacked))

    def call(self, method: str, *args, **kwargs):
        """Dispatch an algorithm-specific learner method (DQN's update_dqn,
        sync_target, ...) through whichever mode this group runs in."""
        if self._learner is not None:
            return getattr(self._learner, method)(*args, **kwargs)
        if self._sharded_group is not None:
            if (method in self._sharded_group.batch_methods
                    and len(args) == 1 and not kwargs):
                # Batch-consuming updates split across the learner
                # processes like update()/update_many() — broadcasting
                # the full batch would duplicate work N times.
                return self._sharded_group.update(method, args[0])
            return self._sharded_group.call_all(method, *args, **kwargs)[0]
        import ray_tpu

        return ray_tpu.get(self._actor.call.remote(method, *args, **kwargs))

    def get_weights(self):
        if self._learner is not None:
            return self._learner.get_weights()
        if self._sharded_group is not None:
            return self._sharded_group.call_rank0("get_weights")
        import ray_tpu

        return ray_tpu.get(self._actor.get_weights.remote())

    def get_state(self):
        if self._learner is not None:
            return self._learner.get_state()
        if self._sharded_group is not None:
            return self._sharded_group.call_rank0("get_state")
        import ray_tpu

        return ray_tpu.get(self._actor.get_state.remote())

    def set_state(self, state):
        if self._learner is not None:
            self._learner.set_state(state)
        elif self._sharded_group is not None:
            self._sharded_group.call_all("set_state", state)
        else:
            import ray_tpu

            ray_tpu.get(self._actor.set_state.remote(state))

    def shutdown(self):
        if self._sharded_group is not None:
            self._sharded_group.shutdown()
        if self._actor is not None:
            import ray_tpu

            try:
                ray_tpu.kill(self._actor)
            except Exception:
                pass


class _ShardedLearnerGroup:
    """num_learners actors forming one SPMD update (multi-host path).

    Mirrors the reference LearnerGroup's N-worker scaling
    (`rllib/core/learner/learner_group.py:114-126`) with the TPU recipe:
    the actors form a `jax.distributed` process group, each builds the
    SAME dp-sharded jitted update over the global mesh, and every
    training round each actor receives only its slice of the batch —
    gradients meet in XLA's psum over ICI/DCN, never on the host.

    Requires a runtime whose process group yields a global device view
    (real multi-host TPU); raises a clear error otherwise — this jax
    build has no multi-process CPU collectives, so tests exercise the
    single-process sharded path and this class's slicing helpers.
    """

    def __init__(self, learner_factory, num_learners: int,
                 resources: Optional[Dict[str, float]] = None):
        import ray_tpu

        self.n = num_learners
        opts: Dict[str, Any] = {}
        if resources:
            res = dict(resources)
            if "CPU" in res:
                opts["num_cpus"] = res.pop("CPU")
            if "TPU" in res:
                opts["num_tpus"] = res.pop("TPU")
            if res:
                opts["resources"] = res
        actor_cls = ray_tpu.remote(_ShardedLearnerWorker)
        if opts:
            actor_cls = actor_cls.options(**opts)
        self.workers = [actor_cls.remote(learner_factory)
                        for _ in range(num_learners)]
        try:
            ray_tpu.get([w.ping.remote() for w in self.workers])
            host, port = ray_tpu.get(
                self.workers[0].get_free_address.remote())
            coordinator = f"{host}:{port}"
            logger.info("forming learner process group: %d procs via %s",
                        num_learners, coordinator)
            ray_tpu.get([w.setup_group.remote(coordinator, num_learners, rank)
                         for rank, w in enumerate(self.workers)])
            counts = ray_tpu.get([w.build.remote(num_learners)
                                  for w in self.workers])
            self.global_devices = counts[0]
            self.dp_axis, self.batch_methods = ray_tpu.get(
                self.workers[0].get_split_spec.remote())
        except Exception:
            # Formation failed (e.g. no global device view): don't leak
            # the spawned actors or their resource reservations.
            self.shutdown()
            raise

    @staticmethod
    def _split(batch: Dict[str, np.ndarray], n: int, axis: int
               ) -> List[Dict[str, np.ndarray]]:
        """Trim the batch axis to a multiple of n processes and cut it
        into n equal contiguous slices (one per learner process)."""
        out: List[Dict[str, np.ndarray]] = [dict() for _ in range(n)]
        for k, v in batch.items():
            v = np.asarray(v)
            per = v.shape[axis] // n
            for i in range(n):
                sl = [slice(None)] * v.ndim
                sl[axis] = slice(i * per, (i + 1) * per)
                out[i][k] = v[tuple(sl)]
        return out

    def update(self, method: str, batch) -> Dict[str, float]:
        import ray_tpu

        axis = self.dp_axis + (1 if method == "update_many" else 0)
        orig_rows = min(np.asarray(v).shape[axis] for v in batch.values())
        slices = self._split(batch, self.n, axis)
        if any(v.shape[axis] == 0 for v in slices[0].values()):
            if method in ("update", "update_many"):
                return {}  # clean no-op, like the local drop-last path
            # Methods returning (metrics, per-row aux) cannot no-op
            # without breaking their callers' unpacking — misconfig.
            raise ValueError(
                f"batch of {orig_rows} rows is too small to split across "
                f"{self.n} learners for {method}; raise train_batch_size "
                f"or lower num_learners")
        refs = [w.update_slice.remote(method, s)
                for w, s in zip(self.workers, slices)]
        results = ray_tpu.get(refs)
        if isinstance(results[0], tuple):
            # (metrics, per-row aux) shape — e.g. DQN's |TD| priorities:
            # metrics are replicated, the aux rows concatenate back in
            # rank order (slices were contiguous). Drop-last trimming may
            # have shed tail rows; re-pad so callers indexing with the
            # ORIGINAL batch's indices (replay priority updates) line up.
            metrics = results[0][0]
            aux = np.concatenate([np.asarray(r[1]) for r in results])
            if len(aux) < orig_rows:
                fill = float(aux.mean()) if len(aux) else 1.0
                aux = np.concatenate(
                    [aux, np.full(orig_rows - len(aux), fill, aux.dtype)])
            return metrics, aux
        return results[0]

    def call_all(self, name: str, *args, **kwargs) -> List[Any]:
        import ray_tpu

        return ray_tpu.get([w.call.remote(name, *args, **kwargs)
                            for w in self.workers])

    def call_rank0(self, name: str, *args, **kwargs):
        import ray_tpu

        return ray_tpu.get(self.workers[0].call.remote(name, *args, **kwargs))

    def shutdown(self):
        import ray_tpu

        try:
            ray_tpu.get([w.teardown.remote() for w in self.workers],
                        timeout=10)
        except Exception:
            pass
        for w in self.workers:
            try:
                ray_tpu.kill(w)
            except Exception:
                pass


class _ShardedLearnerWorker:
    """One process of a multi-host sharded learner (runs inside an actor)."""

    def __init__(self, learner_factory):
        self._factory = learner_factory
        self._learner: Optional[Learner] = None

    def ping(self):
        return True

    def get_free_address(self):
        from ray_tpu.parallel.distributed import get_address_and_port

        return get_address_and_port()

    def setup_group(self, coordinator: str, world: int, rank: int):
        from ray_tpu.parallel.distributed import initialize_distributed

        initialize_distributed(coordinator, world, rank)
        return True

    def build(self, num_learners: int) -> int:
        import jax

        n_global = jax.device_count()
        procs = {d.process_index for d in jax.devices()}
        if n_global < num_learners or len(procs) < num_learners:
            raise RuntimeError(
                f"sharded LearnerGroup needs a global device view spanning "
                f"its {num_learners} processes, but this process sees "
                f"{n_global} device(s) from {len(procs)} process(es) after "
                f"jax.distributed init — multi-process collectives are "
                f"unavailable on this platform; use mode='local' with "
                f"num_learners instead")
        self._learner = self._factory(num_devices=n_global)
        return n_global

    def get_split_spec(self):
        return self._learner.dp_axis, tuple(self._learner.batch_update_methods)

    def update_slice(self, method: str, local_batch):
        return getattr(self._learner, method)(local_batch)

    def call(self, name: str, *args, **kwargs):
        return getattr(self._learner, name)(*args, **kwargs)

    def teardown(self):
        from ray_tpu.parallel.distributed import shutdown_distributed

        shutdown_distributed()
        return True


class _LearnerActor:
    def __init__(self, learner_factory):
        self._learner = learner_factory()

    def ping(self):
        return True

    def call(self, method: str, *args, **kwargs):
        """Algorithm-specific learner methods (e.g. DQN's update_dqn /
        sync_target) without a dedicated RPC per method."""
        return getattr(self._learner, method)(*args, **kwargs)

    def update(self, batch):
        return self._learner.update(batch)

    def update_many(self, stacked):
        return self._learner.update_many(stacked)

    def get_weights(self):
        return self._learner.get_weights()

    def get_state(self):
        return self._learner.get_state()

    def set_state(self, state):
        self._learner.set_state(state)
