"""Learner / LearnerGroup: the gradient-update half of the new stack.

Equivalent of the reference's `Learner.{compute_loss,update}`
(`rllib/core/learner/learner.py:111,645,805`) and `LearnerGroup`
(`learner_group.py:61`) — TPU-first: the update is one jitted function
(loss + grad + optimizer apply fused by XLA onto the chip); a distributed
LearnerGroup shards the batch over a dp mesh axis inside jit instead of
DDP-allreducing torch gradients.
"""

from __future__ import annotations

import logging
from typing import Any, Callable, Dict, List, Optional

import numpy as np

logger = logging.getLogger(__name__)


class Learner:
    """Owns params + optimizer state; `update` is the jitted hot path."""

    def __init__(self, module, config, seed: int = 0):
        from ray_tpu._jax_env import apply_jax_platform_env

        apply_jax_platform_env()
        import jax
        import optax

        self.module = module
        self.config = config
        self.params = module.init_params(jax.random.PRNGKey(seed))
        lr = getattr(config, "lr", 3e-4)
        clip = getattr(config, "grad_clip", 0.5)
        self.optimizer = optax.chain(
            optax.clip_by_global_norm(clip), optax.adam(lr))
        self.opt_state = self.optimizer.init(self.params)
        self._update = jax.jit(self._update_impl)
        self._update_many = jax.jit(self._update_many_impl)

    # -- override point -------------------------------------------------------

    def compute_loss(self, params, batch: Dict[str, Any]):
        """Return (loss, metrics). Overridden per algorithm (PPO below)."""
        raise NotImplementedError

    # -- update ---------------------------------------------------------------

    def _update_impl(self, params, opt_state, batch):
        import jax
        import optax

        (loss, metrics), grads = jax.value_and_grad(
            self.compute_loss, has_aux=True)(params, batch)
        updates, opt_state = self.optimizer.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        metrics["total_loss"] = loss
        metrics["grad_norm"] = optax.global_norm(grads)
        return params, opt_state, metrics

    def _update_many_impl(self, params, opt_state, stacked):
        """One SGD epoch as a single XLA program: lax.scan over the
        leading minibatch axis. TPU-first — a per-minibatch Python loop
        pays one host->device dispatch per step (hundreds of ms through a
        remote-chip tunnel); the scan pays one for the whole epoch."""
        import jax

        def step(carry, mb):
            p, o = carry
            p, o, metrics = self._update_impl(p, o, mb)
            return (p, o), metrics

        (params, opt_state), metrics = jax.lax.scan(
            step, (params, opt_state), stacked)
        # Epoch means for reporting — except KL, where the guard needs the
        # END-of-epoch divergence (the mean is diluted by the first
        # minibatch's near-zero KL and would fire the early stop too late).
        out = jax.tree_util.tree_map(lambda m: m.mean(), metrics)
        if "kl" in metrics:
            out["kl"] = metrics["kl"][-1]
        return params, opt_state, out

    def update(self, batch: Dict[str, np.ndarray]) -> Dict[str, float]:
        self.params, self.opt_state, metrics = self._update(
            self.params, self.opt_state, batch)
        return {k: float(v) for k, v in metrics.items()}

    def update_many(self, stacked: Dict[str, np.ndarray]) -> Dict[str, float]:
        """Run one update per row of the leading minibatch axis."""
        self.params, self.opt_state, metrics = self._update_many(
            self.params, self.opt_state, stacked)
        return {k: float(v) for k, v in metrics.items()}

    def get_weights(self) -> Any:
        import jax

        return jax.device_get(self.params)

    def set_weights(self, weights: Any):
        self.params = weights

    def get_state(self) -> Dict[str, Any]:
        import jax

        return {"params": jax.device_get(self.params),
                "opt_state": jax.device_get(self.opt_state)}

    def set_state(self, state: Dict[str, Any]):
        self.params = state["params"]
        self.opt_state = state["opt_state"]


class LearnerGroup:
    """Local or remote learner execution (reference `learner_group.py:61`).

    mode="local": the learner lives in the calling process (drives the
    local chip directly — the default for 1-host training).
    mode="remote": the learner runs in a dedicated actor (optionally with
    TPU resources) so rollout workers and the driver stay off the chip.
    """

    def __init__(self, learner_factory: Callable[[], Learner],
                 mode: str = "local",
                 resources: Optional[Dict[str, float]] = None):
        self.mode = mode
        if mode == "local":
            self._learner = learner_factory()
            self._actor = None
        else:
            import ray_tpu

            opts: Dict[str, Any] = {}
            if resources:
                res = dict(resources)
                if "CPU" in res:
                    opts["num_cpus"] = res.pop("CPU")
                if "TPU" in res:
                    opts["num_tpus"] = res.pop("TPU")
                if res:
                    opts["resources"] = res
            actor_cls = ray_tpu.remote(_LearnerActor)
            self._actor = (actor_cls.options(**opts) if opts else actor_cls
                           ).remote(learner_factory)
            self._learner = None
            ray_tpu.get(self._actor.ping.remote())

    def update(self, batch) -> Dict[str, float]:
        if self._learner is not None:
            return self._learner.update(batch)
        import ray_tpu

        return ray_tpu.get(self._actor.update.remote(batch))

    def update_many(self, stacked) -> Dict[str, float]:
        if self._learner is not None:
            return self._learner.update_many(stacked)
        import ray_tpu

        return ray_tpu.get(self._actor.update_many.remote(stacked))

    def call(self, method: str, *args, **kwargs):
        """Dispatch an algorithm-specific learner method (DQN's update_dqn,
        sync_target, ...) through whichever mode this group runs in."""
        if self._learner is not None:
            return getattr(self._learner, method)(*args, **kwargs)
        import ray_tpu

        return ray_tpu.get(self._actor.call.remote(method, *args, **kwargs))

    def get_weights(self):
        if self._learner is not None:
            return self._learner.get_weights()
        import ray_tpu

        return ray_tpu.get(self._actor.get_weights.remote())

    def get_state(self):
        if self._learner is not None:
            return self._learner.get_state()
        import ray_tpu

        return ray_tpu.get(self._actor.get_state.remote())

    def set_state(self, state):
        if self._learner is not None:
            self._learner.set_state(state)
        else:
            import ray_tpu

            ray_tpu.get(self._actor.set_state.remote(state))

    def shutdown(self):
        if self._actor is not None:
            import ray_tpu

            try:
                ray_tpu.kill(self._actor)
            except Exception:
                pass


class _LearnerActor:
    def __init__(self, learner_factory):
        self._learner = learner_factory()

    def ping(self):
        return True

    def call(self, method: str, *args, **kwargs):
        """Algorithm-specific learner methods (e.g. DQN's update_dqn /
        sync_target) without a dedicated RPC per method."""
        return getattr(self._learner, method)(*args, **kwargs)

    def update(self, batch):
        return self._learner.update(batch)

    def update_many(self, stacked):
        return self._learner.update_many(stacked)

    def get_weights(self):
        return self._learner.get_weights()

    def get_state(self):
        return self._learner.get_state()

    def set_state(self, state):
        self._learner.set_state(state)
