"""Offline RL IO: persist sample batches through the Data layer, train
policies from them without an environment.

Equivalent of the reference's `rllib/offline/` (JsonWriter/JsonReader,
`dataset_reader.py` reading experiences through Ray Data, and the BC
algorithm `rllib/algorithms/bc/`). Experiences round-trip as row dicts so
they compose with every Data transform (filter/map_batches/split) before
reaching a learner.
"""

from __future__ import annotations

import os
from dataclasses import dataclass
from typing import Any, Dict, Iterator, List, Optional, Sequence

import numpy as np

from ray_tpu.rllib import sample_batch as sb
from ray_tpu.rllib.learner import Learner
from ray_tpu.rllib.rl_module import SpecDict, build_module

_FIELDS = (sb.OBS, sb.ACTIONS, sb.REWARDS, sb.DONES, sb.LOGP)


def batch_to_rows(batch: Dict[str, np.ndarray],
                  fields: Sequence[str] = _FIELDS) -> List[Dict[str, Any]]:
    """Columnar SampleBatch -> row dicts (json/parquet friendly)."""
    present = [f for f in fields if f in batch]
    n = len(batch[present[0]])
    rows = []
    for i in range(n):
        row = {}
        for f in present:
            v = batch[f][i]
            row[f] = v.tolist() if isinstance(v, np.ndarray) else \
                (v.item() if hasattr(v, "item") else v)
        rows.append(row)
    return rows


def rows_to_batch(rows: List[Dict[str, Any]]) -> Dict[str, np.ndarray]:
    """Row dicts -> columnar SampleBatch."""
    if not rows:
        return {}
    out = {}
    for k in rows[0]:
        col = [r[k] for r in rows]
        arr = np.asarray(col)
        if k in (sb.ACTIONS,):
            arr = arr.astype(np.int64)
        elif k in (sb.REWARDS, sb.LOGP):
            arr = arr.astype(np.float32)
        elif k == sb.DONES:
            arr = arr.astype(bool)
        elif arr.dtype == np.float64:
            arr = arr.astype(np.float32)
        out[k] = arr
    return out


def write_batches(path: str, batches: List[Dict[str, np.ndarray]],
                  format: str = "json") -> List[str]:
    """Persist sample batches under `path` via the Data layer."""
    import ray_tpu.data as rdata

    rows: List[Dict[str, Any]] = []
    for b in batches:
        rows.extend(batch_to_rows(b))
    ds = rdata.from_items(rows)
    os.makedirs(path, exist_ok=True)
    if format == "parquet":
        return ds.write_parquet(path)
    return ds.write_json(path)


def read_batches(path: str, format: str = "json"):
    """Load an experience dataset written by `write_batches` as a
    `ray_tpu.data.Dataset` of rows (compose transforms freely). Uses the
    standard read_* path expansion, filtered to this format's extension so
    a directory holding both formats (or sidecar files) reads cleanly."""
    import ray_tpu.data as rdata
    from ray_tpu.data.datasource import expand_paths

    ext = ".parquet" if format == "parquet" else ".json"
    paths = [p for p in expand_paths(path) if p.endswith(ext)] \
        if os.path.isdir(path) else path
    if format == "parquet":
        return rdata.read_parquet(paths)
    return rdata.read_json(paths)


def iter_learner_batches(ds, batch_size: int = 256,
                         seed: int = 0) -> Iterator[Dict[str, np.ndarray]]:
    """Shuffled columnar minibatches from an experience Dataset. The ragged
    tail (and a dataset smaller than batch_size) is yielded too — silently
    training on nothing would be worse than one odd-shaped batch."""
    rows = ds.take_all()
    rng = np.random.default_rng(seed)
    order = rng.permutation(len(rows))
    for s in range(0, len(rows), batch_size):
        chunk = [rows[i] for i in order[s:s + batch_size]]
        if chunk:
            yield rows_to_batch(chunk)


# --------------------------------------------------------------------------- #
# BC: the smallest offline algorithm (reference rllib/algorithms/bc)
# --------------------------------------------------------------------------- #


@dataclass
class BCConfig:
    obs_dim: int = 0
    n_actions: int = 0
    obs_shape: tuple = ()
    hidden: tuple = (64, 64)
    lr: float = 1e-3
    grad_clip: float = 10.0
    seed: int = 0


class BCLearner(Learner):
    """Negative log-likelihood of the logged actions."""

    def compute_loss(self, params, batch):
        import jax.numpy as jnp

        out = self.module.forward_train(params, batch)
        loss = -jnp.mean(out["logp"])
        return loss, {"nll": loss,
                      "entropy": jnp.mean(out["entropy"])}


class BC:
    """Behavior cloning from an experience dataset (no env needed)."""

    def __init__(self, config: BCConfig):
        self.config = config
        spec = SpecDict(config.obs_dim, config.n_actions,
                        tuple(config.obs_shape))
        self.module = build_module(spec, hidden=config.hidden)
        self.learner = BCLearner(self.module, config, seed=config.seed)
        self.iteration = 0

    def train_on_dataset(self, ds, *, epochs: int = 1,
                         batch_size: int = 256) -> Dict[str, float]:
        metrics: Dict[str, float] = {}
        for ep in range(epochs):
            for batch in iter_learner_batches(ds, batch_size,
                                              seed=self.config.seed + ep):
                metrics = self.learner.update(
                    {sb.OBS: batch[sb.OBS], sb.ACTIONS: batch[sb.ACTIONS]})
            self.iteration += 1
        return metrics

    def get_policy_weights(self):
        return self.learner.get_weights()
