"""PPO on the new stack: config -> algorithm -> learner loss.

Equivalent of the reference's `rllib/algorithms/ppo/ppo.py:368,394`
(`PPOConfig`, `PPO.training_step`) and the clip-surrogate loss of
`ppo_torch_policy.py`, on the jitted JAX Learner: sample via WorkerSet,
GAE + standardized advantages, minibatch SGD epochs on the learner (the
XLA-compiled hot loop), then weight broadcast back to workers.
"""

from __future__ import annotations

import logging
import time
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional

import numpy as np

from ray_tpu.rllib import sample_batch as sb
from ray_tpu.rllib.learner import Learner, LearnerGroup
from ray_tpu.rllib.rl_module import build_module_from_env_spec
from ray_tpu.rllib.rollout import WorkerSet

logger = logging.getLogger(__name__)


@dataclass
class PPOConfig:
    env: Any = "CartPole-v1"
    num_rollout_workers: int = 2
    num_envs_per_worker: int = 8
    rollout_fragment_length: int = 64
    train_batch_size: int = 0          # 0 = workers * envs * fragment
    sgd_minibatch_size: int = 256
    num_sgd_iter: int = 8
    lr: float = 3e-4
    gamma: float = 0.99
    lambda_: float = 0.95
    clip_param: float = 0.2
    vf_clip_param: float = 10.0
    vf_loss_coeff: float = 0.5
    entropy_coeff: float = 0.01
    kl_target: float = 0.2
    grad_clip: float = 0.5
    hidden: tuple = (64, 64)
    seed: int = 0
    learner_mode: str = "local"        # local | remote
    num_learners: int = 1              # dp-sharded update (see LearnerGroup)
    learner_resources: Optional[Dict[str, float]] = None
    num_cpus_per_worker: float = 0.4
    # Pin sampler processes to a jax platform ("cpu" keeps the chip free
    # for the learner); None inherits the ambient platform.
    rollout_platform: Optional[str] = "cpu"
    # Observation connector pipeline (reference agent connectors); Atari
    # ids get GrayscaleResize+FrameStack automatically via make_env.
    connectors: Any = None

    # Fluent API parity with the reference's AlgorithmConfig builder.
    def environment(self, env) -> "PPOConfig":
        self.env = env
        return self

    def rollouts(self, *, num_rollout_workers: Optional[int] = None,
                 num_envs_per_worker: Optional[int] = None,
                 rollout_fragment_length: Optional[int] = None) -> "PPOConfig":
        if num_rollout_workers is not None:
            self.num_rollout_workers = num_rollout_workers
        if num_envs_per_worker is not None:
            self.num_envs_per_worker = num_envs_per_worker
        if rollout_fragment_length is not None:
            self.rollout_fragment_length = rollout_fragment_length
        return self

    def training(self, **kwargs) -> "PPOConfig":
        for k, v in kwargs.items():
            key = "lambda_" if k == "lambda" else k
            if not hasattr(self, key):
                raise ValueError(f"unknown PPO option {k}")
            setattr(self, key, v)
        return self

    def build(self) -> "PPO":
        return PPO(self)


class PPOLearner(Learner):
    def compute_loss(self, params, batch):
        import jax.numpy as jnp

        cfg = self.config
        out = self.module.forward_train(params, batch)
        logp_ratio = jnp.exp(out["logp"] - batch[sb.LOGP])
        advantages = batch[sb.ADVANTAGES]
        surrogate = jnp.minimum(
            advantages * logp_ratio,
            advantages * jnp.clip(logp_ratio, 1 - cfg.clip_param,
                                  1 + cfg.clip_param))
        policy_loss = -jnp.mean(surrogate)
        # Clipped value loss (reference ppo_torch_policy vf_clip_param).
        vf = out["vf"]
        vf_old = batch[sb.VF_PREDS]
        vf_clipped = vf_old + jnp.clip(vf - vf_old, -cfg.vf_clip_param,
                                       cfg.vf_clip_param)
        vf_loss = jnp.mean(jnp.maximum(
            (vf - batch[sb.VALUE_TARGETS]) ** 2,
            (vf_clipped - batch[sb.VALUE_TARGETS]) ** 2))
        entropy = jnp.mean(out["entropy"])
        kl = jnp.mean(batch[sb.LOGP] - out["logp"])
        loss = policy_loss + cfg.vf_loss_coeff * vf_loss \
            - cfg.entropy_coeff * entropy
        return loss, {"policy_loss": policy_loss, "vf_loss": vf_loss,
                      "entropy": entropy, "kl": kl}


class PPO:
    """The Algorithm: train() runs one iteration (reference
    `Algorithm.train` -> `PPO.training_step`)."""

    def __init__(self, config: PPOConfig):
        self.config = config
        if config.train_batch_size:
            # Derive the per-worker fragment so one sampling round yields
            # the configured train batch (reference train_batch_size).
            per_step = config.num_rollout_workers * config.num_envs_per_worker
            config.rollout_fragment_length = max(
                1, config.train_batch_size // per_step)
        self.workers = WorkerSet(
            config.env, num_workers=config.num_rollout_workers,
            n_envs=config.num_envs_per_worker, hidden=config.hidden,
            seed=config.seed,
            num_cpus_per_worker=config.num_cpus_per_worker,
            jax_platform=config.rollout_platform,
            connectors=config.connectors)
        module = build_module_from_env_spec(self.workers.env_spec(),
                                            hidden=config.hidden)
        self.learner_group = LearnerGroup(
            lambda **kw: PPOLearner(module, config, seed=config.seed, **kw),
            mode=config.learner_mode,
            resources=config.learner_resources,
            num_learners=config.num_learners)
        self.workers.sync_weights(self.learner_group.get_weights())
        self.iteration = 0
        self._timesteps = 0
        self._rng = np.random.default_rng(config.seed)

    # ------------------------------------------------------------- training

    def training_step(self) -> Dict[str, Any]:
        cfg = self.config
        t0 = time.perf_counter()
        raw_batches = self.workers.sample(cfg.rollout_fragment_length)
        sample_s = time.perf_counter() - t0

        processed = [self._postprocess(b) for b in raw_batches]
        batch = sb.concat_batches(processed)
        batch[sb.ADVANTAGES] = sb.standardize(batch[sb.ADVANTAGES])
        self._timesteps += sb.batch_size(batch)

        t1 = time.perf_counter()
        metrics: Dict[str, float] = {}
        sgd_steps = 0
        for _ in range(cfg.num_sgd_iter):
            shuffled = sb.shuffle_batch(batch, self._rng)
            stacked, remainder = sb.stack_minibatches(
                self._learner_view(shuffled), cfg.sgd_minibatch_size)
            if stacked:
                # Whole epoch in one device dispatch (scan over
                # minibatches) — the per-minibatch Python loop costs one
                # host->chip round trip per step.
                m = self.learner_group.update_many(stacked)
                if m:
                    metrics = m
                    sgd_steps += len(next(iter(stacked.values())))
            if remainder and sb.batch_size(remainder) >= 2:
                # The ragged tail trains too (one ordinary update; may be
                # a no-op {} if dp trimming leaves nothing).
                m = self.learner_group.update(remainder)
                if m:
                    metrics = m
                    sgd_steps += 1
            if not sgd_steps:
                break
            if metrics.get("kl", 0.0) > cfg.kl_target:
                break  # early stop like the reference's KL guard
        learn_s = time.perf_counter() - t1
        self.workers.sync_weights(self.learner_group.get_weights())
        return {"sample_s": sample_s, "learn_s": learn_s,
                "sgd_steps": sgd_steps, **metrics}

    @staticmethod
    def _learner_view(mb: Dict[str, np.ndarray]) -> Dict[str, np.ndarray]:
        return {k: v for k, v in mb.items()
                if not k.startswith("_") and k not in (sb.DONES, sb.TRUNCATEDS,
                                                       sb.REWARDS)}

    def _postprocess(self, batch: Dict[str, np.ndarray]
                     ) -> Dict[str, np.ndarray]:
        cfg = self.config
        T, n = batch.pop("_shape")
        batch.pop("_last_obs", None)       # IMPALA-only bootstrap obs
        batch.pop("_final_obs", None)      # DQN-only truncation bootstrap
        batch.pop("_final_obs_at", None)   # (optional keys would break
        #                                    concat_batches' key union)
        rewards = batch[sb.REWARDS].reshape(T, n)
        values = batch[sb.VF_PREDS].reshape(T, n)
        dones = batch[sb.DONES].reshape(T, n)
        truncs = batch[sb.TRUNCATEDS].reshape(T, n)
        next_values = batch.pop("_next_vf").reshape(T, n)
        adv, targets = sb.compute_gae(rewards, values, dones, truncs,
                                      next_values, gamma=cfg.gamma,
                                      lam=cfg.lambda_)
        batch[sb.ADVANTAGES] = adv.reshape(-1)
        batch[sb.VALUE_TARGETS] = targets.reshape(-1)
        return batch

    def train(self) -> Dict[str, Any]:
        self.iteration += 1
        step_metrics = self.training_step()
        stats = self.workers.episode_stats()
        rewards = [s["episode_reward_mean"] for s in stats
                   if s["episode_reward_mean"] is not None]
        lens = [s["episode_len_mean"] for s in stats
                if s["episode_len_mean"] is not None]
        return {
            "training_iteration": self.iteration,
            "timesteps_total": self._timesteps,
            "episode_reward_mean": float(np.mean(rewards)) if rewards else None,
            "episode_len_mean": float(np.mean(lens)) if lens else None,
            **step_metrics,
        }

    # --------------------------------------------------------- checkpointing

    def save(self, path: str) -> str:
        import os
        import pickle

        os.makedirs(path, exist_ok=True)
        with open(os.path.join(path, "algorithm.pkl"), "wb") as f:
            pickle.dump({"learner": self.learner_group.get_state(),
                         "iteration": self.iteration,
                         "timesteps": self._timesteps}, f)
        return path

    def restore(self, path: str):
        import os
        import pickle

        with open(os.path.join(path, "algorithm.pkl"), "rb") as f:
            state = pickle.load(f)
        self.learner_group.set_state(state["learner"])
        self.iteration = state["iteration"]
        self._timesteps = state["timesteps"]
        self.workers.sync_weights(self.learner_group.get_weights())

    def get_weights(self):
        return self.learner_group.get_weights()

    def stop(self):
        self.workers.shutdown()
        self.learner_group.shutdown()

    # ------------------------------------------------------- Tune trainable

    @staticmethod
    def as_trainable(base_config: PPOConfig) -> Callable:
        def trainable(config: Dict[str, Any]):
            import copy

            from ray_tpu import tune

            cfg = copy.deepcopy(base_config)
            for k, v in (config or {}).items():
                key = "lambda_" if k == "lambda" else k
                if hasattr(cfg, key):
                    setattr(cfg, key, v)
            algo = PPO(cfg)
            try:
                while True:
                    tune.report(algo.train())
            finally:
                algo.stop()

        trainable.__name__ = "PPO"
        return trainable
