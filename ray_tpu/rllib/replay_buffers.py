"""Replay buffers: uniform ring and proportional prioritized.

Equivalent of the reference's `rllib/utils/replay_buffers/` (ReplayBuffer,
PrioritizedReplayBuffer with a segment tree). Redesigned columnar: one
preallocated numpy ring per sample-batch field, so `sample(n)` is a single
fancy-index gather per field (the batch goes straight to `jax.device_put`
with no per-transition Python work), and priorities live in a flat float64
array sampled with `numpy.random.Generator.choice` — O(n) per draw at the
buffer sizes a single host feeds a chip with, without the segment-tree
bookkeeping.
"""

from __future__ import annotations

from typing import Any, Dict, Optional

import numpy as np


class ReplayBuffer:
    """Uniform FIFO ring over columnar transition storage."""

    def __init__(self, capacity: int, seed: int = 0):
        self.capacity = int(capacity)
        self._cols: Dict[str, np.ndarray] = {}
        self._next = 0
        self._size = 0
        self._rng = np.random.default_rng(seed)

    def __len__(self) -> int:
        return self._size

    def add(self, batch: Dict[str, np.ndarray]) -> None:
        """Append a batch of transitions (dict of [N, ...] arrays)."""
        fields = {k: np.asarray(v) for k, v in batch.items()
                  if not k.startswith("_")}
        n = len(next(iter(fields.values())))
        if not self._cols:
            for k, v in fields.items():
                self._cols[k] = np.zeros((self.capacity,) + v.shape[1:],
                                         v.dtype)
        if n >= self.capacity:  # keep the newest `capacity` rows
            for k, v in fields.items():
                self._cols[k][:] = v[-self.capacity:]
            self._next, self._size = 0, self.capacity
            self._on_added(np.arange(self.capacity))
            return
        idx = (self._next + np.arange(n)) % self.capacity
        for k, v in fields.items():
            self._cols[k][idx] = v
        self._on_added(idx)
        self._next = (self._next + n) % self.capacity
        self._size = min(self._size + n, self.capacity)

    def _on_added(self, idx: np.ndarray) -> None:
        pass

    def sample(self, n: int) -> Dict[str, np.ndarray]:
        idx = self._rng.integers(0, self._size, size=n)
        return self._gather(idx)

    def _gather(self, idx: np.ndarray) -> Dict[str, np.ndarray]:
        out = {k: v[idx] for k, v in self._cols.items()}
        out["_batch_indices"] = idx
        return out

    def state(self) -> Dict[str, Any]:
        return {"cols": {k: v.copy() for k, v in self._cols.items()},
                "next": self._next, "size": self._size}

    def set_state(self, state: Dict[str, Any]) -> None:
        self._cols = {k: v.copy() for k, v in state["cols"].items()}
        self._next = int(state["next"])
        self._size = int(state["size"])


class PrioritizedReplayBuffer(ReplayBuffer):
    """Proportional prioritization (reference
    `replay_buffers/prioritized_replay_buffer.py`): P(i) ∝ p_i^alpha, with
    importance weights w_i = (N * P(i))^-beta / max w.
    """

    def __init__(self, capacity: int, alpha: float = 0.6,
                 beta: float = 0.4, eps: float = 1e-6, seed: int = 0):
        super().__init__(capacity, seed=seed)
        self.alpha = float(alpha)
        self.beta = float(beta)
        self.eps = float(eps)
        self._prios = np.zeros(self.capacity, np.float64)
        self._max_prio = 1.0

    def _on_added(self, idx: np.ndarray) -> None:
        # New transitions get max priority so each is trained at least once.
        self._prios[idx] = self._max_prio

    def sample(self, n: int, beta: Optional[float] = None
               ) -> Dict[str, np.ndarray]:
        beta = self.beta if beta is None else beta
        p = self._prios[:self._size] ** self.alpha
        probs = p / p.sum()
        idx = self._rng.choice(self._size, size=n, p=probs)
        out = self._gather(idx)
        w = (self._size * probs[idx]) ** (-beta)
        out["weights"] = (w / w.max()).astype(np.float32)
        return out

    def update_priorities(self, idx: np.ndarray,
                          priorities: np.ndarray) -> None:
        pr = np.abs(np.asarray(priorities, np.float64)) + self.eps
        self._prios[np.asarray(idx)] = pr
        self._max_prio = max(self._max_prio, float(pr.max()))

    def state(self) -> Dict[str, Any]:
        out = super().state()
        out["prios"] = self._prios.copy()
        out["max_prio"] = self._max_prio
        return out

    def set_state(self, state: Dict[str, Any]) -> None:
        super().set_state(state)
        self._prios = state["prios"].copy()
        self._max_prio = float(state["max_prio"])
