"""RLModule: the neural-network abstraction of the new RLlib stack, in flax.

Equivalent of the reference's `RLModule.forward_{inference,exploration,train}`
(`rllib/core/rl_module/rl_module.py:215,383-427`) — redesigned functionally:
a module owns its flax model and exposes pure functions over an explicit
params pytree, so the Learner can jit/grad them and rollout workers can run
them with synced host params.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Dict, Sequence, Tuple

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np


@dataclass
class SpecDict:
    obs_dim: int
    n_actions: int
    # Image modules need the full shape ([H, W] or [H, W, C]); flat modules
    # derive it from obs_dim.
    obs_shape: Tuple[int, ...] = ()

    def shape(self) -> Tuple[int, ...]:
        return tuple(self.obs_shape) if self.obs_shape else (self.obs_dim,)


class _PolicyValueNet(nn.Module):
    """Shared torso -> (logits, value) heads (reference Catalog's default
    fcnet encoder + pi/vf heads)."""

    hidden: Sequence[int]
    n_actions: int

    @nn.compact
    def __call__(self, obs):
        x = obs.astype(jnp.float32)
        for i, width in enumerate(self.hidden):
            x = nn.Dense(width, name=f"torso_{i}",
                         kernel_init=nn.initializers.orthogonal(np.sqrt(2)))(x)
            x = nn.tanh(x)
        logits = nn.Dense(self.n_actions, name="pi",
                          kernel_init=nn.initializers.orthogonal(0.01))(x)
        value = nn.Dense(1, name="vf",
                         kernel_init=nn.initializers.orthogonal(1.0))(x)
        return logits, value[..., 0]


class _ConvPolicyValueNet(nn.Module):
    """Nature-CNN torso -> (logits, value) heads for image observations.

    TPU-first: observations arrive uint8 (4x less sample-batch bandwidth
    than float32) and are normalized to [0, 1] on-device; convolutions are
    NHWC, the layout XLA tiles best on the MXU.
    """

    n_actions: int
    channels: Sequence[int] = (32, 64, 64)
    kernels: Sequence[int] = (8, 4, 3)
    strides: Sequence[int] = (4, 2, 1)
    dense: int = 512

    @nn.compact
    def __call__(self, obs):
        x = obs.astype(jnp.float32)
        if jnp.issubdtype(obs.dtype, jnp.integer):
            x = x / 255.0  # uint8 pixels; float envs are already scaled
        if x.ndim == 3:  # [B, H, W] -> single channel
            x = x[..., None]
        for i, (c, k, s) in enumerate(zip(self.channels, self.kernels,
                                          self.strides)):
            x = nn.Conv(c, (k, k), strides=(s, s), padding="VALID",
                        name=f"conv_{i}",
                        kernel_init=nn.initializers.orthogonal(np.sqrt(2)))(x)
            x = nn.relu(x)
        x = x.reshape(x.shape[0], -1)
        x = nn.Dense(self.dense, name="torso",
                     kernel_init=nn.initializers.orthogonal(np.sqrt(2)))(x)
        x = nn.relu(x)
        logits = nn.Dense(self.n_actions, name="pi",
                          kernel_init=nn.initializers.orthogonal(0.01))(x)
        value = nn.Dense(1, name="vf",
                         kernel_init=nn.initializers.orthogonal(1.0))(x)
        return logits, value[..., 0]


def conv_spec_for(height: int) -> Dict[str, Any]:
    """Conv-stack sizing shared by every vision module (PPO's
    ConvPolicyModule, DQN's QModule): nature-DQN filters need >= 40 px
    frames; smaller synthetic envs get a shallower stack."""
    if height >= 40:
        return dict(channels=(32, 64, 64), kernels=(8, 4, 3),
                    strides=(4, 2, 1))
    return dict(channels=(16, 32), kernels=(4, 3), strides=(2, 1))


class RLModule:
    """Base class; subclasses define the flax model + forward semantics."""

    def init_params(self, rng) -> Any:
        raise NotImplementedError

    def forward_train(self, params, batch: Dict[str, Any]) -> Dict[str, Any]:
        raise NotImplementedError

    def forward_exploration(self, params, obs, rng) -> Dict[str, Any]:
        raise NotImplementedError

    def forward_inference(self, params, obs) -> Dict[str, Any]:
        raise NotImplementedError


class DiscretePolicyModule(RLModule):
    """Categorical-action policy+value module (PPO's default)."""

    def __init__(self, spec: SpecDict, hidden: Sequence[int] = (64, 64)):
        self.spec = spec
        self.model = _PolicyValueNet(hidden=tuple(hidden),
                                     n_actions=spec.n_actions)
        self._sample = jax.jit(self._sample_impl)
        self._greedy = jax.jit(self._greedy_impl)

    def init_params(self, rng) -> Any:
        obs = jnp.zeros((1, self.spec.obs_dim), jnp.float32)
        return self.model.init(rng, obs)

    # -- pure functions (jit-safe) -------------------------------------------

    def forward_train(self, params, batch):
        logits, value = self.model.apply(params, batch["obs"])
        logp_all = jax.nn.log_softmax(logits)
        logp = jnp.take_along_axis(
            logp_all, batch["actions"][..., None].astype(jnp.int32), axis=-1
        )[..., 0]
        entropy = -jnp.sum(jnp.exp(logp_all) * logp_all, axis=-1)
        return {"logits": logits, "vf": value, "logp": logp,
                "entropy": entropy}

    def _sample_impl(self, params, obs, rng):
        logits, value = self.model.apply(params, obs)
        actions = jax.random.categorical(rng, logits)
        logp = jnp.take_along_axis(jax.nn.log_softmax(logits),
                                   actions[..., None], axis=-1)[..., 0]
        return actions, logp, value

    def _greedy_impl(self, params, obs):
        logits, value = self.model.apply(params, obs)
        return jnp.argmax(logits, axis=-1), value

    # -- convenience wrappers -------------------------------------------------

    def forward_exploration(self, params, obs, rng):
        actions, logp, value = self._sample(params, obs, rng)
        return {"actions": actions, "logp": logp, "vf": value}

    def forward_inference(self, params, obs):
        actions, value = self._greedy(params, obs)
        return {"actions": actions, "vf": value}

    def get_state(self, params) -> Any:
        return jax.device_get(params)

    def __reduce__(self):
        return (DiscretePolicyModule, (self.spec, tuple(self.model.hidden)))


class ConvPolicyModule(DiscretePolicyModule):
    """CNN policy+value module for image observations (the Atari module —
    reference Catalog's vision encoder path).

    Architecture auto-sizes to the input: nature-DQN filters for >= 40 px
    frames, a shallower stack for small synthetic envs.
    """

    def __init__(self, spec: SpecDict, dense: int = 512):
        self.spec = spec
        self.dense = dense
        if len(spec.shape()) not in (2, 3):
            raise ValueError(
                f"ConvPolicyModule needs [H, W] or [H, W, C] observations, "
                f"got shape {spec.shape()} — a color env plus FrameStack "
                f"yields rank 4; add GrayscaleResize before the stack")
        self.model = _ConvPolicyValueNet(n_actions=spec.n_actions,
                                         dense=dense,
                                         **conv_spec_for(spec.shape()[0]))
        self._sample = jax.jit(self._sample_impl)
        self._greedy = jax.jit(self._greedy_impl)

    def init_params(self, rng) -> Any:
        obs = jnp.zeros((1,) + self.spec.shape(), jnp.uint8)
        return self.model.init(rng, obs)

    def __reduce__(self):
        return (ConvPolicyModule, (self.spec, self.dense))


def build_module(spec: SpecDict, hidden: Sequence[int] = (64, 64)) -> RLModule:
    """Default module for an env spec: CNN for image observations (rank >=
    2), MLP otherwise (reference Catalog dispatch)."""
    if len(spec.shape()) >= 2:
        return ConvPolicyModule(spec)
    return DiscretePolicyModule(spec, hidden=hidden)


def build_module_from_env_spec(env_spec: Dict[str, Any],
                               hidden: Sequence[int] = (64, 64)) -> RLModule:
    """From a RolloutWorker.env_spec() dict — the single place algorithms
    construct their learner module, so it can never drift from the module
    the rollout workers build."""
    return build_module(
        SpecDict(env_spec["obs_dim"], env_spec["n_actions"],
                 tuple(env_spec.get("obs_shape", ()))),
        hidden=hidden)
