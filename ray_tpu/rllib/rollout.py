"""RolloutWorker / WorkerSet: the sampling half of the algorithm.

Equivalent of the reference's `RolloutWorker.sample`
(`rllib/evaluation/rollout_worker.py:166,879`) + `WorkerSet`
(`worker_set.py:79`, `sync_weights` :384): each worker steps a vectorized
env with the exploration policy, records [T, n_envs] trajectories, computes
per-step next-state values for GAE bootstrapping, and returns a flat
SampleBatch. Workers run as actors; sampling fans out with one task each.
"""

from __future__ import annotations

import logging
from typing import Any, Callable, Dict, List, Optional

import numpy as np

from ray_tpu.rllib import sample_batch as sb
from ray_tpu.rllib.env import make_env
from ray_tpu.rllib.rl_module import build_module_from_env_spec

logger = logging.getLogger(__name__)


class RolloutWorker:
    """Stateful sampler: keeps env state between sample() calls so rollout
    fragments stitch into full episodes across iterations."""

    def __init__(self, env: Any, n_envs: int = 8, seed: int = 0,
                 hidden=(64, 64), module: Optional[Any] = None,
                 jax_platform: Optional[str] = None, connectors: Any = None):
        import os

        from ray_tpu._jax_env import apply_jax_platform_env

        if jax_platform:
            # Samplers are tiny MLP forwards: pin them to host CPU so the
            # chip belongs to the learner (one JAX process per chip —
            # SURVEY.md §7 TPU process model).
            os.environ["RAY_TPU_JAX_PLATFORM"] = jax_platform
        apply_jax_platform_env()
        import jax

        self.env = make_env(env, n_envs=n_envs, seed=seed,
                            connectors=connectors)
        self.module = module or build_module_from_env_spec(
            self.env_spec(), hidden=hidden)
        self.params = self.module.init_params(jax.random.PRNGKey(seed))
        self._rng = jax.random.PRNGKey(seed + 1000)
        self._obs = self.env.reset()
        # Episode-return tracking (for episode_reward_mean).
        self._ep_returns = np.zeros(self.env.n_envs, dtype=np.float64)
        self._ep_lens = np.zeros(self.env.n_envs, dtype=np.int64)
        self._completed: List[float] = []
        self._completed_lens: List[int] = []

    def set_weights(self, weights: Any):
        self.params = weights

    def env_spec(self) -> Dict[str, Any]:
        return {"obs_dim": self.env.obs_dim, "n_actions": self.env.n_actions,
                "n_envs": self.env.n_envs,
                "obs_shape": tuple(self.env.obs_shape)}

    def sample(self, num_steps: int) -> Dict[str, np.ndarray]:
        """Collect `num_steps` env steps (x n_envs transitions), flattened."""
        import jax

        n = self.env.n_envs
        obs_buf = np.empty((num_steps, n) + tuple(self.env.obs_shape),
                           dtype=self.env.obs_dtype)
        act_buf = np.empty((num_steps, n), dtype=np.int64)
        rew_buf = np.empty((num_steps, n), dtype=np.float32)
        done_buf = np.empty((num_steps, n), dtype=bool)
        trunc_buf = np.empty((num_steps, n), dtype=bool)
        logp_buf = np.empty((num_steps, n), dtype=np.float32)
        vf_buf = np.empty((num_steps, n), dtype=np.float32)
        next_vf_buf = np.empty((num_steps, n), dtype=np.float32)

        obs = self._obs
        final_obs_fixups: List = []  # (t, rows, final_obs[rows])
        for t in range(num_steps):
            self._rng, key = jax.random.split(self._rng)
            out = self.module.forward_exploration(self.params, obs, key)
            # The env needs host actions every step — this sync IS the
            # rollout contract; one device_get moves the whole step
            # output in a single transfer instead of three round-trips.
            host = jax.device_get(out)  # raylint: disable=RL021 — per-step sync is the env-step contract
            actions = host["actions"]
            next_obs, rewards, dones, infos = self.env.step(actions)
            obs_buf[t] = obs
            act_buf[t] = actions
            rew_buf[t] = rewards
            done_buf[t] = dones
            trunc_buf[t] = infos.get("truncated", np.zeros(n, dtype=bool))
            logp_buf[t] = host["logp"]
            vf_buf[t] = host["vf"]
            self._ep_returns += rewards
            self._ep_lens += 1
            done_rows = np.nonzero(dones)[0]
            if done_rows.size:
                # Auto-reset replaces the episode's true final obs with the
                # new episode's first obs; keep the real one so truncation
                # bootstraps V(final), not V(reset) (reference stores the
                # final obs the same way).
                fo = infos.get("final_obs")
                if fo is not None:
                    final_obs_fixups.append(
                        (t, done_rows, np.asarray(fo)[done_rows]))
                for i in done_rows:
                    self._completed.append(float(self._ep_returns[i]))
                    self._completed_lens.append(int(self._ep_lens[i]))
                    self._ep_returns[i] = 0.0
                    self._ep_lens[i] = 0
            obs = next_obs
        self._obs = obs

        # One batched value pass for all next-state values: V(s_{t+1}) is
        # V(s_t) shifted, with the tail row evaluated on the final obs.
        next_vf_buf[:-1] = vf_buf[1:]
        tail = self.module.forward_inference(self.params, obs)
        next_vf_buf[-1] = np.asarray(tail["vf"])
        # Patch done rows with V(true final obs): one padded batched
        # forward over every done row in the fragment (padding to a power
        # of two bounds the number of distinct jit shapes).
        if final_obs_fixups:
            all_fo = np.concatenate([f[2] for f in final_obs_fixups])
            k = len(all_fo)
            padded_k = 1
            while padded_k < k:
                padded_k *= 2
            padded = np.zeros((padded_k,) + all_fo.shape[1:], all_fo.dtype)
            padded[:k] = all_fo
            vals = np.asarray(self.module.forward_inference(
                self.params, padded)["vf"])[:k]
            pos = 0
            for t, rows, _ in final_obs_fixups:
                next_vf_buf[t, rows] = vals[pos: pos + rows.size]
                pos += rows.size

        batch = {
            sb.OBS: obs_buf.reshape(
                (num_steps * n,) + tuple(self.env.obs_shape)),
            # Tail observation: lets an off-policy learner (IMPALA) compute
            # its own bootstrap V(x_{T}) with current params.
            "_last_obs": np.asarray(obs, dtype=self.env.obs_dtype),
            sb.ACTIONS: act_buf.reshape(-1),
            sb.REWARDS: rew_buf.reshape(-1),
            sb.DONES: done_buf.reshape(-1),
            sb.TRUNCATEDS: trunc_buf.reshape(-1),
            sb.LOGP: logp_buf.reshape(-1),
            sb.VF_PREDS: vf_buf.reshape(-1),
            "_next_vf": next_vf_buf.reshape(-1),
            "_shape": np.array([num_steps, n]),
        }
        if final_obs_fixups:
            # True final observations for done rows (flat [T*n] indices):
            # off-policy learners bootstrap truncated episodes from the
            # real final state instead of the auto-reset observation.
            batch["_final_obs_at"] = np.concatenate(
                [t * n + rows for t, rows, _ in final_obs_fixups])
            batch["_final_obs"] = np.concatenate(
                [fo for _, _, fo in final_obs_fixups])
        return batch

    def episode_stats(self, clear: bool = True) -> Dict[str, Any]:
        stats = {
            "episodes": len(self._completed),
            "episode_reward_mean": float(np.mean(self._completed))
            if self._completed else None,
            "episode_len_mean": float(np.mean(self._completed_lens))
            if self._completed_lens else None,
        }
        if clear:
            self._completed = self._completed[-100:]
            self._completed_lens = self._completed_lens[-100:]
        return stats


class WorkerSet:
    """N rollout-worker actors + weight broadcast (reference worker_set.py)."""

    def __init__(self, env: Any, num_workers: int = 2, n_envs: int = 8,
                 hidden=(64, 64), seed: int = 0,
                 num_cpus_per_worker: float = 0.5,
                 jax_platform: Optional[str] = None,
                 connectors: Any = None, module: Optional[Any] = None):
        import ray_tpu

        self._ctor = dict(env=env, n_envs=n_envs, hidden=tuple(hidden),
                          jax_platform=jax_platform, seed=seed,
                          num_cpus=num_cpus_per_worker,
                          connectors=connectors, module=module)
        actor_cls = ray_tpu.remote(RolloutWorker)
        self.workers = [
            actor_cls.options(num_cpus=num_cpus_per_worker).remote(
                env, n_envs=n_envs, seed=seed + i, hidden=tuple(hidden),
                jax_platform=jax_platform, connectors=connectors,
                module=module)
            for i in range(num_workers)]
        self.num_workers = num_workers
        self._last_weights_ref = None  # re-sync replacements (see sample)

    def restart_worker(self, idx: int):
        """Replace a dead worker actor in place (fault tolerance —
        reference `FaultTolerantActorManager`)."""
        import ray_tpu

        c = self._ctor
        try:
            ray_tpu.kill(self.workers[idx])
        except Exception:  # noqa: BLE001 — already gone
            pass
        actor_cls = ray_tpu.remote(RolloutWorker)
        self.workers[idx] = actor_cls.options(
            num_cpus=c["num_cpus"]).remote(
            c["env"], n_envs=c["n_envs"], seed=c["seed"] + idx,
            hidden=c["hidden"], jax_platform=c["jax_platform"],
            connectors=c["connectors"], module=c["module"])
        return self.workers[idx]

    def sync_weights(self, weights: Any):
        import ray_tpu

        ref = ray_tpu.put(weights)
        self._last_weights_ref = ref
        refs = [w.set_weights.remote(ref) for w in self.workers]  # fan out
        for r in refs:
            try:
                ray_tpu.get(r)
            except Exception:  # noqa: BLE001 — dead worker: the next
                # sample() replaces it and the following broadcast re-syncs
                # its weights; don't die mid-broadcast.
                logger.warning("sync_weights: a rollout worker is dead")

    def sample(self, steps_per_worker: int) -> List[Dict[str, np.ndarray]]:
        """Fan out one sample task per worker. A dead worker is replaced in
        place and its fragment re-collected from the replacement (reference
        FaultTolerantActorManager) — PPO/DQN iterations survive worker loss
        without their own fault logic."""
        import ray_tpu

        refs = [w.sample.remote(steps_per_worker) for w in self.workers]
        out = []
        for i, r in enumerate(refs):
            try:
                out.append(ray_tpu.get(r))
            except Exception:  # noqa: BLE001 — dead worker
                logger.warning("sample: restarting dead rollout worker %d", i)
                w = self.restart_worker(i)
                if self._last_weights_ref is not None:
                    # The replacement initialized random weights; re-sync
                    # the last broadcast before sampling so its fragment
                    # is on-policy.
                    ray_tpu.get(w.set_weights.remote(self._last_weights_ref))
                out.append(ray_tpu.get(w.sample.remote(steps_per_worker)))
        return out

    def episode_stats(self) -> List[Dict[str, Any]]:
        import ray_tpu

        refs = [w.episode_stats.remote() for w in self.workers]  # fan out
        out = []
        for r in refs:
            try:
                out.append(ray_tpu.get(r))
            except Exception:  # noqa: BLE001 — dead worker: stats are
                # advisory; its replacement reports next iteration.
                pass
        return out

    def env_spec(self) -> Dict[str, int]:
        import ray_tpu

        return ray_tpu.get(self.workers[0].env_spec.remote())

    def shutdown(self):
        import ray_tpu

        for w in self.workers:
            try:
                ray_tpu.kill(w)
            except Exception:
                pass
