"""SampleBatch + advantage estimation.

Equivalent of the reference's `rllib/policy/sample_batch.py` and the GAE
postprocessing in `rllib/evaluation/postprocessing.py:compute_advantages`.
Batches are plain dict[str, np.ndarray]; GAE runs vectorized over the
[T, n_envs] rollout layout before flattening for SGD.
"""

from __future__ import annotations

from typing import Dict, List

import numpy as np

OBS = "obs"
ACTIONS = "actions"
REWARDS = "rewards"
DONES = "dones"
TRUNCATEDS = "truncateds"
LOGP = "logp"
VF_PREDS = "vf_preds"
ADVANTAGES = "advantages"
VALUE_TARGETS = "value_targets"


def concat_batches(batches: List[Dict[str, np.ndarray]]
                   ) -> Dict[str, np.ndarray]:
    keys = batches[0].keys()
    return {k: np.concatenate([b[k] for b in batches]) for k in keys}


def batch_size(batch: Dict[str, np.ndarray]) -> int:
    return len(next(iter(batch.values())))


def shuffle_batch(batch: Dict[str, np.ndarray], rng: np.random.Generator
                  ) -> Dict[str, np.ndarray]:
    perm = rng.permutation(batch_size(batch))
    return {k: v[perm] for k, v in batch.items()}


def minibatches(batch: Dict[str, np.ndarray], minibatch_size: int):
    n = batch_size(batch)
    for start in range(0, n, minibatch_size):
        yield {k: v[start:start + minibatch_size] for k, v in batch.items()}


def stack_minibatches(batch: Dict[str, np.ndarray], minibatch_size: int
                      ) -> tuple:
    """[N, ...] -> ([n_mb, minibatch_size, ...], remainder) for lax.scan
    epochs. The ragged tail (N mod minibatch_size rows) can't join the
    scan (unequal shape) — it's returned separately so the caller can run
    it as one ordinary update. Stacked dict is {} if N < one batch."""
    n = batch_size(batch)
    n_mb = n // minibatch_size
    keep = n_mb * minibatch_size
    stacked = {} if n_mb == 0 else {
        k: v[:keep].reshape((n_mb, minibatch_size) + v.shape[1:])
        for k, v in batch.items()}
    remainder = {} if keep >= n else {k: v[keep:] for k, v in batch.items()}
    return stacked, remainder


def compute_gae(rewards: np.ndarray, values: np.ndarray, dones: np.ndarray,
                truncateds: np.ndarray, bootstrap_values: np.ndarray,
                gamma: float = 0.99, lam: float = 0.95):
    """Vectorized GAE over a [T, n_envs] rollout.

    `dones` marks episode boundaries (terminated OR truncated — the
    recursion resets either way); `truncateds` marks boundaries where the
    episode continued in principle, so the value bootstraps. `bootstrap_values`
    is V(s_{T}) for the final step plus, per step, V(next_obs) is only needed
    at truncation points — callers pass `next_values` [T, n_envs].
    """
    T, n = rewards.shape
    advantages = np.zeros((T, n), dtype=np.float32)
    last_gae = np.zeros(n, dtype=np.float32)
    for t in range(T - 1, -1, -1):
        # Value of the next state: 0 if terminated, V(next) otherwise.
        next_value = bootstrap_values[t]
        non_terminal = 1.0 - (dones[t] & ~truncateds[t]).astype(np.float32)
        not_done = 1.0 - dones[t].astype(np.float32)
        delta = rewards[t] + gamma * next_value * non_terminal - values[t]
        last_gae = delta + gamma * lam * not_done * last_gae
        advantages[t] = last_gae
    value_targets = advantages + values
    return advantages, value_targets


def standardize(x: np.ndarray) -> np.ndarray:
    return (x - x.mean()) / (x.std() + 1e-8)
