"""Tuned configurations with pass/fail thresholds — the learning
north-stars.

Equivalent of the reference's `rllib/tuned_examples/` YAMLs
(`tuned_examples/ppo/atari-ppo.yaml:1-35`, `impala/atari-impala.yaml:1-21`):
each entry pairs an algorithm config with a reward-vs-timestep threshold
that defines "learns". `run_tuned` drives training until the threshold or
the budget is hit.

Real Atari needs `ale-py` + `gymnasium[atari]` at runtime; environments
without them exercise the identical pipeline (CNN module, Atari
connectors, uint8 transport) on the synthetic Atari-shaped env — see
`atari_available()` and tests/test_rllib_atari.py.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Callable, Dict, Optional


def atari_available() -> bool:
    try:
        import ale_py  # noqa: F401
        import gymnasium  # noqa: F401

        return True
    except ImportError:
        return False


@dataclass
class TunedExample:
    name: str
    algo: str                       # "PPO" | "IMPALA" | "DQN"
    config_builder: Callable[[], Any]
    stop_reward: float              # threshold defining "learns"
    max_timesteps: int              # sample budget to reach it


def _atari_ppo_config(env_id: str):
    """Mirrors `tuned_examples/ppo/atari-ppo.yaml`: 5e-5 lr, 0.1 clip,
    10 SGD iters over 500-step fragments, vf clip 10."""
    from ray_tpu.rllib import PPOConfig

    return PPOConfig(
        env=env_id,
        num_rollout_workers=4,
        num_envs_per_worker=8,
        rollout_fragment_length=100,
        sgd_minibatch_size=500,
        num_sgd_iter=10,
        lr=5e-5,
        clip_param=0.1,
        vf_clip_param=10.0,
        entropy_coeff=0.01,
        lambda_=0.95,
        seed=0,
    )


def _atari_impala_config(env_id: str):
    """Mirrors `tuned_examples/impala/atari-impala.yaml`."""
    from ray_tpu.rllib import IMPALAConfig

    return IMPALAConfig(
        env=env_id,
        num_rollout_workers=4,
        num_envs_per_worker=8,
        rollout_fragment_length=50,
        lr=6e-4,
        entropy_coeff=0.01,
        seed=0,
    )


# Thresholds from the reference's tuned examples (reward the config must
# reach within the timestep budget on the real environment).
ATARI_PPO = {
    "breakout-ppo": TunedExample(
        "breakout-ppo", "PPO",
        lambda: _atari_ppo_config("ALE/Breakout-v5"),
        stop_reward=30.0, max_timesteps=5_000_000),
    "beamrider-ppo": TunedExample(
        "beamrider-ppo", "PPO",
        lambda: _atari_ppo_config("ALE/BeamRider-v5"),
        stop_reward=500.0, max_timesteps=5_000_000),
    "qbert-ppo": TunedExample(
        "qbert-ppo", "PPO",
        lambda: _atari_ppo_config("ALE/Qbert-v5"),
        stop_reward=1000.0, max_timesteps=5_000_000),
    "spaceinvaders-ppo": TunedExample(
        "spaceinvaders-ppo", "PPO",
        lambda: _atari_ppo_config("ALE/SpaceInvaders-v5"),
        stop_reward=300.0, max_timesteps=5_000_000),
}

ATARI_IMPALA = {
    "breakout-impala": TunedExample(
        "breakout-impala", "IMPALA",
        lambda: _atari_impala_config("ALE/Breakout-v5"),
        stop_reward=40.0, max_timesteps=10_000_000),
}

TUNED_EXAMPLES: Dict[str, TunedExample] = {**ATARI_PPO, **ATARI_IMPALA}


@dataclass
class TunedRunResult:
    passed: bool
    best_reward: float
    timesteps: int
    curve: list = field(default_factory=list)  # (timesteps, reward) pairs


def run_tuned(example: TunedExample,
              max_timesteps: Optional[int] = None,
              max_iters: int = 10_000) -> TunedRunResult:
    """Train the example's config until stop_reward or the budget runs
    out; returns the reward-vs-timesteps curve for the record."""
    from ray_tpu import rllib

    algo_cls = getattr(rllib, example.algo)
    algo = algo_cls(example.config_builder())
    budget = max_timesteps or example.max_timesteps
    best = float("-inf")
    steps = 0
    curve = []
    try:
        for _ in range(max_iters):
            m = algo.train()
            steps = m.get("timesteps_total", steps)
            r = m.get("episode_reward_mean")
            if r is not None:
                best = max(best, r)
                curve.append((steps, float(r)))
            if best >= example.stop_reward or steps >= budget:
                break
    finally:
        algo.stop()
    return TunedRunResult(passed=best >= example.stop_reward,
                          best_reward=best, timesteps=steps, curve=curve)
