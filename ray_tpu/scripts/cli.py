"""Command-line state inspection: `python -m ray_tpu <command>`.

Equivalent of the reference CLI surface (`ray status`, `ray list ...`,
`ray summary tasks`, `ray timeline`, `python/ray/scripts/scripts.py`)
against a running cluster, addressed by --address (or RAY_TPU_ADDRESS).

Commands:
    start --head | --address=X     start a node daemon (see cluster_cli)
    stop                           stop this machine's node daemons
    status                         cluster resources + node/actor summary
    list nodes|actors|jobs|tasks   entity tables
    summary tasks|actors           aggregated counts
    timeline --output FILE         chrome://tracing JSON
    metrics                        Prometheus text from the GCS
"""

from __future__ import annotations

import argparse
import json
import os
import sys


def _connect(address: str | None):
    import ray_tpu

    if ray_tpu.is_initialized():
        return ray_tpu, False  # piggyback on the caller's runtime
    addr = address or os.environ.get("RAY_TPU_ADDRESS")
    if not addr:
        print("error: --address (or RAY_TPU_ADDRESS) required", file=sys.stderr)
        raise SystemExit(2)
    ray_tpu.init(address=addr)
    return ray_tpu, True


def _dump(obj):
    print(json.dumps(obj, indent=2, default=str))


def main(argv=None):
    from ray_tpu.scripts import cluster_cli

    ap = argparse.ArgumentParser(prog="ray_tpu")
    ap.add_argument("--address", help="GCS address host:port")
    sub = ap.add_subparsers(dest="cmd", required=True)
    cluster_cli.add_start_parser(sub)
    cluster_cli.add_stop_parser(sub)
    sub.add_parser("status")
    p_list = sub.add_parser("list")
    p_list.add_argument("what", choices=["nodes", "actors", "jobs", "tasks",
                                         "objects"])
    p_sum = sub.add_parser("summary")
    p_sum.add_argument("what", choices=["tasks", "actors"])
    p_tl = sub.add_parser("timeline")
    p_tl.add_argument("--output", default="timeline.json")
    sub.add_parser("metrics")
    p_serve = sub.add_parser("serve")
    serve_sub = p_serve.add_subparsers(dest="serve_cmd", required=True)
    p_sd = serve_sub.add_parser("deploy")
    p_sd.add_argument("config", help="YAML config file (serve schema)")
    serve_sub.add_parser("status")
    serve_sub.add_parser("shutdown")
    p_sb = serve_sub.add_parser("build")
    p_sb.add_argument("import_path", help="module:app to describe")
    p_sb.add_argument("--output", default=None)
    p_dbg = sub.add_parser("debug")
    p_dbg.add_argument("--index", type=int, default=None,
                       help="breakpoint index to attach (default: newest)")
    p_dbg.add_argument("--list", action="store_true", dest="list_only")
    args = ap.parse_args(argv)

    # Cluster lifecycle commands manage daemons; they never connect a driver.
    if args.cmd == "start":
        raise SystemExit(cluster_cli.cmd_start(args, args.address))
    if args.cmd == "stop":
        raise SystemExit(cluster_cli.cmd_stop(args))

    ray_tpu, owns_runtime = _connect(args.address)
    from ray_tpu import state

    if args.cmd == "status":
        _dump(state.cluster_summary())
    elif args.cmd == "list":
        _dump(getattr(state, f"list_{args.what}")())
    elif args.cmd == "summary":
        _dump(getattr(state, f"summarize_{args.what}")())
    elif args.cmd == "timeline":
        events = ray_tpu.timeline(filename=args.output)
        print(f"wrote {args.output} ({len(events)} events)")
    elif args.cmd == "metrics":
        print(ray_tpu._require_runtime().gcs.call(
            "metrics_prometheus")["text"])
    elif args.cmd == "serve":
        from ray_tpu import serve as _serve

        if args.serve_cmd == "deploy":
            from ray_tpu.serve.schema import deploy_config_file

            deploy_config_file(args.config)
            _dump(_serve.status())
        elif args.serve_cmd == "status":
            _dump(_serve.status())
        elif args.serve_cmd == "shutdown":
            _serve.shutdown()
            print("serve: shut down")
        elif args.serve_cmd == "build":
            import yaml as _yaml

            from ray_tpu.serve.schema import build as _build, import_attr

            cfg = _build(import_attr(args.import_path))
            cfg["applications"][0]["import_path"] = args.import_path
            text = _yaml.safe_dump(cfg, sort_keys=False)
            if args.output:
                open(args.output, "w").write(text)
                print(f"wrote {args.output}")
            else:
                print(text)
    elif args.cmd == "debug":
        from ray_tpu.util import rpdb

        entries = rpdb.list_breakpoints()
        if args.list_only or not entries:
            _dump(entries or {"breakpoints": []})
        else:
            idx = args.index if args.index is not None else len(entries) - 1
            if not 0 <= idx < len(entries):
                print(f"error: no breakpoint #{idx} "
                      f"({len(entries)} active; run with --list)",
                      file=sys.stderr)
                raise SystemExit(2)
            entry = entries[idx]
            print(f"attaching to {entry['filename']}:{entry['lineno']} "
                  f"(pid {entry['pid']})")
            rpdb.attach(entry)
    if owns_runtime:
        ray_tpu.shutdown()


if __name__ == "__main__":
    main()
