"""Command-line state inspection: `python -m ray_tpu <command>`.

Equivalent of the reference CLI surface (`ray status`, `ray list ...`,
`ray summary tasks`, `ray timeline`, `python/ray/scripts/scripts.py`)
against a running cluster, addressed by --address (or RAY_TPU_ADDRESS).

Commands:
    status                         cluster resources + node/actor summary
    list nodes|actors|jobs|tasks   entity tables
    summary tasks|actors           aggregated counts
    timeline --output FILE         chrome://tracing JSON
    metrics                        Prometheus text from the GCS
"""

from __future__ import annotations

import argparse
import json
import os
import sys


def _connect(address: str | None):
    import ray_tpu

    if ray_tpu.is_initialized():
        return ray_tpu, False  # piggyback on the caller's runtime
    addr = address or os.environ.get("RAY_TPU_ADDRESS")
    if not addr:
        print("error: --address (or RAY_TPU_ADDRESS) required", file=sys.stderr)
        raise SystemExit(2)
    ray_tpu.init(address=addr)
    return ray_tpu, True


def _dump(obj):
    print(json.dumps(obj, indent=2, default=str))


def main(argv=None):
    ap = argparse.ArgumentParser(prog="ray_tpu")
    ap.add_argument("--address", help="GCS address host:port")
    sub = ap.add_subparsers(dest="cmd", required=True)
    sub.add_parser("status")
    p_list = sub.add_parser("list")
    p_list.add_argument("what", choices=["nodes", "actors", "jobs", "tasks",
                                         "objects"])
    p_sum = sub.add_parser("summary")
    p_sum.add_argument("what", choices=["tasks", "actors"])
    p_tl = sub.add_parser("timeline")
    p_tl.add_argument("--output", default="timeline.json")
    sub.add_parser("metrics")
    args = ap.parse_args(argv)

    ray_tpu, owns_runtime = _connect(args.address)
    from ray_tpu import state

    if args.cmd == "status":
        _dump(state.cluster_summary())
    elif args.cmd == "list":
        _dump(getattr(state, f"list_{args.what}")())
    elif args.cmd == "summary":
        _dump(getattr(state, f"summarize_{args.what}")())
    elif args.cmd == "timeline":
        events = ray_tpu.timeline(filename=args.output)
        print(f"wrote {args.output} ({len(events)} events)")
    elif args.cmd == "metrics":
        print(ray_tpu._require_runtime().gcs.call(
            "metrics_prometheus")["text"])
    if owns_runtime:
        ray_tpu.shutdown()


if __name__ == "__main__":
    main()
