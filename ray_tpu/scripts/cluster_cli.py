"""`ray_tpu start` / `ray_tpu stop`: assemble a cluster from OS processes.

Equivalent of `ray start` / `ray stop` (`python/ray/scripts/scripts.py:535,
1231`). `start --head` daemonizes a head node (GCS + raylet + dashboard)
detached from any driver; `start --address=HOST:PORT` daemonizes a worker
node that joins an existing head — this is the command TPU-VM startup
scripts run (`ray_tpu/autoscaler/gcp.py` GCETPUConfig.startup_script).
`stop` terminates every daemon started on this machine.

Drivers connect with `ray_tpu.init(address="host:port")` (or "auto", which
reads the cluster file written by `start --head`) and can connect,
disconnect, and reconnect without affecting the cluster — the reference
runs `gcs_server`/`raylet` as processes separate from any driver for the
same reason (`python/ray/_private/services.py:1280,1353`).

Daemon bookkeeping lives under `$RAY_TPU_TMPDIR` (default /tmp/ray_tpu):
- `ray_current_cluster.json` — head address, read by init("auto")
- `daemons/<pid>.json` — one record per node daemon on this machine
"""

from __future__ import annotations

import json
import os
import signal
import subprocess
import sys
import time
from typing import Any, Dict, List, Optional

_READY_TIMEOUT_S = 40.0


def tmp_base(base: Optional[str] = None) -> str:
    return base or os.environ.get("RAY_TPU_TMPDIR", "/tmp/ray_tpu")


def cluster_file(base: Optional[str] = None) -> str:
    return os.path.join(tmp_base(base), "ray_current_cluster.json")


def daemon_dir(base: Optional[str] = None) -> str:
    return os.path.join(tmp_base(base), "daemons")


def read_cluster_address(base: Optional[str] = None) -> Optional[str]:
    try:
        with open(cluster_file(base)) as f:
            return json.load(f)["address"]
    except Exception:  # noqa: BLE001 — missing/corrupt: no cluster
        return None


def read_daemon_records(base: Optional[str] = None) -> Dict[int, Dict[str, Any]]:
    """pid -> record for every daemon bookkeeping file on this machine
    (stale records for dead pids included — callers check liveness)."""
    out: Dict[int, Dict[str, Any]] = {}
    d = daemon_dir(base)
    try:
        names = os.listdir(d)
    except OSError:
        return out
    for name in names:
        path = os.path.join(d, name)
        try:
            with open(path) as f:
                rec = json.load(f)
            rec["_path"] = path
            out[rec["pid"]] = rec
        except Exception:  # noqa: BLE001 — partial write; skip
            pass
    return out


def _pid_alive(pid: int) -> bool:
    try:
        os.kill(pid, 0)
        return True
    except OSError:
        return False


def _pid_is_ray_daemon(pid: int) -> bool:
    """True only when `pid` is alive AND still our node daemon — a stale
    record surviving a SIGKILLed daemon must never get a recycled PID
    (some unrelated process) signalled."""
    try:
        with open(f"/proc/{pid}/cmdline", "rb") as f:
            cmdline = f.read()
    except OSError:
        return _pid_alive(pid)  # no /proc (non-Linux): fall back to liveness
    return b"ray_tpu" in cmdline


def resolve_bind_host(host: str) -> str:
    """`auto` (and the unroutable-as-advertised 0.0.0.0) resolve to this
    machine's primary interface IP, so the bound address is the same one
    peers can dial — bind host doubles as the advertised address
    throughout (NodeInfo.address, the cluster file, lease replies)."""
    if host not in ("auto", "0.0.0.0"):
        return host
    from ray_tpu.util.net import primary_ip

    return primary_ip()


def _daemon_record_path(pid: int) -> str:
    return os.path.join(daemon_dir(), f"{pid}.json")


def _parse_labels(text: Optional[str]) -> Optional[Dict[str, str]]:
    if not text:
        return None
    out = {}
    for pair in text.split(","):
        if not pair:
            continue
        k, _, v = pair.partition("=")
        out[k.strip()] = v.strip()
    return out


def add_start_parser(sub) -> None:
    p = sub.add_parser("start", help="start a head or worker node daemon")
    p.add_argument("--head", action="store_true",
                   help="start a new cluster head (GCS + raylet)")
    p.add_argument("--address", dest="join_address", default=None,
                   help="GCS address of an existing head to join "
                        "(this is what TPU-VM startup scripts pass)")
    p.add_argument("--port", type=int, default=0,
                   help="fixed GCS port for --head (default: ephemeral)")
    p.add_argument("--host", default="127.0.0.1",
                   help="bind+advertise host for GCS/raylet; 'auto' picks "
                        "this machine's primary IP (use for multi-machine)")
    p.add_argument("--num-cpus", type=float, default=None)
    p.add_argument("--num-tpus", type=float, default=None)
    p.add_argument("--resources", default=None,
                   help='extra resources as JSON, e.g. \'{"worker": 1}\'')
    p.add_argument("--object-store-memory", type=int, default=0)
    p.add_argument("--labels", default=None, help="k=v[,k=v...] node labels")
    p.add_argument("--block", action="store_true",
                   help="run in the foreground instead of daemonizing")


def add_stop_parser(sub) -> None:
    p = sub.add_parser("stop", help="stop all node daemons on this machine")
    p.add_argument("--force", action="store_true",
                   help="SIGKILL immediately instead of graceful SIGTERM")
    p.add_argument("--grace-period", type=float, default=10.0)


def cmd_start(args, global_address: Optional[str]) -> int:
    join = args.join_address or (None if args.head else global_address)
    if args.head == bool(join):
        print("error: pass exactly one of --head or --address=HOST:PORT",
              file=sys.stderr)
        return 2
    if args.head:
        # Refuse to hijack a live cluster: a second head would silently
        # redirect every init(address="auto") driver. The live head daemon
        # RECORD is the signal — the cluster file alone can be pruned or
        # corrupt while the head still runs.
        live = [rec for pid, rec in read_daemon_records().items()
                if rec.get("role") == "head" and _pid_is_ray_daemon(pid)]
        if live:
            addr = read_cluster_address() or live[0].get("gcs_address")
            print(f"error: a cluster is already running at {addr} "
                  "(run `python -m ray_tpu stop` first)", file=sys.stderr)
            return 1
    if args.block:
        return _run_blocking(args, join)
    # Daemonize: re-exec this command with --block in a new session so the
    # node survives this CLI (and any future driver) exiting.
    os.makedirs(os.path.join(tmp_base(), "logs"), exist_ok=True)
    os.makedirs(daemon_dir(), exist_ok=True)
    argv = [sys.executable, "-m", "ray_tpu", "start", "--block"]
    if args.head:
        argv += ["--head", "--port", str(args.port)]
    else:
        argv += ["--address", join]
    argv += ["--host", args.host]
    if args.num_cpus is not None:
        argv += ["--num-cpus", str(args.num_cpus)]
    if args.num_tpus is not None:
        argv += ["--num-tpus", str(args.num_tpus)]
    if args.resources:
        argv += ["--resources", args.resources]
    if args.object_store_memory:
        argv += ["--object-store-memory", str(args.object_store_memory)]
    if args.labels:
        argv += ["--labels", args.labels]
    log_path = os.path.join(
        tmp_base(), "logs",
        f"node-{'head' if args.head else 'worker'}-{int(time.time())}.log")
    with open(log_path, "ab") as log:
        proc = subprocess.Popen(
            argv, stdout=log, stderr=log, stdin=subprocess.DEVNULL,
            start_new_session=True)
    record_path = _daemon_record_path(proc.pid)
    deadline = time.time() + _READY_TIMEOUT_S
    while time.time() < deadline:
        if proc.poll() is not None:
            print(f"error: node daemon exited with rc={proc.returncode}; "
                  f"log: {log_path}", file=sys.stderr)
            return 1
        try:
            with open(record_path) as f:
                rec = json.load(f)
            break
        except Exception:  # noqa: BLE001 — not written yet
            time.sleep(0.1)
    else:
        print(f"error: node daemon not ready after {_READY_TIMEOUT_S:.0f}s; "
              f"log: {log_path}", file=sys.stderr)
        return 1
    if args.head:
        print(f"ray_tpu head started at {rec['gcs_address']} (pid {proc.pid})")
        print(f"  connect drivers with: ray_tpu.init(address="
              f"\"{rec['gcs_address']}\")")
        print(f"  add nodes with:       python -m ray_tpu start "
              f"--address={rec['gcs_address']}")
    else:
        print(f"ray_tpu node joined {join} "
              f"(node {rec['node_id'][:12]}, pid {proc.pid})")
    return 0


def _run_blocking(args, join: Optional[str]) -> int:
    from ray_tpu.core.node import Node

    os.makedirs(daemon_dir(), exist_ok=True)
    resources = json.loads(args.resources) if args.resources else None
    host = resolve_bind_host(args.host)
    node = Node(
        head=args.head,
        gcs_address=join,
        gcs_host=host,
        gcs_port=args.port,
        host=host,
        num_cpus=args.num_cpus,
        num_tpus=args.num_tpus,
        resources=resources,
        object_store_memory=args.object_store_memory,
        labels=_parse_labels(args.labels),
    )
    record = {
        "pid": os.getpid(),
        "role": "head" if args.head else "worker",
        "gcs_address": node.gcs_address,
        "raylet_address": node.raylet_address,
        "node_id": node.node_id.hex(),
        "session_dir": node.session_dir,
        "started_at": time.time(),
    }
    record_path = _daemon_record_path(os.getpid())
    with open(record_path, "w") as f:
        json.dump(record, f)
    wrote_cluster_file = False
    if args.head:
        with open(cluster_file(), "w") as f:
            json.dump({"address": node.gcs_address}, f)
        wrote_cluster_file = True

    stopping = {"flag": False}

    def _term(signum, frame):
        if stopping["flag"]:
            return
        stopping["flag"] = True
        try:
            node.shutdown()
        finally:
            doomed = [record_path]
            # Only remove the cluster file if it still points at THIS head —
            # a newer cluster may have claimed it since.
            if wrote_cluster_file and \
                    read_cluster_address() == node.gcs_address:
                doomed.append(cluster_file())
            for path in doomed:
                try:
                    os.unlink(path)
                except OSError:
                    pass
            os._exit(0)

    signal.signal(signal.SIGTERM, _term)
    signal.signal(signal.SIGINT, _term)
    print(f"node up: gcs={node.gcs_address} raylet={node.raylet_address} "
          f"(pid {os.getpid()})", flush=True)
    while True:  # woken only by signals
        time.sleep(3600)


def _stop_group(records: List[Dict[str, Any]], force: bool,
                grace_period: float) -> int:
    """Signal every daemon in the group first, then run ONE shared grace
    wait, then SIGKILL stragglers — N slow workers cost one grace period,
    not N."""
    sig = signal.SIGKILL if force else signal.SIGTERM
    waiting: List[int] = []
    stopped = 0
    for rec in records:
        if not _pid_is_ray_daemon(rec["pid"]):
            continue  # stale record: dead daemon or recycled PID
        try:
            os.kill(rec["pid"], sig)
            stopped += 1
            waiting.append(rec["pid"])
        except ProcessLookupError:
            pass
    if not force:
        deadline = time.time() + grace_period
        while waiting and time.time() < deadline:
            waiting = [pid for pid in waiting if _pid_alive(pid)]
            if waiting:
                time.sleep(0.1)
        for pid in waiting:
            try:
                os.kill(pid, signal.SIGKILL)
            except ProcessLookupError:
                pass
    for rec in records:
        try:
            os.unlink(rec["_path"])
        except OSError:
            pass
    return stopped


def cmd_stop(args) -> int:
    records = list(read_daemon_records().values())
    # Workers first, head last, so departing nodes can still report to GCS.
    workers = [r for r in records if r.get("role") != "head"]
    heads = [r for r in records if r.get("role") == "head"]
    stopped = _stop_group(workers, args.force, args.grace_period)
    stopped += _stop_group(heads, args.force, args.grace_period)
    try:
        os.unlink(cluster_file())
    except OSError:
        pass
    print(f"stopped {stopped} node daemon(s)")
    return 0
