"""ray_tpu.serve — model serving on the actor substrate.

API parity with the reference's `ray.serve` (`serve/api.py:267`,
`deployment.py:97`): ``@serve.deployment``, ``.bind()``, ``serve.run``,
``serve.shutdown``, ``serve.status``, ``get_deployment_handle``, and
``@serve.batch``. TPU-first: a deployment's replicas are actors scheduled
with their own resource grants (``num_tpus=1`` replicas own a chip and run
batched jitted inference; see `batching.py`), the controller reconciles
replica actors and autoscales on queue depth, and per-node aiohttp proxies
front HTTP traffic.

Typical flow:

    @serve.deployment(num_replicas=2, max_concurrent_queries=4)
    class Echo:
        def __call__(self, payload):
            return payload

    app = Echo.bind()
    handle = serve.run(app)
    out = ray_tpu.get(handle.remote({"x": 1}))
    serve.shutdown()
"""

from __future__ import annotations

import copy
import logging
import time
from dataclasses import replace as _dc_replace
from typing import Any, Callable, Dict, Optional, Union

from ray_tpu.serve.batching import batch
from ray_tpu.serve.config import AutoscalingConfig, DeploymentConfig
from ray_tpu.serve.handle import DeploymentHandle, _drop_process_router
from ray_tpu.shardgroup.spec import ShardSpec

logger = logging.getLogger(__name__)

_PROXY_NAME = "SERVE_PROXY"
_GRPC_PROXY_NAME = "SERVE_GRPC_PROXY"


class Application:
    """A bound deployment (class + init args), ready for serve.run."""

    def __init__(self, deployment: "Deployment", args, kwargs):
        self.deployment = deployment
        self.init_args = args
        self.init_kwargs = kwargs


class Deployment:
    def __init__(self, target: Union[type, Callable], name: str,
                 config: DeploymentConfig):
        self._target = target
        self.name = name
        self.config = config

    def options(self, *, name: Optional[str] = None,
                num_replicas: Optional[int] = None,
                max_concurrent_queries: Optional[int] = None,
                autoscaling_config: Optional[AutoscalingConfig] = None,
                route_prefix: Optional[str] = None,
                ray_actor_options: Optional[Dict[str, Any]] = None,
                user_config: Any = None,
                shard_spec: Optional["ShardSpec"] = None,
                tenant: Optional[str] = None
                ) -> "Deployment":
        cfg = _dc_replace(self.config)
        if num_replicas is not None:
            cfg.num_replicas = num_replicas
        if max_concurrent_queries is not None:
            cfg.max_concurrent_queries = max_concurrent_queries
        if autoscaling_config is not None:
            cfg.autoscaling = autoscaling_config
        if route_prefix is not None:
            cfg.route_prefix = route_prefix
        if ray_actor_options is not None:
            cfg.ray_actor_options = dict(ray_actor_options)
        if user_config is not None:
            cfg.user_config = user_config
        if shard_spec is not None:
            cfg.shard_spec = shard_spec
        if tenant is not None:
            cfg.tenant = tenant
        return Deployment(self._target, name or self.name, cfg)

    def bind(self, *args, **kwargs) -> Application:
        return Application(self, args, kwargs)

    @property
    def user_callable(self):
        if isinstance(self._target, type):
            return self._target
        from ray_tpu.serve.replica import make_function_wrapper

        return make_function_wrapper(self._target)


def deployment(_target=None, *, name: Optional[str] = None,
               num_replicas: int = 1, max_concurrent_queries: int = 8,
               autoscaling_config: Optional[AutoscalingConfig] = None,
               route_prefix: Optional[str] = None,
               ray_actor_options: Optional[Dict[str, Any]] = None,
               user_config: Any = None,
               shard_spec: Optional["ShardSpec"] = None,
               tenant: Optional[str] = None):
    """`@serve.deployment` on a class or function."""

    def wrap(target):
        cfg = DeploymentConfig(
            num_replicas=num_replicas,
            max_concurrent_queries=max_concurrent_queries,
            autoscaling=autoscaling_config,
            route_prefix=route_prefix,
            ray_actor_options=dict(ray_actor_options or {}),
            user_config=user_config,
            shard_spec=shard_spec,
            tenant=tenant,
        )
        return Deployment(target, name or target.__name__, cfg)

    return wrap(_target) if _target is not None else wrap


def ingress(app):
    """Expose an ASGI application as a deployment's HTTP interface.

    Reference `serve.ingress` (`python/ray/serve/api.py`): FastAPI /
    Starlette / any ASGI3 callable. HTTP requests routed to the
    deployment are translated to ASGI scope events on the replica
    (`replica.py:_handle_asgi`); streamed bodies relay back through the
    proxy's stream protocol.

    ``app`` may be the ASGI callable itself, a zero-arg factory
    returning one (for apps that don't pickle), or a one-arg factory
    receiving the deployment instance (routes needing deployment state)::

        @serve.deployment
        @serve.ingress(fastapi_app)
        class Api: ...
    """

    def wrap(cls):
        if not isinstance(cls, type):
            raise TypeError(
                "serve.ingress decorates the deployment class; apply it "
                "under @serve.deployment")
        cls.__serve_asgi_app__ = app
        return cls

    return wrap


# --------------------------------------------------------------------------- #
# Cluster-facing operations
# --------------------------------------------------------------------------- #


def _get_or_create_controller(create: bool = True):
    import ray_tpu
    from ray_tpu.serve.controller import (
        CONTROLLER_NAME,
        SERVE_NAMESPACE,
        ServeController,
    )

    try:
        return ray_tpu.get_actor(CONTROLLER_NAME, namespace=SERVE_NAMESPACE)
    except Exception:  # noqa: BLE001 — not started yet
        if not create:
            raise
    controller = ray_tpu.remote(ServeController).options(
        name=CONTROLLER_NAME, namespace=SERVE_NAMESPACE,
        lifetime="detached", max_concurrency=64, num_cpus=0.1,
    ).remote()
    # Crash recovery (reference controller.py:75): a checkpoint in the
    # GCS KV means a previous controller died — rebuild its state and
    # re-adopt surviving named replicas before reconciling.
    ray_tpu.get(controller.restore.remote(), timeout=60.0)
    controller.reconcile_forever.remote()
    return controller


def start(http_host: str = "127.0.0.1", http_port: int = 8000,
          detached: bool = True, proxy_location: str = "HeadOnly") -> None:
    """Start the Serve control plane (controller + HTTP proxy).

    proxy_location="EveryNode" puts a controller-managed, health-checked
    proxy on every alive node (reference http_state.py:110); the default
    keeps the single head proxy.
    """
    controller = _get_or_create_controller()
    if proxy_location == "EveryNode":
        import ray_tpu

        ray_tpu.get(controller.set_proxy_config.remote(
            http_host, http_port, True), timeout=60.0)
    else:
        _ensure_proxy(http_host, http_port)


def _ensure_proxy_actor(name: str, cls, host: str, port: int) -> int:
    """Get-or-create a detached proxy actor and wait for its bound port —
    one implementation for the HTTP and gRPC front doors."""
    import ray_tpu
    from ray_tpu.serve.controller import SERVE_NAMESPACE

    try:
        proxy = ray_tpu.get_actor(name, namespace=SERVE_NAMESPACE)
    except Exception:  # noqa: BLE001
        proxy = ray_tpu.remote(cls).options(
            name=name, namespace=SERVE_NAMESPACE,
            lifetime="detached", max_concurrency=256, num_cpus=0.1,
        ).remote(host, port)
    return ray_tpu.get(proxy.ready.remote(), timeout=60.0)


def _ensure_proxy(host: str, port: int) -> int:
    from ray_tpu.serve.proxy import HTTPProxy

    return _ensure_proxy_actor(_PROXY_NAME, HTTPProxy, host, port)


def _graph_order(root: Application) -> list:
    """Applications of a composed graph, dependencies first (reference
    deployment graphs: `Driver.bind(model_a.bind(), model_b.bind())`).
    Nested Applications in init args become DeploymentHandles at deploy
    time. Cycles and name collisions are errors."""
    order: list = []
    visiting: set = set()

    def walk_value(value):
        if isinstance(value, Application):
            walk(value)
        elif isinstance(value, (list, tuple)):
            for v in value:
                walk_value(v)
        elif isinstance(value, dict):
            for v in value.values():
                walk_value(v)

    def walk(app: Application):
        if any(a is app for a in order):
            return
        if id(app) in visiting:
            raise ValueError("deployment graph has a cycle at "
                             f"{app.deployment.name!r}")
        visiting.add(id(app))
        # Mirror _sub_handles' traversal exactly: anything that will be
        # substituted with a handle must also be deployed.
        for arg in list(app.init_args) + list(app.init_kwargs.values()):
            walk_value(arg)
        visiting.discard(id(app))
        order.append(app)

    walk(root)
    by_name: Dict[str, Application] = {}
    for a in order:
        other = by_name.setdefault(a.deployment.name, a)
        if other is not a:
            raise ValueError(
                f"two different bindings share the deployment name "
                f"{a.deployment.name!r}; use .options(name=...) to rename")
    return order


def _contains_app(value) -> bool:
    if isinstance(value, Application):
        return True
    if isinstance(value, (list, tuple)):
        return any(_contains_app(v) for v in value)
    if isinstance(value, dict):
        return any(_contains_app(v) for v in value.values())
    return False


def _sub_handles(value):
    if isinstance(value, Application):
        return DeploymentHandle(value.deployment.name)
    if not _contains_app(value):
        # Identity fast-path: containers without bindings pass through
        # untouched (preserving dict/list subclasses and their state).
        return value
    if isinstance(value, tuple) and hasattr(value, "_fields"):  # namedtuple
        return type(value)(*(_sub_handles(v) for v in value))
    if isinstance(value, (list, tuple)):
        return type(value)(_sub_handles(v) for v in value)
    if isinstance(value, dict):
        subbed = {k: _sub_handles(v) for k, v in value.items()}
        try:  # keep dict subclasses (defaultdict, OrderedDict, ...) intact
            out = copy.copy(value)
            out.clear()
            out.update(subbed)
            return out
        except Exception:  # noqa: BLE001 — exotic mapping; plain dict is fine
            return subbed
    return value


def _check_no_stray_apps(value, owner: str):
    """Applications hiding in containers the graph traversal does not
    descend into (sets, frozensets, arbitrary object attributes) would be
    pickled as inert data — fail loudly at deploy time instead."""
    if isinstance(value, Application):
        raise ValueError(
            f"un-substituted bound deployment in init args of {owner!r}: "
            "nested Applications are only resolved inside lists, tuples and "
            "dict values")
    if isinstance(value, (list, tuple, set, frozenset)):
        for v in value:
            _check_no_stray_apps(v, owner)
    elif isinstance(value, dict):
        for k, v in value.items():
            _check_no_stray_apps(k, owner)  # bindings as KEYS escape
            _check_no_stray_apps(v, owner)  # the substitution traversals


def run(app: Union[Application, Deployment], *, _blocking: bool = False,
        http: bool = False, http_host: str = "127.0.0.1",
        http_port: int = 8000, timeout_s: float = 60.0
        ) -> DeploymentHandle:
    """Deploy an application — or a whole composed graph (bound
    deployments passed as init args become live DeploymentHandles) — and
    wait until the initial replicas are RUNNING, dependencies first."""
    import ray_tpu

    if isinstance(app, Deployment):
        app = app.bind()
    controller = _get_or_create_controller()
    for a in _graph_order(app):
        dep = a.deployment
        sub_args = _sub_handles(tuple(a.init_args))
        sub_kwargs = _sub_handles(dict(a.init_kwargs))
        _check_no_stray_apps(sub_args, dep.name)
        _check_no_stray_apps(sub_kwargs, dep.name)
        ray_tpu.get(controller.deploy.remote(
            dep.name, dep.user_callable, sub_args, sub_kwargs,
            dep.config), timeout=timeout_s)
        ok = ray_tpu.get(controller.wait_ready.remote(dep.name, timeout_s),
                         timeout=timeout_s + 5.0)
        if not ok:
            raise TimeoutError(
                f"deployment {dep.name!r} did not become ready "
                f"in {timeout_s}s")
    if http:
        _ensure_proxy(http_host, http_port)
    return DeploymentHandle(app.deployment.name)


def get_deployment_handle(name: str) -> DeploymentHandle:
    return DeploymentHandle(name)


def register_tenant(name: str, *, tier: str = "bronze", weight: int = 0,
                    rps_limit: float = 0.0, burst: float = 0.0,
                    max_inflight: int = 0,
                    timeout_s: float = 30.0) -> None:
    """Create or update a tenant (docs/MULTITENANCY.md): a named
    principal with a priority tier (gold/silver/bronze), a request-rate
    quota (token bucket, over-quota requests answer 429 + Retry-After),
    a per-proxy in-flight cap, and a weighted-fair-queueing weight used
    when replica capacity is contended. Deployments bind to a tenant via
    ``@serve.deployment(tenant=...)``; the tenant must be registered
    before its deployments deploy."""
    import ray_tpu
    from ray_tpu.tenancy.registry import TenantSpec

    spec = TenantSpec(name=name, tier=tier, weight=weight,
                      rps_limit=rps_limit, burst=burst,
                      max_inflight=max_inflight)
    controller = _get_or_create_controller()
    ray_tpu.get(controller.register_tenant.remote(spec.qos()),
                timeout=timeout_s)


def unregister_tenant(name: str, timeout_s: float = 30.0) -> None:
    """Remove a tenant; fails while it still owns deployments."""
    import ray_tpu

    controller = _get_or_create_controller(create=False)
    ray_tpu.get(controller.unregister_tenant.remote(name),
                timeout=timeout_s)


def tenants(timeout_s: float = 10.0) -> Dict[str, Dict[str, Any]]:
    """The registered tenants and their QoS specs."""
    import ray_tpu

    try:
        controller = _get_or_create_controller(create=False)
    except Exception:  # noqa: BLE001 — no live controller: no tenants
        return {}
    return ray_tpu.get(controller.tenants.remote(), timeout=timeout_s)


def status() -> Dict[str, Any]:
    import ray_tpu

    try:
        controller = _get_or_create_controller(create=False)
    except Exception:  # noqa: BLE001 — no live controller
        # Transparent crash recovery: recreate ONLY when a previous
        # controller left a checkpoint — a status probe on a cluster that
        # never ran Serve must stay a read, not spawn a control plane.
        from ray_tpu.serve.controller import ServeController

        runtime = ray_tpu._require_runtime()
        ckpt = runtime.gcs.call(
            "kv_get", {"key": ServeController.CKPT_KEY})["value"]
        if not ckpt:
            return {}
        controller = _get_or_create_controller(create=True)
    return ray_tpu.get(controller.status.remote(), timeout=10.0)


def http_port() -> int:
    """The bound port of the local HTTP proxy (starts it if needed)."""
    return _ensure_proxy("127.0.0.1", 0)


def grpc_port() -> int:
    """The bound port of the local gRPC proxy (starts it if needed).
    Requests route as `/ray_tpu.serve/<Deployment>` with raw-bytes
    request/response (msgpack-decodable bodies are decoded for the
    deployment callable) — see serve/grpc_proxy.py."""
    return _ensure_grpc_proxy("127.0.0.1", 0)


def _ensure_grpc_proxy(host: str, port: int) -> int:
    from ray_tpu.serve.grpc_proxy import GrpcProxy

    return _ensure_proxy_actor(_GRPC_PROXY_NAME, GrpcProxy, host, port)


def delete(name: str, timeout_s: float = 30.0) -> None:
    import ray_tpu

    controller = _get_or_create_controller(create=False)
    ray_tpu.get(controller.delete.remote(name), timeout=timeout_s)


def shutdown() -> None:
    """Tear down all deployments, the proxy, and the controller."""
    import ray_tpu
    from ray_tpu.serve.controller import (
        CONTROLLER_NAME,
        SERVE_NAMESPACE,
    )

    _drop_process_router()
    for name in (_PROXY_NAME, _GRPC_PROXY_NAME):
        try:
            proxy = ray_tpu.get_actor(name, namespace=SERVE_NAMESPACE)
            try:
                ray_tpu.get(proxy.stop.remote(), timeout=5.0)
            except Exception:  # noqa: BLE001
                pass
            ray_tpu.kill(proxy)
        except Exception:  # noqa: BLE001
            pass
    try:
        controller = ray_tpu.get_actor(CONTROLLER_NAME,
                                       namespace=SERVE_NAMESPACE)
    except Exception:  # noqa: BLE001
        return
    try:
        ray_tpu.get(controller.graceful_shutdown.remote(), timeout=10.0)
    except Exception:  # noqa: BLE001
        pass
    try:
        ray_tpu.kill(controller)
    except Exception:  # noqa: BLE001
        pass


def build(app):
    """Application -> editable config dict (reference `serve build`)."""
    from ray_tpu.serve.schema import build as _build

    return _build(app)


def deploy_config(config, *, timeout_s: float = 60.0):
    """Deploy applications from a config dict (reference REST deploy)."""
    from ray_tpu.serve.schema import deploy_config as _deploy

    return _deploy(config, timeout_s=timeout_s)


__all__ = [
    "Application", "AutoscalingConfig", "Deployment", "DeploymentConfig",
    "DeploymentHandle", "ShardSpec", "batch", "build", "delete",
    "deploy_config", "deployment", "get_deployment_handle", "grpc_port",
    "http_port", "ingress", "register_tenant", "run", "shutdown", "start",
    "status", "tenants", "unregister_tenant",
]
