"""Request batching for replicas (`@serve.batch`).

The TPU-critical piece of the serving path: individual requests are queued
on the replica's asyncio loop and flushed as one list into the wrapped
callable — which for a JAX replica means one padded, jitted forward pass on
the MXU instead of N tiny ones. Mirrors the reference's
`python/ray/serve/batching.py` semantics (max_batch_size +
batch_wait_timeout_s) with an asyncio queue + single flusher task.

Usable standalone on any async method; typical use inside a deployment:

    @serve.deployment
    class Model:
        @serve.batch(max_batch_size=8, batch_wait_timeout_s=0.01)
        async def __call__(self, prompts: list[str]) -> list[str]:
            return self._jit_generate(prompts)   # one batched MXU call
"""

from __future__ import annotations

import asyncio
import functools
from typing import Any, Callable, List, Optional, Set

from ray_tpu.observability import tracing as _tracing


class _BatchQueue:
    def __init__(self, fn: Callable, max_batch_size: int,
                 batch_wait_timeout_s: float):
        self._fn = fn
        self._max = max_batch_size
        self._timeout = batch_wait_timeout_s
        self._queue: Optional[asyncio.Queue] = None
        self._flusher: Optional[asyncio.Task] = None
        self._pending: Set[asyncio.Future] = set()
        self._stopped = False

    def _ensure_started(self):
        # Lazily bind to the running loop (the replica's actor loop).
        if self._queue is None:
            self._queue = asyncio.Queue()
            self._flusher = asyncio.get_running_loop().create_task(
                self._flush_forever())

    async def submit(self, item: Any) -> Any:
        if self._stopped:
            raise RuntimeError("batch queue is stopped (replica shutdown)")
        self._ensure_started()
        fut = asyncio.get_running_loop().create_future()
        self._pending.add(fut)
        fut.add_done_callback(self._pending.discard)
        # Trace context rides with the item: the flusher coroutine runs
        # outside any request context, so the batch span re-parents to
        # the first batched request's trace.
        self._queue.put_nowait((item, fut, _tracing.capture()))
        return await fut

    def stop(self) -> int:
        """Replica teardown: cancel the flusher task and fail every
        parked future (queued AND mid-batch) — without this, a replica
        shutdown leaks the `_flush_forever` coroutine forever and strands
        callers awaiting futures nothing will ever resolve. Returns how
        many pending calls were failed."""
        self._stopped = True
        if self._flusher is not None and not self._flusher.done():
            self._flusher.cancel()
        self._flusher = None
        failed = 0
        for fut in list(self._pending):
            if not fut.done():
                fut.set_exception(
                    RuntimeError("replica shut down before the batched "
                                 "call completed"))
                failed += 1
        self._pending.clear()
        if self._queue is not None:
            while not self._queue.empty():
                self._queue.get_nowait()
        return failed

    async def _flush_forever(self):
        while True:
            batch: List = [await self._queue.get()]
            # Admit more until full or the wait timeout elapses.
            deadline = asyncio.get_running_loop().time() + self._timeout
            while len(batch) < self._max:
                remaining = deadline - asyncio.get_running_loop().time()
                if remaining <= 0:
                    break
                try:
                    batch.append(await asyncio.wait_for(
                        self._queue.get(), timeout=remaining))
                except asyncio.TimeoutError:
                    break
            items = [b[0] for b in batch]
            futures = [b[1] for b in batch]
            span = _tracing.NOOP_SPAN
            if _tracing._ENABLED:
                # Parent to the first batched request's SAMPLED context —
                # the flusher task itself inherited whatever context was
                # current when it was first created (not this batch's
                # trace), and an unsampled request's context would
                # no-op the span even when a sampled request shares the
                # batch.
                ctx = next((b[2] for b in batch
                            if b[2] is not None and b[2].get("sampled")),
                           None)
                if ctx is not None:
                    span = _tracing.get_tracer().start_span(
                        "serve.batch", child_of=ctx,
                        attrs={"batch_size": len(items)})
            try:
                with span:
                    results = self._fn(items)
                    if asyncio.iscoroutine(results):
                        results = await results
                    if len(results) != len(items):
                        raise RuntimeError(
                            f"@serve.batch function returned {len(results)} "
                            f"results for a batch of {len(items)}")
                for fut, res in zip(futures, results):
                    if not fut.done():
                        fut.set_result(res)
            except Exception as e:  # noqa: BLE001 — fan the error out
                for fut in futures:
                    if not fut.done():
                        fut.set_exception(e)


def batch(_fn: Optional[Callable] = None, *, max_batch_size: int = 8,
          batch_wait_timeout_s: float = 0.01):
    """Decorator collecting concurrent calls into one list-in/list-out call.

    The wrapped function receives a list of the individual call arguments
    and must return a list of results of the same length.
    """

    def wrap(fn: Callable):
        attr = f"__serve_batch_queue_{fn.__name__}"

        @functools.wraps(fn)
        async def wrapper(*args):
            # Methods: args = (self, item); functions: args = (item,).
            if len(args) == 2:
                owner, item = args
                bound = functools.partial(fn, owner)
            elif len(args) == 1:
                owner, (item,) = wrapper, args
                bound = fn
            else:
                raise TypeError(
                    "@serve.batch methods take exactly one request argument")
            queue = getattr(owner, attr, None)
            if queue is None:
                queue = _BatchQueue(bound, max_batch_size,
                                    batch_wait_timeout_s)
                setattr(owner, attr, queue)
            return await queue.submit(item)

        wrapper.__serve_is_batched__ = True
        return wrapper

    return wrap(_fn) if _fn is not None else wrap
