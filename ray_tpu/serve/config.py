"""Serve configuration dataclasses.

Mirrors the reference's deployment/autoscaling config surface
(`python/ray/serve/config.py`, `_private/autoscaling_policy.py:54-127`) as
plain dataclasses: num_replicas or an AutoscalingConfig, per-replica
max_concurrent_queries (admission control at the router), and the actor
resources a replica runs with (a TPU inference replica asks for
``num_tpus=1`` and owns the chip).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Dict, Optional

from ray_tpu.shardgroup.spec import ShardSpec


@dataclass
class AutoscalingConfig:
    """Queue-depth driven replica autoscaling.

    The controller compares the mean number of ongoing requests per replica
    against ``target_ongoing_requests`` each reconcile tick and moves
    ``num_replicas`` toward ``ceil(total_ongoing / target)``, bounded by
    [min_replicas, max_replicas]. Upscale reacts after
    ``upscale_delay_s`` of sustained pressure, downscale after
    ``downscale_delay_s`` of sustained idleness (reference policy:
    `serve/_private/autoscaling_policy.py:127`).
    """

    min_replicas: int = 1  # 0 enables scale-to-zero (deploys parked)
    max_replicas: int = 4
    target_ongoing_requests: float = 2.0
    upscale_delay_s: float = 0.5
    downscale_delay_s: float = 2.0
    metrics_interval_s: float = 0.25


@dataclass
class DeploymentConfig:
    num_replicas: int = 1
    max_concurrent_queries: int = 8
    autoscaling: Optional[AutoscalingConfig] = None
    route_prefix: Optional[str] = None       # default: "/<deployment name>"
    ray_actor_options: Dict[str, Any] = field(default_factory=dict)
    health_check_period_s: float = 1.0
    graceful_shutdown_timeout_s: float = 5.0
    replica_startup_timeout_s: float = 60.0
    # Arbitrary payload delivered to every replica's `reconfigure(cfg)`
    # hook — model weights, sampling params, feature flags. The controller
    # puts it in the object store ONCE and passes the ref to each replica,
    # so a large payload (a weight pytree) fans out over the object
    # transfer plane's tree broadcast instead of being re-pickled through
    # the controller per replica (reference: serve user_config semantics).
    user_config: Any = None
    # Sharded replica groups (docs/SHARDED.md): when set, every "replica"
    # of this deployment is a gang of shard_spec.world_size rank actors on
    # one placement group driving a shard_spec.tp-wide tensor-parallel
    # mesh. The router still sees ONE handle per replica (rank 0);
    # autoscaling / scale-to-zero operate on whole groups, and any rank
    # death kills and restarts the group as a unit.
    shard_spec: Optional[ShardSpec] = None
    # Multi-tenancy (docs/MULTITENANCY.md): the registered tenant that
    # owns this deployment. Its QoS (tier/weight/rps/in-flight quotas)
    # is pushed to proxies inside the routing-table entry and enforced
    # there; None = untenanted (unmetered, default fair-queue weight).
    tenant: Optional[str] = None

    def initial_replicas(self) -> int:
        if self.autoscaling is not None:
            # min_replicas=0 deploys PARKED: the route exists with zero
            # replicas and the first request cold-starts one through the
            # controller's wake path (scale-to-zero).
            if self.autoscaling.min_replicas <= 0:
                return 0
            return self.autoscaling.min_replicas
        return self.num_replicas


# Replica lifecycle states (reference: `_private/deployment_state.py` —
# STARTING/RUNNING/STOPPING collapsed to what the reconcile loop needs).
REPLICA_STARTING = "STARTING"
REPLICA_RUNNING = "RUNNING"
REPLICA_STOPPING = "STOPPING"
