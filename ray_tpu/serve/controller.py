"""Serve controller: the reconcile loop that owns deployment state.

Equivalent of the reference's `ServeController` (`serve/controller.py:75`)
+ `DeploymentState` (`_private/deployment_state.py:1037`): a named async
actor holding desired deployment specs, reconciling actual replica actors
toward them (spawn / drain+kill / replace-on-failed-health-check), applying
the queue-depth autoscaling policy, and long-poll-pushing a versioned
routing table to routers (`_private/long_poll.py` equivalent via an
asyncio.Condition — our actor RPC already multiplexes concurrent method
calls onto the replica's asyncio loop, so a parked long-poll costs one
coroutine, not a thread).

All blocking cluster calls (ray_tpu.get/wait) run in the default executor
so the reconcile loop never stalls the actor's event loop.
"""

from __future__ import annotations

import asyncio
import functools
import logging
import math
import threading
import time
from typing import Any, Dict, List, Optional, Tuple

from ray_tpu.chaos.deadline import TransitionWatch
from ray_tpu.serve.config import (
    REPLICA_RUNNING,
    REPLICA_STARTING,
    DeploymentConfig,
)
from ray_tpu.tenancy.registry import TenantSpec

logger = logging.getLogger(__name__)

CONTROLLER_NAME = "SERVE_CONTROLLER"
SERVE_NAMESPACE = "serve"


def _user_config_changed(old: Any, new: Any) -> bool:
    """Equality with array-friendly semantics: identical object or a
    cleanly-True comparison means unchanged; anything ambiguous (numpy
    arrays raise on bool()) counts as changed."""
    if old is new:
        return False
    try:
        return not bool(old == new)
    except Exception:  # noqa: BLE001 — ambiguous equality: assume changed
        return True


class _ReplicaInfo:
    def __init__(self, handle, replica_id: str):
        self.handle = handle
        self.replica_id = replica_id
        self.state = REPLICA_STARTING
        self.last_ongoing = 0
        self.started_at = time.time()
        # Last user_config version pushed to this replica (0 = never).
        self.user_config_version = 0
        # Placement, reported by the replica's ping: published in the
        # routing table so routers can prefer co-located replicas.
        self.node_hex = ""
        # Sharded replica groups: the gang behind this logical replica
        # (None for plain single-actor replicas). `handle` is rank 0 —
        # the only endpoint routers ever see; lifecycle ops (ping
        # promotion, health check, stop) treat the gang as one unit.
        self.group = None
        # Model-multiplexed replicas: resident adapter ids, reported by
        # the replica's health stats and pushed in the routing table so
        # routers can prefer replicas that already hold an adapter.
        self.adapters: List[str] = []


class _DeploymentInfo:
    def __init__(self, user_cls, init_args, init_kwargs,
                 config: DeploymentConfig):
        self.user_cls = user_cls
        self.init_args = init_args
        self.init_kwargs = init_kwargs
        self.config = config
        self.replicas: List[_ReplicaInfo] = []
        self.target = config.initial_replicas()
        self.next_replica_seq = 0
        # Checkpoint blob cache: cloudpickle of (cls, args, kwargs, cfg)
        # is invariant between deploys, and re-pickling it for every one
        # of a model zoo's deployments on every replica-set change made
        # checkpointing O(deployments^2) across a zoo bring-up.
        # Invalidated by deploy().
        self.ckpt_blob: Optional[bytes] = None
        # Weight/config broadcast plane: the user_config payload is put in
        # the object store ONCE per version; replicas receive the REF, so
        # N replicas pulling a big payload concurrently form a transfer
        # tree instead of N pickled copies through this actor.
        self.user_config_version = 1 if config.user_config is not None else 0
        self.user_config_ref = None
        # Autoscaling bookkeeping: when pressure/idleness began.
        self.pressure_since: Optional[float] = None
        self.idle_since: Optional[float] = None
        self.last_health_check = 0.0
        # Scale-to-zero: when the last router wake arrived (downscale
        # hysteresis), when the in-flight cold start began, and the last
        # measured cold-start latency (wake -> first RUNNING replica).
        self.last_wake_at = 0.0
        self.cold_start_t0: Optional[float] = None
        self.last_cold_start_ms: Optional[float] = None


class ServeController:
    """Async actor; create with max_concurrency >> 1 (long-polls park)."""

    CKPT_KEY = b"serve:controller_ckpt"

    # Anti-entropy sweep width: each tick additionally scans ~1/N of the
    # parked (inactive) deployments, so a lost dirty mark heals within N
    # ticks while a 200-deployment zoo still costs ~nothing per tick.
    ANTI_ENTROPY_SHARDS = 16

    def __init__(self):
        self._deployments: Dict[str, _DeploymentInfo] = {}
        self._version = 0
        self._routing_table: Dict[str, Any] = {}
        self._shutdown = False
        self._change: Optional[asyncio.Condition] = None
        # Multi-tenant QoS registry (docs/MULTITENANCY.md): named tenants
        # with tier/weight/quotas. Checkpointed with the controller;
        # pushed to proxies inside each owned deployment's routing-table
        # entry. qos_version is PER TENANT (stamped from one monotonic
        # counter): proxies rebuild a tenant's token bucket only when
        # THAT tenant's spec changed — a global version would hand every
        # tenant a full burst of fresh tokens each time any unrelated
        # tenant registered.
        self._tenants: Dict[str, TenantSpec] = {}
        self._tenant_versions: Dict[str, int] = {}
        self._tenant_version = 1
        # Sharded reconciler state: reconcile scans the ACTIVE set (any
        # replicas, nonzero target, or a cold start in flight) plus
        # explicitly DIRTIED names (deploy/delete/wake) plus a rotating
        # anti-entropy shard of the parked majority — tick cost scales
        # with live work, not with how many deployments exist.
        self._dirty: set = set()
        self._active: set = set()
        self._parked_cursor = 0
        self._reconcile_stats: Dict[str, Any] = {
            "ticks": 0, "last_tick_ms": 0.0, "last_scanned": 0,
            "last_parked_skipped": 0, "deployments": 0}
        # Per-node proxy management (reference http_state.py:110): set via
        # set_proxy_config; reconcile keeps one proxy per alive node.
        self._proxy_cfg: Optional[Dict[str, Any]] = None
        self._proxies: Dict[str, Any] = {}   # node hex -> proxy handle
        # Checkpoint IO: one writer thread owns every KV round trip, so no
        # lock is ever held across the RPC (raylint RL002 — the old design
        # issued kv_put under _ckpt_lock, letting a slow GCS hold the
        # teardown path, which shares the lock, hostage for the full RPC
        # timeout). Ordering is latest-wins: a monotonic sequence taken on
        # the loop thread plus a single pending slot — an older payload can
        # never overwrite a newer one because the writer only ever sees the
        # newest snapshot.
        self._ckpt_seq = 0
        self._ckpt_written = 0
        self._ckpt_attempted = 0  # last seq the writer finished (ok or not)
        self._ckpt_lock = threading.Lock()
        self._ckpt_cond = threading.Condition(self._ckpt_lock)
        self._ckpt_pending: Optional[tuple] = None
        self._ckpt_thread: Optional[threading.Thread] = None
        # Writer liveness, flipped ONLY under _ckpt_cond: Thread.is_alive()
        # stays True while the loop is unwinding after it decided to exit,
        # so an enqueue racing that window would see a "live" writer that
        # will never drain its payload.
        self._ckpt_writer_alive = False
        # Recovery-deadline enforcement (chaos_recovery_deadline_s):
        # replica STARTING phases and deployment convergence are tracked
        # transitions — any of them stuck past the deadline fails loudly
        # (attributed critical log + forced replacement + counter in
        # status()) instead of quietly retrying forever. Driven only from
        # the reconcile loop (TransitionWatch is single-threaded).
        self._transitions = TransitionWatch("serve-controller")

    # ------------------------------------------------- checkpoint/recovery

    def _kv(self):
        import ray_tpu

        return ray_tpu._require_runtime().gcs

    def _checkpoint(self) -> None:
        """Durable control-plane state in the GCS KV (reference
        controller.py:75 + kv_store.py:24): enough to rebuild deployments
        and re-adopt live named replicas after a controller crash. The
        snapshot is built on the calling (loop) thread — cheap — but the
        blocking KV round trip runs off-loop so deploys and long-polls
        never stall behind a slow GCS."""
        import pickle

        import cloudpickle

        import dataclasses

        state = {}
        for name, info in self._deployments.items():
            # user_config may be a multi-GB weight pytree (that's the
            # point of the ref-broadcast path) — never re-pickle it into
            # every checkpoint. Post-crash, surviving replicas keep their
            # applied config; pushing it to NEW replicas requires a
            # redeploy (restore() zeroes the version accordingly).
            if info.ckpt_blob is None:
                cfg = info.config
                if cfg.user_config is not None:
                    cfg = dataclasses.replace(cfg, user_config=None)
                info.ckpt_blob = cloudpickle.dumps(
                    (info.user_cls, info.init_args, info.init_kwargs, cfg))
            state[name] = {
                "blob": info.ckpt_blob,
                "target": info.target,
                "next_replica_seq": info.next_replica_seq,
                # Groups are never re-adopted (a gang with a dead owner
                # restarts as a unit); their descriptions are kept so
                # restore can kill stale rank actors and release the pg.
                "replica_ids": [r.replica_id for r in info.replicas
                                if r.group is None],
                "groups": [r.group.describe() for r in info.replicas
                           if r.group is not None],
            }
        payload = pickle.dumps(
            {"deployments": state, "proxy_cfg": self._proxy_cfg,
             "tenants": {n: s.qos() for n, s in self._tenants.items()},
             "tenant_versions": dict(self._tenant_versions),
             "tenant_version": self._tenant_version})
        self._enqueue_ckpt(payload)

    def _enqueue_ckpt(self, payload: Optional[bytes]) -> int:
        """Queue one checkpoint write (None = delete) for the writer
        thread; only the newest snapshot is kept. Returns its sequence
        number so callers can wait for durability."""
        with self._ckpt_cond:
            self._ckpt_seq += 1
            seq = self._ckpt_seq
            self._ckpt_pending = (seq, payload)
            if not self._ckpt_writer_alive:
                self._ckpt_writer_alive = True
                thread = threading.Thread(
                    target=self._ckpt_writer_loop, name="serve-ckpt",
                    daemon=True)
                try:
                    thread.start()
                except BaseException:
                    # start() can fail under thread exhaustion; leaving
                    # alive=True would wedge checkpointing forever (every
                    # later enqueue would see a "live" writer that does
                    # not exist).
                    self._ckpt_writer_alive = False
                    raise
                self._ckpt_thread = thread
            self._ckpt_cond.notify_all()
        return seq

    def _ckpt_writer_loop(self) -> None:
        """Single checkpoint writer: drains the pending slot and issues
        the KV RPC with no lock held — deploys, long-polls and teardown
        never stall behind a slow GCS."""
        try:
            self._ckpt_writer_run()
        finally:
            # Normally the clean-exit path below already flipped this
            # under the cond; the finally covers anything else escaping
            # the loop (e.g. KeyboardInterrupt delivered to this thread)
            # so a dead writer can never keep alive=True and silently
            # stop all future checkpoints. Identity-guarded: after a
            # clean exit a NEW writer may already be registered, and its
            # liveness must not be clobbered by the old thread's unwind.
            with self._ckpt_cond:
                if self._ckpt_thread is threading.current_thread():
                    self._ckpt_writer_alive = False
                    self._ckpt_cond.notify_all()

    def _ckpt_writer_run(self) -> None:
        while True:
            with self._ckpt_cond:
                while self._ckpt_pending is None:
                    if self._shutdown:
                        # Exit decision and liveness flip are atomic under
                        # the cond: a concurrent enqueue either saw
                        # alive=True and its payload is in the pending slot
                        # we just checked, or sees False and starts a
                        # fresh writer.
                        self._ckpt_writer_alive = False
                        return
                    self._ckpt_cond.wait(timeout=1.0)
                seq, payload = self._ckpt_pending
                self._ckpt_pending = None
                if seq <= self._ckpt_written:
                    continue
            try:
                if payload is None:
                    self._kv().call("kv_del", {"key": self.CKPT_KEY})
                else:
                    self._kv().call("kv_put", {"key": self.CKPT_KEY,
                                               "value": payload})
            except Exception:  # noqa: BLE001 — best effort; next change retries
                logger.warning("serve: controller checkpoint failed",
                               exc_info=True)
                with self._ckpt_cond:
                    # Record the attempt and wake waiters even on failure:
                    # _drop_checkpoint's bounded wait must return as soon
                    # as the outcome is known, not burn its full timeout
                    # against a fast-failing (dead) GCS.
                    if seq > self._ckpt_attempted:
                        self._ckpt_attempted = seq
                    self._ckpt_cond.notify_all()
                continue
            with self._ckpt_cond:
                if seq > self._ckpt_written:
                    self._ckpt_written = seq
                if seq > self._ckpt_attempted:
                    self._ckpt_attempted = seq
                self._ckpt_cond.notify_all()

    async def restore(self) -> bool:
        """Rebuild state from the KV checkpoint after a controller death:
        re-adopt replicas that survived (they are detached-named actors),
        let reconcile respawn the rest. Returns True if state was found."""
        import pickle

        import ray_tpu

        try:
            value = self._kv().call("kv_get",
                                    {"key": self.CKPT_KEY})["value"]
        except Exception:  # noqa: BLE001
            return False
        if not value:
            return False
        snap = pickle.loads(value)
        import cloudpickle

        self._tenants = {
            name: TenantSpec(**qos)
            for name, qos in (snap.get("tenants") or {}).items()}
        self._tenant_versions = dict(snap.get("tenant_versions") or {})
        self._tenant_version = snap.get("tenant_version", 1)
        for name, rec in snap.get("deployments", {}).items():
            user_cls, init_args, init_kwargs, config = cloudpickle.loads(
                rec["blob"])
            info = _DeploymentInfo(user_cls, init_args, init_kwargs, config)
            # Seed the blob cache with the exact bytes we just loaded:
            # the first post-restore checkpoint must not re-pickle all N
            # deployments in one tick — recovery is precisely the path
            # the cache exists to protect.
            info.ckpt_blob = rec["blob"]
            info.target = rec["target"]
            info.next_replica_seq = rec["next_replica_seq"]
            for replica_id in rec["replica_ids"]:
                try:
                    handle = ray_tpu.get_actor(
                        f"SERVE_REPLICA::{replica_id}",
                        namespace=SERVE_NAMESPACE)
                except Exception:  # noqa: BLE001 — died with controller
                    continue
                rep = _ReplicaInfo(handle, replica_id)
                rep.state = REPLICA_STARTING  # re-proven by reconcile ping
                info.replicas.append(rep)
            # Stale gangs from the dead controller's tenure: kill every
            # rank and release the placement group — reconcile spawns
            # fresh groups (a gang only ever restarts as a unit, and its
            # group_id/rendezvous must be fresh per incarnation).
            for desc in rec.get("groups", ()):
                _cleanup_stale_group(desc)
            self._deployments[name] = info
            # One post-restore sweep per deployment (classification +
            # re-proving re-adopted replicas); parked deployments then
            # leave the scan set until woken. Restore itself stays
            # bounded: no pings, no spawns — reconcile owns both.
            self._dirty.add(name)
            if rec["replica_ids"] or rec.get("groups") or info.target:
                logger.info("serve: restored deployment %s (re-adopted "
                            "%d/%d replicas)", name, len(info.replicas),
                            len(rec["replica_ids"]))
        self._proxy_cfg = snap.get("proxy_cfg")
        self._rebuild_routing_table()
        return True

    def _drop_checkpoint(self) -> None:
        # The delete takes a sequence number past every queued write, so a
        # stale snapshot landing after it can never resurrect torn-down
        # deployments on the next controller restart. Best-effort bounded
        # wait for durability: teardown should not return with the delete
        # still queued, but a dead GCS must not hang it either.
        seq = self._enqueue_ckpt(None)
        with self._ckpt_cond:
            self._ckpt_cond.wait_for(lambda: self._ckpt_attempted >= seq,
                                     timeout=5.0)

    # ---------------------------------------------------------------- API
    # All public methods are async so every mutation runs on the actor's
    # single event loop — no cross-thread races with the reconcile task.

    async def deploy(self, name: str, user_cls, init_args, init_kwargs,
                     config: DeploymentConfig) -> None:
        if config.tenant and config.tenant not in self._tenants:
            raise ValueError(
                f"deployment {name!r} names unregistered tenant "
                f"{config.tenant!r} — serve.register_tenant() it first")
        info = self._deployments.get(name)
        if info is None:
            self._deployments[name] = _DeploymentInfo(
                user_cls, init_args, init_kwargs, config)
        else:
            # Config-only update (replica count, concurrency); new code or
            # args means new replicas — drain all and let reconcile respawn.
            changed_code = (user_cls is not info.user_cls
                            or init_args != info.init_args
                            or init_kwargs != info.init_kwargs)
            old_user_config = info.config.user_config
            info.user_cls = user_cls
            info.init_args = init_args
            info.init_kwargs = init_kwargs
            info.config = config
            info.target = config.initial_replicas()
            if config.user_config is not None and _user_config_changed(
                    old_user_config, config.user_config):
                # New payload version: re-put lazily and re-push to every
                # replica (running ones via reconfigure, new ones on
                # promotion) — live weight updates without a restart. An
                # unchanged payload (a redeploy that only moved replica
                # counts) is NOT re-pushed.
                info.user_config_version += 1
                info.user_config_ref = None
            if changed_code:
                for rep in info.replicas:
                    self._stop_replica(rep)
                info.replicas = []
            info.ckpt_blob = None   # cls/args/config may all have moved
        # Config-only updates (route_prefix, max_concurrent_queries) must
        # reach routers even when the replica set doesn't change.
        self._dirty.add(name)
        self._publish_entry(name)
        self._bump()
        self._checkpoint()
        logger.info("serve: deployed %s (target=%d)", name,
                    self._deployments[name].target)

    async def delete(self, name: str) -> None:
        info = self._deployments.pop(name, None)
        if info is not None:
            for rep in info.replicas:
                self._stop_replica(rep)
            self._dirty.discard(name)
            self._active.discard(name)
            self._routing_table.pop(name, None)
            self._bump()
            self._checkpoint()

    # ---------------------------------------------------------- tenants

    async def register_tenant(self, qos: Dict[str, Any]) -> None:
        """Create or update a tenant (serve.register_tenant). Updates
        re-push every owned deployment's entry with a bumped qos_version
        so proxies rebuild their local buckets."""
        spec = TenantSpec(**qos)
        self._tenants[spec.name] = spec
        self._tenant_version += 1
        self._tenant_versions[spec.name] = self._tenant_version
        republished = False
        for name, info in self._deployments.items():
            if info.config.tenant == spec.name:
                self._publish_entry(name)
                republished = True
        if republished:
            self._bump()
        self._checkpoint()
        logger.info("serve: tenant %s registered (tier=%s weight=%d "
                    "rps=%g inflight=%d)", spec.name, spec.tier,
                    spec.weight, spec.rps_limit, spec.max_inflight)

    async def unregister_tenant(self, name: str) -> None:
        owned = [d for d, info in self._deployments.items()
                 if info.config.tenant == name]
        if owned:
            raise ValueError(
                f"tenant {name!r} still owns deployments {sorted(owned)} "
                "— delete them first")
        if self._tenants.pop(name, None) is not None:
            self._tenant_versions.pop(name, None)
            self._checkpoint()

    async def tenants(self) -> Dict[str, Dict[str, Any]]:
        return {name: spec.qos() for name, spec in self._tenants.items()}

    async def reconcile_stats(self) -> Dict[str, Any]:
        """Reconciler introspection (bench_zoo's sublinearity proof):
        last tick wall time, how many deployments it actually scanned,
        and how many parked ones it skipped."""
        return dict(self._reconcile_stats,
                    active=len(self._active),
                    deployments=len(self._deployments))

    async def wait_ready(self, name: str, timeout_s: float = 60.0) -> bool:
        deadline = time.time() + timeout_s
        while time.time() < deadline:
            info = self._deployments.get(name)
            if info is not None:
                running = sum(1 for r in info.replicas
                              if r.state == REPLICA_RUNNING)
                # Autoscaled deployments are ready at one replica; fixed
                # deployments wait for the full target; scale-to-zero
                # (min_replicas=0) deployments deploy parked — ready with
                # zero replicas, the first request cold-starts one.
                auto = info.config.autoscaling
                if auto is not None:
                    need = 0 if auto.min_replicas == 0 else 1
                else:
                    need = info.target
                if running >= need:
                    return True
            await asyncio.sleep(0.05)
        return False

    async def wake_deployment(self, name: str) -> bool:
        """Scale-to-zero wake: a router saw a request for a parked
        deployment. Spawns the first replica IMMEDIATELY (not on the next
        reconcile tick — every tick is ~100ms of cold-start budget) and
        arms the downscale hysteresis so the autoscaler cannot re-park
        the deployment before the buffered request lands."""
        info = self._deployments.get(name)
        if info is None:
            return False
        info.last_wake_at = time.time()
        info.idle_since = None
        if info.target < 1:
            info.target = 1
        # A woken deployment re-enters the reconcile scan set NOW — the
        # sharded reconciler skips parked deployments, and the cold
        # start's STARTING->RUNNING promotion must not wait for the
        # anti-entropy sweep to rediscover it.
        self._dirty.add(name)
        self._active.add(name)
        if not info.replicas:
            if info.cold_start_t0 is None:
                info.cold_start_t0 = time.time()
            logger.info("serve: waking %s (scale-to-zero cold start)", name)
            info.replicas.append(self._start_replica(name, info))
            self._checkpoint()
        return True

    async def get_routing_table(self) -> tuple:
        return self._version, self._routing_table

    async def listen_for_change(self, known_version: int,
                                timeout_s: float = 30.0) -> tuple:
        """Long-poll: parks until the routing table moves past
        known_version (or times out, returning the current view)."""
        if self._change is None:
            self._change = asyncio.Condition()
        deadline = time.time() + timeout_s
        async with self._change:
            while self._version <= known_version and not self._shutdown:
                remaining = deadline - time.time()
                if remaining <= 0:
                    break
                try:
                    await asyncio.wait_for(self._change.wait(),
                                           timeout=remaining)
                except asyncio.TimeoutError:
                    break
        return self._version, self._routing_table

    async def status(self) -> Dict[str, Any]:
        out = {}
        for name, info in self._deployments.items():
            out[name] = {
                "target": info.target,
                "replicas": {
                    r.replica_id: r.state for r in info.replicas},
                "ongoing": sum(r.last_ongoing for r in info.replicas),
                "cold_start_ms": info.last_cold_start_ms,
                "stuck_transitions": self._transitions.stuck_total,
            }
            if info.config.tenant:
                out[name]["tenant"] = info.config.tenant
            if info.config.shard_spec is not None:
                spec = info.config.shard_spec
                out[name]["shard"] = {"world_size": spec.world_size,
                                      "tp": spec.tp}
        return out

    async def graceful_shutdown(self) -> None:
        self._shutdown = True
        import ray_tpu

        for info in self._deployments.values():
            for rep in info.replicas:
                self._stop_replica(rep)
        self._deployments.clear()
        self._dirty.clear()
        self._active.clear()
        self._tenants.clear()
        self._tenant_versions.clear()
        self._routing_table = {}
        for handle in self._proxies.values():
            try:
                ray_tpu.kill(handle)
            except Exception:  # noqa: BLE001
                pass
        self._proxies.clear()
        self._drop_checkpoint()
        self._bump()
        del ray_tpu

    # ----------------------------------------------------------- reconcile

    async def reconcile_forever(self, period_s: float = 0.1) -> None:
        proxy_tick = 0.0
        while not self._shutdown:
            try:
                await self._reconcile_once()
            except Exception:  # noqa: BLE001 — the loop must survive
                logger.exception("serve reconcile error")
            if self._proxy_cfg is not None and \
                    time.time() - proxy_tick >= 1.0:
                proxy_tick = time.time()
                try:
                    await self._reconcile_proxies()
                except Exception:  # noqa: BLE001
                    logger.exception("serve proxy reconcile error")
            await asyncio.sleep(period_s)

    # ------------------------------------------------------ proxy management

    async def set_proxy_config(self, host: str, port: int,
                               every_node: bool) -> None:
        """Controller-managed HTTP proxies (reference http_state.py:110
        HTTPProxyStateManager): one per alive node (every_node) or head
        only, health-checked and replaced on death."""
        self._proxy_cfg = {"host": host, "port": port,
                           "every_node": every_node}
        self._checkpoint()
        await self._reconcile_proxies()

    async def proxy_status(self) -> Dict[str, Any]:
        import ray_tpu

        loop = asyncio.get_running_loop()
        out = {}
        for node_hex, handle in list(self._proxies.items()):
            port = await loop.run_in_executor(
                None, functools.partial(_try_proxy_port, handle))
            out[node_hex] = {"alive": port is not None, "port": port}
        del ray_tpu
        return out

    async def _reconcile_proxies(self) -> None:
        import ray_tpu
        from ray_tpu.serve.proxy import HTTPProxy
        from ray_tpu.util.scheduling_strategies import (
            NodeAffinitySchedulingStrategy,
        )

        cfg = self._proxy_cfg
        nodes = [n for n in ray_tpu.nodes() if n["Alive"]]
        node_ip = {n["NodeID"]: n.get("NodeManagerAddress", "")
                   for n in nodes}
        if cfg["every_node"]:
            want = {n["NodeID"] for n in nodes}
        else:
            want = {n["NodeID"] for n in nodes if n.get("IsHead")} or \
                {nodes[0]["NodeID"]} if nodes else set()
        loop = asyncio.get_running_loop()
        # Health-check managed proxies; drop the dead and the unwanted.
        for node_hex, handle in list(self._proxies.items()):
            if node_hex not in want:
                try:
                    ray_tpu.kill(handle)
                except Exception:  # noqa: BLE001
                    pass
                self._proxies.pop(node_hex, None)
                continue
            port = await loop.run_in_executor(
                None, functools.partial(_try_proxy_port, handle))
            if port is None:
                logger.warning("serve: proxy on node %s died — replacing",
                               node_hex[:12])
                self._proxies.pop(node_hex, None)
        # The configured port binds once PER HOST: on a real multi-host
        # cluster every node's proxy listens on cfg["port"]; in the
        # in-process sim (all "nodes" share one IP) only the first proxy
        # on that IP gets it and the rest fall back to ephemeral ports.
        ips_with_cfg_port = {node_ip.get(nh) for nh in self._proxies}
        for node_hex in sorted(want - set(self._proxies),
                               key=lambda nh: (node_ip.get(nh, ""), nh)):
            try:
                existing = ray_tpu.get_actor(
                    f"SERVE_PROXY::{node_hex[:16]}",
                    namespace=SERVE_NAMESPACE)
                self._proxies[node_hex] = existing
                ips_with_cfg_port.add(node_ip.get(node_hex))
                continue
            except Exception:  # noqa: BLE001 — create fresh
                pass
            ip = node_ip.get(node_hex)
            port = cfg["port"] if ip not in ips_with_cfg_port else 0
            ips_with_cfg_port.add(ip)
            handle = ray_tpu.remote(HTTPProxy).options(
                name=f"SERVE_PROXY::{node_hex[:16]}",
                namespace=SERVE_NAMESPACE,
                lifetime="detached", max_concurrency=256, num_cpus=0.01,
                scheduling_strategy=NodeAffinitySchedulingStrategy(
                    node_id=node_hex),
            ).remote(cfg["host"], port)
            self._proxies[node_hex] = handle
            logger.info("serve: started proxy on node %s (port %s)",
                        node_hex[:12], port or "ephemeral")

    @staticmethod
    def _is_active(info: _DeploymentInfo) -> bool:
        """Whether a deployment needs per-tick reconcile work. Parked
        (scale-to-zero at zero replicas, target 0, no cold start in
        flight) deployments have nothing time-driven to do — wake/deploy
        /delete all dirty them explicitly."""
        return bool(info.replicas or info.target > 0
                    or info.cold_start_t0 is not None)

    def _scan_set(self) -> Tuple[list, int]:
        """Names to reconcile this tick: every active deployment, every
        dirtied one, plus a rotating anti-entropy shard of the parked
        majority (a lost dirty mark heals within ANTI_ENTROPY_SHARDS
        ticks instead of never). Returns (names, parked_skipped)."""
        dirty, self._dirty = self._dirty, set()
        scan = [n for n in self._deployments
                if n in self._active or n in dirty]
        parked = [n for n in self._deployments
                  if n not in self._active and n not in dirty]
        take = -(-len(parked) // self.ANTI_ENTROPY_SHARDS) if parked else 0
        for i in range(take):
            scan.append(parked[(self._parked_cursor + i) % len(parked)])
        self._parked_cursor += take
        return scan, len(parked) - take

    async def _reconcile_once(self) -> None:
        loop = asyncio.get_running_loop()
        t0 = time.perf_counter()
        tracked_keys = set()
        publish: set = set()
        any_changed = False
        scan, parked_skipped = self._scan_set()
        for name in scan:
            info = self._deployments.get(name)
            if info is None:
                continue  # deleted between dirtying and this tick
            changed, depths_moved = await self._reconcile_deployment(
                loop, name, info, tracked_keys)
            if changed:
                any_changed = True
            if changed or depths_moved:
                publish.add(name)
            # Re-classify for the next tick's scan set.
            if self._is_active(info):
                self._active.add(name)
            else:
                self._active.discard(name)

        # Prune transitions whose subject completed or vanished this tick,
        # then enforce the deadline: a stuck replica is force-replaced
        # (reconcile respawns it), a stuck deployment is counted and
        # re-armed — both land in status()["stuck_transitions"] and a
        # CRITICAL log with the stuck state attributed. Transitions only
        # ever belong to ACTIVE deployments, which every tick scans, so
        # the sharded scan cannot mis-prune a parked deployment's state.
        self._transitions.prune(tracked_keys)
        for key, state, elapsed in self._transitions.fail_stuck():
            for name in list(self._active):
                info = self._deployments.get(name)
                if info is None:
                    continue
                for rep in list(info.replicas):
                    if rep.replica_id == key:
                        self._stop_replica(rep, graceful=False)
                        info.replicas.remove(rep)
                        any_changed = True
                        publish.add(name)

        if publish:
            for name in publish:
                self._publish_entry(name)
            # Depth-only changes bump the version without a checkpoint
            # (routers never poll per-request); membership moves below
            # also checkpoint so recovery stays current.
            self._bump()
        if any_changed:
            self._checkpoint()
        stats = self._reconcile_stats
        stats["ticks"] += 1
        stats["last_tick_ms"] = round((time.perf_counter() - t0) * 1e3, 3)
        stats["last_scanned"] = len(scan)
        stats["last_parked_skipped"] = parked_skipped
        stats["deployments"] = len(self._deployments)

    async def _reconcile_deployment(self, loop, name: str,
                                    info: _DeploymentInfo,
                                    tracked_keys: set) -> Tuple[bool, bool]:
        """One deployment's reconcile step (the body of the old
        monolithic loop): promote/cull STARTING replicas, push
        user_config, health-check, autoscale, converge toward target.
        Returns (membership_changed, depths_moved)."""
        changed = False
        depths_moved = False
        # 1. Promote STARTING replicas that answer ping; cull ones that
        # died in __init__ (ping resolves to an actor error) or never
        # came up within the startup timeout.
        for rep in [r for r in info.replicas
                    if r.state == REPLICA_STARTING]:
            state, node = await loop.run_in_executor(
                None, functools.partial(_try_ping_replica, rep, 0.05))
            if state == "ok":
                if node:
                    rep.node_hex = node
                # Deliver the current user_config BEFORE the replica
                # becomes routable: a request must never reach user
                # code whose reconfigure(weights) hasn't run. A failed
                # push leaves it STARTING (retried next tick until the
                # startup timeout below replaces it).
                needs_cfg = (info.user_config_version
                             and info.config.user_config is not None
                             and rep.user_config_version
                             < info.user_config_version)
                if not needs_cfg or await self._push_user_config(
                        loop, info, rep):
                    rep.state = REPLICA_RUNNING
                    changed = True
                    if info.cold_start_t0 is not None:
                        info.last_cold_start_ms = round(
                            (time.time() - info.cold_start_t0) * 1e3, 1)
                        info.cold_start_t0 = None
                        logger.info(
                            "serve: %s cold start served in %.0fms",
                            name, info.last_cold_start_ms)
            if rep.state == REPLICA_STARTING and (
                    state == "dead"
                    or time.time() - rep.started_at
                    > info.config.replica_startup_timeout_s):
                logger.warning(
                    "serve: replica %s of %s failed to start — "
                    "replacing", rep.replica_id, name)
                self._stop_replica(rep, graceful=False)
                info.replicas.remove(rep)
                changed = True

        # 1.5 Weight/config broadcast: push the current user_config to
        # RUNNING replicas behind on it (a live update bumped the
        # version). The payload lives in the object store once per
        # version; each replica receives the REF as its reconfigure
        # argument and pulls the bytes over the transfer plane
        # (concurrent replicas self-organize into a tree there — the
        # controller never re-pickles the payload per replica).
        if info.user_config_version and info.config.user_config is not None:
            behind = [r for r in info.replicas
                      if r.state == REPLICA_RUNNING
                      and r.user_config_version < info.user_config_version]
            if behind:
                # Materialize the ref BEFORE fanning out: concurrent
                # pushes racing the first put would each serialize
                # their own copy of the payload.
                await self._ensure_user_config_ref(loop, info)
                await asyncio.gather(
                    *(self._push_user_config(loop, info, rep)
                      for rep in behind))

        # 2. Health-check RUNNING replicas; replace the dead.
        if (time.time() - info.last_health_check
                >= info.config.health_check_period_s):
            info.last_health_check = time.time()
            stats = await loop.run_in_executor(
                None, functools.partial(_gather_stats, info.replicas))
            dead = []
            for rep, st in zip(list(info.replicas), stats):
                if rep.state != REPLICA_RUNNING:
                    continue
                if st is None:
                    dead.append(rep)
                else:
                    # Deployment-exported backlog (__serve_metrics__,
                    # e.g. the inference engine's queued + running
                    # sequences) counts as pressure: streamed
                    # generations leave `ongoing` as soon as the
                    # stream marker returns, so the engine's own
                    # counts are the only saturation signal for them.
                    # max() against ongoing, not sum — a unary
                    # generate() is BOTH an ongoing RPC and an engine
                    # request, and adding them would double-count it.
                    user = st.get("user") or {}

                    def _n(key):
                        try:
                            return int(user.get(key, 0) or 0)
                        except (TypeError, ValueError):
                            return 0

                    new_load = max(
                        st.get("ongoing", 0),
                        _n("queue_depth") + _n("running"))
                    if new_load != rep.last_ongoing:
                        depths_moved = True
                    rep.last_ongoing = new_load
                    if st.get("node"):
                        rep.node_hex = st["node"]
                    # Model-multiplexed replicas report resident
                    # adapters; pushed in the table so routers can
                    # prefer a replica that already holds one.
                    adapters = user.get("adapters")
                    if adapters is not None:
                        adapters = [str(a) for a in adapters]
                        if adapters != rep.adapters:
                            rep.adapters = adapters
                            depths_moved = True
            for rep in dead:
                logger.warning("serve: replica %s of %s failed health "
                               "check — replacing", rep.replica_id, name)
                self._stop_replica(rep, graceful=False)
                info.replicas.remove(rep)
                changed = True

        # 3. Autoscaling decision.
        if info.config.autoscaling is not None:
            new_target = self._autoscale_decision(info)
            if new_target != info.target:
                logger.info("serve: autoscaling %s %d -> %d",
                            name, info.target, new_target)
                info.target = new_target

        # 4. Converge replica count toward target.
        live = [r for r in info.replicas]
        if len(live) < info.target:
            for _ in range(info.target - len(live)):
                info.replicas.append(self._start_replica(name, info))
            changed = True
        elif len(live) > info.target:
            # Drain the newest first (stable prefix keeps warm caches).
            excess = live[info.target:]
            for rep in excess:
                self._stop_replica(rep)
                info.replicas.remove(rep)
            changed = True

        # 5. Recovery-deadline tracking: every STARTING replica and
        # the deployment's convergence toward target are in-flight
        # transitions; anything stuck past chaos_recovery_deadline_s
        # is failed loudly below (attributed), never left to spin.
        running_n = sum(1 for r in info.replicas
                        if r.state == REPLICA_RUNNING)
        for rep in info.replicas:
            if rep.state == REPLICA_STARTING:
                self._transitions.enter(rep.replica_id, "STARTING")
                tracked_keys.add(rep.replica_id)
        if running_n < info.target:
            key = f"deployment:{name}"
            self._transitions.enter(
                key, f"converging({running_n}/{info.target})")
            tracked_keys.add(key)
        return changed, depths_moved

    async def _ensure_user_config_ref(self, loop, info: _DeploymentInfo):
        """Put the payload ONCE per version, serially — concurrent
        _push_user_config coroutines must never each put their own copy."""
        import ray_tpu

        if info.user_config_ref is None:
            info.user_config_ref = await loop.run_in_executor(
                None, ray_tpu.put, info.config.user_config)

    async def _push_user_config(self, loop, info: _DeploymentInfo,
                                rep: _ReplicaInfo) -> bool:
        """Deliver the current user_config version to one replica and
        AWAIT its reconfigure hook: the version is only marked applied on
        success, so failures are retried next tick instead of silently
        leaving the replica on stale config."""
        import ray_tpu

        await self._ensure_user_config_ref(loop, info)
        version = info.user_config_version
        try:
            ref = rep.handle.reconfigure.remote(info.user_config_ref)
            await loop.run_in_executor(
                None, functools.partial(ray_tpu.get, ref, timeout=60.0))
        except Exception:  # noqa: BLE001 — user hook raised or replica died
            logger.warning("serve: reconfigure of replica %s failed",
                           rep.replica_id, exc_info=True)
            return False
        rep.user_config_version = version
        return True

    def _autoscale_decision(self, info: _DeploymentInfo) -> int:
        cfg = info.config.autoscaling
        running = [r for r in info.replicas if r.state == REPLICA_RUNNING]
        if not running:
            # Parked (scale-to-zero) or mid cold start: wake_deployment
            # owns upscale from zero; there is no load signal to act on.
            return info.target
        total_ongoing = sum(r.last_ongoing for r in running)
        desired = math.ceil(total_ongoing / cfg.target_ongoing_requests) \
            if total_ongoing else cfg.min_replicas
        desired = min(max(desired, cfg.min_replicas), cfg.max_replicas)
        now = time.time()
        if desired > info.target:
            info.idle_since = None
            if info.pressure_since is None:
                info.pressure_since = now
            if now - info.pressure_since >= cfg.upscale_delay_s:
                info.pressure_since = None
                return desired
        elif desired < info.target:
            info.pressure_since = None
            if info.idle_since is None:
                info.idle_since = now
            if now - info.idle_since >= cfg.downscale_delay_s:
                if desired == 0 and now - info.last_wake_at < max(
                        cfg.downscale_delay_s, 1.0):
                    # Wake hysteresis: a cold start is (or just was) in
                    # flight — parking now would strand the request that
                    # triggered it in a wake/park livelock.
                    return info.target
                info.idle_since = None
                return desired
        else:
            info.pressure_since = None
            info.idle_since = None
        return info.target

    # ------------------------------------------------------------- helpers

    def _start_replica(self, name: str, info: _DeploymentInfo):
        import ray_tpu
        from ray_tpu.serve.replica import Replica

        if info.config.shard_spec is not None:
            return self._start_replica_group(name, info)
        replica_id = f"{name}#{info.next_replica_seq}"
        info.next_replica_seq += 1
        opts = dict(info.config.ray_actor_options)
        opts.setdefault("num_cpus", 0.1)
        opts["max_concurrency"] = info.config.max_concurrent_queries + 8
        opts["name"] = f"SERVE_REPLICA::{replica_id}"
        opts["namespace"] = SERVE_NAMESPACE
        actor_cls = ray_tpu.remote(Replica)
        handle = actor_cls.options(**opts).remote(
            name, info.user_cls, info.init_args, info.init_kwargs,
            replica_id)
        logger.info("serve: starting replica %s", replica_id)
        return _ReplicaInfo(handle, replica_id)

    def _start_replica_group(self, name: str, info: _DeploymentInfo):
        """One logical replica = one gang: shard_spec.world_size rank
        actors on a fresh placement group. Rank 0 keeps the plain
        replica's name (SERVE_REPLICA::<id>) so routing, the dataplane
        and by-name test hooks are oblivious; ranks > 0 are
        SERVE_RANK::<id>#r<k>. Creation is non-blocking (wait_ready=
        False): the STARTING->RUNNING ping loop owns promotion, and a
        rank that never comes up trips the startup timeout, which stops
        the whole gang (all-or-nothing by way of the lifecycle)."""
        from ray_tpu.serve.replica import Replica
        from ray_tpu.shardgroup import create_gang

        spec = info.config.shard_spec
        replica_id = f"{name}#{info.next_replica_seq}"
        info.next_replica_seq += 1
        base_opts = dict(info.config.ray_actor_options)
        base_opts.setdefault("num_cpus", 0.05)
        base_opts["max_concurrency"] = info.config.max_concurrent_queries + 8
        base_opts["namespace"] = SERVE_NAMESPACE

        def rank_options(rank: int):
            opts = dict(base_opts)
            opts["name"] = (f"SERVE_REPLICA::{replica_id}" if rank == 0
                            else f"SERVE_RANK::{replica_id}#r{rank}")
            return opts

        def rank_args(rank: int):
            ctx = {"group_id": replica_id, "rank": rank,
                   "world_size": spec.world_size, "tp": spec.tp,
                   "spmd": spec.world_size > 1}
            return ((name, info.user_cls, info.init_args,
                     info.init_kwargs, replica_id), {"shard_ctx": ctx})

        group = create_gang(
            Replica, spec, group_id=replica_id,
            bundle=spec.rank_bundle(base_opts),
            rank_options=rank_options, rank_args=rank_args,
            wait_ready=False)
        logger.info("serve: starting replica group %s (world=%d, tp=%d)",
                    replica_id, spec.world_size, spec.tp)
        rep = _ReplicaInfo(group.handle, replica_id)
        rep.group = group
        return rep

    def _stop_replica(self, rep: _ReplicaInfo, graceful: bool = True):
        import ray_tpu

        rep.state = "STOPPING"
        if rep.group is not None:
            # Gangs die as a unit: every rank AND the placement group
            # (bundle release) — a half-alive gang is never left behind.
            rep.group.kill(graceful_timeout_s=1.0 if graceful else 0.0)
            return
        try:
            if graceful:
                rep.handle.prepare_shutdown.remote(1.0)
            ray_tpu.kill(rep.handle)
        except Exception:  # noqa: BLE001 — already dead is fine
            pass

    def _publish_entry(self, name: str) -> None:
        """(Re)build ONE deployment's routing-table entry in place —
        with a zoo of mostly-parked deployments, rebuilding all N
        entries because one replica's depth moved made every push
        O(deployments). The caller owns the version bump."""
        info = self._deployments.get(name)
        if info is None:
            self._routing_table.pop(name, None)
            return
        running = [r for r in info.replicas
                   if r.state == REPLICA_RUNNING]
        prefix = info.config.route_prefix or f"/{name}"
        auto = info.config.autoscaling
        entry = {
            "replicas": [(r.replica_id, r.handle) for r in running],
            "max_concurrent_queries":
                info.config.max_concurrent_queries,
            "route_prefix": prefix,
            # Placement + depth piggyback for the routers' locality /
            # power-of-two-choices pick (pushed, never polled).
            "nodes": {r.replica_id: r.node_hex for r in running
                      if r.node_hex},
            "depths": {r.replica_id: r.last_ongoing for r in running},
            # Scale-to-zero marker: an empty replica list means "wake
            # me", not "unknown deployment".
            "parked": bool(auto is not None and auto.min_replicas == 0
                           and not running),
        }
        # Tenant QoS piggyback: proxies enforce quotas/WFQ off the
        # pushed entry (tenancy/admission.py), never a per-request RPC.
        tenant = info.config.tenant
        if tenant and tenant in self._tenants:
            entry["tenant"] = tenant
            entry["qos"] = self._tenants[tenant].qos()
            entry["qos_version"] = self._tenant_versions.get(tenant, 1)
        # Adapter residency (model-multiplexed replicas): lets the
        # router prefer a replica that already holds the request's
        # model_id (avoids a load+evict on every dispatch).
        adapters = {r.replica_id: r.adapters for r in running
                    if r.adapters}
        if adapters:
            entry["adapters"] = adapters
            entry["mux"] = True
        self._routing_table[name] = entry

    def _rebuild_routing_table(self) -> None:
        """Full rebuild + bump (restore / teardown); steady-state paths
        publish single entries and bump once per batch."""
        for name in list(self._routing_table):
            if name not in self._deployments:
                self._routing_table.pop(name, None)
        for name in self._deployments:
            self._publish_entry(name)
        self._bump()

    def _bump(self) -> None:
        self._version += 1
        if self._change is not None:
            async def notify():
                async with self._change:
                    self._change.notify_all()
            try:
                asyncio.get_running_loop().create_task(notify())
            except RuntimeError:
                pass  # called outside the loop (sync method): next bump


def _try_proxy_port(handle) -> Optional[int]:
    """The proxy's bound port, or None when it is dead/unreachable."""
    import ray_tpu

    try:
        return ray_tpu.get(handle.ready.remote(), timeout=5.0)
    except Exception:  # noqa: BLE001
        return None


def _cleanup_stale_group(desc: Dict[str, Any]) -> None:
    """Tear down a gang recorded in a dead controller's checkpoint:
    best-effort kill of every rank actor by name, then release the
    placement group's bundles."""
    import ray_tpu
    from ray_tpu.core.ids import PlacementGroupID
    from ray_tpu.util.placement_group import (
        PlacementGroup,
        remove_placement_group,
    )

    for rank_name in desc.get("rank_names", ()):
        try:
            ray_tpu.kill(ray_tpu.get_actor(rank_name,
                                           namespace=SERVE_NAMESPACE))
        except Exception:  # noqa: BLE001 — died with the controller
            pass
    if desc.get("pg_id"):
        try:
            remove_placement_group(PlacementGroup(
                PlacementGroupID.from_hex(desc["pg_id"]),
                desc.get("bundles") or [], desc.get("strategy") or "PACK"))
        except Exception:  # noqa: BLE001 — already removed
            logger.debug("serve: stale group pg removal failed",
                         exc_info=True)


def _try_ping_replica(rep: _ReplicaInfo, timeout_s: float) -> tuple:
    """Group-aware STARTING probe: a plain replica is its own ping; a
    gang is "ok" only when EVERY rank answers (coordinated mesh bring-up
    finished everywhere), "dead" as soon as ANY rank died — the startup
    path then stops the whole gang (all-or-nothing), releasing its
    placement group."""
    if rep.group is None:
        return _try_ping(rep.handle, timeout_s)
    state, node = _try_ping(rep.handle, timeout_s)
    if state == "dead":
        return "dead", ""
    # Rank 0 was just probed (it carries the node id); sweep only the
    # other ranks so each STARTING tick costs world_size pings, not
    # world_size + 1.
    statuses = rep.group.ping_all(
        timeout_s=timeout_s, indices=range(1, rep.group.world_size))
    if any(s == "dead" for s in statuses):
        return "dead", ""
    if state == "ok" and all(s == "ok" for s in statuses):
        return "ok", node
    return "pending", node


def _try_ping(handle, timeout_s: float) -> tuple:
    """Returns ("ok" | "pending" | "dead", node_hex) — a resolved-but-
    errored ping is a dead replica, not a slow one. The node id rides the
    ping so placement reaches the routing table with no extra RPC."""
    import ray_tpu

    # Never SUBMIT to a not-yet-ALIVE actor: submission resolves the
    # address via a blocking wait_for_actor, so one replica wedged in its
    # __init__ would park the whole reconcile loop — and the stuck-state
    # enforcement that exists to catch exactly that could never run.
    liveness = ray_tpu._require_runtime().actor_liveness(handle._actor_id)
    if liveness != "alive":
        return ("dead" if liveness == "dead" else "pending"), ""
    try:
        ref = handle.ping.remote()
        ready, _ = ray_tpu.wait([ref], num_returns=1, timeout=timeout_s)
        if not ready:
            return "pending", ""
        out = ray_tpu.get(ready[0])
        node = out.get("node", "") if isinstance(out, dict) else ""
        return "ok", node
    except Exception:  # noqa: BLE001
        return "dead", ""


def _gather_stats(replicas) -> list:
    import ray_tpu

    runtime = ray_tpu._require_runtime()
    refs, out = [], []
    for rep in replicas:
        # Only RUNNING replicas are probed, and only via a non-blocking
        # liveness check first: submitting to a not-ALIVE actor blocks on
        # address resolution, and one wedged replica would park the whole
        # reconcile loop (non-RUNNING entries get a None placeholder the
        # consumer's state check skips).
        if rep.state != REPLICA_RUNNING or \
                runtime.actor_liveness(rep.handle._actor_id) != "alive":
            refs.append(None)
            continue
        try:
            refs.append(rep.handle.stats.remote())
        except Exception:  # noqa: BLE001
            refs.append(None)
    for ref in refs:
        if ref is None:
            out.append(None)
            continue
        try:
            out.append(ray_tpu.get(ref, timeout=1.0))
        except Exception:  # noqa: BLE001
            out.append(None)
    # Gang liveness rides the same health check: a group whose rank 0
    # still answers but whose rank k died reports as DEAD — the
    # controller then kills and restarts the gang as one unit (any rank
    # death is a group death; docs/SHARDED.md failure semantics).
    for i, rep in enumerate(replicas):
        if out[i] is not None and rep.group is not None:
            # Rank 0 already answered stats above — sweep only ranks > 0.
            if rep.group.dead_ranks(timeout_s=1.0,
                                    indices=range(1, rep.group.world_size)):
                out[i] = None
    return out
