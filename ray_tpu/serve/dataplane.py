"""Serve fast data plane: raw-bytes frames, coalescing, direct routing.

The classic serve path pays, per request: pickle framing of args and
result, an executor hop for admission, and one RPC wakeup per request on
the replica. This module is the proxy half of the fast path that removes
all three (ISSUE 8 / ROADMAP item 1):

- **Zero-copy frames.** Request/response bodies travel as raw bytes on
  the worker's direct RPC server (``serve_raw``/``serve_stream`` raw
  methods) — no pickle of bodies anywhere on the path. A frame is
  ``[4B LE meta length][msgpack meta][bodies...]``; the msgpack meta
  lists per-request entries, each with its body length ``n``, so bodies
  are sliced out of the received buffer as memoryviews.
- **Connection-level coalescing.** Concurrent requests to the same
  replica that land in the same event-loop tick ride ONE frame (one
  send, one replica wakeup) and their responses come back in one reply
  frame — `@serve.batch` on the replica forms its gang batch from a
  single wakeup instead of N.
- **Locality-aware direct routing.** Replica choice (Router._pick)
  prefers a co-located replica and falls back to power-of-two-choices by
  pushed queue depth; the fast lane dispatches straight to the chosen
  replica's direct server (`serve.direct` span).
- **Retry-once on replica death.** A frame lost to a dead connection (or
  a per-request `retriable` error, e.g. a draining replica) re-routes
  each affected request to a different replica exactly once; a second
  loss surfaces as ConnectionError. Note the documented at-least-once
  caveat: a request lost AFTER delivery may have executed.
- **Scale-to-zero buffering.** Requests for a parked (0-replica)
  deployment wake the controller and wait buffered at the proxy, bounded
  by ``serve_park_max_bytes`` / ``serve_park_timeout_s``, then dispatch
  normally once the cold-started replica lands in the routing table
  (`serve.coldstart` span).

Frame meta schema (request): ``{"v": 1, "reqs": [entry, ...]}`` where an
entry is ``{"k": "http"|"call", "n": body_len, ...}`` (http: ``m`` method,
``p`` path, ``rp`` root_path, ``q`` query string, ``c`` client ip, ``h``
optional header pairs; call: ``m`` method name). Response:
``{"v": 1, "resps": [entry, ...]}`` with per-entry ``n`` plus ``status``/
``ct``/``hdr``/``stream``/``a`` (http) or ``enc`` (call), and ``err`` +
``code`` + ``retriable`` for per-request failures — one bad request never
poisons its coalesced neighbours. A frame-level failure is
``{"v": 1, "err": msg}``. Stream pull: ``{"sid": id, "max": n}`` →
``{"done": bool, "err": msg?, "lens": [..]}`` + chunk bytes.
"""

from __future__ import annotations

import asyncio
import logging
import struct
import threading
import time
from typing import Any, Dict, List, Optional, Tuple

import msgpack

from ray_tpu.core.config import GLOBAL_CONFIG
from ray_tpu.observability import tracing as _tracing
from ray_tpu.tenancy.admission import (
    QuotaExceeded,
    TenantAdmission,
    WfqScheduler,
)

logger = logging.getLogger(__name__)

_HDR = struct.Struct("<I")

# Process-local fast-path accounting: proxies count dispatch outcomes,
# replicas count frame arrivals (replica.py increments the raw_dispatch_*
# keys). The echo acceptance proof reads these: raw_requests == N and
# fallback_requests == 0 means no request body was ever pickled.
COUNTERS: Dict[str, int] = {
    "raw_frames": 0,          # frames sent by this proxy
    "raw_requests": 0,        # requests answered via the fast lane
    "coalesced_requests": 0,  # requests that shared a frame with others
    "fallback_requests": 0,   # requests that left for the pickle lanes
    "retries": 0,             # requests re-routed after a lost replica
    "stream_pulls": 0,        # raw stream chunk frames pulled
    "park_buffered": 0,       # requests buffered for a parked deployment
    "park_rejected": 0,       # requests refused by the park byte cap
    "quota_rejected": 0,      # tenant over-quota 429s (never parked)
    "wfq_queued": 0,          # requests that waited in the fair queue
    "raw_dispatch_frames": 0,    # replica side: frames received
    "raw_dispatch_requests": 0,  # replica side: requests decoded from frames
}


def counters_snapshot() -> Dict[str, int]:
    return dict(COUNTERS)


def counters_reset() -> None:
    for k in COUNTERS:
        COUNTERS[k] = 0


# ------------------------------------------------------------------ codec


def encode_frame(meta: Dict[str, Any], bodies: List[Any]) -> List[Any]:
    """Frame a meta dict + body buffers as a raw-RPC parts list. Bodies
    pass through as-is (bytes/memoryview) — the RPC layer's vectored send
    puts them on the wire without concatenation."""
    packed = msgpack.packb(meta, use_bin_type=True)
    return [_HDR.pack(len(packed)), packed, *bodies]


def encode_error_frame(exc: BaseException) -> List[Any]:
    return encode_frame({"v": 1, "err": f"{type(exc).__name__}: {exc}"}, [])


def decode_frame(buf) -> Tuple[Dict[str, Any], memoryview]:
    """Split a received frame into (meta, body region view). The body
    region is one contiguous memoryview; slice it with `slice_bodies`
    using the per-entry lengths the meta carries."""
    view = memoryview(buf)
    (mlen,) = _HDR.unpack(view[:4])
    meta = msgpack.unpackb(bytes(view[4:4 + mlen]), raw=False,
                           strict_map_key=False)
    return meta, view[4 + mlen:]


def slice_bodies(region: memoryview, lens: List[int]) -> List[memoryview]:
    out, pos = [], 0
    for n in lens:
        out.append(region[pos:pos + n])
        pos += n
    return out


# -------------------------------------------------------------- fast lane


class FrameLostError(ConnectionError):
    """The connection to the replica died with the frame in flight."""


# Resolved to a fair-queued waiter whose deployment stopped being
# routable ("active") while it waited — deleted, redeployed, or parked.
# The dispatch loop re-runs its state handling (classic-lane fallback /
# cold-start buffering) instead of polling a dead closure to timeout.
_STATE_CHANGED = object()


class PreExecError(Exception):
    """The replica provably never started executing the frame (transport
    refused pre-send, or the server rejected it before dispatch) — safe
    to fall back to the classic lane."""


class ParkBufferFull(RuntimeError):
    """Scale-to-zero buffer cap hit: the proxy is already holding the
    configured byte budget for this parked deployment."""


class _Pending:
    __slots__ = ("entry", "body", "fut", "replica_id")

    def __init__(self, entry, body, fut, replica_id):
        self.entry = entry
        self.body = body
        self.fut = fut
        self.replica_id = replica_id


class _Channel:
    """Per-replica send channel: one direct RPC client + the coalescing
    buffer of requests waiting for the next flush."""

    __slots__ = ("client", "pending", "scheduled")

    def __init__(self):
        self.client = None
        self.pending: List[_Pending] = []
        self.scheduled = False


class FastLane:
    """Raw-frame dispatcher for one proxy process. All public coroutines
    run on the proxy's event loop; RPC completions arrive on client
    reader threads and hop back via call_soon_threadsafe."""

    REQUEST_TIMEOUT_S = 60.0

    def __init__(self, router, runtime):
        self._router = router
        self._runtime = runtime
        # Per-replica send channels mutate without locks: every dispatch,
        # flush and prune runs on the proxy's event loop (RPC completions
        # marshal back via call_soon_threadsafe). RL016-checked.
        self._channels: Dict[str, _Channel] = {}  # raylint: confine=loop
        self._version = -2  # != router's initial -1: prune on first use
        # Scale-to-zero buffer accounting, per deployment: one parked
        # deployment's cold-start backlog must not 503 another's first
        # request.
        self._park_bytes: Dict[str, int] = {}  # raylint: confine=loop
        # Multi-tenant QoS (docs/MULTITENANCY.md): per-tenant token
        # buckets + in-flight caps off the table-pushed QoS, and the
        # weighted fair queue that orders waiters under contention.
        self._admission = TenantAdmission()
        self._wfq = WfqScheduler()

    # ------------------------------------------------------------ dispatch

    async def dispatch(self, loop, deployment: str, entry: Dict[str, Any],
                       body, model_id: Optional[str] = None
                       ) -> Optional[Tuple[Dict[str, Any], memoryview]]:
        """Route one request entry (+ raw body) to a replica over the raw
        frame lane. Returns (response entry, body view) — the entry may
        carry a per-request "err" — or None when the fast lane cannot
        serve it (disabled, unknown deployment, saturated, or a transport
        path that is safer on the classic lane). Raises QuotaExceeded /
        ParkBufferFull / TimeoutError / ConnectionError for terminal
        fast-lane failures."""
        if not GLOBAL_CONFIG.serve_fastpath_enabled:
            return None
        self._prune_channels()
        table_entry = self._router.entry_snapshot(deployment)
        tenant = self._admission.resolve(table_entry)
        # Admission ordering: the quota gate runs FIRST — an over-quota
        # request answers 429 in one dict lookup, never occupying a
        # replica slot, a park buffer, or a fair-queue position.
        try:
            self._admission.admit(tenant)
        except QuotaExceeded:
            COUNTERS["quota_rejected"] += 1
            raise
        try:
            return await self._dispatch_admitted(
                loop, deployment, entry, body, model_id, table_entry)
        finally:
            # In-flight accounting covers queue time + execution: that is
            # what max_inflight bounds.
            self._admission.release(tenant)

    async def _dispatch_admitted(self, loop, deployment: str,
                                 entry: Dict[str, Any], body,
                                 model_id, table_entry
                                 ) -> Optional[Tuple[Dict[str, Any],
                                                     memoryview]]:
        nbytes = len(body) if body is not None else 0
        entry = dict(entry)
        entry["n"] = nbytes
        attempts = 0
        exclude: Optional[set] = None
        deadline = loop.time() + self.REQUEST_TIMEOUT_S
        while True:
            choice = None
            if not self._wfq.has_waiters() \
                    or not self._wfq.has_waiters_for(deployment):
                # With a backlog queued FOR THIS deployment, newcomers
                # must not jump it — contended reservations go through
                # the fair queue's virtual-time order. A backlog on
                # some other deployment's pool is irrelevant: routing
                # an idle deployment's request through the pump would
                # tax every tenant with the pump's backoff latency.
                choice = self._router.reserve_fast(deployment,
                                                   exclude=exclude,
                                                   model_id=model_id)
            if choice is None:
                state = self._router.deployment_state(deployment)
                if state == "unknown":
                    return None  # classic lane owns the KeyError grace
                if state == "parked":
                    await self._await_cold_start(loop, deployment, nbytes)
                    continue
                remaining = deadline - loop.time()
                if remaining <= 0:
                    raise TimeoutError(
                        f"no replica of {deployment!r} available within "
                        f"{self.REQUEST_TIMEOUT_S}s")
                # Saturated: park in the weighted fair queue. A hot
                # tenant's backlog drains behind its own weight; other
                # tiers interleave by theirs, so saturation by one
                # tenant cannot starve the rest.
                qos = (table_entry or {}).get("qos") or {}
                COUNTERS["wfq_queued"] += 1
                excl = exclude

                def try_reserve():
                    c = self._router.reserve_fast(
                        deployment, exclude=excl, model_id=model_id)
                    if c is not None:
                        return c
                    if self._router.deployment_state(deployment) \
                            != "active":
                        # Deleted or parked mid-wait: leave the queue
                        # NOW — the dispatch loop owns state handling.
                        return _STATE_CHANGED
                    return None

                def drop_grant(c):
                    # A granted choice the waiter can't consume carries
                    # a reserved router slot — return it.
                    if c is not _STATE_CHANGED:
                        self._router.release(c[0])

                choice = await self._wfq.acquire(
                    loop, qos.get("name"), qos.get("weight", 1),
                    try_reserve, remaining, deployment=deployment,
                    on_drop=drop_grant)
                if choice is _STATE_CHANGED:
                    continue
            replica_id, handle, colocated = choice
            if _tracing._ENABLED:
                span = _tracing.get_tracer().start_span(
                    "serve.direct", attrs={"deployment": deployment,
                                           "replica": replica_id,
                                           "colocated": colocated})
            else:
                span = _tracing.NOOP_SPAN
            try:
                with span:
                    resp, view = await self._send(loop, replica_id, handle,
                                                  entry, body)
            except PreExecError:
                # Provably not executed: the classic lane (which queues
                # and retries properly) owns it — and its counter.
                return None
            except FrameLostError:
                attempts += 1
                if attempts > 1:
                    raise ConnectionError(
                        f"request to {deployment} lost on two replicas "
                        f"(last: {replica_id}); giving up")
                COUNTERS["retries"] += 1
                exclude = {replica_id}
                continue
            if resp.get("err") and resp.get("retriable") and attempts == 0:
                # Provably-not-executed replica-side refusal (draining):
                # safe to re-route once without the at-least-once caveat.
                attempts += 1
                COUNTERS["retries"] += 1
                exclude = {replica_id}
                continue
            COUNTERS["raw_requests"] += 1
            return resp, view

    async def _await_cold_start(self, loop, deployment: str, nbytes: int):
        cap = GLOBAL_CONFIG.serve_park_max_bytes
        held = self._park_bytes.get(deployment, 0)
        if held + nbytes > cap:
            COUNTERS["park_rejected"] += 1
            raise ParkBufferFull(
                f"scale-to-zero buffer for {deployment!r} is full "
                f"({held}B held, cap {cap}B)")
        COUNTERS["park_buffered"] += 1
        self._park_bytes[deployment] = held + nbytes
        span = _tracing.NOOP_SPAN
        if _tracing._ENABLED:
            span = _tracing.get_tracer().start_span(
                "serve.coldstart", attrs={"deployment": deployment,
                                          "buffered_bytes": nbytes})
        t0 = time.monotonic()
        timeout = GLOBAL_CONFIG.serve_park_timeout_s
        try:
            with span:
                # Loop-friendly wait: hundreds of requests can buffer for
                # one cold start, and each parking an executor thread
                # would starve the pool for every deployment in this
                # proxy. A 20ms poll costs ~nothing against a ~100ms
                # cold start and holds no thread.
                while True:
                    if self._router.has_replicas(deployment):
                        span.set_attr("wait_ms",
                                      round((time.monotonic() - t0) * 1e3))
                        return
                    self._router.wake(deployment)  # throttled internally
                    if time.monotonic() - t0 > timeout:
                        raise TimeoutError(
                            f"deployment {deployment!r} did not cold-start "
                            f"a replica within {timeout}s")
                    await asyncio.sleep(0.02)
        finally:
            left = self._park_bytes.get(deployment, 0) - nbytes
            if left > 0:
                self._park_bytes[deployment] = left
            else:
                self._park_bytes.pop(deployment, None)

    # ----------------------------------------------------- frame transport

    def _prune_channels(self):
        version = self._router._version
        if version == self._version:
            return
        self._version = version
        live = self._router.live_replica_ids()
        for rid in list(self._channels):
            ch = self._channels[rid]
            # Never drop a channel with queued requests: its flush task is
            # about to consume ch.pending.
            if rid not in live and not ch.pending:
                self._channels.pop(rid, None)
        # Tenant admission state follows the table too: quota buckets for
        # tenants whose deployments all left must not accumulate forever.
        self._admission.prune(self._router.live_tenants())

    def _send(self, loop, replica_id: str, handle, entry, body):
        """Queue one request on the replica's channel and return the
        future for its slice of the reply frame. Coalescing window = the
        current event-loop tick: every request queued before the flush
        task runs shares the frame. No per-request wait_for — the frame
        schedules ONE timeout timer for all its requests (a per-request
        timer handle was measurable at fast-path rates)."""
        ch = self._channels.get(replica_id)
        if ch is None:
            ch = self._channels[replica_id] = _Channel()
        fut = loop.create_future()
        ch.pending.append(_Pending(entry, body, fut, replica_id))
        if not ch.scheduled:
            ch.scheduled = True
            loop.create_task(self._flush(loop, replica_id, handle, ch))
        return fut

    async def _flush(self, loop, replica_id: str, handle, ch: _Channel):
        """Drain the channel's pending requests as one or more frames.
        Slot ownership: the router slot for every request in a sent frame
        is released by the frame's completion callback (reply OR
        connection loss — the client guarantees exactly one fires); a
        frame that provably never left releases here."""
        max_reqs = GLOBAL_CONFIG.serve_coalesce_max_requests
        max_bytes = GLOBAL_CONFIG.serve_coalesce_max_bytes
        try:
            while ch.pending:
                batch: List[_Pending] = []
                total = 0
                while ch.pending and len(batch) < max_reqs:
                    nxt = ch.pending[0]
                    # A request that would push the frame past the byte
                    # cap waits for the next frame (a single oversized
                    # body still goes alone).
                    if batch and total + nxt.entry["n"] > max_bytes:
                        break
                    batch.append(ch.pending.pop(0))
                    total += nxt.entry["n"]
                client = await self._ensure_client(loop, replica_id, handle)
                if client is None:
                    self._fail_batch(batch, PreExecError(
                        f"no direct connection to replica {replica_id}"))
                    continue
                self._send_frame(loop, client, replica_id, batch)
        finally:
            ch.scheduled = False
            if ch.pending and not ch.scheduled:
                # Requests raced in while we were unwinding: reschedule.
                ch.scheduled = True
                loop.create_task(self._flush(loop, replica_id, handle, ch))

    async def _ensure_client(self, loop, replica_id: str, handle):
        ch = self._channels.get(replica_id)
        if ch is not None and ch.client is not None \
                and not ch.client.is_closed:
            return ch.client
        try:
            client = await loop.run_in_executor(
                None,
                lambda: self._runtime._actor_client(handle._actor_id).client)
        except Exception:  # noqa: BLE001 — replica gone/restarting
            return None
        if ch is not None:
            ch.client = client
        return client

    def _fail_batch(self, batch: List[_Pending], exc: Exception,
                    release: bool = True):
        for p in batch:
            if release:
                self._router.release(p.replica_id)
            if not p.fut.done():
                p.fut.set_exception(exc)

    def _send_frame(self, loop, client, replica_id: str,
                    batch: List[_Pending]):
        meta = {"v": 1, "reqs": [p.entry for p in batch]}
        parts = encode_frame(meta, [p.body for p in batch if p.entry["n"]])
        COUNTERS["raw_frames"] += 1
        if len(batch) > 1:
            COUNTERS["coalesced_requests"] += len(batch)
        timer = None

        def timeout_all():
            # Waiters stop waiting; the slots stay owned by complete() —
            # a timed-out request's replica is still busy with it, and
            # releasing early would let admission dispatch on top of it.
            for p in batch:
                if not p.fut.done():
                    p.fut.set_exception(TimeoutError(
                        f"request to replica {replica_id} timed out after "
                        f"{self.REQUEST_TIMEOUT_S}s"))

        def complete(env, payload):
            # Reader thread: decode outside the loop (cheap), resolve on
            # the loop. Slots release here unconditionally — the replica
            # is done with (or dead to) every request in the frame.
            if timer is not None:
                loop.call_soon_threadsafe(timer.cancel)
            try:
                results = self._frame_results(env, payload, batch)
            finally:
                for p in batch:
                    self._router.release(p.replica_id)
            loop.call_soon_threadsafe(self._resolve_batch, batch, results)

        try:
            client.call_raw_async("serve_raw", parts, complete)
        except Exception:  # noqa: BLE001 — send failed before the slot
            # registered: complete() will never fire, we still own slots.
            self._drop_channel_client(replica_id)
            self._fail_batch(batch, FrameLostError(
                f"connection to replica {replica_id} lost pre-send"))
            return
        timer = loop.call_later(self.REQUEST_TIMEOUT_S, timeout_all)

    def _frame_results(self, env, payload, batch: List[_Pending]) -> list:
        """Map one reply envelope/frame to a per-request result list:
        (entry, body) tuples or exceptions."""
        if env.get("_lost"):
            self._drop_channel_client(batch[0].replica_id)
            return [FrameLostError("connection to replica "
                                   f"{batch[0].replica_id} lost mid-frame")
                    ] * len(batch)
        if env.get("e"):
            # Server-side rejection before dispatch (actor still
            # initializing, no serve hook): provably not executed.
            self._drop_channel_client(batch[0].replica_id)
            return [PreExecError(str(env["e"]))] * len(batch)
        try:
            meta, region = decode_frame(payload)
            if meta.get("err"):
                raise RuntimeError(f"replica frame error: {meta['err']}")
            resps = meta["resps"]
            if len(resps) != len(batch):
                raise RuntimeError(
                    f"frame answered {len(resps)}/{len(batch)} requests")
            bodies = slice_bodies(region, [r.get("n", 0) for r in resps])
            return list(zip(resps, bodies))
        except Exception as e:  # noqa: BLE001 — corrupt/short frame
            return [e] * len(batch)

    @staticmethod
    def _resolve_batch(batch: List[_Pending], results: list):
        for p, r in zip(batch, results):
            if p.fut.done():
                continue  # timed out waiter; slot already released
            if isinstance(r, BaseException):
                p.fut.set_exception(r)
            else:
                p.fut.set_result(r)

    def _drop_channel_client(self, replica_id: str):
        ch = self._channels.get(replica_id)
        if ch is not None:
            ch.client = None

    # -------------------------------------------------------------- streams

    async def stream_pull(self, loop, deployment: str, sid: str,
                          max_items: int = 64, timeout_s: float = 30.0
                          ) -> Optional[Tuple[Dict[str, Any],
                                              List[memoryview]]]:
        """Pull the next raw chunk frame of a replica-side stream.
        Returns (meta, chunk views) or None when the replica left the
        table / the connection died (truncation — caller aborts)."""
        replica_id = sid.rsplit(":", 1)[0]
        handle = self._router.replica_for_stream(deployment, sid)
        if handle is None:
            return None
        client = await self._ensure_client(loop, replica_id, handle)
        if client is None:
            return None
        fut = loop.create_future()

        def complete(env, payload):
            def _set():
                if fut.done():
                    return
                if env.get("_lost") or env.get("e"):
                    fut.set_result(None)
                    return
                try:
                    meta, region = decode_frame(payload)
                    fut.set_result(
                        (meta, slice_bodies(region, meta.get("lens") or [])))
                except Exception as e:  # noqa: BLE001 — corrupt frame
                    fut.set_exception(e)
            loop.call_soon_threadsafe(_set)

        frame = encode_frame({"sid": sid, "max": max_items,
                              "timeout": timeout_s}, [])
        try:
            client.call_raw_async("serve_stream", frame, complete)
        except Exception:  # noqa: BLE001 — replica gone: truncated
            self._drop_channel_client(replica_id)
            return None
        COUNTERS["stream_pulls"] += 1
        try:
            return await asyncio.wait_for(fut, timeout_s + 30.0)
        except asyncio.TimeoutError:
            return None

    def stream_cancel(self, loop, deployment: str, sid: str) -> None:
        """Best-effort release of an abandoned stream's replica-side pump
        (fire-and-forget raw frame; the idle reaper is the backstop).
        Runs on the event loop, so it only ever uses an ALREADY-OPEN
        channel client — dialing a fresh connection here (the replica is
        often dead when cancels fire) would block every in-flight request
        in the proxy behind the connect timeout."""
        replica_id = sid.rsplit(":", 1)[0]
        ch = self._channels.get(replica_id)
        client = ch.client if ch is not None else None
        if client is None or client.is_closed:
            return  # no live channel: the idle reaper cleans up
        try:
            client.call_raw_async("serve_stream",
                                  encode_frame({"sid": sid, "cancel": True},
                                               []),
                                  lambda env, payload: None)
        except Exception:  # noqa: BLE001 — reaper is the backstop
            pass
