"""Reference deployments: batched JAX inference replicas.

The serving counterpart of the flagship model (BASELINE.json names a Serve
LLM deployment): a GPT-2 sampler replica that owns its accelerator, pads
incoming prompts into fixed shape buckets (stable shapes = one XLA
compilation), and rides `@serve.batch` so concurrent HTTP requests share
one MXU forward pass per decode step.
"""

from __future__ import annotations

from typing import Any, Dict, List

from ray_tpu import serve


@serve.deployment(max_concurrent_queries=32)
class GPT2Sampler:
    """Greedy sampler over a GPT-2 checkpoint (randomly initialized by
    default — serving-path benchmarking doesn't need trained weights).

    Request: {"ids": [int, ...], "max_new_tokens": int} -> {"ids": [...]}.
    """

    def __init__(self, model_size: str = "tiny", max_seq: int = 256,
                 default_new_tokens: int = 8):
        import jax
        import jax.numpy as jnp

        from ray_tpu.models.gpt2 import GPT2, GPT2Config

        cfg = {"tiny": GPT2Config.tiny(seq=max_seq),
               "small": GPT2Config.small(),
               "medium": GPT2Config.medium()}[model_size]
        self._cfg = cfg
        self._max_seq = min(max_seq, cfg.n_positions)
        self._default_new = default_new_tokens
        self._model = GPT2(cfg)
        rng = jax.random.PRNGKey(0)
        sample = jnp.zeros((1, self._max_seq), jnp.int32)
        self._params = jax.jit(
            lambda: self._model.init(rng, sample))()

        def next_token(params, ids, lengths):
            # ids: [b, max_seq] padded; lengths: [b] current lengths.
            logits = self._model.apply(params, ids)
            last = jnp.take_along_axis(
                logits, (lengths - 1)[:, None, None], axis=1)[:, 0]
            return jnp.argmax(last, axis=-1).astype(jnp.int32)

        self._next_token = jax.jit(next_token)
        self._batches_served = 0
        self._batch_size_sum = 0

    @serve.batch(max_batch_size=8, batch_wait_timeout_s=0.02)
    async def __call__(self, requests: List[Dict[str, Any]]):
        import jax.numpy as jnp
        import numpy as np

        self._batches_served += 1
        self._batch_size_sum += len(requests)
        prompts = [list(r.get("ids", []))[: self._max_seq - 1]
                   or [0] for r in requests]
        # Per-request decode budget: rows stop advancing at their own
        # max_new_tokens; the loop runs to the batch max.
        budgets = np.zeros(len(prompts), np.int32)
        for i, r in enumerate(requests):
            budgets[i] = max(1, min(
                int(r.get("max_new_tokens", self._default_new)),
                self._max_seq - 1 - len(prompts[i])))
        # Pad the batch dim to max_batch_size too: one XLA compilation for
        # every batch the flusher can produce, not one per distinct size.
        padded_b = 8
        while padded_b < len(prompts):
            padded_b *= 2
        ids = np.zeros((padded_b, self._max_seq), np.int32)
        lengths = np.ones(padded_b, np.int32)
        lengths[: len(prompts)] = [len(p) for p in prompts]
        for i, p in enumerate(prompts):
            ids[i, : len(p)] = p
        full_budgets = np.zeros(padded_b, np.int32)
        full_budgets[: len(prompts)] = budgets
        ids = jnp.asarray(ids)
        lengths = jnp.asarray(lengths)
        full_budgets = jnp.asarray(full_budgets)
        for step in range(int(budgets.max())):
            nxt = self._next_token(self._params, ids, lengths)
            active = (step < full_budgets) & (lengths < self._max_seq - 1)
            new_ids = ids.at[jnp.arange(ids.shape[0]), lengths].set(nxt)
            ids = jnp.where(active[:, None], new_ids, ids)
            lengths = jnp.where(active, lengths + 1, lengths)
        out_ids = np.asarray(ids)
        out_lens = np.asarray(lengths)
        return [{"ids": out_ids[i, : out_lens[i]].tolist()}
                for i in range(len(prompts))]

    def metrics(self, _=None) -> Dict[str, Any]:
        served = self._batches_served
        return {
            "batches_served": served,
            "mean_batch_size":
                (self._batch_size_sum / served) if served else 0.0,
        }
