"""Reference deployments: batched JAX inference replicas.

The serving counterpart of the flagship model (BASELINE.json names a Serve
LLM deployment): a GPT-2 sampler replica that owns its accelerator, pads
incoming prompts into fixed shape buckets (stable shapes = one XLA
compilation), and rides `@serve.batch` so concurrent HTTP requests share
one MXU forward pass per decode step.
"""

from __future__ import annotations

from typing import Any, Dict, List

from ray_tpu import serve

# One compiled batch shape: @serve.batch caps request batches here and the
# samplers pad row counts to exactly this.
SAMPLER_BATCH = 8


def _prompts_and_budgets(requests: List[Dict[str, Any]], max_seq: int,
                         default_new: int):
    """Truncated prompts + per-request decode budgets (shared by all
    sampler deployments so clamping semantics can't drift)."""
    import numpy as np

    prompts = [list(r.get("ids", []))[: max_seq - 1] or [0]
               for r in requests]
    budgets = np.zeros(len(prompts), np.int32)
    for i, r in enumerate(requests):
        budgets[i] = max(1, min(int(r.get("max_new_tokens", default_new)),
                                max_seq - 1 - len(prompts[i])))
    return prompts, budgets


class _SamplerMetrics:
    _batches_served = 0
    _batch_size_sum = 0

    def _observe_batch(self, n: int):
        self._batches_served += 1
        self._batch_size_sum += n

    def metrics(self, _=None) -> Dict[str, Any]:
        served = self._batches_served
        return {
            "batches_served": served,
            "mean_batch_size":
                (self._batch_size_sum / served) if served else 0.0,
        }


@serve.deployment(max_concurrent_queries=32)
class GPT2Sampler(_SamplerMetrics):
    """Greedy sampler over a GPT-2 checkpoint (randomly initialized by
    default — serving-path benchmarking doesn't need trained weights).

    Request: {"ids": [int, ...], "max_new_tokens": int} -> {"ids": [...]}.
    """

    def __init__(self, model_size: str = "tiny", max_seq: int = 256,
                 default_new_tokens: int = 8):
        import jax
        import jax.numpy as jnp

        from ray_tpu.models.gpt2 import GPT2, GPT2Config

        cfg = {"tiny": GPT2Config.tiny(seq=max_seq),
               "small": GPT2Config.small(),
               "medium": GPT2Config.medium()}[model_size]
        self._cfg = cfg
        self._max_seq = min(max_seq, cfg.n_positions)
        self._default_new = default_new_tokens
        self._model = GPT2(cfg)
        rng = jax.random.PRNGKey(0)
        sample = jnp.zeros((1, self._max_seq), jnp.int32)
        self._params = jax.jit(
            lambda: self._model.init(rng, sample))()

        def next_token(params, ids, lengths):
            # ids: [b, max_seq] padded; lengths: [b] current lengths.
            logits = self._model.apply(params, ids)
            last = jnp.take_along_axis(
                logits, (lengths - 1)[:, None, None], axis=1)[:, 0]
            return jnp.argmax(last, axis=-1).astype(jnp.int32)

        max_pos = self._max_seq - 1

        def decode(params, ids, lengths, budgets):
            # The WHOLE decode loop is one compiled program: the
            # masking/append glue between forwards must not run as eager
            # ops — on a relay-attached chip each eager dispatch costs
            # ~ms, which made per-step glue 20x the forward itself. A
            # while_loop with a TRACED bound (max budget) gives exactly
            # one XLA compilation for every batch shape and exactly
            # max-budget forwards — no static step count to recompile on,
            # no masked-out padding passes.
            import jax.lax as lax

            def cond(carry):
                step, _, _ = carry
                return step < jnp.max(budgets)

            def body(carry):
                step, ids, lengths = carry
                nxt = next_token(params, ids, lengths)
                active = (step < budgets) & (lengths < max_pos)
                appended = ids.at[jnp.arange(ids.shape[0]), lengths].set(nxt)
                ids = jnp.where(active[:, None], appended, ids)
                lengths = jnp.where(active, lengths + 1, lengths)
                return step + 1, ids, lengths

            _, ids, lengths = lax.while_loop(
                cond, body, (jnp.int32(0), ids, lengths))
            return ids, lengths

        self._decode = jax.jit(decode)

    @serve.batch(max_batch_size=SAMPLER_BATCH, batch_wait_timeout_s=0.02)
    async def __call__(self, requests: List[Dict[str, Any]]):
        import jax.numpy as jnp
        import numpy as np

        self._observe_batch(len(requests))
        prompts, budgets = _prompts_and_budgets(requests, self._max_seq,
                                                self._default_new)
        # Pad the batch dim to the decorator's cap: one XLA compilation for
        # every batch the flusher can produce, not one per distinct size.
        padded_b = SAMPLER_BATCH
        ids = np.zeros((padded_b, self._max_seq), np.int32)
        lengths = np.ones(padded_b, np.int32)
        lengths[: len(prompts)] = [len(p) for p in prompts]
        for i, p in enumerate(prompts):
            ids[i, : len(p)] = p
        full_budgets = np.zeros(padded_b, np.int32)
        full_budgets[: len(prompts)] = budgets
        ids = jnp.asarray(ids)
        lengths = jnp.asarray(lengths)
        full_budgets = jnp.asarray(full_budgets)
        ids, lengths = self._decode(self._params, ids, lengths,
                                    full_budgets)
        out_ids = np.asarray(ids)
        out_lens = np.asarray(lengths)
        return [{"ids": out_ids[i, : out_lens[i]].tolist()}
                for i in range(len(prompts))]


@serve.deployment(max_concurrent_queries=32)
class LlamaSampler(_SamplerMetrics):
    """KV-cached greedy sampler over a Llama-family model (BASELINE.json's
    Serve Llama deployment). Unlike GPT2Sampler's recompute-per-token
    loop, this prefills the prompt K/V once and then runs O(1)-attention
    decode steps against the cache — the TPU-serving decode shape.

    Request: {"ids": [int, ...], "max_new_tokens": int} -> {"ids": [...]}.
    """

    def __init__(self, model_size: str = "tiny", max_seq: int = 256,
                 default_new_tokens: int = 8):
        import jax
        import jax.numpy as jnp

        from ray_tpu.models.llama import Llama, LlamaConfig, make_cache

        cfg = {"tiny": LlamaConfig.tiny(seq=max_seq),
               "small": LlamaConfig.small(),
               "7b": LlamaConfig.llama7b()}[model_size]
        self._cfg = cfg
        self._max_seq = min(max_seq, cfg.n_positions)
        self._default_new = default_new_tokens
        self._model = Llama(cfg)
        rng = jax.random.PRNGKey(0)
        self._params = jax.jit(lambda: self._model.init(
            rng, jnp.zeros((1, 8), jnp.int32)))()
        # One preallocated cache, reused across batches: every slot a query
        # can see is rewritten during its own call (prefill writes the
        # prompt span, decode overwrites onward; the position mask hides
        # the rest), so cross-batch reuse is safe and avoids re-zeroing
        # gigabytes per request batch on big configs.
        self._cache = make_cache(self._cfg, SAMPLER_BATCH, self._max_seq)

        def prefill(params, ids, cache, lens):
            logits, cache = self._model.apply(
                params, ids, cache, jnp.zeros(ids.shape[0], jnp.int32),
                method=Llama.decode)
            # Each row's next token comes from ITS last real position.
            first = jnp.argmax(jnp.take_along_axis(
                logits, (lens - 1)[:, None, None], axis=1)[:, 0],
                axis=-1).astype(jnp.int32)
            return first, cache

        def decode_step(params, tok, cache, out, lens, budgets, step):
            # Append tok at each active row's position, then decode the
            # next token — all on-device, no host sync per token.
            active = (step < budgets) & (lens < self._max_seq - 1)
            rows = jnp.arange(out.shape[0])
            appended = out.at[rows, lens].set(tok)
            out = jnp.where(active[:, None], appended, out)
            lens = jnp.where(active, lens + 1, lens)
            logits, cache = self._model.apply(params, tok[:, None], cache,
                                              lens - 1, method=Llama.decode)
            nxt = jnp.argmax(logits[:, -1], axis=-1).astype(jnp.int32)
            return nxt, cache, out, lens

        self._prefill = jax.jit(prefill)
        self._decode = jax.jit(decode_step)

    @serve.batch(max_batch_size=SAMPLER_BATCH, batch_wait_timeout_s=0.02)
    async def __call__(self, requests: List[Dict[str, Any]]):
        import jax.numpy as jnp
        import numpy as np

        self._observe_batch(len(requests))
        prompts, budgets = _prompts_and_budgets(requests, self._max_seq,
                                                self._default_new)
        b = SAMPLER_BATCH
        # Prompt pad to a power of two: a handful of prefill programs total.
        plen = max(len(p) for p in prompts)
        pad = 8
        while pad < plen:
            pad *= 2
        pad = min(pad, self._max_seq)
        ids = np.zeros((b, pad), np.int32)
        lens = np.ones(b, np.int32)
        for i, p in enumerate(prompts):
            ids[i, : len(p)] = p
            lens[i] = len(p)
        full_budgets = np.zeros(b, np.int32)
        full_budgets[: len(prompts)] = budgets

        tok, self._cache = self._prefill(self._params, jnp.asarray(ids),
                                         self._cache, jnp.asarray(lens))
        out = jnp.zeros((b, self._max_seq), jnp.int32)
        out = out.at[:, :pad].set(jnp.asarray(ids))
        lens_j = jnp.asarray(lens)
        budgets_j = jnp.asarray(full_budgets)
        for step in range(int(budgets.max())):
            tok, self._cache, out, lens_j = self._decode(
                self._params, tok, self._cache, out, lens_j, budgets_j,
                jnp.int32(step))
        out_np = np.asarray(out)
        out_lens = np.asarray(lens_j)
        return [{"ids": out_np[i, : out_lens[i]].tolist()}
                for i in range(len(prompts))]
