"""gRPC ingress for Serve deployments.

Equivalent of the reference's gRPC proxy (`serve/_private/proxy.py`
gRPCProxy / `grpc_util.py`): a `grpc.aio` server whose requests route to
deployment replicas through the same ReplicaDispatcher (light lane +
heavy fallback) the HTTP proxy uses.

Protocol: generic RPC handlers, no protoc step. The fully-qualified
method is `/ray_tpu.serve/<DeploymentName>`; request and response bodies
are raw bytes. A msgpack-decodable request is decoded and handed to the
deployment callable as a Python value (and a non-bytes result is
msgpack-encoded back); opaque bytes pass through untouched in both
directions, so any serialization the caller prefers — protobuf included
— rides as bytes. Deployment errors surface as StatusCode.INTERNAL with
the exception text; unknown deployments as NOT_FOUND. Unary only (HTTP
owns streaming responses).

Clients need no stubs either:

    ch = grpc.insecure_channel(f"127.0.0.1:{port}")
    call = ch.unary_unary("/ray_tpu.serve/Echo")   # bytes in/out
    out = msgpack.unpackb(call(msgpack.packb({"x": 1})))
"""

from __future__ import annotations

import asyncio
import logging

logger = logging.getLogger(__name__)

SERVICE = "ray_tpu.serve"


class GrpcProxy:
    def __init__(self, host: str = "127.0.0.1", port: int = 0):
        self._host = host
        self._port = port
        self._server = None
        self._router = None
        self._ready_lock = None

    async def ready(self) -> int:
        """Start the gRPC server; returns the bound port."""
        if self._ready_lock is None:  # created pre-await: no interleave yet
            self._ready_lock = asyncio.Lock()
        async with self._ready_lock:
            return await self._ready_locked()

    async def _ready_locked(self) -> int:
        if self._server is not None:
            return self._port
        import grpc

        import ray_tpu
        from ray_tpu.serve.controller import (
            CONTROLLER_NAME,
            SERVE_NAMESPACE,
        )
        from ray_tpu.serve.proxy import ReplicaDispatcher
        from ray_tpu.serve.router import Router

        controller = ray_tpu.get_actor(CONTROLLER_NAME,
                                       namespace=SERVE_NAMESPACE)
        self._runtime = ray_tpu._global_runtime
        # Router state is only adopted after the server binds: a failed
        # bind must leave the actor retryable without leaking a started
        # Router thread pair per attempt.
        router = Router(controller)
        await asyncio.get_running_loop().run_in_executor(
            None, router._ensure_started)

        proxy = self

        class _Handler(grpc.GenericRpcHandler):
            def service(self, call_details):
                # '/ray_tpu.serve/<Deployment>' -> unary bytes handler.
                parts = call_details.method.lstrip("/").split("/")
                if len(parts) != 2 or parts[0] != SERVICE:
                    return None
                deployment = parts[1]

                async def unary(request: bytes, context):
                    return await proxy._handle(deployment, request, context)

                return grpc.unary_unary_rpc_method_handler(
                    unary,
                    request_deserializer=None,   # raw bytes both ways
                    response_serializer=None)

        try:
            server = grpc.aio.server()
            server.add_generic_rpc_handlers((_Handler(),))
            bound = server.add_insecure_port(f"{self._host}:{self._port}")
            if bound == 0:
                # grpc reports bind failure as port 0, not an exception —
                # a silently-"ready" proxy on port 0 would strand every
                # caller.
                raise RuntimeError(
                    f"grpc proxy failed to bind {self._host}:{self._port}")
            # Handlers read these; they must exist before serving starts.
            self._router = router
            self._dispatcher = ReplicaDispatcher(router, self._runtime)
            await server.start()
        except BaseException:
            router.stop()
            self._router = None
            raise
        self._port = bound
        self._server = server
        logger.info("serve grpc proxy listening on %s:%d",
                    self._host, self._port)
        return self._port

    async def _handle(self, deployment: str, request: bytes, context):
        import grpc
        import msgpack

        with self._router._lock:
            known = deployment in self._router._table
        if not known:
            # A request fired right after serve.run can beat the proxy
            # router's long-poll refresh. One authoritative controller
            # fetch decides immediately: genuinely-unknown names get
            # NOT_FOUND now (no multi-second stall per typo/retry), while
            # an in-flight deploy waits out the router's own grace
            # (Router.UNKNOWN_GRACE_S) for the local table to catch up.
            import ray_tpu

            loop = asyncio.get_running_loop()
            try:
                _, table = await loop.run_in_executor(
                    None, lambda: ray_tpu.get(
                        self._router._controller.listen_for_change.remote(
                            -1, 0), timeout=10))
                authoritative = deployment in table
            except Exception:  # noqa: BLE001 — controller busy: fall back
                authoritative = True  # to the grace poll below
            if not authoritative:
                await context.abort(
                    grpc.StatusCode.NOT_FOUND,
                    f"no deployment named {deployment!r}")
            from ray_tpu.serve.router import Router

            deadline = loop.time() + Router.UNKNOWN_GRACE_S
            while True:
                with self._router._lock:
                    if deployment in self._router._table:
                        break
                if loop.time() >= deadline:
                    await context.abort(
                        grpc.StatusCode.NOT_FOUND,
                        f"no deployment named {deployment!r}")
                await asyncio.sleep(0.1)
        loop = asyncio.get_running_loop()
        # Fast data plane first — the SAME dispatch path as the HTTP
        # proxy (ReplicaDispatcher.fastlane), so the two ingresses cannot
        # drift: request bytes ride a raw frame, the replica decodes
        # msgpack/opaque bodies and encodes the reply symmetrically.
        from ray_tpu.serve import dataplane

        try:
            out = await self._dispatcher.dispatch_call(loop, deployment,
                                                       bytes(request))
        except dataplane.QuotaExceeded as e:
            # Tenant over quota: the gRPC spelling of the HTTP 429.
            await context.abort(grpc.StatusCode.RESOURCE_EXHAUSTED,
                                f"{e} (retry after {e.retry_after_s:.3f}s)")
        except dataplane.ParkBufferFull as e:
            await context.abort(grpc.StatusCode.RESOURCE_EXHAUSTED, str(e))
        except (asyncio.TimeoutError, TimeoutError):
            await context.abort(grpc.StatusCode.DEADLINE_EXCEEDED,
                                "request timed out")
        except ConnectionError as e:
            await context.abort(grpc.StatusCode.UNAVAILABLE, str(e))
        if out is not None:
            entry, body = out
            sid = entry.get("stream")
            if sid:
                # Release the replica-side pump/queue NOW, not at the
                # 120s idle reap.
                self._dispatcher.fastlane.stream_cancel(loop, deployment,
                                                        sid)
            if entry.get("err"):
                code = grpc.StatusCode.UNIMPLEMENTED \
                    if entry.get("code") == 501 else grpc.StatusCode.INTERNAL
                await context.abort(code, entry["err"])
            return bytes(body)
        dataplane.COUNTERS["fallback_requests"] += 1
        try:
            payload = msgpack.unpackb(bytes(request), raw=False,
                                      strict_map_key=False)
        except Exception:  # noqa: BLE001 — opaque bytes pass through
            payload = bytes(request)
        try:
            result = await self._dispatcher.dispatch(
                loop, deployment, "__call__", (payload,))
        except asyncio.TimeoutError:
            await context.abort(grpc.StatusCode.DEADLINE_EXCEEDED,
                                "request timed out after 60s")
        except Exception as e:  # noqa: BLE001 — user code error (a user
            # KeyError included: unknown deployments were pre-checked
            # above, so mapping KeyError to NOT_FOUND here would
            # misclassify application errors)
            await context.abort(grpc.StatusCode.INTERNAL,
                                f"{type(e).__name__}: {e}")
        if isinstance(result, dict) and (
                result.get("__serve_stream__") or result.get("__serve_http__")):
            # Generator/ASGI results need the HTTP proxy's stream pump;
            # leaking the internal sentinel would hand the client a
            # meaningless stream id while the replica's queue idles full.
            sid = (result.get("__serve_stream__")
                   or result.get("stream"))
            if sid:
                # Release the replica-side pump/queue NOW, not at the
                # 120s idle reap — each abandoned call otherwise strands
                # a full queue and a running generator.
                handle = self._router.replica_for_stream(deployment, sid)
                if handle is not None:
                    try:
                        handle.stream_cancel.remote(sid)
                    except Exception:  # noqa: BLE001 — reaper is backstop
                        pass
            await context.abort(
                grpc.StatusCode.UNIMPLEMENTED,
                "streaming/ASGI deployments are not servable over the "
                "unary gRPC ingress — use the HTTP proxy")
        if isinstance(result, (bytes, bytearray, memoryview)):
            return bytes(result)
        try:
            return msgpack.packb(result, use_bin_type=True)
        except Exception as e:  # noqa: BLE001
            await context.abort(
                grpc.StatusCode.INTERNAL,
                f"result of type {type(result).__name__} is not "
                f"msgpack-serializable: {e}")

    async def counters(self) -> dict:
        """This proxy process's fast-path counters (shared-path test
        support: proves gRPC rides the same raw dispatch as HTTP)."""
        from ray_tpu.serve import dataplane

        return dataplane.counters_snapshot()

    async def stop(self):
        if self._router is not None:
            self._router.stop()
        if self._server is not None:
            await self._server.stop(grace=1.0)
            self._server = None
