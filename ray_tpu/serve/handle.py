"""DeploymentHandle: Python-side entry into a deployment.

Equivalent of the reference's `RayServeHandle` (`serve/handle.py:78`).
``handle.remote(arg)`` routes through the process-local Router (admission
control + least-loaded choice) and returns an ObjectRef; composition
between deployments works because handles pickle down to their deployment
name and rebuild their router lazily inside the borrowing process.
"""

from __future__ import annotations

import threading
from typing import Any, Optional

_router_lock = threading.Lock()
_router = None


def _process_router():
    """One Router per process, shared by every handle and thread (shared
    in-flight accounting keeps max_concurrent_queries global to the
    process)."""
    global _router
    import ray_tpu
    from ray_tpu.serve.controller import CONTROLLER_NAME, SERVE_NAMESPACE
    from ray_tpu.serve.router import Router

    with _router_lock:
        if _router is None or _router._stopped:
            controller = ray_tpu.get_actor(CONTROLLER_NAME,
                                           namespace=SERVE_NAMESPACE)
            _router = Router(controller)
        return _router


def _drop_process_router():
    global _router
    with _router_lock:
        if _router is not None:
            _router.stop()
            _router = None


class DeploymentHandle:
    def __init__(self, deployment_name: str, method_name: str = "__call__"):
        self._deployment = deployment_name
        self._method = method_name

    def options(self, method_name: Optional[str] = None
                ) -> "DeploymentHandle":
        return DeploymentHandle(self._deployment,
                                method_name or self._method)

    def method(self, name: str) -> "DeploymentHandle":
        return DeploymentHandle(self._deployment, name)

    def remote(self, *args, **kwargs) -> Any:
        return _process_router().assign(
            self._deployment, self._method, args, kwargs)

    def __getattr__(self, name: str):
        if name.startswith("_"):
            raise AttributeError(name)
        return DeploymentHandle(self._deployment, name)

    def __reduce__(self):
        return DeploymentHandle, (self._deployment, self._method)

    def __eq__(self, other):
        # Value equality so an unchanged redeploy (same graph, fresh handle
        # objects) doesn't read as a code change and drain replicas.
        return (isinstance(other, DeploymentHandle)
                and self._deployment == other._deployment
                and self._method == other._method)

    def __hash__(self):
        return hash((self._deployment, self._method))

    def __repr__(self):
        return f"DeploymentHandle({self._deployment!r}, {self._method!r})"
