"""DeploymentHandle: Python-side entry into a deployment.

Equivalent of the reference's `RayServeHandle` (`serve/handle.py:78`).
``handle.remote(arg)`` routes through the process-local Router (admission
control + least-loaded choice) and returns an ObjectRef; composition
between deployments works because handles pickle down to their deployment
name and rebuild their router lazily inside the borrowing process.
"""

from __future__ import annotations

import threading
from typing import Any, Optional

_router_lock = threading.Lock()
_router = None


def _process_router():
    """One Router per process, shared by every handle and thread (shared
    in-flight accounting keeps max_concurrent_queries global to the
    process)."""
    global _router
    import ray_tpu
    from ray_tpu.serve.controller import CONTROLLER_NAME, SERVE_NAMESPACE
    from ray_tpu.serve.router import Router

    with _router_lock:
        if _router is None or _router._stopped:
            controller = ray_tpu.get_actor(CONTROLLER_NAME,
                                           namespace=SERVE_NAMESPACE)
            _router = Router(controller)
        return _router


def _drop_process_router():
    global _router
    with _router_lock:
        if _router is not None:
            _router.stop()
            _router = None


class DeploymentHandle:
    def __init__(self, deployment_name: str, method_name: str = "__call__",
                 stream: bool = False):
        self._deployment = deployment_name
        self._method = method_name
        self._stream = stream

    def options(self, method_name: Optional[str] = None,
                stream: Optional[bool] = None) -> "DeploymentHandle":
        return DeploymentHandle(self._deployment,
                                method_name or self._method,
                                self._stream if stream is None else stream)

    def method(self, name: str) -> "DeploymentHandle":
        return DeploymentHandle(self._deployment, name, self._stream)

    def remote(self, *args, **kwargs) -> Any:
        ref = _process_router().assign(
            self._deployment, self._method, args, kwargs)
        if not self._stream:
            return ref
        return _StreamingResult(self._deployment, ref)

    def __getattr__(self, name: str):
        if name.startswith("_"):
            raise AttributeError(name)
        return DeploymentHandle(self._deployment, name, self._stream)

    def __reduce__(self):
        return DeploymentHandle, (self._deployment, self._method,
                                  self._stream)

    def __eq__(self, other):
        # Value equality so an unchanged redeploy (same graph, fresh handle
        # objects) doesn't read as a code change and drain replicas.
        return (isinstance(other, DeploymentHandle)
                and self._deployment == other._deployment
                and self._method == other._method)

    def __hash__(self):
        return hash((self._deployment, self._method))

    def __repr__(self):
        return f"DeploymentHandle({self._deployment!r}, {self._method!r})"


class _StreamingResult:
    """Iterator over a streamed deployment response
    (`handle.options(stream=True)`, reference streaming handles): the
    replica pumps generator items into a queue; this pulls batches via
    its stream_next method until exhaustion."""

    def __init__(self, deployment: str, ref):
        self._deployment = deployment
        self._ref = ref
        self._sid: Optional[str] = None
        self._buffer: list = []
        self._done = False

    def _start(self):
        import ray_tpu

        marker = ray_tpu.get(self._ref)
        if not (isinstance(marker, dict) and "__serve_stream__" in marker):
            # Non-generator result: yield it once for iterator symmetry.
            self._buffer = [marker]
            self._done = True
            return
        self._sid = marker["__serve_stream__"]

    def _replica_handle(self):
        handle = _process_router().replica_for_stream(
            self._deployment, self._sid)
        if handle is None:
            raise RuntimeError(
                f"replica for stream {self._sid} no longer in the routing "
                f"table; stream lost")
        return handle

    def __iter__(self):
        import ray_tpu

        if self._sid is None and not self._done:
            self._start()
        while self._buffer or not self._done:
            while self._buffer:
                yield self._buffer.pop(0)
            if self._done:
                return
            batch = ray_tpu.get(
                self._replica_handle().stream_next.remote(self._sid))
            self._buffer.extend(batch.get("items") or [])
            if batch.get("error"):
                self._done = True
                while self._buffer:
                    yield self._buffer.pop(0)
                raise RuntimeError(f"streamed call failed: {batch['error']}")
            if batch.get("done"):
                self._done = True
