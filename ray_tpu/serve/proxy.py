"""HTTP proxy actor: aiohttp front door routing to deployment replicas.

Equivalent of the reference's `HTTPProxyActor`
(`serve/_private/http_proxy.py:250,463`): an async actor running an
aiohttp server; each request is matched against deployment route prefixes
from the (long-poll refreshed) routing table and dispatched through the
proxy's Router. ``ray_tpu.get`` on the response ref runs in the default
executor so the event loop keeps accepting connections while replicas
work — request-level parallelism is bounded by the router's
max_concurrent_queries admission control, not the proxy.

Wire format: request body is JSON (or raw text) → the deployment callable
receives the decoded payload; dict/list/str/number results come back as
JSON (bytes results stream back raw). Matches what a JAX text-generation
replica needs without dragging in an ASGI framework.

Request path (fast data plane, serve/dataplane.py): bodies ride raw-bytes
frames to the replica's direct RPC server — coalesced per event-loop tick,
no pickle, replies carry final response bytes — with the classic light
(pickled RPC) and heavy (actor task) lanes as fallback. docs/
SERVE_DATAPLANE.md has the wire contract.
"""

from __future__ import annotations

import asyncio
import json
import logging
import threading
from typing import Optional

from ray_tpu.observability import tracing as _tracing
from ray_tpu.serve import dataplane

logger = logging.getLogger(__name__)


class HTTPProxy:
    def __init__(self, host: str = "127.0.0.1", port: int = 8000):
        self._host = host
        self._port = port
        self._runner = None
        self._router = None
        self._ready_lock = None
        self._route_cache = None  # (table version, [(prefix, name, entry)])

    async def ready(self) -> int:
        """Start the server; returns the bound port. Serialized: two
        concurrent first calls racing the awaits in the body would start
        two servers and leak a Router thread pair."""
        if self._ready_lock is None:  # created pre-await: no interleave yet
            self._ready_lock = asyncio.Lock()
        async with self._ready_lock:
            return await self._ready_locked()

    async def _ready_locked(self) -> int:
        if self._runner is not None:
            return self._port
        from aiohttp import web

        import ray_tpu
        from ray_tpu.serve.controller import (
            CONTROLLER_NAME,
            SERVE_NAMESPACE,
        )
        from ray_tpu.serve.router import Router

        controller = ray_tpu.get_actor(CONTROLLER_NAME,
                                       namespace=SERVE_NAMESPACE)
        self._runtime = ray_tpu._global_runtime
        # deployment -> is it ASGI? (unknown = True: send full headers
        # until the first response reveals the shape)
        self._asgi_deployments: dict = {}
        # Nothing below may assign self state until the server is actually
        # listening: a failed start (port in use) must leave the actor
        # retryable, not "ready" with no server — and must not leak a
        # started Router thread pair per attempt.
        router = Router(controller)
        try:
            # First table fetch is blocking — keep it off the event loop.
            await asyncio.get_running_loop().run_in_executor(
                None, router._ensure_started)
            app = web.Application()
            app.router.add_route("*", "/{tail:.*}", self._handle)
            runner = web.AppRunner(app, access_log=None)
            await runner.setup()
            site = web.TCPSite(runner, self._host, self._port)
            await site.start()
        except BaseException:
            router.stop()
            raise
        self._router = router
        self._dispatcher = ReplicaDispatcher(router, self._runtime)
        self._runner = runner
        # Port 0 = ephemeral: recover the real one.
        if self._port == 0:
            self._port = runner.addresses[0][1]
        logger.info("serve proxy listening on %s:%d", self._host, self._port)
        return self._port

    async def _handle(self, request):
        # Root (or traceparent-continued) span for the whole HTTP
        # request: this is where serve traces begin. W3C propagation in:
        # clients set `traceparent`; the context then flows proxy ->
        # router -> replica -> engine over RPC framing and task specs.
        # Disabled tracing skips even the no-op span plumbing — this is
        # the per-request hot path.
        if not _tracing._ENABLED:
            return await self._handle_inner(request)
        span = _tracing.get_tracer().start_span(
            "serve.http",
            child_of=_tracing.parse_traceparent(
                request.headers.get("traceparent")),
            attrs={"method": request.method, "path": request.path})
        with span:
            resp = await self._handle_inner(request)
            span.set_attr("status", getattr(resp, "status", None))
            return resp

    async def _handle_inner(self, request):
        from aiohttp import web

        path = "/" + request.match_info["tail"]
        match = self._match_route(path)
        if match is None:
            return web.json_response(
                {"error": f"no deployment for path {path!r}"}, status=404)
        deployment, entry = match
        prefix = entry.get("route_prefix", "/") or "/"
        body = await request.read() if request.can_read_body else b""
        dispatch_version = self._router._version
        cached = self._asgi_deployments.get(deployment)
        # Full header set only when the deployment might be ASGI — plain
        # JSON deployments never read them, and encoding ~20 tuples per
        # request is measurable at high rps. Learned from the first
        # response's shape, invalidated on routing-table changes (a
        # redeploy can change the type). Names lowercase per the ASGI
        # spec (apps look up b"content-type", not the client's casing).
        want_headers = (cached is None or cached[0] != dispatch_version
                        or cached[1])
        loop = asyncio.get_running_loop()

        # Fast data plane: the request body rides a raw-bytes frame to
        # the replica's direct server (coalesced with its same-tick
        # neighbours) and the replica answers with final response bytes —
        # no pickle of bodies anywhere. None = fall back to the classic
        # pickle lanes (fast path disabled / saturated / transport says
        # the classic lane is safer).
        req_entry = {"k": "http", "m": request.method,
                     "p": self._strip_prefix(path, prefix),
                     "rp": prefix.rstrip("/"),
                     "q": request.query_string.encode("latin-1"),
                     "c": request.remote or "127.0.0.1"}
        if want_headers:
            req_entry["h"] = [(k.lower(), v)
                              for k, v in request.headers.items()]
        # Adapter-affinity routing hint for multiplexed deployments: the
        # model_id query param (the body stays opaque bytes on the fast
        # lane — the replica's engine reads the authoritative copy from
        # the payload). Parsed only when the table marks the deployment
        # multiplexed, so plain deployments never pay the query parse.
        model_id = None
        if entry.get("mux"):
            model_id = request.query.get("model_id") \
                or request.headers.get("x-model-id")
        try:
            out = await self._dispatcher.dispatch_raw_http(
                loop, deployment, req_entry, body, model_id=model_id)
        except dataplane.QuotaExceeded as e:
            # Fast 429 + Retry-After: over-quota traffic is answered at
            # the proxy door, never parked or fair-queued.
            retry_after = max(e.retry_after_s, 0.001)
            return web.json_response(
                {"error": str(e), "retry_after_s": round(retry_after, 3)},
                status=429,
                headers={"Retry-After": f"{retry_after:.3f}"})
        except dataplane.ParkBufferFull as e:
            return web.json_response({"error": str(e)}, status=503)
        except (asyncio.TimeoutError, TimeoutError):
            return web.json_response(
                {"error": "request timed out"}, status=504)
        except ConnectionError as e:
            return web.json_response({"error": str(e)}, status=502)
        except Exception as e:  # noqa: BLE001 — framing/transport bug → 500
            return web.json_response(
                {"error": f"{type(e).__name__}: {e}"}, status=500)
        if out is not None:
            resp_entry, resp_body = out
            return await self._respond_fast(request, deployment, resp_entry,
                                            resp_body, dispatch_version)

        dataplane.COUNTERS["fallback_requests"] += 1
        http_req = {
            "method": request.method,
            # ASGI path is relative to the deployment's mount point
            # (root_path), matching how the reference mounts FastAPI apps
            # under their route_prefix.
            "path": self._strip_prefix(path, prefix),
            "root_path": prefix.rstrip("/"),
            "query_string": request.query_string.encode("latin-1"),
            "client": (request.remote or "127.0.0.1", 0),
            "body": body,
        }
        if want_headers:
            http_req["headers"] = [
                (k.lower().encode("latin-1"), v.encode("latin-1"))
                for k, v in request.headers.items()]
        try:
            result = await self._dispatch(loop, deployment, http_req)
        except asyncio.TimeoutError:
            return web.json_response(
                {"error": "request timed out after 60s"}, status=500)
        except Exception as e:  # noqa: BLE001 — user code error → 500
            return web.json_response(
                {"error": f"{type(e).__name__}: {e}"}, status=500)
        return await self._respond(request, deployment, result,
                                   dispatch_version)

    async def _dispatch(self, loop, deployment: str, http_req: dict):
        return await self._dispatcher.dispatch(loop, deployment,
                                               "__serve_http__", (http_req,))

    @staticmethod
    def _strip_prefix(path: str, prefix: str) -> str:
        if prefix != "/" and path.startswith(prefix.rstrip("/")):
            rest = path[len(prefix.rstrip("/")):]
            return rest or "/"
        return path

    def _match_route(self, path: str) -> Optional[tuple]:
        """Longest-prefix route match against a per-version cache of the
        routing table — the per-request lock + table copy the old _match
        paid was measurable at fast-path rates (entries are immutable
        once published: the router swaps whole tables per version)."""
        version = self._router._version
        cache = self._route_cache
        if cache is None or cache[0] != version:
            with self._router._lock:
                routes = [(entry["route_prefix"], name, entry)
                          for name, entry in self._router._table.items()]
            cache = (version, routes)
            self._route_cache = cache
        best, best_len = None, -1
        for prefix, name, entry in cache[1]:
            if (path == prefix or path.startswith(prefix.rstrip("/") + "/")
                    or (prefix == "/" and path.startswith("/"))):
                if len(prefix) > best_len:
                    best, best_len = (name, entry), len(prefix)
        return best

    def _table_entry(self, deployment: str) -> Optional[dict]:
        with self._router._lock:
            return self._router._table.get(deployment)

    async def _respond_fast(self, request, deployment: str, entry: dict,
                            body, dispatch_version: int):
        """Write a fast-lane response: the replica already produced the
        final body bytes, status and content type — the proxy only frames
        HTTP. Streamed responses relay raw chunk frames."""
        from aiohttp import web
        from multidict import CIMultiDict

        if entry.get("err"):
            # No ASGI-ness cache update from error entries: they carry no
            # 'a' flag, and caching False here would strip headers from
            # every later request to an ASGI deployment.
            return web.json_response({"error": entry["err"]},
                                     status=int(entry.get("code") or 500))
        self._asgi_deployments[deployment] = (dispatch_version,
                                              bool(entry.get("a")))
        status = int(entry.get("status") or 200)
        if entry.get("hdr") is not None:
            # Multidict: repeated headers (Set-Cookie) must all survive.
            headers = CIMultiDict((k, v) for k, v in entry.get("hdr") or [])
        else:
            headers = CIMultiDict(
                {"Content-Type":
                 entry.get("ct") or "application/octet-stream"})
        sid = entry.get("stream")
        if sid is None:
            return web.Response(status=status, headers=headers,
                                body=bytes(body))
        # Streamed tail: chunked framing owns the length.
        headers.popall("Content-Length", None)
        headers.popall("Transfer-Encoding", None)
        resp = web.StreamResponse(status=status, headers=headers)
        resp.enable_chunked_encoding()
        await resp.prepare(request)
        if len(body):
            await resp.write(bytes(body))
        ok = await self._relay_stream_fast(deployment, sid, resp.write)
        if not ok:
            # Truncated (generator error / replica gone): abort the
            # connection so the client can't mistake a partial body for a
            # complete 200.
            if request.transport is not None:
                request.transport.close()
            return resp
        await resp.write_eof()
        return resp

    async def _relay_stream_fast(self, deployment: str, sid: str,
                                 write) -> bool:
        """Drain a replica-side stream as raw chunk frames (the PR-3
        token stream rides this). Returns False on truncation."""
        loop = asyncio.get_running_loop()
        lane = self._dispatcher.fastlane
        try:
            while True:
                out = await lane.stream_pull(loop, deployment, sid)
                if out is None:
                    logger.warning("stream %s: replica unreachable "
                                   "(truncated)", sid)
                    return False
                meta, chunks = out
                for c in chunks:
                    await write(bytes(c))
                if meta.get("err"):
                    logger.warning("stream %s failed: %s", sid, meta["err"])
                    return False
                if meta.get("done"):
                    return True
        except BaseException:
            # Client disconnect (write failed) or handler cancellation:
            # release the replica-side pump/queue NOW instead of letting
            # the generator idle against a full queue until the 120s reap.
            lane.stream_cancel(loop, deployment, sid)
            raise

    async def _respond(self, request, deployment: str, result,
                       dispatch_version: int):
        from aiohttp import web

        # Stamp with the version the request was DISPATCHED under: a
        # redeploy landing mid-flight must not get its type cached from
        # the old replica's response shape.
        self._asgi_deployments[deployment] = (
            dispatch_version,
            isinstance(result, dict) and bool(result.get("__serve_http__")))
        if isinstance(result, dict) and result.get("__serve_http__"):
            from multidict import CIMultiDict

            # Multidict: repeated headers (Set-Cookie) must all survive.
            headers = CIMultiDict(
                (k, v) for k, v in result.get("headers") or [])
            sid = result.get("stream")
            if sid is None:
                return web.Response(status=result["status"], headers=headers,
                                    body=result.get("body") or b"")
            # Streamed ASGI body: first chunk(s) already in hand, relay
            # the rest from the replica's stream queue. Chunked framing
            # owns the length — the app's content-length (e.g. a
            # FileResponse) would make aiohttp reject chunked mode.
            headers.popall("Content-Length", None)
            headers.popall("Transfer-Encoding", None)
            resp = web.StreamResponse(status=result["status"],
                                      headers=headers)
            resp.enable_chunked_encoding()
            await resp.prepare(request)
            await resp.write(result.get("body") or b"")
            ok = await self._relay_stream(deployment, sid, resp.write)
            if not ok:
                # Truncated (generator error / replica gone): abort the
                # connection so the client can't mistake a partial body
                # for a complete 200.
                if request.transport is not None:
                    request.transport.close()
                return resp
            await resp.write_eof()
            return resp
        if isinstance(result, dict) and result.get("__serve_stream__"):
            # Plain deployment returned a generator: stream items as
            # chunked text/bytes.
            resp = web.StreamResponse(status=200)
            resp.enable_chunked_encoding()
            await resp.prepare(request)

            async def write(item):
                if isinstance(item, (bytes, bytearray, memoryview)):
                    await resp.write(bytes(item))
                elif isinstance(item, str):
                    await resp.write(item.encode())
                else:
                    await resp.write((json.dumps(item) + "\n").encode())

            ok = await self._relay_stream(deployment,
                                          result["__serve_stream__"], write)
            if not ok:
                if request.transport is not None:
                    request.transport.close()
                return resp
            await resp.write_eof()
            return resp
        if isinstance(result, (dict, list, int, float, bool)) \
                or result is None:
            return web.json_response({"result": result})
        if isinstance(result, (bytes, bytearray, memoryview)):
            # Lane parity: the fast lane returns bytes results raw; a
            # request that fell back here must not get the str() repr.
            return web.Response(body=bytes(result),
                                content_type="application/octet-stream")
        return web.Response(text=str(result))

    async def _relay_stream(self, deployment: str, sid: str, write) -> bool:
        """Drain a replica-side stream (stream_next pulls) into `write`.
        Returns False on truncation (stream error / replica gone)."""
        handle = self._router.replica_for_stream(deployment, sid)
        if handle is None:
            logger.warning("stream %s: replica left the table", sid)
            return False
        try:
            while True:
                ref = handle.stream_next.remote(sid)
                batch = await asyncio.wrap_future(
                    self._runtime.get_future(ref))
                for item in batch.get("items") or []:
                    await write(item)
                if batch.get("error"):
                    logger.warning("stream %s failed: %s", sid,
                                   batch["error"])
                    return False
                if batch.get("done"):
                    return True
        except BaseException:
            # Client disconnect (write failed) or handler cancellation:
            # release the replica-side pump/queue NOW instead of letting
            # the generator idle against a full queue until the 120s reap.
            try:
                handle.stream_cancel.remote(sid)
            except Exception:  # noqa: BLE001 — reaper is the backstop
                pass
            raise

    def _match(self, path: str) -> Optional[str]:
        match = self._match_route(path)
        return match[0] if match is not None else None

    async def counters(self) -> dict:
        """This proxy process's fast-path counters (the zero-pickle
        acceptance proof reads these)."""
        return dataplane.counters_snapshot()

    async def stop(self):
        if self._router is not None:
            self._router.stop()
        if self._runner is not None:
            await self._runner.cleanup()
            self._runner = None


class ReplicaDispatcher:
    """Routes one call to a replica of a deployment; shared by the HTTP
    and gRPC proxies so the two ingresses cannot drift. Lanes, fastest
    first: (1) the raw fast lane (`self.fastlane`, serve/dataplane.py)
    — zero-pickle coalesced frames on the replica's direct server; (2)
    the light lane below: admission via router.reserve(), then
    `actor_call_light` — pickled args, result rides the RPC response,
    skipping the actor-task path (TaskSpec + ObjectRef + reply push);
    (3) the full actor-call path, which owns retries and backpressure.
    Any light-lane transport problem (replica restarting, stale
    connection, saturation) falls through to the heavy lane.

    `method` follows the router convention: the "__serve_http__" sentinel
    targets the replica's HTTP entry point; anything else is a user
    method routed through the replica's handle_request."""

    def __init__(self, router, runtime):
        self._router = router
        self._runtime = runtime
        # Raw fast lane: coalesced zero-pickle frames (serve/dataplane.py)
        # shared by the HTTP and gRPC ingresses so they cannot drift.
        self.fastlane = dataplane.FastLane(router, runtime)
        # replica_id -> RpcClient for the light request/response lane
        # (invalidated on any transport error; pruned against the routing
        # table when its version changes).
        self._light_clients: dict = {}
        self._light_version = -2  # != router's initial -1: prune on first use

    async def dispatch_raw_http(self, loop, deployment: str,
                                entry: dict, body, model_id=None):
        """HTTP request over the raw fast lane; None = use the classic
        lanes (the caller owns the fallback and its counter)."""
        return await self.fastlane.dispatch(loop, deployment, entry, body,
                                            model_id=model_id)

    async def dispatch_call(self, loop, deployment: str, body: bytes,
                            model_id=None):
        """Unary call (gRPC ingress parity) over the raw fast lane: the
        request bytes pass through untouched; the replica decodes
        msgpack-decodable bodies and encodes the result symmetrically."""
        return await self.fastlane.dispatch(
            loop, deployment, {"k": "call", "m": "__call__"}, body,
            model_id=model_id)

    @staticmethod
    def _light_call(method: str, args: tuple) -> dict:
        """actor_call_light payload for a router-convention call. The
        light lane invokes the replica wrapper's methods directly:
        handle_http for the HTTP sentinel, handle_request for user
        methods (both async on the replica's actor loop)."""
        from ray_tpu.core import serialization

        if method == "__serve_http__":
            return {"m": "handle_http",
                    "a": serialization.serialize_to_bytes(args)}
        return {"m": "handle_request",
                "a": serialization.serialize_to_bytes((method, args, {}))}

    async def dispatch(self, loop, deployment: str, method: str,
                       args: tuple):
        span = _tracing.NOOP_SPAN
        if _tracing._ENABLED:
            span = _tracing.get_tracer().start_span(
                "serve.dispatch", attrs={"deployment": deployment})
        with span:
            return await self._dispatch_traced(loop, deployment, method,
                                               args, span)

    async def _dispatch_traced(self, loop, deployment: str, method: str,
                               args: tuple, span):
        from ray_tpu.core import serialization

        version = self._router._version
        if version != self._light_version:
            # Prune clients for replicas that left the table (scale-down /
            # redeploy): without this a long-lived proxy leaks one client
            # per dead replica under autoscaling churn.
            self._light_version = version
            with self._router._lock:
                live = {rid for entry in self._router._table.values()
                        for rid, _ in entry.get("replicas", ())}
            for rid in list(self._light_clients):
                if rid not in live:
                    self._light_clients.pop(rid, None)
        route_span = _tracing.NOOP_SPAN
        if _tracing._ENABLED:
            route_span = _tracing.get_tracer().start_span(
                "serve.route", attrs={"deployment": deployment})
        with route_span:
            choice = self._router.reserve(deployment)
            route_span.set_attr("replica",
                                choice[0] if choice is not None else None)
        if choice is not None:
            span.set_attr("lane", "light")
            replica_id, handle = choice
            # Slot ownership: exactly one of (this coroutine, the late
            # callback) releases. On timeout the REPLICA IS STILL RUNNING
            # the request, so the slot transfers to the callback and is
            # only freed when the reply (or connection loss) arrives —
            # releasing early would let admission control dispatch on top
            # of an overloaded replica. pop-from-dict decides the owner.
            slot = {"owned": True}
            slot_lock = threading.Lock()

            def _release_once():
                with slot_lock:
                    owned, slot["owned"] = slot["owned"], False
                if owned:
                    self._router.release(replica_id)

            sent = False
            try:
                client = self._light_clients.get(replica_id)
                if client is None:
                    client = await loop.run_in_executor(
                        None, lambda: self._runtime._actor_client(
                            handle._actor_id).client)
                    self._light_clients[replica_id] = client
                fut = loop.create_future()

                def _complete(f, env, payload):
                    if not f.done():
                        f.set_result((env, payload))

                def cb(env, payload):
                    # Reply (or connection loss) arrived: the replica is
                    # done with this request — free the slot regardless of
                    # whether the waiter is still listening (it may have
                    # timed out; a timed-out request keeps its slot until
                    # here precisely because the replica was still busy).
                    try:
                        loop.call_soon_threadsafe(_complete, fut, env,
                                                  bytes(payload or b""))
                    finally:
                        _release_once()

                client.call_async("actor_call_light",
                                  self._light_call(method, args), cb)
                sent = True
                env, payload = await asyncio.wait_for(fut, timeout=60.0)
            except (asyncio.TimeoutError, asyncio.CancelledError):
                if not sent:
                    _release_once()  # cancelled pre-send: cb never fires
                raise  # otherwise cb releases when the replica finishes
            except Exception:  # noqa: BLE001 — dead/stale connection
                self._light_clients.pop(replica_id, None)
                if sent:
                    # call_async raised after a possible partial send, and
                    # the client delivered (or will deliver) the loss to
                    # cb, which releases the slot. The request MAY have
                    # executed — re-dispatching would double-run
                    # non-idempotent work.
                    raise
                _release_once()  # cb never registered: we still own it
                span.set_attr("lane", "heavy")
                return await self._dispatch_heavy(loop, deployment, method,
                                                  args)
            if env.get("_lost"):
                # Connection died after delivery: ambiguous whether the
                # replica executed the request. Surface the failure —
                # at-most-once, like the heavy actor path — instead of
                # blindly re-executing.
                self._light_clients.pop(replica_id, None)
                raise ConnectionError(
                    f"replica {replica_id} connection lost mid-request")
            if env.get("e"):
                # Pre-execution failure (actor still initializing, direct
                # server up before the instance): provably not executed,
                # safe to fall back to the heavy path, which queues and
                # retries properly.
                self._light_clients.pop(replica_id, None)
                span.set_attr("lane", "heavy")
                return await self._dispatch_heavy(loop, deployment, method,
                                                  args)
            data = serialization.loads(payload)
            if data.get("err") is not None:
                raise serialization.deserialize_exception(data["err"])
            return serialization.deserialize(data["r"])
        span.set_attr("lane", "heavy")
        return await self._dispatch_heavy(loop, deployment, method, args)

    async def _dispatch_heavy(self, loop, deployment: str, method: str,
                              args: tuple):
        """Full actor-call path (blocking admission control on a thread;
        result via the runtime's future registry)."""
        import functools

        ref = self._router.try_assign(deployment, method, args, {})
        if ref is None:
            ref = await loop.run_in_executor(
                None, functools.partial(
                    self._router.assign, deployment, method,
                    args, {}, timeout_s=30.0))
        return await asyncio.wait_for(
            asyncio.wrap_future(self._runtime.get_future(ref)),
            timeout=60.0)
