"""HTTP proxy actor: aiohttp front door routing to deployment replicas.

Equivalent of the reference's `HTTPProxyActor`
(`serve/_private/http_proxy.py:250,463`): an async actor running an
aiohttp server; each request is matched against deployment route prefixes
from the (long-poll refreshed) routing table and dispatched through the
proxy's Router. ``ray_tpu.get`` on the response ref runs in the default
executor so the event loop keeps accepting connections while replicas
work — request-level parallelism is bounded by the router's
max_concurrent_queries admission control, not the proxy.

Wire format: request body is JSON (or raw text) → the deployment callable
receives the decoded payload; dict/list/str/number results come back as
JSON. Matches what a JAX text-generation replica needs without dragging in
an ASGI framework.
"""

from __future__ import annotations

import asyncio
import json
import logging
from typing import Optional

logger = logging.getLogger(__name__)


class HTTPProxy:
    def __init__(self, host: str = "127.0.0.1", port: int = 8000):
        self._host = host
        self._port = port
        self._runner = None
        self._router = None

    async def ready(self) -> int:
        """Start the server; returns the bound port."""
        if self._runner is not None:
            return self._port
        from aiohttp import web

        import ray_tpu
        from ray_tpu.serve.controller import (
            CONTROLLER_NAME,
            SERVE_NAMESPACE,
        )
        from ray_tpu.serve.router import Router

        controller = ray_tpu.get_actor(CONTROLLER_NAME,
                                       namespace=SERVE_NAMESPACE)
        self._router = Router(controller)
        # First table fetch is blocking — keep it off the event loop.
        await asyncio.get_running_loop().run_in_executor(
            None, self._router._ensure_started)

        app = web.Application()
        app.router.add_route("*", "/{tail:.*}", self._handle)
        self._runner = web.AppRunner(app, access_log=None)
        await self._runner.setup()
        site = web.TCPSite(self._runner, self._host, self._port)
        await site.start()
        # Port 0 = ephemeral: recover the real one.
        if self._port == 0:
            self._port = self._runner.addresses[0][1]
        logger.info("serve proxy listening on %s:%d", self._host, self._port)
        return self._port

    async def _handle(self, request):
        from aiohttp import web

        path = "/" + request.match_info["tail"]
        deployment = self._match(path)
        if deployment is None:
            return web.json_response(
                {"error": f"no deployment for path {path!r}"}, status=404)
        if request.can_read_body:
            raw = await request.read()
            try:
                payload = json.loads(raw) if raw else None
            except json.JSONDecodeError:
                payload = raw.decode("utf-8", "replace")
        else:
            payload = dict(request.query) or None
        loop = asyncio.get_running_loop()
        try:
            result = await loop.run_in_executor(
                None, self._dispatch, deployment, payload)
        except Exception as e:  # noqa: BLE001 — user code error → 500
            return web.json_response(
                {"error": f"{type(e).__name__}: {e}"}, status=500)
        if isinstance(result, (dict, list, int, float, bool)) \
                or result is None:
            return web.json_response({"result": result})
        return web.Response(text=str(result))

    def _dispatch(self, deployment: str, payload):
        import ray_tpu

        ref = self._router.assign(deployment, "__call__", (payload,), {},
                                  timeout_s=30.0)
        return ray_tpu.get(ref, timeout=60.0)

    def _match(self, path: str) -> Optional[str]:
        with self._router._lock:
            table = dict(self._router._table)
        best, best_len = None, -1
        for name, entry in table.items():
            prefix = entry["route_prefix"]
            if (path == prefix or path.startswith(prefix.rstrip("/") + "/")
                    or (prefix == "/" and path.startswith("/"))):
                if len(prefix) > best_len:
                    best, best_len = name, len(prefix)
        return best

    async def stop(self):
        if self._router is not None:
            self._router.stop()
        if self._runner is not None:
            await self._runner.cleanup()
            self._runner = None
