"""Replica actor: hosts one copy of a deployment's user callable.

Equivalent of the reference's `RayServeReplica`
(`serve/_private/replica.py:285`, `handle_request` :508) — an async actor
whose asyncio loop gives request-level concurrency (the reference uses the
same design), tracks ongoing/processed counts for the controller's
autoscaler, and answers health checks. JAX inference runs on the replica's
chip: the replica actor is scheduled with the deployment's
``ray_actor_options`` (e.g. ``num_tpus=1``) so the raylet grants it the
accelerator env before the process initializes JAX.
"""

from __future__ import annotations

import asyncio
import inspect
import time
from typing import Any, Dict

from ray_tpu.observability import tracing as _tracing


class Replica:
    """Generic wrapper actor; instantiated via ActorClass options with
    max_concurrency > max_concurrent_queries so control-plane calls
    (stats/ping/prepare_shutdown) never starve behind user requests."""

    def __init__(self, deployment_name: str, user_cls, init_args,
                 init_kwargs, replica_id: str = ""):
        self._deployment = deployment_name
        self._replica_id = replica_id
        self._user = user_cls(*init_args, **(init_kwargs or {}))
        self._asgi_app = self._resolve_asgi_app(user_cls)
        self._ongoing = 0
        self._processed = 0
        self._errored = 0
        self._started_at = time.time()
        self._draining = False
        # Streamed responses in flight: id -> [queue, pump_task, last_use]
        # (events: ("chunk", item) | ("end", None) | ("error", str)).
        # Reaped after STREAM_IDLE_S without a pull — an HTTP client that
        # disconnects mid-stream would otherwise leak the queue and a
        # pump coroutine forever.
        self._streams: Dict[str, list] = {}
        self._stream_seq = 0

    STREAM_IDLE_S = 120.0

    def _resolve_asgi_app(self, user_cls):
        """serve.ingress attachment: the ASGI callable itself, a zero-arg
        factory (apps that don't pickle), or a one-arg factory receiving
        the deployment instance (routes that need deployment state)."""
        app = getattr(user_cls, "__serve_asgi_app__", None)
        if app is None:
            return None
        params = []
        try:
            params = [p for p in inspect.signature(app).parameters.values()
                      if p.default is p.empty
                      and p.kind in (p.POSITIONAL_ONLY,
                                     p.POSITIONAL_OR_KEYWORD)]
        except (TypeError, ValueError):
            pass
        if len(params) >= 2:
            return app       # ASGI callable: (scope, receive, send)
        if len(params) == 1:
            return app(self._user)
        return app()

    async def handle_request(self, method_name: str, args, kwargs) -> Any:
        if self._draining:
            raise RuntimeError(
                f"replica of {self._deployment} is draining")
        # Replica-side span: the trace context arrived over the light
        # lane's RPC framing or the heavy path's task spec.
        span = _tracing.NOOP_SPAN
        if _tracing._ENABLED:
            span = _tracing.get_tracer().start_span(
                "serve.replica", attrs={"deployment": self._deployment,
                                        "method": method_name,
                                        "replica": self._replica_id})
        with span:
            return await self._handle_request_inner(method_name, args,
                                                    kwargs)

    async def _handle_request_inner(self, method_name: str, args,
                                    kwargs) -> Any:
        self._ongoing += 1
        try:
            method = getattr(self._user, method_name)
            if inspect.iscoroutinefunction(method) or (
                    getattr(method, "__serve_is_batched__", False)):
                out = await method(*args, **(kwargs or {}))
            else:
                # Sync user callables must not block the replica's event
                # loop — request concurrency (and honest queue-depth stats
                # for the autoscaler) depends on it.
                import functools

                out = await asyncio.get_running_loop().run_in_executor(
                    None, functools.partial(method, *args,
                                            **(kwargs or {})))
                if inspect.iscoroutine(out):
                    out = await out
            if inspect.isgenerator(out) or inspect.isasyncgen(out):
                # Streamed result: pump items through a queue the caller
                # drains with stream_next (reference streaming generators,
                # `handle.options(stream=True)`).
                return {"__serve_stream__": self._pump_generator(out)}
            self._processed += 1
            return out
        except Exception:
            self._errored += 1
            raise
        finally:
            self._ongoing -= 1

    def _pump_generator(self, gen) -> str:
        queue: asyncio.Queue = asyncio.Queue(maxsize=256)
        loop = asyncio.get_running_loop()

        async def pump():
            try:
                if inspect.isasyncgen(gen):
                    async for item in gen:
                        await queue.put(("chunk", item))
                else:
                    sentinel = object()
                    while True:
                        item = await loop.run_in_executor(
                            None, next, gen, sentinel)
                        if item is sentinel:
                            break
                        await queue.put(("chunk", item))
                await queue.put(("end", None))
            except Exception as e:  # noqa: BLE001 — delivered to consumer
                await queue.put(("error", f"{type(e).__name__}: {e}"))

        task = asyncio.ensure_future(pump())
        return self._register_stream(queue, task)

    # ------------------------------------------------------------- HTTP

    async def handle_http(self, request: Dict[str, Any]) -> Any:
        """One HTTP request, translated by the proxy to a plain dict
        (method/path/query_string/headers/body). ASGI deployments
        (serve.ingress) get a full ASGI scope; plain deployments get the
        decoded JSON payload, preserving the simple wire format."""
        if self._asgi_app is not None:
            span = _tracing.NOOP_SPAN
            if _tracing._ENABLED:
                span = _tracing.get_tracer().start_span(
                    "serve.replica", attrs={"deployment": self._deployment,
                                            "method": "asgi",
                                            "replica": self._replica_id})
            with span:
                return await self._handle_asgi(request)
        body = request.get("body") or b""
        if body:
            import json

            try:
                payload = json.loads(body)
            except json.JSONDecodeError:
                payload = body.decode("utf-8", "replace")
        else:
            from urllib.parse import parse_qsl

            qs = dict(parse_qsl(
                (request.get("query_string") or b"").decode("latin-1")))
            payload = qs or None
        return await self.handle_request("__call__", (payload,), {})

    async def _handle_asgi(self, request: Dict[str, Any]) -> Dict[str, Any]:
        """Run the ASGI app; buffered responses return whole, streamed
        ones (more_body chunks) hand back a stream id the proxy drains
        via stream_next (reference `http_proxy.py:355` pipes ASGI sends
        straight to the socket; here they cross an actor boundary)."""
        self._ongoing += 1
        scope = {
            "type": "http",
            "asgi": {"version": "3.0", "spec_version": "2.3"},
            "http_version": "1.1",
            "method": request["method"],
            "scheme": "http",
            "path": request["path"],
            "raw_path": request["path"].encode("latin-1"),
            "query_string": request.get("query_string") or b"",
            "root_path": request.get("root_path") or "",
            "headers": [(k, v) for k, v in request.get("headers") or []],
            "client": tuple(request.get("client") or ("127.0.0.1", 0)),
            "server": ("127.0.0.1", 0),
        }
        body = request.get("body") or b""
        # Bounded: an abandoned stream must not buffer the app's whole
        # remaining body; the app's send() backpressures instead and the
        # idle reaper cancels the pump.
        queue: asyncio.Queue = asyncio.Queue(maxsize=256)
        state = {"status": 200, "headers": [], "started": False}

        body_sent = {"done": False}

        async def receive():
            if not body_sent["done"]:
                body_sent["done"] = True
                return {"type": "http.request", "body": body,
                        "more_body": False}
            return {"type": "http.disconnect"}

        async def send(event):
            if event["type"] == "http.response.start":
                state["status"] = event["status"]
                state["headers"] = [
                    (bytes(k).decode("latin-1"), bytes(v).decode("latin-1"))
                    for k, v in event.get("headers") or []]
                state["started"] = True
            elif event["type"] == "http.response.body":
                chunk = event.get("body") or b""
                if chunk:
                    await queue.put(("chunk", chunk))
                if not event.get("more_body"):
                    await queue.put(("end", None))

        async def run_app():
            try:
                await self._asgi_app(scope, receive, send)
                await queue.put(("end", None))
            except Exception as e:  # noqa: BLE001 — app error -> 500
                await queue.put(("error", f"{type(e).__name__}: {e}"))
            finally:
                self._ongoing -= 1
                self._processed += 1

        task = asyncio.ensure_future(run_app())
        # Drain eagerly: if the app finishes (or errors) before streaming
        # past one chunk, answer in one shot; otherwise register a stream.
        chunks = []
        while True:
            kind, item = await queue.get()
            if kind == "chunk":
                chunks.append(item)
                if not task.done():
                    # App still producing: stream the rest.
                    sid = self._register_stream(queue, task)
                    return {"__serve_http__": True, "status": state["status"],
                            "headers": state["headers"],
                            "body": b"".join(chunks), "stream": sid}
            elif kind == "end":
                return {"__serve_http__": True, "status": state["status"],
                        "headers": state["headers"],
                        "body": b"".join(chunks)}
            else:  # error
                self._errored += 1
                return {"__serve_http__": True, "status": 500,
                        "headers": [("content-type", "text/plain")],
                        "body": item.encode()}

    def _register_stream(self, queue: asyncio.Queue, task) -> str:
        self._reap_idle_streams()
        self._stream_seq += 1
        sid = f"{self._replica_id}:{self._stream_seq}"
        self._streams[sid] = [queue, task, time.monotonic()]
        return sid

    def _reap_idle_streams(self):
        now = time.monotonic()
        for sid, (queue, task, last) in list(self._streams.items()):
            if now - last > self.STREAM_IDLE_S:
                self._streams.pop(sid, None)
                if task is not None and not task.done():
                    task.cancel()

    async def stream_cancel(self, sid: str) -> bool:
        """Abandon a registered stream: cancel its pump task and drop the
        queue now instead of letting them idle until the reaper (a caller
        that cannot consume the stream — e.g. the unary gRPC ingress —
        must not strand a full queue + running generator per request)."""
        rec = self._streams.pop(sid, None)
        if rec is None:
            return False
        task = rec[1]
        if task is not None and not task.done():
            task.cancel()
        return True

    async def stream_next(self, sid: str, max_items: int = 64,
                          timeout_s: float = 30.0) -> Dict[str, Any]:
        """Pull the next batch of items from a registered stream."""
        self._reap_idle_streams()
        rec = self._streams.get(sid)
        if rec is None:
            return {"items": [], "done": True,
                    "error": "unknown stream (expired or replica restart)"}
        queue = rec[0]
        rec[2] = time.monotonic()
        items, done, error = [], False, None
        try:
            kind, item = await asyncio.wait_for(queue.get(), timeout_s)
        except asyncio.TimeoutError:
            return {"items": [], "done": False}
        while True:
            if kind == "chunk":
                items.append(item)
            elif kind == "end":
                done = True
            else:
                done, error = True, item
            if done or len(items) >= max_items or queue.empty():
                break
            kind, item = queue.get_nowait()
        if done:
            self._streams.pop(sid, None)
        else:
            rec[2] = time.monotonic()
        return {"items": items, "done": done, "error": error}

    def stats(self) -> Dict[str, Any]:
        out = {
            "deployment": self._deployment,
            "ongoing": self._ongoing,
            "processed": self._processed,
            "errored": self._errored,
            "uptime_s": time.time() - self._started_at,
        }
        # User-exported metrics (e.g. the inference engine's queue depth
        # and tokens/s): the controller folds `queue_depth` into its
        # autoscaling signal so backlog inside the deployment counts as
        # pressure, not just in-flight RPCs.
        hook = getattr(self._user, "__serve_metrics__", None)
        if hook is not None:
            try:
                out["user"] = dict(hook())
            except Exception:  # noqa: BLE001 — stats must never fail
                pass
        return out

    def ping(self) -> str:
        # The controller health-checks periodically: piggyback the idle
        # stream sweep so abandoned streams are reaped even when no new
        # streaming request ever reaches this replica.
        self._reap_idle_streams()
        return "pong"

    async def prepare_shutdown(self, timeout_s: float = 5.0) -> int:
        """Graceful drain: refuse new requests, wait for ongoing ones,
        then tear down user-side resources — every `@serve.batch` queue
        (its flusher task and parked futures would otherwise leak) and
        the optional `__serve_shutdown__` hook (e.g. the inference
        engine's scheduler thread)."""
        self._draining = True
        deadline = time.time() + timeout_s
        # Streamed responses decrement _ongoing as soon as the stream id
        # is returned — wait on the registered streams too, or a graceful
        # drain would kill the engine mid-generation for clients that are
        # still pulling tokens.
        while (self._ongoing > 0 or self._streams) \
                and time.time() < deadline:
            await asyncio.sleep(0.02)
        from ray_tpu.serve.batching import _BatchQueue

        for value in list(getattr(self._user, "__dict__", {}).values()):
            if isinstance(value, _BatchQueue):
                try:
                    value.stop()
                except Exception:  # noqa: BLE001 — teardown is best effort
                    pass
        hook = getattr(self._user, "__serve_shutdown__", None)
        if hook is not None:
            try:
                out = hook()
                if inspect.iscoroutine(out):
                    await out
            except Exception:  # noqa: BLE001
                pass
        return self._ongoing

    def reconfigure(self, user_config: Any) -> None:
        hook = getattr(self._user, "reconfigure", None)
        if hook is not None:
            hook(user_config)


def make_function_wrapper(fn):
    """Adapt a bare function deployment into a callable class."""

    class _FunctionDeployment:
        def __init__(self, *args, **kwargs):
            self._args = args
            self._kwargs = kwargs

        def __call__(self, request):
            return fn(request, *self._args, **self._kwargs)

    _FunctionDeployment.__name__ = getattr(fn, "__name__", "function")
    return _FunctionDeployment
