"""Replica actor: hosts one copy of a deployment's user callable.

Equivalent of the reference's `RayServeReplica`
(`serve/_private/replica.py:285`, `handle_request` :508) — an async actor
whose asyncio loop gives request-level concurrency (the reference uses the
same design), tracks ongoing/processed counts for the controller's
autoscaler, and answers health checks. JAX inference runs on the replica's
chip: the replica actor is scheduled with the deployment's
``ray_actor_options`` (e.g. ``num_tpus=1``) so the raylet grants it the
accelerator env before the process initializes JAX.
"""

from __future__ import annotations

import asyncio
import inspect
import time
from typing import Any, Dict


class Replica:
    """Generic wrapper actor; instantiated via ActorClass options with
    max_concurrency > max_concurrent_queries so control-plane calls
    (stats/ping/prepare_shutdown) never starve behind user requests."""

    def __init__(self, deployment_name: str, user_cls, init_args,
                 init_kwargs):
        self._deployment = deployment_name
        self._user = user_cls(*init_args, **(init_kwargs or {}))
        self._ongoing = 0
        self._processed = 0
        self._errored = 0
        self._started_at = time.time()
        self._draining = False

    async def handle_request(self, method_name: str, args, kwargs) -> Any:
        if self._draining:
            raise RuntimeError(
                f"replica of {self._deployment} is draining")
        self._ongoing += 1
        try:
            method = getattr(self._user, method_name)
            if inspect.iscoroutinefunction(method) or (
                    getattr(method, "__serve_is_batched__", False)):
                out = await method(*args, **(kwargs or {}))
            else:
                # Sync user callables must not block the replica's event
                # loop — request concurrency (and honest queue-depth stats
                # for the autoscaler) depends on it.
                import functools

                out = await asyncio.get_running_loop().run_in_executor(
                    None, functools.partial(method, *args,
                                            **(kwargs or {})))
                if inspect.iscoroutine(out):
                    out = await out
            self._processed += 1
            return out
        except Exception:
            self._errored += 1
            raise
        finally:
            self._ongoing -= 1

    def stats(self) -> Dict[str, Any]:
        return {
            "deployment": self._deployment,
            "ongoing": self._ongoing,
            "processed": self._processed,
            "errored": self._errored,
            "uptime_s": time.time() - self._started_at,
        }

    def ping(self) -> str:
        return "pong"

    async def prepare_shutdown(self, timeout_s: float = 5.0) -> int:
        """Graceful drain: refuse new requests, wait for ongoing ones."""
        self._draining = True
        deadline = time.time() + timeout_s
        while self._ongoing > 0 and time.time() < deadline:
            await asyncio.sleep(0.02)
        return self._ongoing

    def reconfigure(self, user_config: Any) -> None:
        hook = getattr(self._user, "reconfigure", None)
        if hook is not None:
            hook(user_config)


def make_function_wrapper(fn):
    """Adapt a bare function deployment into a callable class."""

    class _FunctionDeployment:
        def __init__(self, *args, **kwargs):
            self._args = args
            self._kwargs = kwargs

        def __call__(self, request):
            return fn(request, *self._args, **self._kwargs)

    _FunctionDeployment.__name__ = getattr(fn, "__name__", "function")
    return _FunctionDeployment
