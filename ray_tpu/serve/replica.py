"""Replica actor: hosts one copy of a deployment's user callable.

Equivalent of the reference's `RayServeReplica`
(`serve/_private/replica.py:285`, `handle_request` :508) — an async actor
whose asyncio loop gives request-level concurrency (the reference uses the
same design), tracks ongoing/processed counts for the controller's
autoscaler, and answers health checks. JAX inference runs on the replica's
chip: the replica actor is scheduled with the deployment's
``ray_actor_options`` (e.g. ``num_tpus=1``) so the raylet grants it the
accelerator env before the process initializes JAX.
"""

from __future__ import annotations

import asyncio
import functools
import inspect
import json
import time
from typing import Any, Dict, List, Tuple

from ray_tpu.observability import tracing as _tracing
from ray_tpu.serve import dataplane


class Replica:
    """Generic wrapper actor; instantiated via ActorClass options with
    max_concurrency > max_concurrent_queries so control-plane calls
    (stats/ping/prepare_shutdown) never starve behind user requests."""

    def __init__(self, deployment_name: str, user_cls, init_args,
                 init_kwargs, replica_id: str = "", shard_ctx=None):
        self._deployment = deployment_name
        self._replica_id = replica_id
        # Sharded replica groups: activate this rank's shard context
        # BEFORE user code runs — mesh bring-up (and, on SPMD backends,
        # jax.distributed) must win the race with the deployment ctor's
        # first jax computation (XLA backends freeze on first use). The
        # deployment reads its mesh via `shardgroup.current_mesh()`.
        self._shard_ctx = None
        if shard_ctx is not None:
            from ray_tpu import shardgroup

            self._shard_ctx = shardgroup.activate(shard_ctx)
        self._user = user_cls(*init_args, **(init_kwargs or {}))
        self._asgi_app = self._resolve_asgi_app(user_cls)
        self._ongoing = 0
        self._processed = 0
        self._errored = 0
        self._started_at = time.time()
        self._draining = False
        # Fast-lane method resolution cache: name -> (bound method,
        # needs_await). The user class is fixed for the replica's
        # lifetime, so iscoroutinefunction/batched checks run once per
        # method instead of per request.
        self._raw_methods: Dict[str, tuple] = {}
        # Streamed responses in flight: id -> [queue, pump_task, last_use]
        # (events: ("chunk", item) | ("end", None) | ("error", str)).
        # Reaped after STREAM_IDLE_S without a pull — an HTTP client that
        # disconnects mid-stream would otherwise leak the queue and a
        # pump coroutine forever.
        self._streams: Dict[str, list] = {}
        self._stream_seq = 0

    STREAM_IDLE_S = 120.0

    def _resolve_asgi_app(self, user_cls):
        """serve.ingress attachment: the ASGI callable itself, a zero-arg
        factory (apps that don't pickle), or a one-arg factory receiving
        the deployment instance (routes that need deployment state)."""
        app = getattr(user_cls, "__serve_asgi_app__", None)
        if app is None:
            return None
        params = []
        try:
            params = [p for p in inspect.signature(app).parameters.values()
                      if p.default is p.empty
                      and p.kind in (p.POSITIONAL_ONLY,
                                     p.POSITIONAL_OR_KEYWORD)]
        except (TypeError, ValueError):
            pass
        if len(params) >= 2:
            return app       # ASGI callable: (scope, receive, send)
        if len(params) == 1:
            return app(self._user)
        return app()

    async def handle_request(self, method_name: str, args, kwargs) -> Any:
        if self._draining:
            raise RuntimeError(
                f"replica of {self._deployment} is draining")
        # Replica-side span: the trace context arrived over the light
        # lane's RPC framing or the heavy path's task spec.
        span = _tracing.NOOP_SPAN
        if _tracing._ENABLED:
            span = _tracing.get_tracer().start_span(
                "serve.replica", attrs={"deployment": self._deployment,
                                        "method": method_name,
                                        "replica": self._replica_id})
        with span:
            return await self._handle_request_inner(method_name, args,
                                                    kwargs)

    async def _handle_request_inner(self, method_name: str, args,
                                    kwargs) -> Any:
        self._ongoing += 1
        try:
            method = getattr(self._user, method_name)
            if inspect.iscoroutinefunction(method) or (
                    getattr(method, "__serve_is_batched__", False)):
                out = await method(*args, **(kwargs or {}))
            else:
                # Sync user callables must not block the replica's event
                # loop — request concurrency (and honest queue-depth stats
                # for the autoscaler) depends on it.
                import functools

                out = await asyncio.get_running_loop().run_in_executor(
                    None, functools.partial(method, *args,
                                            **(kwargs or {})))
                if inspect.iscoroutine(out):
                    out = await out
            if inspect.isgenerator(out) or inspect.isasyncgen(out):
                # Streamed result: pump items through a queue the caller
                # drains with stream_next (reference streaming generators,
                # `handle.options(stream=True)`).
                return {"__serve_stream__": self._pump_generator(out)}
            self._processed += 1
            return out
        except Exception:
            self._errored += 1
            raise
        finally:
            self._ongoing -= 1

    def _pump_generator(self, gen) -> str:
        queue: asyncio.Queue = asyncio.Queue(maxsize=256)
        loop = asyncio.get_running_loop()

        async def pump():
            try:
                if inspect.isasyncgen(gen):
                    async for item in gen:
                        await queue.put(("chunk", item))
                else:
                    sentinel = object()
                    while True:
                        item = await loop.run_in_executor(
                            None, next, gen, sentinel)
                        if item is sentinel:
                            break
                        await queue.put(("chunk", item))
                await queue.put(("end", None))
            except Exception as e:  # noqa: BLE001 — delivered to consumer
                await queue.put(("error", f"{type(e).__name__}: {e}"))

        task = asyncio.ensure_future(pump())
        return self._register_stream(queue, task)

    # ------------------------------------------------------------- HTTP

    async def handle_http(self, request: Dict[str, Any]) -> Any:
        """One HTTP request, translated by the proxy to a plain dict
        (method/path/query_string/headers/body). ASGI deployments
        (serve.ingress) get a full ASGI scope; plain deployments get the
        decoded JSON payload, preserving the simple wire format."""
        if self._asgi_app is not None:
            span = _tracing.NOOP_SPAN
            if _tracing._ENABLED:
                span = _tracing.get_tracer().start_span(
                    "serve.replica", attrs={"deployment": self._deployment,
                                            "method": "asgi",
                                            "replica": self._replica_id})
            with span:
                return await self._handle_asgi(request)
        payload = self._decode_http_payload(
            request.get("body") or b"",
            request.get("query_string") or b"")
        return await self.handle_request("__call__", (payload,), {})

    @staticmethod
    def _decode_http_payload(body: bytes, query_string: bytes):
        """HTTP body -> deployment payload, shared by the classic and
        raw lanes so their decode semantics cannot drift: JSON body if
        it parses, raw text otherwise, query-string dict (or None) for
        body-less requests."""
        if body:
            try:
                return json.loads(body)
            except json.JSONDecodeError:
                return body.decode("utf-8", "replace")
        from urllib.parse import parse_qsl

        qs = dict(parse_qsl(bytes(query_string).decode("latin-1")))
        return qs or None

    async def _handle_asgi(self, request: Dict[str, Any]) -> Dict[str, Any]:
        """Run the ASGI app; buffered responses return whole, streamed
        ones (more_body chunks) hand back a stream id the proxy drains
        via stream_next (reference `http_proxy.py:355` pipes ASGI sends
        straight to the socket; here they cross an actor boundary)."""
        self._ongoing += 1
        scope = {
            "type": "http",
            "asgi": {"version": "3.0", "spec_version": "2.3"},
            "http_version": "1.1",
            "method": request["method"],
            "scheme": "http",
            "path": request["path"],
            "raw_path": request["path"].encode("latin-1"),
            "query_string": request.get("query_string") or b"",
            "root_path": request.get("root_path") or "",
            "headers": [(k, v) for k, v in request.get("headers") or []],
            "client": tuple(request.get("client") or ("127.0.0.1", 0)),
            "server": ("127.0.0.1", 0),
        }
        body = request.get("body") or b""
        # Bounded: an abandoned stream must not buffer the app's whole
        # remaining body; the app's send() backpressures instead and the
        # idle reaper cancels the pump.
        queue: asyncio.Queue = asyncio.Queue(maxsize=256)
        state = {"status": 200, "headers": [], "started": False}

        body_sent = {"done": False}

        async def receive():
            if not body_sent["done"]:
                body_sent["done"] = True
                return {"type": "http.request", "body": body,
                        "more_body": False}
            return {"type": "http.disconnect"}

        async def send(event):
            if event["type"] == "http.response.start":
                state["status"] = event["status"]
                state["headers"] = [
                    (bytes(k).decode("latin-1"), bytes(v).decode("latin-1"))
                    for k, v in event.get("headers") or []]
                state["started"] = True
            elif event["type"] == "http.response.body":
                chunk = event.get("body") or b""
                if chunk:
                    await queue.put(("chunk", chunk))
                if not event.get("more_body"):
                    await queue.put(("end", None))

        async def run_app():
            try:
                await self._asgi_app(scope, receive, send)
                await queue.put(("end", None))
            except Exception as e:  # noqa: BLE001 — app error -> 500
                await queue.put(("error", f"{type(e).__name__}: {e}"))
            finally:
                self._ongoing -= 1
                self._processed += 1

        task = asyncio.ensure_future(run_app())
        # Drain eagerly: if the app finishes (or errors) before streaming
        # past one chunk, answer in one shot; otherwise register a stream.
        chunks = []
        while True:
            kind, item = await queue.get()
            if kind == "chunk":
                chunks.append(item)
                if not task.done():
                    # App still producing: stream the rest.
                    sid = self._register_stream(queue, task)
                    return {"__serve_http__": True, "status": state["status"],
                            "headers": state["headers"],
                            "body": b"".join(chunks), "stream": sid}
            elif kind == "end":
                return {"__serve_http__": True, "status": state["status"],
                        "headers": state["headers"],
                        "body": b"".join(chunks)}
            else:  # error
                self._errored += 1
                return {"__serve_http__": True, "status": 500,
                        "headers": [("content-type", "text/plain")],
                        "body": item.encode()}

    # ------------------------------------------------------ raw fast lane

    async def __serve_raw_dispatch__(self, frame: memoryview) -> list:
        """Serve fast-lane entry point (the worker's `serve_raw` raw
        handler): decode one coalesced request frame, answer every
        request, encode one reply frame. Bodies are raw bytes end to end
        — request payloads and response bodies never touch pickle, and a
        frame of N requests costs one replica wakeup (sync callables
        additionally share a single executor hop)."""
        meta, region = dataplane.decode_frame(frame)
        reqs = meta.get("reqs") or []
        bodies = dataplane.slice_bodies(region,
                                        [r.get("n", 0) for r in reqs])
        dataplane.COUNTERS["raw_dispatch_frames"] += 1
        dataplane.COUNTERS["raw_dispatch_requests"] += len(reqs)
        span = _tracing.NOOP_SPAN
        if _tracing._ENABLED:
            span = _tracing.get_tracer().start_span(
                "serve.replica", attrs={"deployment": self._deployment,
                                        "replica": self._replica_id,
                                        "raw": True,
                                        "frame_size": len(reqs)})
        with span:
            results = await self._raw_dispatch_all(reqs, bodies)
        entries: List[Dict[str, Any]] = []
        out_bodies: List[Any] = []
        for entry, body in results:
            entry["n"] = len(body)
            entries.append(entry)
            if entry["n"]:
                out_bodies.append(body)
        return dataplane.encode_frame({"v": 1, "resps": entries},
                                      out_bodies)

    async def _raw_dispatch_all(self, reqs, bodies
                                ) -> List[Tuple[Dict[str, Any], bytes]]:
        if self._draining:
            # Provably not executed: the proxy may safely re-route these
            # to another replica (retriable).
            return [({"err": f"replica of {self._deployment} is draining",
                      "code": 503, "retriable": True}, b"")
                    for _ in reqs]
        n = len(reqs)
        results: List[Any] = [None] * n
        sync_jobs: List[Tuple[int, Any]] = []   # (idx, zero-arg callable)
        coro_jobs: List[Tuple[int, Any]] = []   # (idx, coroutine)
        # ASGI requests account their own ongoing/processed counts inside
        # _handle_asgi — counting them here too would double the load the
        # autoscaler sees. ("call"-kind requests on an ASGI deployment
        # still count here: they bypass the ASGI app.)
        def _self_counting(req):
            return self._asgi_app is not None and req.get("k") == "http"

        n_own = sum(1 for r in reqs if not _self_counting(r))
        self._ongoing += n_own
        try:
            for i, (req, body) in enumerate(zip(reqs, bodies)):
                try:
                    kind, job = self._raw_prepare(req, body)
                except Exception as e:  # noqa: BLE001 — per-request error
                    results[i] = e
                    continue
                if kind == "sync":
                    sync_jobs.append((i, job))
                else:
                    coro_jobs.append((i, job))
            if sync_jobs:
                # ONE executor hop for the whole frame's sync callables:
                # per-request hops were a measurable tax at proxy rates.
                def run_sync():
                    out = []
                    for i, job in sync_jobs:
                        try:
                            out.append((i, job(), None))
                        except Exception as e:  # noqa: BLE001 — per-request
                            out.append((i, None, e))
                    return out

                loop = asyncio.get_running_loop()
                for i, value, err in await loop.run_in_executor(None,
                                                                run_sync):
                    results[i] = err if err is not None else (value,)
            if coro_jobs:
                gathered = await asyncio.gather(
                    *(job for _, job in coro_jobs), return_exceptions=True)
                for (i, _), value in zip(coro_jobs, gathered):
                    results[i] = value if isinstance(value, BaseException) \
                        else (value,)
            out: List[Tuple[Dict[str, Any], bytes]] = []
            for i, req in enumerate(reqs):
                r = results[i]
                if isinstance(r, BaseException):
                    self._errored += 1
                    out.append(({"err": f"{type(r).__name__}: {r}",
                                 "code": 500}, b""))
                    continue
                value = r[0]
                if inspect.iscoroutine(value):
                    # A sync callable returned a coroutine: await on loop.
                    try:
                        value = await value
                    except Exception as e:  # noqa: BLE001 — per-request
                        self._errored += 1
                        out.append(({"err": f"{type(e).__name__}: {e}",
                                     "code": 500}, b""))
                        continue
                if inspect.isgenerator(value) or inspect.isasyncgen(value):
                    value = {"__serve_stream__": self._pump_generator(value)}
                if not _self_counting(req):
                    self._processed += 1
                out.append(self._encode_raw_result(req, value))
            return out
        finally:
            self._ongoing -= n_own

    def _resolve_raw_method(self, name: str) -> tuple:
        cached = self._raw_methods.get(name)
        if cached is None:
            method = getattr(self._user, name, None)
            if method is None:
                raise AttributeError(
                    f"deployment {self._deployment!r} has no method "
                    f"{name!r}")
            needs_await = inspect.iscoroutinefunction(method) or bool(
                getattr(method, "__serve_is_batched__", False))
            # Keys are the user class's method names (getattr above
            # rejects anything else): bounded by the deployment's code.
            # raylint: disable=RL011 — bounded by user-class methods
            cached = self._raw_methods[name] = (method, needs_await)
        return cached

    def _raw_prepare(self, req: Dict[str, Any], body: memoryview):
        """One request entry -> ("sync", zero-arg callable) or ("coro",
        coroutine). Raising here is a per-request error."""
        kind = req.get("k")
        if kind == "http":
            if self._asgi_app is not None:
                return "coro", self._handle_asgi(self._raw_http_req(req,
                                                                    body))
            method, needs_await = self._resolve_raw_method("__call__")
            if needs_await:
                decode = functools.partial(self._raw_http_payload, req,
                                           bytes(body))

                async def run():
                    return await method(decode())
                return "coro", run()
            # Payload decode (json) rides the sync job into the shared
            # executor hop — the loop never touches request bodies.
            return "sync", functools.partial(
                self._call_sync_http, method, req, bytes(body))
        if kind == "call":
            method, needs_await = self._resolve_raw_method(
                req.get("m") or "__call__")
            payload = self._raw_call_payload(bytes(body))
            if needs_await:
                async def run_call():
                    return await method(payload)
                return "coro", run_call()
            return "sync", functools.partial(method, payload)
        raise ValueError(f"unknown fast-lane request kind {kind!r}")

    def _call_sync_http(self, method, req, body: bytes):
        return method(self._raw_http_payload(req, body))

    @staticmethod
    def _raw_http_req(req: Dict[str, Any], body) -> Dict[str, Any]:
        return {
            "method": req.get("m") or "GET",
            "path": req.get("p") or "/",
            "root_path": req.get("rp") or "",
            "query_string": req.get("q") or b"",
            "client": (req.get("c") or "127.0.0.1", 0),
            # ASGI scope headers are (bytes, bytes) pairs — the frame
            # meta carries them as str (msgpack), encode like the classic
            # lane does.
            "headers": [
                (k.encode("latin-1") if isinstance(k, str) else bytes(k),
                 v.encode("latin-1") if isinstance(v, str) else bytes(v))
                for k, v in req.get("h") or []],
            "body": bytes(body),
        }

    def _raw_http_payload(self, req: Dict[str, Any], body: bytes):
        return self._decode_http_payload(body, req.get("q") or b"")

    @staticmethod
    def _raw_call_payload(body: bytes):
        """gRPC-parity payload: msgpack-decodable bodies are decoded to a
        Python value, opaque bytes pass through untouched."""
        import msgpack

        try:
            return msgpack.unpackb(body, raw=False, strict_map_key=False)
        except Exception:  # noqa: BLE001 — opaque bytes pass through
            return body

    def _encode_raw_result(self, req: Dict[str, Any], result
                           ) -> Tuple[Dict[str, Any], bytes]:
        if req.get("k") == "call":
            import msgpack

            if isinstance(result, dict) and (
                    result.get("__serve_stream__")
                    or result.get("__serve_http__")):
                sid = (result.get("__serve_stream__")
                       or result.get("stream"))
                return {"stream": sid or "", "err":
                        "streaming/ASGI deployments are not servable over "
                        "the unary gRPC ingress — use the HTTP proxy",
                        "code": 501}, b""
            if isinstance(result, (bytes, bytearray, memoryview)):
                return {"enc": "bin"}, bytes(result)
            try:
                return {"enc": "msgpack"}, msgpack.packb(result,
                                                         use_bin_type=True)
            except Exception as e:  # noqa: BLE001 — per-request error
                return {"err": f"result of type {type(result).__name__} is "
                        f"not msgpack-serializable: {e}", "code": 500}, b""
        # HTTP result -> final response: status + headers + body bytes so
        # the proxy writes them through without touching the payload.
        if isinstance(result, dict) and result.get("__serve_http__"):
            entry = {"status": result.get("status", 200),
                     "hdr": list(result.get("headers") or []), "a": 1}
            sid = result.get("stream")
            if sid:
                entry["stream"] = sid
            return entry, bytes(result.get("body") or b"")
        if isinstance(result, dict) and result.get("__serve_stream__"):
            return {"status": 200, "stream": result["__serve_stream__"],
                    "ct": "application/octet-stream"}, b""
        if isinstance(result, (bytes, bytearray, memoryview)):
            return {"status": 200,
                    "ct": "application/octet-stream"}, bytes(result)
        if isinstance(result, str):
            return {"status": 200, "ct": "text/plain; charset=utf-8"}, \
                result.encode()
        if isinstance(result, (dict, list, int, float, bool)) \
                or result is None:
            return {"status": 200, "ct": "application/json"}, \
                json.dumps({"result": result}).encode()
        return {"status": 200, "ct": "text/plain; charset=utf-8"}, \
            str(result).encode()

    async def __serve_stream_raw__(self, frame: memoryview) -> list:
        """Raw stream pull (the worker's `serve_stream` handler): drain
        the next batch of a registered stream as length-prefixed chunk
        bytes — the PR-3 token stream rides this as just another
        consumer. `cancel` frames release the pump immediately."""
        meta, _ = dataplane.decode_frame(frame)
        sid = meta.get("sid") or ""
        if meta.get("cancel"):
            await self.stream_cancel(sid)
            return dataplane.encode_frame({"done": True, "lens": []}, [])
        batch = await self.stream_next(sid,
                                       max_items=meta.get("max") or 64,
                                       timeout_s=meta.get("timeout") or 30.0)
        chunks = [self._encode_stream_item(it)
                  for it in batch.get("items") or []]
        out = {"done": bool(batch.get("done")),
               "lens": [len(c) for c in chunks]}
        if batch.get("error"):
            out["err"] = batch["error"]
        return dataplane.encode_frame(out, chunks)

    @staticmethod
    def _encode_stream_item(item) -> bytes:
        if isinstance(item, (bytes, bytearray, memoryview)):
            return bytes(item)
        if isinstance(item, str):
            return item.encode()
        return (json.dumps(item) + "\n").encode()

    def _register_stream(self, queue: asyncio.Queue, task) -> str:
        self._reap_idle_streams()
        self._stream_seq += 1
        sid = f"{self._replica_id}:{self._stream_seq}"
        self._streams[sid] = [queue, task, time.monotonic()]
        return sid

    def _reap_idle_streams(self):
        now = time.monotonic()
        for sid, (queue, task, last) in list(self._streams.items()):
            if now - last > self.STREAM_IDLE_S:
                self._streams.pop(sid, None)
                if task is not None and not task.done():
                    task.cancel()

    async def stream_cancel(self, sid: str) -> bool:
        """Abandon a registered stream: cancel its pump task and drop the
        queue now instead of letting them idle until the reaper (a caller
        that cannot consume the stream — e.g. the unary gRPC ingress —
        must not strand a full queue + running generator per request)."""
        rec = self._streams.pop(sid, None)
        if rec is None:
            return False
        task = rec[1]
        if task is not None and not task.done():
            task.cancel()
        return True

    async def stream_next(self, sid: str, max_items: int = 64,
                          timeout_s: float = 30.0) -> Dict[str, Any]:
        """Pull the next batch of items from a registered stream."""
        self._reap_idle_streams()
        rec = self._streams.get(sid)
        if rec is None:
            return {"items": [], "done": True,
                    "error": "unknown stream (expired or replica restart)"}
        queue = rec[0]
        rec[2] = time.monotonic()
        items, done, error = [], False, None
        try:
            kind, item = await asyncio.wait_for(queue.get(), timeout_s)
        except asyncio.TimeoutError:
            return {"items": [], "done": False}
        while True:
            if kind == "chunk":
                items.append(item)
            elif kind == "end":
                done = True
            else:
                done, error = True, item
            if done or len(items) >= max_items or queue.empty():
                break
            kind, item = queue.get_nowait()
        if done:
            self._streams.pop(sid, None)
        else:
            rec[2] = time.monotonic()
        return {"items": items, "done": done, "error": error}

    @staticmethod
    def _node_hex() -> str:
        """This replica's node id (for the controller's locality table);
        empty when instantiated outside a cluster (unit tests)."""
        import ray_tpu

        rt = ray_tpu._global_runtime
        if rt is None or rt.node_id is None:
            return ""
        return rt.node_id.hex()

    def stats(self) -> Dict[str, Any]:
        out = {
            "deployment": self._deployment,
            "ongoing": self._ongoing,
            "processed": self._processed,
            "errored": self._errored,
            "uptime_s": time.time() - self._started_at,
            "node": self._node_hex(),
            "fastpath": {
                "frames": dataplane.COUNTERS["raw_dispatch_frames"],
                "requests": dataplane.COUNTERS["raw_dispatch_requests"],
            },
        }
        if self._shard_ctx is not None:
            out["shard"] = self._shard_ctx.as_dict()
        # User-exported metrics (e.g. the inference engine's queue depth
        # and tokens/s): the controller folds `queue_depth` into its
        # autoscaling signal so backlog inside the deployment counts as
        # pressure, not just in-flight RPCs.
        hook = getattr(self._user, "__serve_metrics__", None)
        if hook is not None:
            try:
                out["user"] = dict(hook())
            except Exception:  # noqa: BLE001 — stats must never fail
                pass
        return out

    def ping(self) -> Dict[str, Any]:
        # The controller health-checks periodically: piggyback the idle
        # stream sweep so abandoned streams are reaped even when no new
        # streaming request ever reaches this replica. The node id rides
        # along so the controller can publish replica placement in the
        # routing table (locality-aware direct routing) without an extra
        # round trip.
        self._reap_idle_streams()
        return {"ok": True, "node": self._node_hex()}

    async def prepare_shutdown(self, timeout_s: float = 5.0) -> int:
        """Graceful drain: refuse new requests, wait for ongoing ones,
        then tear down user-side resources — every `@serve.batch` queue
        (its flusher task and parked futures would otherwise leak) and
        the optional `__serve_shutdown__` hook (e.g. the inference
        engine's scheduler thread)."""
        self._draining = True
        deadline = time.time() + timeout_s
        # Streamed responses decrement _ongoing as soon as the stream id
        # is returned — wait on the registered streams too, or a graceful
        # drain would kill the engine mid-generation for clients that are
        # still pulling tokens.
        while (self._ongoing > 0 or self._streams) \
                and time.time() < deadline:
            await asyncio.sleep(0.02)
        from ray_tpu.serve.batching import _BatchQueue

        for value in list(getattr(self._user, "__dict__", {}).values()):
            if isinstance(value, _BatchQueue):
                try:
                    value.stop()
                except Exception:  # noqa: BLE001 — teardown is best effort
                    pass
        hook = getattr(self._user, "__serve_shutdown__", None)
        if hook is not None:
            try:
                out = hook()
                if inspect.iscoroutine(out):
                    await out
            except Exception:  # noqa: BLE001
                pass
        return self._ongoing

    def reconfigure(self, user_config: Any) -> None:
        hook = getattr(self._user, "reconfigure", None)
        if hook is not None:
            hook(user_config)


def make_function_wrapper(fn):
    """Adapt a bare function deployment into a callable class."""

    class _FunctionDeployment:
        def __init__(self, *args, **kwargs):
            self._args = args
            self._kwargs = kwargs

        def __call__(self, request):
            return fn(request, *self._args, **self._kwargs)

    _FunctionDeployment.__name__ = getattr(fn, "__name__", "function")
    return _FunctionDeployment
