"""Client-side router: replica choice + per-replica admission control.

Equivalent of the reference's `Router`/`ReplicaSet.assign_replica`
(`serve/_private/router.py:274,227`): keeps a local snapshot of the
controller's routing table (pushed via a background long-poll thread —
never polled per-request; the controller piggybacks replica placement
and queue depths on the same push), prefers a co-located replica with
headroom and otherwise picks by power-of-two-choices over local
in-flight + pushed depth, and blocks when all replicas are saturated.
Scale-to-zero deployments appear as `parked` entries; routing to one
fires a throttled wake RPC and waits for the cold-started replica to be
pushed into the table.
In-flight counts are decremented by a reaper thread that waits on the
outstanding ObjectRefs — the framework has no future callbacks by design
(completion events ride the worker push channel), so one thread per router
amortizes completion tracking across all requests.
"""

from __future__ import annotations

import logging
import random
import threading
import time
from typing import Dict, Optional, Tuple

logger = logging.getLogger(__name__)


class Router:
    UNKNOWN_GRACE_S = 5.0  # deploy-in-progress grace before KeyError
    WAKE_THROTTLE_S = 0.5  # min gap between wake RPCs per deployment

    def __init__(self, controller_handle, poll_timeout_s: float = 5.0):
        self._controller = controller_handle
        self._poll_timeout_s = poll_timeout_s
        self._lock = threading.Condition()
        # Locality: this process's node (lazy — resolving it needs a live
        # runtime) so _pick can prefer co-located replicas.
        self._local_node: Optional[str] = None
        # Scale-to-zero wake throttling: deployment -> last wake monotonic.
        self._last_wake: Dict[str, float] = {}
        # Threads parked in assign()'s backpressure wait. notify_all costs
        # two context switches per call; at proxy request rates an
        # unconditional notify in release() measurably taxes the hot path,
        # so completions only notify when someone is actually waiting.
        self._waiters = 0
        self._version = -1
        self._table: Dict[str, dict] = {}
        # replica_id -> local in-flight count
        self._inflight: Dict[str, int] = {}
        # outstanding ref -> replica_id (reaped for decrements)
        self._outstanding: Dict[object, str] = {}
        self._stopped = False
        self._poller = threading.Thread(
            target=self._poll_loop, name="serve-router-poll", daemon=True)
        self._reaper = threading.Thread(
            target=self._reap_loop, name="serve-router-reap", daemon=True)
        self._started = False
        self._start_lock = threading.Lock()

    def _ensure_started(self):
        # The router is process-global (handle.py), so first use can race
        # across threads: only one may start the background threads, and
        # latecomers must wait for the synchronous first table fetch.
        with self._start_lock:
            if not self._started:
                # Synchronous first fetch so the first request sees a table.
                self._refresh_once(timeout=10.0)
                self._poller.start()
                self._reaper.start()
                self._started = True

    def stop(self):
        self._stopped = True

    # ------------------------------------------------------------- routing

    def assign(self, deployment: str, method_name: str, args, kwargs,
               timeout_s: Optional[float] = None):
        """Pick a replica and submit; returns the ObjectRef. Blocks while
        every replica is at max_concurrent_queries (backpressure)."""
        self._ensure_started()
        start = time.monotonic()
        deadline = None if timeout_s is None else start + timeout_s
        with self._lock:
            while True:
                entry = self._table.get(deployment)
                choice = self._reserve_locked(entry)
                if choice is not None:
                    replica_id, handle = choice[0], choice[1]
                    break
                if entry is not None and not entry["replicas"] \
                        and entry.get("parked"):
                    # Scale-to-zero: ask the controller for a replica
                    # (throttled, off-thread — never an RPC under the
                    # router lock) and keep waiting for the table push.
                    self.wake(deployment)
                # A name absent from the table is (after a short grace for
                # an in-progress deploy) an error, not backpressure — don't
                # park forever on a typo.
                if entry is None and \
                        time.monotonic() - start > self.UNKNOWN_GRACE_S:
                    raise KeyError(
                        f"no deployment named {deployment!r} "
                        f"(known: {sorted(self._table)})")
                # No replicas yet or all saturated: wait for a table change
                # or a completion (reaper notifies).
                wait_t = 1.0
                if deadline is not None:
                    remaining = deadline - time.monotonic()
                    if remaining <= 0:
                        raise TimeoutError(
                            f"no replica of {deployment!r} available within "
                            f"{timeout_s}s")
                    wait_t = min(wait_t, remaining)
                self._waiters += 1
                try:
                    self._lock.wait(timeout=wait_t)
                finally:
                    self._waiters -= 1
        return self._submit(handle, replica_id, method_name, args, kwargs)

    def try_assign(self, deployment: str, method_name: str, args, kwargs):
        """Non-blocking assign: submit iff a replica has headroom right
        now, else None (caller falls back to the blocking path). Lets an
        event loop dispatch without an executor hop in the common
        unsaturated case."""
        if not self._started:
            return None
        with self._lock:
            choice = self._reserve_locked(self._table.get(deployment))
        if choice is None:
            return None
        replica_id, handle = choice[0], choice[1]
        return self._submit(handle, replica_id, method_name, args, kwargs)

    def reserve(self, deployment: str) -> Optional[Tuple[str, object]]:
        """Non-blocking admission: count an in-flight slot on a replica
        with headroom and return (replica_id, handle), or None when
        saturated/unknown. The caller OWNS the slot and must call
        release() when its request completes — used by transports that
        bypass _submit/ObjectRefs (the proxy's light lane)."""
        choice = self.reserve_fast(deployment)
        if choice is None:
            return None
        return choice[0], choice[1]

    def reserve_fast(self, deployment: str, exclude: Optional[set] = None,
                     model_id: Optional[str] = None
                     ) -> Optional[Tuple[str, object, bool]]:
        """reserve() for the raw fast lane: returns (replica_id, handle,
        colocated) — `colocated` reports whether the locality-first pick
        landed on this process's node. `exclude` skips replicas the
        caller just lost a frame to (the retry-once path). `model_id`
        steers multiplexed deployments toward a replica that already
        holds that adapter (table-pushed residency)."""
        if not self._started:
            return None
        with self._lock:
            return self._reserve_locked(self._table.get(deployment),
                                        exclude or (), model_id)

    def deployment_state(self, deployment: str) -> str:
        """Coarse state for the fast lane's no-replica handling:
        "unknown" (not in the table), "parked" (scale-to-zero, waiting
        for a cold start), or "active"."""
        with self._lock:
            entry = self._table.get(deployment)
        if entry is None:
            return "unknown"
        if not entry["replicas"] and entry.get("parked"):
            return "parked"
        return "active"

    def has_replicas(self, deployment: str) -> bool:
        """Cheap routable-replica probe (the fast lane's cold-start wait
        polls this on the event loop — it must hold no thread)."""
        with self._lock:
            entry = self._table.get(deployment)
            return bool(entry and entry["replicas"])

    def live_replica_ids(self) -> set:
        with self._lock:
            return {rid for entry in self._table.values()
                    for rid, _ in entry.get("replicas", ())}

    def live_tenants(self) -> set:
        """Tenant names referenced by the current table (the proxy's
        admission registry prunes against this on version changes)."""
        with self._lock:
            return {entry["tenant"] for entry in self._table.values()
                    if entry.get("tenant")}

    def entry_snapshot(self, deployment: str) -> Optional[dict]:
        """The deployment's current table entry (immutable once pushed —
        the controller publishes fresh dicts and the router swaps whole
        tables per version, so returning the reference is safe)."""
        with self._lock:
            return self._table.get(deployment)

    def wake(self, deployment: str) -> None:
        """Nudge the controller to cold-start a parked deployment.
        Throttled per deployment and fired from a one-shot thread: the
        actor submit may block resolving the controller connection, and
        callers hold the router lock or sit on an event loop."""
        now = time.monotonic()
        last = self._last_wake.get(deployment, 0.0)
        if now - last < self.WAKE_THROTTLE_S:
            return
        self._last_wake[deployment] = now

        def fire():
            try:
                self._controller.wake_deployment.remote(deployment)
            except Exception:  # noqa: BLE001 — next throttled wake retries
                logger.debug("serve: wake of %s failed", deployment,
                             exc_info=True)

        threading.Thread(target=fire, name="serve-wake", daemon=True).start()

    def release(self, replica_id: str):
        """Return a slot taken with reserve()."""
        with self._lock:
            self._dec_inflight_locked(replica_id)
            if self._waiters:
                self._lock.notify_all()

    def _dec_inflight_locked(self, replica_id: str) -> None:
        # Entries vanish at zero instead of lingering at 0: replica ids
        # churn forever under autoscaling, and a dict keyed by every
        # replica that ever existed is exactly the unbounded-keyed-state
        # leak RL011 hunts.
        n = self._inflight.get(replica_id, 0) - 1
        if n > 0:
            self._inflight[replica_id] = n
        else:
            self._inflight.pop(replica_id, None)

    def _reserve_locked(self, entry, exclude=(), model_id=None):
        """Pick a replica with headroom and count the in-flight slot —
        the single admission-accounting point for every assign path."""
        if not entry or not entry["replicas"]:
            return None
        choice = self._pick(entry, exclude, model_id)
        if choice is None:
            return None
        replica_id = choice[0]
        self._inflight[replica_id] = self._inflight.get(replica_id, 0) + 1
        return choice

    def _submit(self, handle, replica_id: str, method_name: str, args,
                kwargs):
        if method_name == "__serve_http__":
            # Reserved sentinel for the replica-level HTTP entry point
            # (dunder so it can't shadow a user deployment method).
            ref = handle.handle_http.remote(*args)
        else:
            ref = handle.handle_request.remote(method_name, args, kwargs)
        with self._lock:
            self._outstanding[ref] = replica_id
        return ref

    def replica_for_stream(self, deployment: str, sid: str):
        """Resolve the replica actor handle a stream id points back to
        (stream ids are '<replica_id>:<seq>'); None once the replica has
        left the routing table."""
        replica_id = sid.rsplit(":", 1)[0]
        with self._lock:
            entry = self._table.get(deployment)
            for rid, handle in (entry or {}).get("replicas", ()):
                if rid == replica_id:
                    return handle
        return None

    def _local_node_hex(self) -> Optional[str]:
        if self._local_node is None:
            try:
                import ray_tpu

                rt = ray_tpu._global_runtime
                if rt is not None and rt.node_id is not None:
                    self._local_node = rt.node_id.hex()
            except Exception:  # noqa: BLE001 — no runtime (unit tests)
                pass
            if self._local_node is None:
                self._local_node = ""  # resolved-and-absent: don't retry
        return self._local_node or None

    def _pick(self, entry: dict, exclude=(), model_id=None
              ) -> Optional[Tuple[str, object, bool]]:
        """Replica choice: adapter affinity, then locality, then
        power-of-two-choices.

        For a multiplexed deployment with a request `model_id`, replicas
        already holding that adapter (per the table-pushed residency map)
        are preferred — routing a hot adapter's traffic to a cold replica
        costs that replica a load (and possibly an LRU eviction of
        someone else's adapter). A co-located replica (same node as this
        router, per the table's pushed placement map) with headroom wins
        within the preferred set — that request skips the network
        entirely. Otherwise two random candidates are compared by local
        in-flight + the controller-pushed queue depth (stale by at most
        the health-check cadence; the local in-flight half is exact) and
        the lighter one is picked — the classic p2c bound on max load
        without scanning every replica under the lock. Only RUNNING
        replicas ever appear in the table, so DEAD and draining replicas
        are structurally unroutable here."""
        limit = entry["max_concurrent_queries"]
        nodes = entry.get("nodes") or {}
        depths = entry.get("depths") or {}
        replicas = entry["replicas"]
        if model_id is not None:
            residency = entry.get("adapters") or {}
            holders = [(rid, h) for rid, h in replicas
                       if rid not in exclude
                       and model_id in residency.get(rid, ())
                       and self._inflight.get(rid, 0) < limit]
            if holders:
                replicas = holders
        local = self._local_node_hex() if nodes else None
        co_best, co_load = None, None
        candidates = []
        for replica_id, handle in replicas:
            if replica_id in exclude:
                continue
            load = self._inflight.get(replica_id, 0)
            if load >= limit:
                continue
            if local is not None and nodes.get(replica_id) == local:
                # Pack-first among co-located replicas: the MOST loaded
                # one that still has headroom. Requests concentrating on
                # one replica coalesce into bigger frames (and bigger
                # @serve.batch gangs); admission spills to the next
                # replica only at max_concurrent_queries, which bounds
                # the latency cost.
                if co_load is None or load > co_load:
                    co_best, co_load = (replica_id, handle), load
            else:
                candidates.append((replica_id, handle, load))
        if co_best is not None:
            return co_best[0], co_best[1], True
        if not candidates:
            return None
        if len(candidates) == 1:
            replica_id, handle, _ = candidates[0]
            return replica_id, handle, False
        i = random.randrange(len(candidates))
        j = random.randrange(len(candidates) - 1)
        if j >= i:
            j += 1
        a, b = candidates[i], candidates[j]
        pick = a if (a[2] + depths.get(a[0], 0)
                     <= b[2] + depths.get(b[0], 0)) else b
        return pick[0], pick[1], False

    # ------------------------------------------------------- background IO

    def _refresh_once(self, timeout: float):
        import ray_tpu

        try:
            version, table = ray_tpu.get(
                self._controller.listen_for_change.remote(
                    self._version, self._poll_timeout_s),
                timeout=timeout)
        except Exception:  # noqa: BLE001 — controller busy/briefly down
            return
        with self._lock:
            if version != self._version:
                self._version = version
                self._table = table
                # Wake-throttle entries are keyed by deployment name:
                # prune against the fresh table so deleted deployments
                # don't accumulate here forever (RL011 discipline).
                for dep in list(self._last_wake):
                    if dep not in table:
                        self._last_wake.pop(dep, None)
                self._lock.notify_all()

    def _poll_loop(self):
        while not self._stopped:
            self._refresh_once(timeout=self._poll_timeout_s + 10.0)

    def _reap_loop(self):
        import ray_tpu

        while not self._stopped:
            with self._lock:
                refs = list(self._outstanding.keys())
            if not refs:
                with self._lock:
                    self._lock.wait(timeout=0.05)
                continue
            try:
                ready, _ = ray_tpu.wait(refs, num_returns=len(refs),
                                        timeout=0.05)
            except Exception:  # noqa: BLE001
                continue
            if ready:
                with self._lock:
                    for ref in ready:
                        replica_id = self._outstanding.pop(ref, None)
                        if replica_id is not None:
                            self._dec_inflight_locked(replica_id)
                    if self._waiters:
                        self._lock.notify_all()
