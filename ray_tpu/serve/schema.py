"""Declarative Serve config: build an app to a dict/YAML, deploy from one.

Equivalent of the reference's `python/ray/serve/schema.py` +
`serve build`/`serve deploy` CLI flow: an application is described by an
import path plus per-deployment config overrides, validated and applied
without touching the application code. Plain dicts rather than pydantic
models (not a baked-in dependency) — `validate_config` gives the same
fail-at-submit ergonomics.

Config shape::

    http: {host: "127.0.0.1", port: 8000}
    applications:
      - name: default
        import_path: my_module:app        # Application or Deployment
        deployments:                      # optional per-deployment overrides
          - name: GPT2Sampler
            num_replicas: 2
            max_concurrent_queries: 16
            autoscaling: {min_replicas: 1, max_replicas: 4,
                          target_ongoing_requests: 2.0}
            route_prefix: /gpt2
"""

from __future__ import annotations

import importlib
from dataclasses import asdict
from typing import Any, Dict, List, Optional

from ray_tpu.serve.config import AutoscalingConfig

_DEPLOYMENT_KEYS = {"name", "num_replicas", "max_concurrent_queries",
                    "autoscaling", "route_prefix", "ray_actor_options",
                    "shard_spec"}


def validate_config(config: Dict[str, Any]) -> None:
    if not isinstance(config, dict):
        raise ValueError("serve config must be a mapping")
    apps = config.get("applications")
    if not isinstance(apps, list) or not apps:
        raise ValueError("serve config needs a non-empty 'applications' list")
    for app in apps:
        if "import_path" not in app:
            raise ValueError(
                f"application {app.get('name', '?')!r} needs an import_path "
                "('module:attribute')")
        if ":" not in app["import_path"]:
            raise ValueError(
                f"import_path {app['import_path']!r} must be "
                "'module:attribute'")
        for dep in app.get("deployments", []) or []:
            if "name" not in dep:
                raise ValueError("every deployment override needs a 'name'")
            unknown = set(dep) - _DEPLOYMENT_KEYS
            if unknown:
                raise ValueError(
                    f"unknown deployment option(s) {sorted(unknown)} for "
                    f"{dep['name']!r}; valid: {sorted(_DEPLOYMENT_KEYS)}")
    http = config.get("http") or {}
    if http and not isinstance(http.get("port", 0), int):
        raise ValueError("http.port must be an integer")


def import_attr(import_path: str):
    module_name, attr = import_path.split(":", 1)
    module = importlib.import_module(module_name)
    obj = module
    for part in attr.split("."):
        obj = getattr(obj, part)
    return obj


def _apply_overrides(app, overrides: List[Dict[str, Any]]):
    """Return the app graph with per-deployment config overrides applied.

    Deployment objects are shared by reference inside Application nodes;
    overriding swaps each affected node's deployment for an `.options()`
    copy so the caller's module-level objects stay untouched.
    """
    from ray_tpu.serve import Application

    by_name = {o["name"]: o for o in overrides}
    consumed = set()

    def overridden(dep):
        o = by_name.get(dep.name)
        if not o:
            return dep
        consumed.add(dep.name)
        kwargs: Dict[str, Any] = {}
        if "num_replicas" in o:
            kwargs["num_replicas"] = int(o["num_replicas"])
        if "max_concurrent_queries" in o:
            kwargs["max_concurrent_queries"] = int(o["max_concurrent_queries"])
        if "route_prefix" in o:
            kwargs["route_prefix"] = o["route_prefix"]
        if "ray_actor_options" in o:
            kwargs["ray_actor_options"] = dict(o["ray_actor_options"])
        if "autoscaling" in o and o["autoscaling"] is not None:
            kwargs["autoscaling_config"] = AutoscalingConfig(
                **o["autoscaling"])
        if "shard_spec" in o and o["shard_spec"] is not None:
            from ray_tpu.shardgroup import ShardSpec

            kwargs["shard_spec"] = ShardSpec(**o["shard_spec"])
        return dep.options(**kwargs) if kwargs else dep

    def rebuild(node):
        if isinstance(node, Application):
            new_args = tuple(rebuild(a) for a in node.init_args)
            new_kwargs = {k: rebuild(v) for k, v in node.init_kwargs.items()}
            return Application(overridden(node.deployment), new_args,
                               new_kwargs)
        if isinstance(node, (list, tuple)):
            return type(node)(rebuild(v) for v in node)
        if isinstance(node, dict):
            return {k: rebuild(v) for k, v in node.items()}
        return node

    rebuilt = rebuild(app)
    unmatched = set(by_name) - consumed
    if unmatched:
        # A typo'd name silently deploying defaults would be worse than an
        # error (the operator believes their scale-up applied).
        raise ValueError(
            f"deployment override(s) {sorted(unmatched)} match no "
            "deployment in the application graph")
    return rebuilt


def build(app) -> Dict[str, Any]:
    """Application graph -> config dict (reference `serve build`): every
    deployment's current config, ready to edit and `deploy_config`."""
    from ray_tpu.serve import Deployment, _graph_order

    if isinstance(app, Deployment):
        app = app.bind()
    deployments = []
    for node in _graph_order(app):
        cfg = node.deployment.config
        entry: Dict[str, Any] = {
            "name": node.deployment.name,
            "num_replicas": cfg.num_replicas,
            "max_concurrent_queries": cfg.max_concurrent_queries,
        }
        if cfg.route_prefix:
            entry["route_prefix"] = cfg.route_prefix
        if cfg.ray_actor_options:
            entry["ray_actor_options"] = dict(cfg.ray_actor_options)
        if cfg.autoscaling is not None:
            entry["autoscaling"] = asdict(cfg.autoscaling)
        if cfg.shard_spec is not None:
            entry["shard_spec"] = asdict(cfg.shard_spec)
        deployments.append(entry)
    return {"applications": [{"name": "default",
                              "import_path": "<module>:<app>",
                              "deployments": deployments}]}


def deploy_config(config: Dict[str, Any], *, timeout_s: float = 60.0):
    """Deploy every application in a validated config dict; returns the
    handle of the last application's root deployment."""
    from ray_tpu import serve

    validate_config(config)
    http = config.get("http") or {}
    handle = None
    for app_cfg in config["applications"]:
        target = import_attr(app_cfg["import_path"])
        if isinstance(target, serve.Deployment):
            target = target.bind()
        target = _apply_overrides(target,
                                  app_cfg.get("deployments") or [])
        handle = serve.run(target, timeout_s=timeout_s,
                           http=bool(http),
                           http_host=http.get("host", "127.0.0.1"),
                           http_port=int(http.get("port", 8000)))
    return handle


def deploy_config_file(path: str, *, timeout_s: float = 60.0):
    import yaml

    with open(path) as f:
        config = yaml.safe_load(f)
    return deploy_config(config, timeout_s=timeout_s)
