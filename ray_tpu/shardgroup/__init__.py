"""ray_tpu.shardgroup — gang-scheduled sharded replica groups.

Makes N rank actors spanning hosts look like ONE logical replica: atomic
all-or-nothing gang creation on a placement group, coordinated tp-mesh
bring-up (rank 0 coordinates `jax.distributed`; every rank builds the
same cross-host Mesh), group-level lifecycle (any rank death kills and
restarts the whole gang), and a group handle the serve router/dataplane
treat as a single replica — requests land on rank 0, which drives the
SPMD step. See docs/SHARDED.md.
"""

from ray_tpu.shardgroup.gang import create_gang, create_replica_group
from ray_tpu.shardgroup.group import GangError, GangMonitor, ReplicaGroup
from ray_tpu.shardgroup.runtime import (
    ShardContext,
    activate,
    current,
    current_mesh,
    deactivate,
)
from ray_tpu.shardgroup.spec import ShardSpec

__all__ = [
    "GangError", "GangMonitor", "ReplicaGroup", "ShardContext",
    "ShardSpec", "activate", "create_gang", "create_replica_group",
    "current", "current_mesh", "deactivate",
]
