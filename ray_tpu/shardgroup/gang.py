"""Gang scheduling: atomic all-or-nothing creation of a rank-actor gang.

The creation contract (raylint RL009 enforces its shape on hand-rolled
gangs):

1. one placement group reserves every rank's bundle via the GCS 2PC —
   an infeasible/timed-out group is REMOVED before the error surfaces;
2. rank actors are created one bundle each; ANY mid-gang failure kills
   every already-created rank, removes the placement group (releasing
   all bundles, including the ones later ranks never reached), and
   raises ONE rank-attributed `GangError` — no leaked reservations, no
   half-alive gangs;
3. the synchronous path then waits for every rank's first ping — a rank
   that dies in its ctor aborts the whole gang the same way;
4. a death hook is registered (`GangMonitor`, or the caller's own — the
   serve controller's health check plays this role for serve gangs).
"""

from __future__ import annotations

import logging
import uuid
from typing import Any, Callable, Dict, List, Optional, Tuple

from ray_tpu.shardgroup import spec as _spec
from ray_tpu.shardgroup.group import GangError, GangMonitor, ReplicaGroup
from ray_tpu.shardgroup.spec import ShardSpec

logger = logging.getLogger(__name__)


def _abort_gang(pg, created: List[Any], group_id: str) -> None:
    """Release EVERYTHING a partially-created gang holds: every created
    rank actor, the whole placement group (all bundles, acquired or
    not), and the rendezvous keys."""
    import ray_tpu
    from ray_tpu.shardgroup import runtime as _rt
    from ray_tpu.util.placement_group import remove_placement_group

    for handle in created:
        try:
            ray_tpu.kill(handle)
        except Exception:  # noqa: BLE001 — never created / already dead
            pass
    if pg is not None:
        try:
            remove_placement_group(pg)
        except Exception:  # noqa: BLE001 — GCS unreachable: nothing left
            logger.warning("shardgroup: failed to remove placement group "
                           "of aborted gang %s", group_id, exc_info=True)
    _rt.clear_rendezvous(group_id)


def create_gang(
    actor_cls,
    spec: ShardSpec,
    *,
    group_id: Optional[str] = None,
    bundle: Optional[Dict[str, float]] = None,
    rank_options: Optional[Callable[[int], Dict[str, Any]]] = None,
    rank_args: Optional[Callable[[int], Tuple[tuple, dict]]] = None,
    pg_timeout_s: float = 30.0,
    ready_timeout_s: float = 60.0,
    wait_ready: bool = True,
    on_death: Optional[Callable[[ReplicaGroup, int], None]] = None,
) -> ReplicaGroup:
    """Create a `spec.world_size`-rank gang of `actor_cls` actors on one
    placement group. All-or-nothing: returns a fully-formed
    `ReplicaGroup` or raises `GangError` with nothing left behind.

    `rank_options(rank)` -> extra actor options (name, max_concurrency,
    num_cpus...); `rank_args(rank)` -> (args, kwargs) for the rank's
    ctor. With `wait_ready=False` the readiness wait (step 3) is skipped
    — the caller owns promotion (the serve controller's STARTING->RUNNING
    ping loop) — but mid-creation abort (step 2) still applies.
    """
    import ray_tpu
    from ray_tpu.util.placement_group import placement_group
    from ray_tpu.util.scheduling_strategies import (
        PlacementGroupSchedulingStrategy,
    )

    group_id = group_id or f"gang-{uuid.uuid4().hex[:12]}"
    bundle = dict(bundle) if bundle else spec.rank_bundle()
    # Fail fast on a rank asking for more than its bundle holds: the GCS
    # would otherwise spin the creation unplaceable until its lease
    # deadline (minutes) with the whole gang's bundles held hostage.
    for rank in range(spec.world_size):
        opts = rank_options(rank) if rank_options else {}
        for res, amt in _spec.resources_of(opts).items():
            if amt > bundle.get(res, 0.0):
                raise GangError(
                    f"gang {group_id}: rank {rank} requests {res}={amt} "
                    f"but its bundle only reserves "
                    f"{bundle.get(res, 0.0)} — grow ShardSpec.bundle",
                    group_id=group_id, rank=rank)
    pg = None
    created: List[Any] = []
    names: List[str] = []
    try:
        pg = placement_group([dict(bundle)] * spec.world_size,
                             strategy=spec.strategy)
        if wait_ready and not pg.wait(timeout_seconds=pg_timeout_s):
            raise GangError(
                f"gang {group_id}: placement group of "
                f"{spec.world_size} x {bundle} bundles not placeable in "
                f"{pg_timeout_s}s", group_id=group_id)
        for rank in range(spec.world_size):
            opts = dict(rank_options(rank)) if rank_options else {}
            opts["scheduling_strategy"] = PlacementGroupSchedulingStrategy(
                placement_group=pg, placement_group_bundle_index=rank)
            args, kwargs = rank_args(rank) if rank_args else ((), {})
            try:
                handle = ray_tpu.remote(actor_cls).options(**opts).remote(
                    *args, **kwargs)
            except Exception as e:
                raise GangError(
                    f"gang {group_id}: creating rank {rank}/"
                    f"{spec.world_size} failed: "
                    f"{type(e).__name__}: {e}",
                    group_id=group_id, rank=rank) from e
            created.append(handle)
            names.append(opts.get("name") or f"{group_id}#r{rank}")
        group = ReplicaGroup(group_id, spec, pg, created, names)
        if wait_ready:
            statuses = group.ping_all(timeout_s=ready_timeout_s)
            bad = [i for i, s in enumerate(statuses) if s != "ok"]
            if bad:
                raise GangError(
                    f"gang {group_id}: rank {bad[0]}/{spec.world_size} "
                    f"{'died during startup' if statuses[bad[0]] == 'dead' else 'not ready in time'}"
                    f" (statuses: {statuses}) — gang aborted",
                    group_id=group_id, rank=bad[0])
    except GangError:
        _abort_gang(pg, created, group_id)
        raise
    except Exception as e:
        _abort_gang(pg, created, group_id)
        raise GangError(
            f"gang {group_id}: creation failed: {type(e).__name__}: {e}",
            group_id=group_id) from e
    if on_death is not None:
        GangMonitor(group, on_death)
    return group


def create_replica_group(
    user_cls,
    spec: ShardSpec,
    *,
    init_args: tuple = (),
    init_kwargs: Optional[dict] = None,
    deployment_name: str = "group",
    group_id: Optional[str] = None,
    actor_options: Optional[Dict[str, Any]] = None,
    pg_timeout_s: float = 30.0,
    ready_timeout_s: float = 60.0,
    on_death: Optional[Callable[[ReplicaGroup, int], None]] = None,
) -> ReplicaGroup:
    """The standalone (non-serve) front door: gang-create `world_size`
    serve-style `Replica` actors hosting `user_cls` with an activated
    shard context, wait until every rank is up, register the death hook.
    Returns the group; `group.handle` drives requests on rank 0."""
    from ray_tpu.serve.replica import Replica

    group_id = group_id or f"{deployment_name}-{uuid.uuid4().hex[:8]}"
    base_opts = dict(actor_options or {})
    base_opts.setdefault("num_cpus", 0.05)
    base_opts.setdefault("max_concurrency", 16)

    def rank_options(rank: int) -> Dict[str, Any]:
        opts = dict(base_opts)
        opts["name"] = f"SHARDGROUP::{group_id}#r{rank}"
        return opts

    def rank_args(rank: int):
        ctx = {"group_id": group_id, "rank": rank,
               "world_size": spec.world_size, "tp": spec.tp,
               "pp": spec.pp, "sp": spec.sp,
               "spmd": spec.world_size > 1}
        return ((deployment_name, user_cls, init_args, init_kwargs or {},
                 f"{group_id}#r{rank}"), {"shard_ctx": ctx})

    return create_gang(
        Replica, spec, group_id=group_id,
        bundle=spec.rank_bundle(base_opts),
        rank_options=rank_options, rank_args=rank_args,
        pg_timeout_s=pg_timeout_s, ready_timeout_s=ready_timeout_s,
        wait_ready=True, on_death=on_death)
