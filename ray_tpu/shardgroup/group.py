"""ReplicaGroup: the handle that makes N rank actors look like one.

The serve router/dataplane never learn about gangs — they route to
``group.handle`` (rank 0), which drives the SPMD step; the controller
and the standalone API use the group-level operations (ping_all /
check_alive / broadcast / kill) that treat the gang as one unit.

Lifecycle invariant: a ReplicaGroup is all-or-nothing. It is only ever
returned fully formed by `gang.create_gang` (partial creation aborts and
releases every bundle there), and `kill()` tears down every rank AND the
placement group — a gang never survives the death of any member (the
controller's health check or a `GangMonitor` notices a dead rank and
kills + replaces the whole group).
"""

from __future__ import annotations

import logging
import threading
import time
from typing import Any, Callable, Dict, List, Optional

from ray_tpu.shardgroup.spec import ShardSpec

logger = logging.getLogger(__name__)


class GangError(RuntimeError):
    """Gang-level failure, attributed to the rank that caused it
    (rank < 0 means the group as a whole, e.g. an infeasible placement
    group)."""

    def __init__(self, message: str, group_id: str = "", rank: int = -1):
        super().__init__(message)
        self.group_id = group_id
        self.rank = rank


class ReplicaGroup:
    def __init__(self, group_id: str, spec: ShardSpec, pg,
                 ranks: List[Any], rank_names: List[str]):
        self.group_id = group_id
        self.spec = spec
        self.pg = pg
        self.ranks = list(ranks)
        self.rank_names = list(rank_names)
        self._dead = False

    @property
    def world_size(self) -> int:
        return len(self.ranks)

    @property
    def handle(self):
        """Rank 0 — the gang's single routable endpoint."""
        return self.ranks[0]

    # ---------------------------------------------------------- liveness

    def ping_all(self, timeout_s: float = 5.0,
                 indices: Optional[List[int]] = None) -> List[str]:
        """Per-rank status: "ok" | "pending" | "dead", aligned to
        `indices` (default: every rank). A resolved-but-errored ping is
        a dead rank; an unresolved one is merely slow. Callers that
        already probed rank 0 another way (the controller's stats/node
        ping) pass `indices=range(1, world_size)` so rank 0 isn't
        pinged twice per sweep."""
        import ray_tpu

        indices = list(indices) if indices is not None \
            else list(range(len(self.ranks)))
        runtime = ray_tpu._require_runtime()
        deadline = time.monotonic() + timeout_s
        refs = []
        for rank in indices:
            handle = self.ranks[rank]
            # Liveness probe BEFORE submission: submitting to a rank
            # still in creation blocks UNBOUNDEDLY on address resolution,
            # so one wedged rank ctor would park every health sweep (the
            # serve reconcile loop among them). A pending rank is polled
            # only within this call's own deadline — readiness waits
            # (create_gang's wait_ready) keep their blocking semantics,
            # short sweeps return "pending" immediately. Backoff on the
            # poll: each actor_liveness on a pending rank is a GCS
            # directory RPC, and a gang readiness wait at a fixed 50ms
            # cadence would hammer the GCS with ~20 RPCs/s per rank for
            # the whole spawn+__init__ window.
            poll = 0.05
            liveness = runtime.actor_liveness(handle._actor_id)
            while liveness == "pending" and time.monotonic() < deadline:
                time.sleep(min(poll, max(0.0,
                                         deadline - time.monotonic())))
                poll = min(poll * 2, 0.5)
                liveness = runtime.actor_liveness(handle._actor_id)
            if liveness != "alive":
                refs.append("dead" if liveness == "dead" else "pending")
                continue
            try:
                refs.append(handle.ping.remote())
            except Exception:  # noqa: BLE001 — submit to a dead actor
                refs.append(None)
        out = []
        for ref in refs:
            if isinstance(ref, str):
                out.append(ref)
                continue
            if ref is None:
                out.append("dead")
                continue
            remaining = max(0.0, deadline - time.monotonic())
            try:
                ready, _ = ray_tpu.wait([ref], num_returns=1,
                                        timeout=remaining)
                if not ready:
                    out.append("pending")
                    continue
                ray_tpu.get(ready[0])
                out.append("ok")
            except Exception:  # noqa: BLE001 — rank actor died
                out.append("dead")
        return out

    def check_alive(self, timeout_s: float = 5.0) -> bool:
        """True iff EVERY rank answers its ping — one dead rank means
        the whole group is dead (the caller kills and replaces it)."""
        return all(s == "ok" for s in self.ping_all(timeout_s))

    def dead_ranks(self, timeout_s: float = 2.0,
                   indices: Optional[List[int]] = None) -> List[int]:
        indices = list(indices) if indices is not None \
            else list(range(len(self.ranks)))
        return [rank for rank, s in zip(indices,
                                        self.ping_all(timeout_s, indices))
                if s == "dead"]

    # --------------------------------------------------------- operations

    def broadcast(self, method: str, *args, timeout_s: float = 30.0,
                  **kwargs) -> List[Any]:
        """Invoke `method` on every rank, gather all results (rank
        order). Any rank failure raises — group-level calls are
        all-or-nothing like the gang itself."""
        import ray_tpu

        refs = [getattr(h, method).remote(*args, **kwargs)
                for h in self.ranks]
        return list(ray_tpu.get(refs, timeout=timeout_s))

    def kill(self, graceful_timeout_s: float = 0.0) -> None:
        """Tear the gang down as a unit: every rank, then the placement
        group (bundle release), then the rendezvous keys. Idempotent and
        best-effort — ranks may already be dead."""
        import ray_tpu
        from ray_tpu.shardgroup import runtime as _rt
        from ray_tpu.util.placement_group import remove_placement_group

        self._dead = True
        if graceful_timeout_s > 0:
            try:
                self.handle.prepare_shutdown.remote(graceful_timeout_s)
            except Exception:  # noqa: BLE001 — rank 0 already dead
                pass
        for handle in self.ranks:
            try:
                ray_tpu.kill(handle)
            except Exception:  # noqa: BLE001 — already dead is fine
                pass
        if self.pg is not None:
            try:
                remove_placement_group(self.pg)
            except Exception:  # noqa: BLE001 — pg already removed
                logger.debug("shardgroup: pg removal for %s failed",
                             self.group_id, exc_info=True)
        _rt.clear_rendezvous(self.group_id)

    def describe(self) -> Dict[str, Any]:
        """Plain-data description, durable enough to clean the gang up
        after the owner's crash (the serve controller checkpoints this
        and uses it to kill stale rank actors / release the pg)."""
        out = {"group_id": self.group_id, "world_size": self.world_size,
               "tp": self.spec.tp, "rank_names": list(self.rank_names),
               "pg_id": None}
        if self.pg is not None:
            out.update(pg_id=self.pg.id.hex(), bundles=self.pg.bundles,
                       strategy=self.pg.strategy)
        return out

    def __repr__(self):
        return (f"ReplicaGroup({self.group_id}, world={self.world_size}, "
                f"tp={self.spec.tp})")


class GangMonitor:
    """Death hook for standalone (non-serve) gangs: a daemon thread pings
    every rank each `period_s`; the first dead rank fires `on_death(group,
    rank)` ONCE and the monitor stops — the owner decides whether to
    kill/recreate. (Serve gangs don't use this: the controller's health
    check is their death hook.)"""

    def __init__(self, group: ReplicaGroup,
                 on_death: Callable[[ReplicaGroup, int], None],
                 period_s: float = 0.5):
        self.group = group
        self._on_death = on_death
        self._period = period_s
        self._stopped = threading.Event()
        self._thread = threading.Thread(
            target=self._run, name=f"gang-monitor-{group.group_id}",
            daemon=True)
        self._thread.start()

    def stop(self):
        self._stopped.set()

    def _run(self):
        while not self._stopped.wait(self._period):
            if self.group._dead:
                return
            dead = self.group.dead_ranks(timeout_s=2.0)
            if dead:
                logger.warning(
                    "shardgroup: rank %d of %s died — firing death hook",
                    dead[0], self.group.group_id)
                try:
                    self._on_death(self.group, dead[0])
                except Exception:  # noqa: BLE001 — owner hook must not
                    logger.exception("gang death hook failed")
                return
