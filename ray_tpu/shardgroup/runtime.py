"""Worker-side shard context: coordinated mesh bring-up for one rank.

Every rank actor of a gang calls :func:`activate` BEFORE any user code
(the serve `Replica` does it ahead of the deployment ctor, exactly like
`train.JaxBackend.on_start` runs `initialize_distributed` before the
train loop — XLA backends freeze on first use, so distributed init must
win that race).  Protocol:

1. rank 0 picks a free port and publishes ``host:port`` under the
   group's GCS KV key (`shardgroup:<group>:coordinator:<epoch>`);
2. every rank polls that key, then — on backends that support
   multi-process XLA — joins `jax.distributed` via
   `parallel.distributed.initialize_distributed`;
3. every rank builds the SAME `jax.sharding.Mesh` with a single "tp"
   axis over the first `tp` global devices.

On the CPU test backend jax has no multi-process runtime
("Multiprocess computations aren't implemented on the CPU backend"), so
step 2 is skipped and each rank builds a local mesh over its own forced
host devices (`--xla_force_host_platform_device_count`) — rank 0 drives
the real SPMD math, the other ranks keep the gang-lifecycle contract.
The deployment reads its mesh through :func:`current_mesh`.
"""

from __future__ import annotations

import logging
import os
import time
from dataclasses import dataclass
from typing import Any, Dict, Optional

logger = logging.getLogger(__name__)

_KV_PREFIX = "shardgroup:"


@dataclass(frozen=True)
class ShardContext:
    """One rank's view of its gang (delivered by the gang scheduler)."""

    group_id: str          # unique per gang INCARNATION (restart = new id)
    rank: int
    world_size: int
    tp: int
    spmd: bool             # cross-process XLA active (jax.distributed)
    pp: int = 1            # pipeline stages (ranks, not mesh columns)
    sp: int = 1            # sequence-parallel mesh axis width

    @property
    def is_coordinator(self) -> bool:
        return self.rank == 0

    @property
    def stage(self) -> int:
        """This rank's pipeline stage (ranks are laid out stage-major:
        rank // ranks_per_stage)."""
        per = max(1, self.world_size // max(1, self.pp))
        return self.rank // per

    def as_dict(self) -> Dict[str, Any]:
        return {"group_id": self.group_id, "rank": self.rank,
                "world_size": self.world_size, "tp": self.tp,
                "pp": self.pp, "sp": self.sp, "spmd": self.spmd}


_current: Optional[ShardContext] = None
_mesh = None


def _platform_is_cpu() -> bool:
    """Decide WITHOUT touching jax backends (probing them would
    initialize XLA before `jax.distributed` gets its chance). Unset env
    counts as NOT-cpu — on a TPU pod nothing pins the platform and the
    SPMD path must not silently degrade; a bare-CPU process with no env
    hits the initialize_distributed fallback below instead."""
    plat = (os.environ.get("RAY_TPU_JAX_PLATFORM")
            or os.environ.get("JAX_PLATFORMS") or "")
    return "cpu" in plat.lower()


def _kv():
    import ray_tpu

    return ray_tpu._require_runtime().gcs


def _coord_key(group_id: str) -> bytes:
    return (_KV_PREFIX + group_id + ":coordinator").encode()


def publish_coordinator(group_id: str, address: str) -> None:
    _kv().call("kv_put", {"key": _coord_key(group_id),
                          "value": address.encode()})


def wait_coordinator(group_id: str, timeout_s: float = 30.0) -> str:
    deadline = time.monotonic() + timeout_s
    while time.monotonic() < deadline:
        value = _kv().call("kv_get", {"key": _coord_key(group_id)})["value"]
        if value:
            return bytes(value).decode()
        time.sleep(0.02)
    raise TimeoutError(
        f"shard group {group_id}: coordinator address not published "
        f"within {timeout_s}s (rank 0 never came up?)")


def clear_rendezvous(group_id: str) -> None:
    """Drop the group's KV keys (gang teardown); a restarted gang has a
    fresh group_id, so this is hygiene, not correctness."""
    try:
        _kv().call("kv_del", {"key": _coord_key(group_id)})
    except Exception:  # noqa: BLE001 — best effort, GCS may be going down
        pass


def activate(ctx: Any, rendezvous_timeout_s: float = 30.0) -> ShardContext:
    """Join the gang: rendezvous, (maybe) jax.distributed, build the tp
    mesh. Idempotent for the same group_id; a different one raises —
    one process hosts one rank of one gang, ever (XLA state is global).
    """
    global _current, _mesh
    if isinstance(ctx, dict):
        ctx = ShardContext(**ctx)
    if _current is not None:
        if _current.group_id == ctx.group_id and _current.rank == ctx.rank:
            return _current
        raise RuntimeError(
            f"shard context already active for group {_current.group_id} "
            f"rank {_current.rank}; cannot re-activate as "
            f"{ctx.group_id} rank {ctx.rank}")

    spmd = bool(ctx.spmd) and ctx.world_size > 1 and not _platform_is_cpu()
    if ctx.world_size > 1 and spmd:
        from ray_tpu.parallel import distributed

        if ctx.is_coordinator:
            host, port = distributed.get_address_and_port()
            address = f"{host}:{port}"
            publish_coordinator(ctx.group_id, address)
        else:
            address = wait_coordinator(ctx.group_id, rendezvous_timeout_s)
        try:
            distributed.initialize_distributed(
                coordinator_address=address,
                num_processes=ctx.world_size,
                process_id=ctx.rank)
        except RuntimeError as e:
            # Backends without multi-process XLA (CPU with no platform
            # env pinned) degrade to per-process meshes rather than
            # killing the rank — the gang lifecycle still holds, rank 0
            # still drives the real math.
            logger.warning(
                "shardgroup %s rank %d: jax.distributed unavailable "
                "(%s) — degrading to per-process mesh", ctx.group_id,
                ctx.rank, e)
            spmd = False
    elif ctx.world_size > 1 and ctx.is_coordinator:
        # CPU degraded mode: still publish so laggard ranks (and tests)
        # can observe that rank 0 reached bring-up.
        publish_coordinator(ctx.group_id, "local")

    ctx = ShardContext(group_id=ctx.group_id, rank=ctx.rank,
                       world_size=ctx.world_size, tp=ctx.tp, spmd=spmd,
                       pp=ctx.pp, sp=ctx.sp)
    _mesh = _build_stage_mesh(ctx)
    _current = ctx
    logger.info("shardgroup: rank %d/%d of %s active (tp=%d, pp=%d, "
                "sp=%d, spmd=%s)", ctx.rank, ctx.world_size, ctx.group_id,
                ctx.tp, ctx.pp, ctx.sp, spmd)
    return ctx


def _build_stage_mesh(ctx: ShardContext):
    """The gang's per-stage device mesh: ("sp", "tp") axes over the
    first `sp*tp` (global) devices — "pp" is realized as stage PROCESSES
    exchanging activations over the collective plane, never as an
    in-program mesh axis. Every rank of an SPMD gang computes the
    identical mesh — `jax.devices()` is globally ordered after
    `jax.distributed` init. Size-1 axes are dropped (a tp-only gang gets
    the same single-axis mesh as before)."""
    axes = {name: size for name, size in (("sp", ctx.sp), ("tp", ctx.tp))
            if size > 1}
    if not axes:
        return None
    import jax

    from ray_tpu._jax_env import apply_jax_platform_env
    from ray_tpu.parallel.mesh import MeshSpec, build_mesh

    apply_jax_platform_env()
    devices = jax.devices()
    need = 1
    for size in axes.values():
        need *= size
    if not (ctx.spmd or ctx.world_size == 1) and len(devices) < need:
        # CPU degraded mode: shrink the tp axis to what this process can
        # see (sp must fit — ring attention cannot run on a partial ring).
        tp_fit = max(1, len(devices) // max(1, ctx.sp))
        axes = {name: (min(size, tp_fit) if name == "tp" else size)
                for name, size in axes.items()}
        axes = {name: size for name, size in axes.items() if size > 1}
        if not axes:
            return None
        need = 1
        for size in axes.values():
            need *= size
    if len(devices) < need:
        raise RuntimeError(
            f"shard group {ctx.group_id}: mesh axes {axes} need {need} "
            f"devices, only {len(devices)} visible (set "
            "--xla_force_host_platform_device_count on CPU)")
    return build_mesh(MeshSpec(axes), devices=devices[:need])


def current() -> Optional[ShardContext]:
    return _current


def current_mesh():
    """The active gang's tp mesh (None outside a gang or at tp=1) —
    deployments/engines read this to decide the sharded path."""
    return _mesh


def deactivate() -> None:
    """Test hook: forget the context (does NOT undo jax.distributed)."""
    global _current, _mesh
    _current = None
    _mesh = None
