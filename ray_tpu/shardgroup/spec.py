"""ShardSpec: the declarative shape of a sharded replica group.

One logical serve replica (or train worker "super-rank") may be a GANG of
`world_size` rank actors spanning hosts, together driving one pjit
program over a `tp`-wide tensor-parallel device mesh.  The spec is pure
data — serve's `DeploymentConfig` carries it, the controller hands it to
the gang scheduler (`shardgroup.gang`), and every rank receives its
per-rank slice as a `ShardContext` (`shardgroup.runtime`).

TPU mapping: a llama-70B replica on a v5e-16 is
``ShardSpec(tp=16, world_size=4, strategy="STRICT_SPREAD",
bundle={"TPU": 4})`` — four hosts of four chips, one bundle per host, the
mesh's tp axis laid over all 16 chips via `jax.distributed`.  On the CPU
test backend (no cross-process XLA), `world_size > 1` gangs still
exercise every gang-scheduling/lifecycle path while the mesh itself is
per-process over `--xla_force_host_platform_device_count` devices.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, Optional


def resources_of(actor_options: Optional[Dict] = None) -> Dict[str, float]:
    """Actor options -> the resource dict they actually request. The
    SINGLE translation both sides of the bundle contract use: what
    `rank_bundle` reserves and what the gang's fail-fast overflow check
    compares against must never disagree."""
    opts = actor_options or {}
    resources: Dict[str, float] = {}
    if opts.get("num_cpus"):
        resources["CPU"] = float(opts["num_cpus"])
    if opts.get("num_tpus"):
        resources["TPU"] = float(opts["num_tpus"])
    for k, v in (opts.get("resources") or {}).items():
        resources[k] = float(v)
    return resources


@dataclass(frozen=True)
class ShardSpec:
    """Gang shape for one logical replica.

    tp          tensor-parallel width: the size of the mesh's "tp" axis
                (attention heads / MLP hidden / vocab shard over it, the
                paged KV arena shards its kv-head dim with it).
    pp          pipeline-parallel width: the number of STAGE ranks of a
                pipelined train gang. Stages are whole processes (one
                rank per stage), activations/grads move over the host
                collective plane — so pp multiplies world_size rather
                than the per-rank device mesh.
    sp          sequence-parallel width: the size of the mesh's "sp"
                axis (ring attention shards the sequence dim over it
                for long contexts).
    world_size  number of rank ACTORS (processes/hosts) in the gang.
    strategy    placement-group strategy for the gang's bundles
                ("PACK" for single-host tests, "STRICT_SPREAD" for one
                rank per host on a pod).
    bundle      per-rank resource bundle; empty means "derive from the
                deployment's ray_actor_options, default {CPU: 0.1}".
    """

    tp: int = 1
    world_size: int = 1
    strategy: str = "PACK"
    bundle: Dict[str, float] = field(default_factory=dict)
    pp: int = 1
    sp: int = 1

    def __post_init__(self):
        if self.tp < 1 or self.world_size < 1 or self.pp < 1 or self.sp < 1:
            raise ValueError(
                f"ShardSpec needs tp/pp/sp >= 1 and world_size >= 1, got "
                f"tp={self.tp} pp={self.pp} sp={self.sp} "
                f"world_size={self.world_size}")
        if self.pp > 1 and self.world_size % self.pp:
            raise ValueError(
                f"pp={self.pp} must divide world_size={self.world_size} "
                "(each pipeline stage is a contiguous block of ranks)")
        if self.tp > 1 and self.tp % self.ranks_per_stage:
            raise ValueError(
                f"tp={self.tp} must be divisible by the "
                f"{self.ranks_per_stage} ranks of each stage (every rank "
                "hosts tp/ranks contiguous mesh columns)")

    @property
    def ranks_per_stage(self) -> int:
        return max(1, self.world_size // self.pp)

    @property
    def tp_per_rank(self) -> int:
        return max(1, self.tp // self.ranks_per_stage)

    def mesh_axes(self) -> Dict[str, int]:
        """The logical device grid this spec spans, as MeshSpec axes
        (size-1 axes dropped; ("pp", "sp", "tp") in AXIS_ORDER). On a
        real multi-host bring-up this is the global mesh; on the CPU
        backend each stage rank builds :meth:`stage_mesh_axes` locally
        and "pp" lives across processes, not inside the mesh."""
        return {name: size
                for name, size in (("pp", self.pp), ("sp", self.sp),
                                   ("tp", self.tp))
                if size > 1}

    def stage_mesh_axes(self) -> Dict[str, int]:
        """The per-stage device mesh: ("sp", "tp") only — the pp axis is
        realized as separate stage processes exchanging activations over
        the collective plane, never as an in-program mesh axis."""
        return {name: size for name, size in (("sp", self.sp),
                                              ("tp", self.tp)) if size > 1}

    @property
    def devices_per_stage(self) -> int:
        return self.sp * self.tp

    def rank_bundle(self, actor_options: Optional[Dict] = None
                    ) -> Dict[str, float]:
        """The placement-group bundle one rank reserves."""
        if self.bundle:
            return {k: float(v) for k, v in self.bundle.items()}
        return resources_of(actor_options) or {"CPU": 0.1}
