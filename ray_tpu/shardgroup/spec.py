"""ShardSpec: the declarative shape of a sharded replica group.

One logical serve replica (or train worker "super-rank") may be a GANG of
`world_size` rank actors spanning hosts, together driving one pjit
program over a `tp`-wide tensor-parallel device mesh.  The spec is pure
data — serve's `DeploymentConfig` carries it, the controller hands it to
the gang scheduler (`shardgroup.gang`), and every rank receives its
per-rank slice as a `ShardContext` (`shardgroup.runtime`).

TPU mapping: a llama-70B replica on a v5e-16 is
``ShardSpec(tp=16, world_size=4, strategy="STRICT_SPREAD",
bundle={"TPU": 4})`` — four hosts of four chips, one bundle per host, the
mesh's tp axis laid over all 16 chips via `jax.distributed`.  On the CPU
test backend (no cross-process XLA), `world_size > 1` gangs still
exercise every gang-scheduling/lifecycle path while the mesh itself is
per-process over `--xla_force_host_platform_device_count` devices.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, Optional


def resources_of(actor_options: Optional[Dict] = None) -> Dict[str, float]:
    """Actor options -> the resource dict they actually request. The
    SINGLE translation both sides of the bundle contract use: what
    `rank_bundle` reserves and what the gang's fail-fast overflow check
    compares against must never disagree."""
    opts = actor_options or {}
    resources: Dict[str, float] = {}
    if opts.get("num_cpus"):
        resources["CPU"] = float(opts["num_cpus"])
    if opts.get("num_tpus"):
        resources["TPU"] = float(opts["num_tpus"])
    for k, v in (opts.get("resources") or {}).items():
        resources[k] = float(v)
    return resources


@dataclass(frozen=True)
class ShardSpec:
    """Gang shape for one logical replica.

    tp          tensor-parallel width: the size of the mesh's "tp" axis
                (attention heads / MLP hidden / vocab shard over it, the
                paged KV arena shards its kv-head dim with it).
    world_size  number of rank ACTORS (processes/hosts) in the gang.
    strategy    placement-group strategy for the gang's bundles
                ("PACK" for single-host tests, "STRICT_SPREAD" for one
                rank per host on a pod).
    bundle      per-rank resource bundle; empty means "derive from the
                deployment's ray_actor_options, default {CPU: 0.1}".
    """

    tp: int = 1
    world_size: int = 1
    strategy: str = "PACK"
    bundle: Dict[str, float] = field(default_factory=dict)

    def __post_init__(self):
        if self.tp < 1 or self.world_size < 1:
            raise ValueError(
                f"ShardSpec needs tp >= 1 and world_size >= 1, got "
                f"tp={self.tp} world_size={self.world_size}")
        if self.tp > 1 and self.tp % self.world_size:
            raise ValueError(
                f"tp={self.tp} must be divisible by world_size="
                f"{self.world_size} (every rank hosts tp/world_size "
                "contiguous mesh columns)")

    @property
    def tp_per_rank(self) -> int:
        return max(1, self.tp // self.world_size)

    def rank_bundle(self, actor_options: Optional[Dict] = None
                    ) -> Dict[str, float]:
        """The placement-group bundle one rank reserves."""
        if self.bundle:
            return {k: float(v) for k, v in self.bundle.items()}
        return resources_of(actor_options) or {"CPU": 0.1}
