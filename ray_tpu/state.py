"""State observability API.

Equivalent of the reference's state API (`python/ray/experimental/state/api.py`
:115 StateApiClient, :754 list_actors, :1302 summarize_tasks, served by
`dashboard/state_aggregator.py`): list cluster entities and summarize tasks.
"""

from __future__ import annotations

from collections import Counter
from typing import Any, Dict, List, Optional


def _gcs():
    import ray_tpu

    return ray_tpu._require_runtime().gcs


def list_nodes() -> List[Dict[str, Any]]:
    return _gcs().call("get_nodes")


def list_actors(filters: Optional[List] = None) -> List[Dict[str, Any]]:
    actors = _gcs().call("get_actors")
    if filters:
        for key, op, value in filters:
            assert op == "=", "only equality filters supported"
            actors = [a for a in actors if a.get(key) == value]
    return actors


def list_jobs() -> List[Dict[str, Any]]:
    return _gcs().call("get_jobs")


def list_named_actors(namespace: Optional[str] = None,
                      all_namespaces: bool = False) -> List[Dict[str, Any]]:
    """Registered actor names as [{"namespace", "name"}, ...] — the
    reference's `ray.util.list_named_actors`. With `namespace` omitted
    it lists the CURRENT runtime namespace, matching get_actor's
    resolution — not the GCS's literal "default"."""
    import ray_tpu

    if namespace is None:
        namespace = ray_tpu._require_runtime().namespace
    req: Dict[str, Any] = {"all_namespaces": all_namespaces,
                           "namespace": namespace}
    return _gcs().call("list_named_actors", req)["names"]


def list_placement_groups() -> List[Dict[str, Any]]:
    # PGs are published per-id; enumerate via the GCS table dump.
    import ray_tpu

    runtime = ray_tpu._require_runtime()
    return runtime.gcs.call("get_task_events", {"limit": 0}).get("pgs", []) or []


def list_tasks(limit: int = 10000) -> List[Dict[str, Any]]:
    return _gcs().call("get_task_events", {"limit": limit})["events"]


def list_objects() -> List[Dict[str, Any]]:
    import ray_tpu

    runtime = ray_tpu._require_runtime()
    out = []
    for node in runtime.gcs.call("get_nodes"):
        if not node["Alive"]:
            continue
        from ray_tpu.core.rpc import RpcClient

        client = RpcClient(node["RayletAddress"], name="state-probe")
        try:
            state = client.call("debug_state")
            out.append({"NodeID": node["NodeID"], "Store": state["store"]})
        finally:
            client.close()
    return out


def summarize_tasks() -> Dict[str, Any]:
    events = list_tasks()
    by_name = Counter(e.get("name", "?") for e in events)
    by_state = Counter(e.get("state", "?") for e in events)
    return {"by_func_name": dict(by_name), "by_state": dict(by_state),
            "total": len(events)}


def summarize_actors() -> Dict[str, Any]:
    actors = list_actors()
    by_state = Counter(a["State"] for a in actors)
    by_class = Counter(a["ClassName"] for a in actors)
    return {"by_state": dict(by_state), "by_class": dict(by_class),
            "total": len(actors)}


def cluster_summary() -> Dict[str, Any]:
    import ray_tpu

    return {
        "nodes": len([n for n in list_nodes() if n["Alive"]]),
        "resources_total": ray_tpu.cluster_resources(),
        "resources_available": ray_tpu.available_resources(),
        "actors": summarize_actors(),
    }
