"""ray_tpu.tenancy — the multi-tenant QoS plane over serve.

PAPER.md's L4 Serve stack multiplexes hundreds of applications per
cluster; this package is the native equivalent for the model zoo
(ROADMAP item 3): named tenants with priority tiers and quotas, per-
tenant token-bucket admission plus weighted fair queueing at the proxy,
and the controller-side registry the routing table pushes to every
router (quotas are enforced where requests arrive, never polled).

Layering (docs/MULTITENANCY.md has the full contract):

- `registry`   — TenantSpec / tier defaults; lives in the serve
  controller, checkpointed with it, pushed to proxies inside the
  routing table.
- `admission`  — proxy-side enforcement: TokenBucket (RPS + burst,
  over-quota answers a fast 429 with retry-after), per-tenant in-flight
  caps, and a WfqScheduler (virtual-time weighted fair queueing) that
  orders waiters when replica capacity is contended, so a hot tenant
  queues behind its own weight instead of starving other tiers.
"""

from ray_tpu.tenancy.admission import (
    QuotaExceeded,
    TenantAdmission,
    TokenBucket,
    WfqScheduler,
)
from ray_tpu.tenancy.registry import TIER_WEIGHTS, TenantSpec

__all__ = [
    "QuotaExceeded",
    "TIER_WEIGHTS",
    "TenantAdmission",
    "TenantSpec",
    "TokenBucket",
    "WfqScheduler",
]
