"""Proxy-side tenant admission: token buckets, in-flight caps, WFQ.

Enforcement lives where requests arrive (the proxy event loop), off the
pushed routing table — per-request enforcement never issues an RPC. The
pipeline, in order (docs/SERVE_DATAPLANE.md "admission ordering"):

1. **Token bucket** (rps_limit/burst): an over-rate request is answered
   immediately with `QuotaExceeded` (HTTP 429 + Retry-After) — it never
   occupies a replica slot, a park buffer, or a queue position. Fast
   rejection is the point: a tenant blasting 10x its quota costs the
   proxy one dict lookup + two float ops per excess request.
2. **In-flight cap** (max_inflight): bounds a tenant's concurrently
   executing requests per proxy, also a fast 429 (the work already in
   flight IS the retry-after signal).
3. **Weighted fair queueing**: requests that pass their quota but find
   every replica saturated wait in per-tenant queues drained in
   virtual-time order — a hot tenant's backlog queues behind its own
   weight, so it cannot starve a lighter tier no matter how deep its
   queue grows.

Everything here is event-loop-confined (one instance per proxy process,
all calls from that proxy's asyncio loop) — no locks, by design.
"""

from __future__ import annotations

import asyncio
import heapq
import time
from collections import deque
from typing import Any, Callable, Deque, Dict, Optional

from ray_tpu.tenancy.registry import TenantSpec


class QuotaExceeded(RuntimeError):
    """Tenant over its rate or in-flight quota: answer 429, never park."""

    def __init__(self, message: str, retry_after_s: float):
        super().__init__(message)
        self.retry_after_s = max(0.0, retry_after_s)


class TokenBucket:
    """Classic token bucket: `rate` tokens/s refill, `burst` capacity.

    `take()` either admits (returns 0.0) or returns the seconds until a
    token will be available — the 429's Retry-After. Lazy refill: no
    timer, two float ops per call.
    """

    __slots__ = ("rate", "burst", "_tokens", "_last")

    def __init__(self, rate: float, burst: float,
                 now: Optional[float] = None):
        self.rate = float(rate)
        self.burst = max(1.0, float(burst))
        self._tokens = self.burst
        self._last = time.monotonic() if now is None else now

    def take(self, now: Optional[float] = None, cost: float = 1.0) -> float:
        now = time.monotonic() if now is None else now
        if now > self._last:
            self._tokens = min(self.burst,
                               self._tokens + (now - self._last) * self.rate)
            self._last = now
        if self._tokens >= cost:
            self._tokens -= cost
            return 0.0
        if self.rate <= 0:
            return float("inf")
        return (cost - self._tokens) / self.rate


class _TenantState:
    __slots__ = ("spec", "bucket", "inflight", "seen_version")

    def __init__(self, spec: TenantSpec):
        self.spec = spec
        self.bucket = (TokenBucket(spec.rps_limit, spec.burst)
                       if spec.rps_limit else None)
        self.inflight = 0
        self.seen_version = -1


class TenantAdmission:
    """Per-proxy quota enforcement keyed by tenant name.

    Tenant state is (re)built from the QoS dict each routing-table entry
    carries; `prune(live)` drops state for tenants that left the table
    (deployment churn must not grow this registry forever)."""

    def __init__(self):
        # raylint: confine=loop
        self._tenants: Dict[str, _TenantState] = {}

    def resolve(self, entry: Optional[Dict[str, Any]]
                ) -> Optional[_TenantState]:
        """Tenant state for a routing-table entry (None = untenanted
        deployment: unmetered, default weight)."""
        if not entry:
            return None
        qos = entry.get("qos")
        if not qos:
            return None
        name = qos["name"]
        state = self._tenants.get(name)
        version = entry.get("qos_version", 0)
        if state is None:
            state = self._tenants[name] = _TenantState(TenantSpec(**qos))
            state.seen_version = version
        elif version > state.seen_version:
            # Quota update pushed: rebuild the bucket, keep inflight.
            state.spec = TenantSpec(**qos)
            state.bucket = (TokenBucket(state.spec.rps_limit,
                                        state.spec.burst)
                            if state.spec.rps_limit else None)
            state.seen_version = version
        return state

    def admit(self, state: Optional[_TenantState]) -> None:
        """Quota gate; raises QuotaExceeded (the caller answers 429).
        On success the caller owns one in-flight slot — `release(state)`
        exactly once when the request completes."""
        if state is None:
            return
        spec = state.spec
        if spec.max_inflight and state.inflight >= spec.max_inflight:
            raise QuotaExceeded(
                f"tenant {spec.name!r} is at its in-flight cap "
                f"({spec.max_inflight})", retry_after_s=0.05)
        if state.bucket is not None:
            wait = state.bucket.take()
            if wait > 0.0:
                raise QuotaExceeded(
                    f"tenant {spec.name!r} is over its {spec.rps_limit:g} "
                    "rps quota", retry_after_s=min(wait, 30.0))
        state.inflight += 1

    @staticmethod
    def release(state: Optional[_TenantState]) -> None:
        if state is not None and state.inflight > 0:
            state.inflight -= 1

    def prune(self, live_names) -> None:
        for name in list(self._tenants):
            if name not in live_names:
                del self._tenants[name]

    def snapshot(self) -> Dict[str, Dict[str, Any]]:
        return {name: {"inflight": st.inflight,
                       "tier": st.spec.tier,
                       "weight": st.spec.weight}
                for name, st in self._tenants.items()}


class _Waiter:
    __slots__ = ("fut", "try_reserve", "finish")

    def __init__(self, fut, try_reserve, finish: float):
        self.fut = fut
        self.try_reserve = try_reserve
        self.finish = finish


class WfqScheduler:
    """Virtual-time weighted fair queueing over contended dispatch.

    Waiters park in per-tenant FIFO queues; each carries a virtual
    finish time ``start + 1/weight`` where ``start = max(global vtime,
    tenant's last finish)`` — the classic WFQ recurrence with unit cost
    per request. The pump drains heads in ascending finish order; a
    tenant at weight 8 therefore gets ~8 queue turns for every turn a
    weight-1 tenant gets, and an idle tenant's first request lands at
    the global vtime (no banked credit, no starvation).

    A waiter's ``try_reserve`` is a zero-arg callable returning a
    replica choice or None; heads whose deployment is still saturated
    are skipped (another head may target a deployment with room).
    Queues are keyed by (tenant, deployment) while the virtual clock
    chains per TENANT — fairness is a tenant property, but FIFO order
    only binds requests contending for the SAME replica pool, so one
    saturated deployment can never head-of-line-block the same
    tenant's (or the untenanted pool's) traffic to a deployment with
    free capacity.
    """

    PUMP_MIN_S = 0.002
    PUMP_MAX_S = 0.032

    def __init__(self):
        # Lock-free BY DESIGN (module docstring): every touch happens on
        # the owning proxy's asyncio loop. The annotations make that a
        # checked contract — RL016 fails the gate if this state becomes
        # reachable from an executor thread.
        self._queues: Dict[tuple, Deque[_Waiter]] = {}  # raylint: confine=loop
        # raylint: confine=loop
        self._tenant_finish: Dict[str, float] = {}
        self._vtime = 0.0
        self._pump_task: Optional[asyncio.Task] = None

    def has_waiters(self) -> bool:
        return any(self._queues.values())

    def has_waiters_for(self, deployment: str) -> bool:
        """Whether anyone is queued for THIS deployment's replica pool.
        Fairness only binds requests contending for the same pool, so
        the dispatch fast path bypasses the queue for other deployments
        even while this one is backed up."""
        return any(q for key, q in self._queues.items()
                   if key[1] == deployment)

    def queued(self, tenant: Optional[str] = None) -> int:
        if tenant is not None:
            return sum(len(q) for key, q in self._queues.items()
                       if key[0] == (tenant or ""))
        return sum(len(q) for q in self._queues.values())

    async def acquire(self, loop, tenant: Optional[str], weight: float,
                      try_reserve: Callable[[], Any],
                      timeout_s: float, deployment: str = "",
                      on_drop: Optional[Callable[[Any], None]] = None):
        """Park until this waiter's WFQ turn yields a replica choice.
        Raises TimeoutError when no turn produced capacity in time.

        `on_drop` receives a granted choice the waiter can no longer
        consume (timeout/cancellation raced the pump's grant): the
        grant carries an already-reserved router slot, and dropping it
        silently would leak that replica's concurrency forever."""
        name = tenant or ""
        start = max(self._vtime, self._tenant_finish.get(name, 0.0))
        finish = start + 1.0 / max(1.0, float(weight))
        self._tenant_finish[name] = finish
        fut = loop.create_future()
        key = (name, deployment)
        queue = self._queues.get(key)
        if queue is None:
            queue = self._queues[key] = deque()
        queue.append(_Waiter(fut, try_reserve, finish))
        if self._pump_task is None or self._pump_task.done():
            self._pump_task = loop.create_task(self._pump())

        def _drop_grant():
            if on_drop is not None and fut.done() \
                    and not fut.cancelled() and fut.exception() is None:
                on_drop(fut.result())

        try:
            return await asyncio.wait_for(fut, timeout_s)
        except asyncio.TimeoutError:
            _drop_grant()   # grant raced the timeout: give it back
            raise TimeoutError(
                "no replica capacity within "
                f"{timeout_s:.0f}s (tenant {tenant!r} fair-queued)")
        except asyncio.CancelledError:
            # Client disconnect cancelled the dispatching task; on
            # interpreters where wait_for re-raises the cancellation
            # even for a completed future (py >= 3.12), the grant would
            # otherwise vanish with its reserved slot.
            _drop_grant()
            raise
        # Cancelled/timed-out waiters stay in their deque; the pump
        # discards done futures when their turn comes.

    async def _pump(self):
        """Single drain task per scheduler: admit in virtual-time order
        while anyone waits, polling capacity with capped backoff (the
        router has no loop-side free-slot callback by design)."""
        backoff = self.PUMP_MIN_S
        while self.has_waiters():
            if self._drain_once():
                backoff = self.PUMP_MIN_S
                continue
            await asyncio.sleep(backoff)
            backoff = min(backoff * 2, self.PUMP_MAX_S)
        self._pump_task = None
        # Bounded state under tenant churn: with no waiters left, the
        # virtual clock can reset (fairness is only defined while a
        # backlog exists) and per-tenant tails go with it.
        if not self.has_waiters():
            self._queues.clear()
            self._tenant_finish.clear()
            self._vtime = 0.0

    def _drain_once(self) -> bool:
        """One admission sweep in finish-time order. Returns whether any
        waiter was admitted (progress resets the pump backoff)."""
        heads = []
        for key, queue in self._queues.items():
            while queue and queue[0].fut.done():
                queue.popleft()   # timed out / cancelled waiter
            if queue:
                heapq.heappush(heads, (queue[0].finish, key))
        admitted = False
        while heads:
            finish, key = heapq.heappop(heads)
            queue = self._queues.get(key)
            if not queue or queue[0].finish != finish \
                    or queue[0].fut.done():
                continue
            choice = queue[0].try_reserve()
            if choice is None:
                continue  # this head's deployment is still saturated
            waiter = queue.popleft()
            self._vtime = max(self._vtime, waiter.finish)
            waiter.fut.set_result(choice)
            admitted = True
            while queue and queue[0].fut.done():
                queue.popleft()
            if queue:
                heapq.heappush(heads, (queue[0].finish, key))
        return admitted
