"""Tenant registry types: who owns a deployment and at what QoS.

A tenant is a named principal with a priority tier, a request-rate
quota, and an in-flight cap. The authoritative registry lives in the
serve controller (checkpointed with it — quotas survive a controller
crash); proxies receive each deployment's tenant QoS inside the pushed
routing-table entry and enforce it locally in `tenancy.admission`.
Registration is explicit (`serve.register_tenant`) so a deploy naming
an unknown tenant fails fast instead of silently running unmetered.
"""

from __future__ import annotations

from dataclasses import asdict, dataclass
from typing import Any, Dict, Optional

# Priority tiers and their default WFQ weights: under contention a gold
# tenant's queued requests drain 8x as often as a bronze tenant's. A
# spec may override the weight directly; the tier remains the label the
# bench's per-tier p99 budgets key on.
TIER_WEIGHTS: Dict[str, int] = {"gold": 8, "silver": 4, "bronze": 1}


@dataclass
class TenantSpec:
    """One tenant's identity + QoS contract.

    rps_limit / burst feed the proxy token bucket (0 = unmetered);
    max_inflight caps the tenant's concurrently executing requests per
    proxy (0 = uncapped); weight orders the fair queue when replica
    capacity is contended (defaults to the tier's weight).
    """

    name: str
    tier: str = "bronze"
    weight: int = 0                 # 0 = use the tier default
    rps_limit: float = 0.0          # sustained requests/s (0 = unmetered)
    burst: float = 0.0              # bucket depth (0 = 1s worth of rps)
    max_inflight: int = 0           # per-proxy concurrent cap (0 = none)

    def __post_init__(self):
        if not self.name:
            raise ValueError("tenant name must be non-empty")
        if self.tier not in TIER_WEIGHTS:
            raise ValueError(
                f"unknown tier {self.tier!r} (one of {sorted(TIER_WEIGHTS)})")
        if self.weight <= 0:
            self.weight = TIER_WEIGHTS[self.tier]
        if self.rps_limit and self.burst <= 0:
            self.burst = max(1.0, self.rps_limit)

    def qos(self) -> Dict[str, Any]:
        """The wire form pushed inside routing-table entries (plain
        dict: the table crosses pickle + msgpack boundaries)."""
        return asdict(self)

    @staticmethod
    def from_qos(d: Optional[Dict[str, Any]]) -> Optional["TenantSpec"]:
        if not d:
            return None
        return TenantSpec(**d)
