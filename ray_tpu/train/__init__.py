"""ray_tpu.train: multi-worker training harness (reference: Ray Train + AIR).

The north-star path: `JaxTrainer.fit()` places one JAX process per TPU host,
forms the process group (`jax.distributed`), builds the mesh, and runs the
user's SPMD loop with `session.report` streaming metrics/checkpoints back.
"""

from ray_tpu.train.backend import (
    Backend,
    BackendConfig,
    JaxBackend,
    JaxConfig,
    TorchBackend,
    TorchConfig,
)
from ray_tpu.train.backend_executor import BackendExecutor, TrainingFailedError
from ray_tpu.train.batch_predictor import (
    BatchPredictor,
    JaxPredictor,
    Predictor,
)
from ray_tpu.train.checkpoint import Checkpoint, CheckpointManager
from ray_tpu.train.config import (
    CheckpointConfig,
    FailureConfig,
    RunConfig,
    ScalingConfig,
)
from ray_tpu.train.session import (
    broadcast_params,
    get_checkpoint,
    get_collective,
    get_context,
    get_dataset_shard,
    get_mesh,
    get_world_rank,
    get_world_size,
    report,
    sync_gradients,
)
from ray_tpu.train.trainer import (
    BaseTrainer,
    DataParallelTrainer,
    JaxTrainer,
    Result,
    TorchTrainer,
)
from ray_tpu.train.integrations import (
    LightGBMTrainer,
    TransformersTrainer,
    XGBoostTrainer,
    prepare_trainer,
)
from ray_tpu.train.worker_group import TrainWorker, WorkerGroup

__all__ = [
    "TransformersTrainer", "XGBoostTrainer", "LightGBMTrainer",
    "prepare_trainer",
    "Backend", "BackendConfig", "JaxBackend", "JaxConfig", "BackendExecutor",
    "TrainingFailedError", "Checkpoint", "CheckpointManager",
    "BatchPredictor", "Predictor", "JaxPredictor",
    "TorchTrainer", "TorchConfig", "TorchBackend",
    "CheckpointConfig", "FailureConfig", "RunConfig", "ScalingConfig",
    "report", "get_checkpoint", "get_context", "get_dataset_shard",
    "get_mesh", "get_world_rank", "get_world_size", "BaseTrainer",
    "DataParallelTrainer", "JaxTrainer", "Result", "TrainWorker",
    "WorkerGroup",
]
