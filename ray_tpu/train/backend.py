"""Training backends: the multi-host process-group seam.

Equivalent of the reference's `Backend.on_start` (`python/ray/train/backend.py:53`)
whose Torch implementation runs `dist.init_process_group` over NCCL
(`torch/config.py:69-113`). The TPU-native JaxBackend instead does
coordinator election + `jax.distributed.initialize` + mesh construction —
after which collectives live inside XLA programs (SURVEY.md §3.4 step 3).
"""

from __future__ import annotations

import logging
from dataclasses import dataclass, field
from typing import Any, Dict, Optional

from ray_tpu.parallel.mesh import MeshSpec

logger = logging.getLogger(__name__)


@dataclass
class BackendConfig:
    backend_name: str = "none"

    def backend_cls(self):
        return Backend


class Backend:
    """No-op backend: workers run independently (pure data-parallel via
    host-level collectives, or single-worker)."""

    def on_start(self, worker_group, backend_config: "BackendConfig"):
        pass

    def on_training_start(self, worker_group, backend_config: "BackendConfig"):
        pass

    def on_shutdown(self, worker_group, backend_config: "BackendConfig"):
        pass


@dataclass
class JaxConfig(BackendConfig):
    """Configuration for the JAX/TPU backend.

    mesh: logical mesh laid over the job's global device set.
    force_platform: override jax platform inside workers ("cpu" for tests).
    coordinator_port: fixed port for jax.distributed (0 = auto).
    """

    backend_name: str = "jax"
    mesh: Optional[MeshSpec] = None
    force_platform: Optional[str] = None
    coordinator_port: int = 0
    distributed: Optional[bool] = None  # None = auto (world_size > 1)

    def backend_cls(self):
        return JaxBackend


def _set_platform(platform: str):
    import jax

    jax.config.update("jax_platforms", platform)
    return True


def _init_jax_distributed(coordinator: str, world: int, rank: int):
    from ray_tpu.parallel.distributed import initialize_distributed

    initialize_distributed(coordinator, world, rank)
    return True


def _mesh_builder_for(spec: Optional[MeshSpec]):
    if spec is None:
        return None

    def build():
        from ray_tpu.parallel.mesh import build_mesh

        return build_mesh(spec)

    return build


def _enable_compile_cache():
    from ray_tpu._jax_env import enable_compilation_cache

    enable_compilation_cache()
    return True


class JaxBackend(Backend):
    def on_start(self, worker_group, backend_config: JaxConfig):
        world = len(worker_group)
        if backend_config.force_platform:
            worker_group.execute(_set_platform, backend_config.force_platform)
        # Persistent XLA compilation cache on every train worker: repeated
        # fits (tune trials, restarts, bench re-runs) skip cold compiles.
        worker_group.execute(_enable_compile_cache)
        distributed = backend_config.distributed
        if distributed is None:
            distributed = world > 1
        if distributed and world > 1:
            from ray_tpu.parallel.distributed import get_address_and_port

            host, port = worker_group.execute_single(0, get_address_and_port)
            if backend_config.coordinator_port:
                port = backend_config.coordinator_port
            coordinator = f"{host}:{port}"
            logger.info("forming JAX process group: %d procs via %s",
                        world, coordinator)
            # All ranks must call initialize concurrently (rank 0 hosts the
            # coordination service).
            import ray_tpu

            refs = [w.execute.remote(_init_jax_distributed, coordinator, world, rank)
                    for rank, w in enumerate(worker_group.workers)]
            ray_tpu.get(refs)

    def mesh_builder(self, backend_config: JaxConfig):
        return _mesh_builder_for(backend_config.mesh)

    def on_shutdown(self, worker_group, backend_config: JaxConfig):
        from ray_tpu.parallel.distributed import shutdown_distributed

        try:
            worker_group.execute(shutdown_distributed)
        except Exception:
            pass


# --------------------------------------------------------------------------- #
# Torch backend (reference `train/torch/config.py`)
# --------------------------------------------------------------------------- #


@dataclass
class TorchConfig(BackendConfig):
    """torch.distributed process group over the worker group.

    CPU hosts use gloo (this environment has no CUDA); the seam matches
    the reference's `_TorchBackend.on_start` -> `_setup_torch_process_group`
    (`python/ray/train/torch/config.py:69-113`).
    """

    backend_name: str = "torch"
    backend: str = "gloo"
    init_timeout_s: int = 120

    def backend_cls(self):
        return TorchBackend


def _setup_torch_process_group(backend: str, addr: str, port: int,
                               rank: int, world: int, timeout_s: int):
    import datetime
    import os

    import torch.distributed as dist

    os.environ["MASTER_ADDR"] = addr
    os.environ["MASTER_PORT"] = str(port)
    dist.init_process_group(
        backend, init_method="env://", rank=rank, world_size=world,
        timeout=datetime.timedelta(seconds=timeout_s))
    return True


def _teardown_torch_process_group():
    import torch.distributed as dist

    if dist.is_initialized():
        dist.destroy_process_group()
    return True


class TorchBackend(Backend):
    def on_start(self, worker_group, backend_config: "TorchConfig"):
        import ray_tpu

        world = len(worker_group)
        if world <= 1:
            return
        from ray_tpu.parallel.distributed import get_address_and_port

        host, port = worker_group.execute_single(0, get_address_and_port)
        logger.info("forming torch %s process group: %d procs via %s:%d",
                    backend_config.backend, world, host, port)
        refs = [w.execute.remote(_setup_torch_process_group,
                                 backend_config.backend, host, port,
                                 rank, world, backend_config.init_timeout_s)
                for rank, w in enumerate(worker_group.workers)]
        ray_tpu.get(refs)

    def on_shutdown(self, worker_group, backend_config: "TorchConfig"):
        try:
            worker_group.execute(_teardown_torch_process_group)
        except Exception:  # noqa: BLE001 — workers may already be gone
            pass
