"""BackendExecutor: worker-group lifecycle + result streaming.

Equivalent of the reference's `python/ray/train/_internal/backend_executor.py:43`
(`start` :94, `start_training` :332): starts the WorkerGroup, runs the
backend's process-group setup, launches the per-worker loop, and streams
reported results back; whole-group restart on failure (FailureConfig).
"""

from __future__ import annotations

import logging
import os
from typing import Any, Callable, Dict, Iterator, List, Optional

import ray_tpu
from ray_tpu.core import serialization
from ray_tpu.exceptions import RayActorError
from ray_tpu.train.backend import Backend, BackendConfig
from ray_tpu.train.config import ScalingConfig
from ray_tpu.train.worker_group import WorkerGroup

logger = logging.getLogger(__name__)


class TrainingFailedError(RuntimeError):
    pass


class BackendExecutor:
    def __init__(self, backend_config: BackendConfig,
                 scaling_config: ScalingConfig,
                 max_failures: int = 0,
                 elastic_world_fn: Optional[Callable[[int, int],
                                                     Optional[int]]] = None):
        self.backend_config = backend_config
        self.backend: Backend = backend_config.backend_cls()()
        self.scaling_config = scaling_config
        self.max_failures = max_failures
        # Policy hook for elastic restarts: called with (failure_index,
        # current_world) before each gang restart; a non-None return
        # OVERRIDES the restart width (pipeline runs shrink pp this way
        # — the checkpoint restore re-splits stages at the new width).
        # None keeps the default same-size-then-shrink-on-placement
        # behavior of WorkerGroup.restart.
        self.elastic_world_fn = elastic_world_fn
        self.worker_group: Optional[WorkerGroup] = None
        # Latest checkpoint REPORTED by the run (rank 0), so a gang
        # restart resumes at the last reported step — not from the
        # checkpoint the run originally started from.
        self.latest_checkpoint = None
        # (restart_count, world_size) history for observability/benches.
        self.restarts: List[Dict[str, Any]] = []

    def start(self):
        sc = self.scaling_config
        self.worker_group = WorkerGroup(
            num_workers=sc.num_workers,
            resources_per_worker=sc.worker_resources(),
            placement_strategy=sc.placement_strategy,
            use_placement_group=sc.num_workers > 1,
        )
        self.backend.on_start(self.worker_group, self.backend_config)

    def run(self, train_fn: Callable, config: Dict[str, Any],
            checkpoint=None, datasets_per_worker: Optional[List[Dict]] = None,
            experiment_name: str = "") -> Iterator[List[Dict[str, Any]]]:
        """Generator: yields one list of per-worker results per report round;
        returns when all workers finish.

        Failure semantics (gang-native elastic restart): any rank death
        aborts the whole gang (PR-8 death-hook discipline — ICI slice
        membership is static, SURVEY.md §7: no partial elasticity WITHIN
        a run), then the gang restarts as a unit on a FRESH placement
        group, shrinking the world if the surviving topology cannot place
        it, and the loop resumes from the LATEST reported checkpoint (the
        worker's session hands it to train_fn via session.get_checkpoint;
        restore reshards when the world changed). Up to max_failures."""
        failures = 0
        self.latest_checkpoint = checkpoint
        while True:
            try:
                yield from self._run_once(
                    train_fn, config, self.latest_checkpoint,
                    datasets_per_worker, experiment_name)
                return
            except (RayActorError, TrainingFailedError):
                failures += 1
                if failures > self.max_failures:
                    raise
                logger.warning("worker group failed; gang restart %d/%d "
                               "(resuming from %s checkpoint)",
                               failures, self.max_failures,
                               "latest" if self.latest_checkpoint is not None
                               else "no")
                self._restart_group()

    def _restart_group(self):
        """Gang restart: abort + recreate as a unit (fresh pg), elastic
        shrink on an unplaceable world, backend re-setup on the new
        incarnation. Falls back to a cold start() when no group exists."""
        if self.worker_group is None:
            self.start()
            return
        try:
            self.backend.on_shutdown(self.worker_group, self.backend_config)
        except Exception:  # noqa: BLE001 — dead ranks can't shut down
            logger.debug("backend on_shutdown during restart failed",
                         exc_info=True)
        target = None
        if self.elastic_world_fn is not None:
            target = self.elastic_world_fn(len(self.restarts) + 1,
                                           self.worker_group.num_workers)
        world = self.worker_group.restart(num_workers=target)
        self.restarts.append({"world_size": world,
                              "incarnation": self.worker_group.incarnation})
        self.backend.on_start(self.worker_group, self.backend_config)

    def _run_once(self, train_fn, config, checkpoint, datasets_per_worker,
                  experiment_name):
        wg = self.worker_group
        mesh_builder = None
        if hasattr(self.backend, "mesh_builder"):
            mesh_builder = self.backend.mesh_builder(self.backend_config)
        self.backend.on_training_start(wg, self.backend_config)
        # Run-unique tag shared by all ranks: the host-collective group is
        # named per RUN, so concurrent runs (or a restart of this one)
        # can never interleave joins into one group.
        run_nonce = os.urandom(4).hex()
        start_refs = []
        for i, w in enumerate(wg.workers):
            ds = datasets_per_worker[i] if datasets_per_worker else None
            start_refs.append(w.start_training.remote(
                train_fn, config, checkpoint, mesh_builder, ds,
                experiment_name, run_nonce))
        ray_tpu.get(start_refs)
        done = [False] * len(wg.workers)
        while not all(done):
            refs = [w.next_result.remote()
                    for w, d in zip(wg.workers, done) if not d]
            alive = [i for i, d in enumerate(done) if not d]
            results = ray_tpu.get(refs)
            round_results: List[Dict[str, Any]] = []
            for idx, res in zip(alive, results):
                if res.get("done"):
                    done[idx] = True
                    if res.get("error") is not None:
                        err = serialization.deserialize_exception(res["error"])
                        raise TrainingFailedError(
                            f"worker {idx} train loop failed") from err
                else:
                    round_results.append({"rank": idx, **res})
                    if idx == 0 and res.get("checkpoint") is not None:
                        # Rank 0's reported checkpoint is the resume
                        # point for a gang restart (same choice the
                        # trainer makes for its CheckpointManager).
                        self.latest_checkpoint = res["checkpoint"]
            if round_results:
                yield round_results

    def shutdown(self):
        if self.worker_group is not None:
            try:
                self.backend.on_shutdown(self.worker_group, self.backend_config)
            except Exception:
                pass
            self.worker_group.shutdown()
            self.worker_group = None
