"""BatchPredictor: checkpointed-model inference over a Dataset.

Equivalent of the reference's `python/ray/train/batch_predictor.py`: a
`Predictor` class is constructed from a `Checkpoint` once per scoring
actor (via the Data layer's ActorPoolStrategy map operator), then streams
batches through `predict`. The expensive parts — restore + jit compile —
happen once per actor, not once per block; the batch format is the
numpy-dict the Data layer already produces, so outputs feed
`jax.device_put` or further Data transforms directly.
"""

from __future__ import annotations

from typing import Any, Dict, Optional, Type

import numpy as np

from ray_tpu.train.checkpoint import Checkpoint


class Predictor:
    """Base: subclass with from_checkpoint + predict (reference
    `air.predictor.Predictor`)."""

    @classmethod
    def from_checkpoint(cls, checkpoint: Checkpoint, **kwargs) -> "Predictor":
        raise NotImplementedError

    def predict(self, batch: Dict[str, np.ndarray]) -> Dict[str, np.ndarray]:
        raise NotImplementedError


class JaxPredictor(Predictor):
    """Predictor over a flax module + pytree-checkpointed params: applies
    `model.apply(params, batch[input_column])` jitted, emitting
    `predictions` (plus the passthrough of `keep_columns`)."""

    def __init__(self, model: Any, params: Any, input_column: str = "x",
                 keep_columns: tuple = ()):
        import jax

        self.model = model
        self.params = params
        self.input_column = input_column
        self.keep_columns = tuple(keep_columns)
        self._apply = jax.jit(model.apply)

    @classmethod
    def from_checkpoint(cls, checkpoint: Checkpoint, *, model: Any,
                        input_column: str = "x",
                        keep_columns: tuple = ()) -> "JaxPredictor":
        from ray_tpu.train.checkpoint import unbox_value_nodes

        # Targetless restore surfaces flax partitioning boxes as
        # {'value': leaf} nodes; inference wants the plain arrays.
        params = unbox_value_nodes(checkpoint.get_pytree())
        return cls(model, params, input_column, keep_columns)

    def predict(self, batch: Dict[str, np.ndarray]) -> Dict[str, np.ndarray]:
        out = np.asarray(self._apply(self.params, batch[self.input_column]))
        result = {"predictions": out}
        for col in self.keep_columns:
            if col in batch:
                result[col] = batch[col]
        return result


class _ScoringWorker:
    """Stateful map_batches UDF: one Predictor per pool actor."""

    def __init__(self, checkpoint: Checkpoint,
                 predictor_cls: Type[Predictor], predictor_kwargs: Dict):
        self._predictor = predictor_cls.from_checkpoint(checkpoint,
                                                        **predictor_kwargs)

    def __call__(self, batch: Dict[str, np.ndarray]) -> Dict[str, np.ndarray]:
        return self._predictor.predict(batch)


class BatchPredictor:
    def __init__(self, checkpoint: Checkpoint,
                 predictor_cls: Type[Predictor], **predictor_kwargs):
        self._checkpoint = checkpoint
        self._predictor_cls = predictor_cls
        self._predictor_kwargs = predictor_kwargs

    @classmethod
    def from_checkpoint(cls, checkpoint: Checkpoint,
                        predictor_cls: Type[Predictor],
                        **predictor_kwargs) -> "BatchPredictor":
        return cls(checkpoint, predictor_cls, **predictor_kwargs)

    def predict(self, dataset, *, batch_size: int = 256,
                max_scoring_workers: int = 2,
                keep_columns: Optional[tuple] = None):
        """Score a Dataset; returns the lazy Dataset of prediction batches."""
        from ray_tpu.data.dataset import ActorPoolStrategy

        kwargs = dict(self._predictor_kwargs)
        if keep_columns is not None:
            kwargs["keep_columns"] = tuple(keep_columns)
        return dataset.map_batches(
            _ScoringWorker,
            batch_size=batch_size,
            compute=ActorPoolStrategy(size=max_scoring_workers),
            fn_constructor_args=(self._checkpoint, self._predictor_cls,
                                 kwargs),
        )
