"""Checkpoint: the interchange unit between Train/Tune/RLlib/Serve.

Equivalent of the reference's AIR `Checkpoint` (`python/ray/air/checkpoint.py:65`
— morphs dict <-> directory <-> URI). TPU-native addition: pytree payloads are
stored via Orbax (`save_pytree`/`restore_pytree`) so sharded jax.Arrays
checkpoint without host-gathering the whole model on one process.
"""

from __future__ import annotations

import json
import os
import pickle
import shutil
import tempfile
import time
from typing import Any, Dict, Optional

_DICT_BLOB = "_ckpt_dict.pkl"


class Checkpoint:
    def __init__(self, data: Optional[Dict[str, Any]] = None,
                 path: Optional[str] = None):
        if (data is None) == (path is None):
            raise ValueError("Checkpoint needs exactly one of data or path")
        self._data = data
        self._path = path

    # -- constructors --------------------------------------------------------

    @classmethod
    def from_dict(cls, data: Dict[str, Any]) -> "Checkpoint":
        return cls(data=dict(data))

    @classmethod
    def from_directory(cls, path: str) -> "Checkpoint":
        return cls(path=os.path.abspath(path))

    @classmethod
    def from_uri(cls, uri: str) -> "Checkpoint":
        """Materialize from a URI: file:// maps directly; cloud schemes
        (gs://, s3://, memory://) download through the pluggable storage
        backends (reference `air/checkpoint.py:65` from_uri)."""
        if uri.startswith("file://"):
            return cls.from_directory(uri[len("file://"):])
        from ray_tpu.train import storage

        local = tempfile.mkdtemp(prefix="rtpu_ckpt_dl_")
        storage.download_dir(uri, local)
        return cls.from_directory(local)

    @classmethod
    def from_pytree(cls, tree: Any, path: Optional[str] = None) -> "Checkpoint":
        path = path or tempfile.mkdtemp(prefix="rtpu_ckpt_")
        save_pytree(os.path.join(path, "pytree"), tree)
        return cls.from_directory(path)

    @classmethod
    def from_sharded_pytree(cls, tree: Any, path: Optional[str] = None,
                            process_index: int = 0, process_count: int = 1,
                            meta: Optional[Dict[str, Any]] = None
                            ) -> "Checkpoint":
        """Shard-aware variant of from_pytree: each rank writes only its
        addressable shards + an index manifest (see save_sharded_pytree);
        restore via get_sharded_pytree reshards to ANY tp width."""
        path = path or tempfile.mkdtemp(prefix="rtpu_ckpt_")
        save_sharded_pytree(os.path.join(path, "sharded"), tree,
                            process_index=process_index,
                            process_count=process_count, meta=meta)
        return cls.from_directory(path)

    # -- views ---------------------------------------------------------------

    def to_dict(self) -> Dict[str, Any]:
        if self._data is not None:
            return dict(self._data)
        blob = os.path.join(self._path, _DICT_BLOB)
        if os.path.exists(blob):
            with open(blob, "rb") as f:
                return pickle.load(f)
        raise ValueError(
            f"Directory checkpoint at {self._path} has no dict payload; "
            "use to_directory()/get_pytree()")

    def to_directory(self, path: Optional[str] = None) -> str:
        path = path or tempfile.mkdtemp(prefix="rtpu_ckpt_")
        os.makedirs(path, exist_ok=True)
        if self._data is not None:
            with open(os.path.join(path, _DICT_BLOB), "wb") as f:
                pickle.dump(self._data, f)
        elif os.path.abspath(self._path) != os.path.abspath(path):
            shutil.copytree(self._path, path, dirs_exist_ok=True)
        return path

    def get_pytree(self, target: Any = None) -> Any:
        assert self._path, "pytree checkpoints are directory-backed"
        return restore_pytree(os.path.join(self._path, "pytree"), target)

    def get_sharded_pytree(self, target: Any = None,
                           shardings: Any = None) -> Any:
        assert self._path, "sharded checkpoints are directory-backed"
        return restore_sharded_pytree(os.path.join(self._path, "sharded"),
                                      target=target, shardings=shardings)

    def to_uri(self, uri: str) -> str:
        """Persist to a URI and return it; cloud schemes upload through
        the storage backends (on TPU pods local disk dies with the VM —
        durable checkpoints go through here)."""
        if uri.startswith("file://"):
            self.to_directory(uri[len("file://"):])
            return uri
        from ray_tpu.train import storage

        if self._path is not None:
            local = self._path
        else:
            local = self.to_directory()
        storage.upload_dir(local, uri)
        return uri

    @property
    def uri(self) -> Optional[str]:
        return f"file://{self._path}" if self._path else None

    @property
    def path(self) -> Optional[str]:
        return self._path

    def __repr__(self):
        kind = "dict" if self._data is not None else f"dir:{self._path}"
        return f"Checkpoint({kind})"

    def __reduce__(self):
        # Dict checkpoints travel by value; directory checkpoints by path
        # (the path must be reachable by the receiver — same host or shared fs).
        return (Checkpoint, (self._data, self._path))


# --------------------------------------------------------------------------- #
# Orbax-backed pytree persistence (sharded-array aware)
# --------------------------------------------------------------------------- #


def save_pytree(path: str, tree: Any):
    """Save a jax pytree with orbax; falls back to pickle for plain trees."""
    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    try:
        import orbax.checkpoint as ocp

        ckptr = ocp.PyTreeCheckpointer()
        ckptr.save(os.path.abspath(path), tree, force=True)
    except Exception:
        with open(path + ".pkl", "wb") as f:
            pickle.dump(tree, f)


def restore_pytree(path: str, target: Any = None) -> Any:
    pkl = path + ".pkl"
    if os.path.exists(pkl):
        with open(pkl, "rb") as f:
            return pickle.load(f)
    import orbax.checkpoint as ocp

    ckptr = ocp.PyTreeCheckpointer()
    if target is not None:
        return ckptr.restore(os.path.abspath(path), item=target)
    try:
        return ckptr.restore(os.path.abspath(path))
    except ValueError:
        # Without a target, arrays need an explicit restore type — ask for
        # host numpy (a worker restoring for inference re-shards or
        # device_puts afterwards itself).
        import jax
        import numpy as _np

        meta = ckptr.metadata(os.path.abspath(path))
        tree = getattr(getattr(meta, "item_metadata", meta), "tree", meta)
        restore_args = jax.tree.map(
            lambda _: ocp.RestoreArgs(restore_type=_np.ndarray), tree)
        return ckptr.restore(os.path.abspath(path),
                             restore_args=restore_args)


def unbox_value_nodes(tree: Any) -> Any:
    """Flax `LogicallyPartitioned`/`Partitioned` boxes serialize through
    orbax as {'value': leaf} subtrees; a targetless restore surfaces them.
    Callers that want plain arrays (inference without a mesh — e.g.
    JaxPredictor) unbox explicitly with this. Only {'value': leaf} dicts
    are collapsed, so unboxed trees pass through unchanged."""
    if isinstance(tree, dict):
        if set(tree.keys()) == {"value"} and not isinstance(
                tree["value"], dict):
            return tree["value"]
        return {k: unbox_value_nodes(v) for k, v in tree.items()}
    return tree


# --------------------------------------------------------------------------- #
# Shard-aware checkpoints: per-rank shard files + an index manifest
# --------------------------------------------------------------------------- #
#
# A tp-sharded model must checkpoint WITHOUT host-gathering the whole
# pytree on one process: each rank writes only its addressable shards as
# raw little-endian files (np.save chokes on bfloat16; raw bytes +
# dtype-in-manifest is bit-exact by construction) plus a per-rank
# manifest; rank 0 merges them into one index (`manifest.json`) mapping
# every leaf to {shape, dtype, shards: [{file, index}]}. Restore
# assembles each leaf from its shard slices and re-places it under ANY
# sharding — a tp=2 save restores onto a tp=1 or tp=4 mesh bit-exactly,
# because resharding raw bytes is pure slicing, no arithmetic.

_SHARD_MANIFEST = "manifest.json"


def _shard_key(key_path) -> str:
    """Stable, readable leaf key from a jax KeyPath: dict keys and
    attribute names joined by "/" (flax boxes surface as a trailing
    "value" level — the same shape `unbox_value_nodes` collapses)."""
    parts = []
    for entry in key_path:
        name = getattr(entry, "key", None)
        if name is None:
            name = getattr(entry, "name", None)
        if name is None:
            name = getattr(entry, "idx", None)
        parts.append(str(name))
    return "/".join(parts)


def _np_dtype(name: str):
    import numpy as np

    try:
        return np.dtype(name)
    except TypeError:
        import ml_dtypes  # registered by jax; covers bfloat16 & friends

        return np.dtype(getattr(ml_dtypes, name))


def _norm_index(index, shape) -> list:
    """A shard's slice tuple -> [[start, stop], ...] (JSON-safe)."""
    out = []
    for sl, dim in zip(index, shape):
        start = 0 if sl.start is None else int(sl.start)
        stop = int(dim) if sl.stop is None else int(sl.stop)
        out.append([start, stop])
    return out


def _sanitize(key: str) -> str:
    """Filesystem-safe shard-file stem. Distinct keys can sanitize to
    the same text ('a/b_c' vs 'a_b/c'), so a crc of the ORIGINAL key is
    appended — two leaves must never share a shard file."""
    import zlib

    text = "".join(c if c.isalnum() or c in "._-" else "_" for c in key)
    return f"{text}.{zlib.crc32(key.encode()):08x}"


def save_sharded_pytree(path: str, tree: Any, process_index: int = 0,
                        process_count: int = 1,
                        meta: Optional[Dict[str, Any]] = None,
                        own_replicated: Optional[bool] = None) -> str:
    """Save this process's shards of `tree` under `path`. Single-process
    saves are complete immediately; multi-process saves need every rank
    to call this, then rank 0 to call `merge_sharded_manifest` (after a
    barrier) to write the unified index.

    `own_replicated` controls who writes fully-replicated (and plain
    host) leaves. Default (None -> rank 0 only) fits SPMD saves where
    every rank holds the same tree. Pipeline-stage saves hold DISJOINT
    subtrees per rank — no other rank has this rank's keys — so they
    pass True and each rank writes its own replicated leaves; the merge
    dedupes any key two ranks both wrote by shard index, so mixed modes
    stay safe."""
    import jax
    import numpy as np

    owns = process_index == 0 if own_replicated is None else own_replicated
    os.makedirs(path, exist_ok=True)
    flat, _ = jax.tree_util.tree_flatten_with_path(tree)
    entries: Dict[str, Any] = {}
    for key_path, leaf in flat:
        key = _shard_key(key_path)
        shards = []
        if isinstance(leaf, jax.Array) and hasattr(leaf, "sharding"):
            arr = leaf
            shape = tuple(arr.shape)
            dtype = arr.dtype.name
            seen = set()
            fully_replicated = arr.sharding.is_fully_replicated
            if fully_replicated and not owns:
                # Every rank holds the whole value; the owner's copy wins.
                entries[key] = {"shape": list(shape), "dtype": dtype,
                                "shards": []}
                continue
            for s in arr.addressable_shards:
                idx = _norm_index(s.index, shape)
                tkey = tuple(map(tuple, idx))
                if tkey in seen:
                    continue  # replicated copy on another local device
                seen.add(tkey)
                fname = (f"{_sanitize(key)}.p{process_index}"
                         f".s{len(shards)}.bin")
                data = np.ascontiguousarray(np.asarray(s.data))
                with open(os.path.join(path, fname), "wb") as f:
                    f.write(data.tobytes())
                shards.append({"file": fname, "index": idx})
        else:
            data = np.ascontiguousarray(np.asarray(leaf))
            shape, dtype = tuple(data.shape), data.dtype.name
            if owns:
                fname = f"{_sanitize(key)}.p{process_index}.s0.bin"
                with open(os.path.join(path, fname), "wb") as f:
                    f.write(data.tobytes())
                shards.append({"file": fname,
                               "index": _norm_index(
                                   tuple(slice(0, d) for d in shape),
                                   shape)})
        entries[key] = {"shape": list(shape), "dtype": dtype,
                        "shards": shards}
    rank_manifest = {"process_index": process_index,
                     "process_count": process_count,
                     "meta": dict(meta or {}), "entries": entries}
    with open(os.path.join(path, f"manifest.p{process_index}.json"),
              "w") as f:
        json.dump(rank_manifest, f)
    if process_count == 1:
        merge_sharded_manifest(path, process_count=1)
    return path


def merge_sharded_manifest(path: str, process_count: int) -> str:
    """Merge every rank's manifest into the single restore index —
    called by rank 0 AFTER all ranks finished saving (the caller owns
    the barrier; `train.session`/collective barrier or the gang's
    broadcast both work). Validates full coverage of every leaf."""
    merged: Dict[str, Any] = {}
    meta: Dict[str, Any] = {}
    for p in range(process_count):
        with open(os.path.join(path, f"manifest.p{p}.json")) as f:
            rank_manifest = json.load(f)
        meta.update(rank_manifest.get("meta") or {})
        for key, entry in rank_manifest["entries"].items():
            into = merged.setdefault(
                key, {"shape": entry["shape"], "dtype": entry["dtype"],
                      "shards": []})
            if into["shape"] != entry["shape"] \
                    or into["dtype"] != entry["dtype"]:
                raise ValueError(
                    f"sharded checkpoint {path}: leaf {key!r} disagrees "
                    f"across ranks ({into['shape']}/{into['dtype']} vs "
                    f"{entry['shape']}/{entry['dtype']})")
            seen = {tuple(map(tuple, s["index"])) for s in into["shards"]}
            for s in entry["shards"]:
                if tuple(map(tuple, s["index"])) not in seen:
                    into["shards"].append(s)
    import math

    for key, entry in merged.items():
        total = math.prod(entry["shape"]) if entry["shape"] else 1
        shards = entry["shards"]
        # Overlap would let the volume sum mask a genuinely missing
        # region (restore fills np.empty garbage there) — a save's
        # shards partition the array, so ANY overlap is a corrupt
        # manifest, and with none the volume sum is an exact check.
        for i in range(len(shards)):
            for j in range(i + 1, len(shards)):
                if all(a1 < b2 and a2 < b1
                       for (a1, b1), (a2, b2)
                       in zip(shards[i]["index"], shards[j]["index"])):
                    raise ValueError(
                        f"sharded checkpoint {path}: leaf {key!r} has "
                        f"overlapping shards {shards[i]['index']} and "
                        f"{shards[j]['index']} — manifests disagree on "
                        "the partitioning")
        covered = sum(
            math.prod(max(0, b - a) for a, b in s["index"]) if s["index"]
            else 1
            for s in shards)
        if covered < total:
            raise ValueError(
                f"sharded checkpoint {path}: leaf {key!r} covers only "
                f"{covered}/{total} elements — a rank's shards are "
                "missing (did every rank save before the merge?)")
    with open(os.path.join(path, _SHARD_MANIFEST), "w") as f:
        json.dump({"process_count": process_count, "meta": meta,
                   "entries": merged}, f)
    return path


def sharded_manifest(path: str) -> Dict[str, Any]:
    with open(os.path.join(path, _SHARD_MANIFEST)) as f:
        return json.load(f)


def restore_sharded_pytree(path: str, target: Any = None,
                           shardings: Any = None) -> Any:
    """Restore a sharded checkpoint, resharding as needed.

    - `target`: a pytree with the SAME structure as the saved one (e.g.
      `jax.eval_shape` of the model init); leaves are replaced by the
      restored arrays. Without it a nested dict keyed by the manifest
      paths is returned (flax boxes appear as {'value': leaf} — see
      `unbox_value_nodes`).
    - `shardings`: optional pytree of shardings matching the result (or
      a single sharding applied to every leaf); leaves are device_put
      into it — THIS is the resharding path, bit-exact for any source/
      target tp width because assembly and re-slicing move raw bytes.
    """
    import numpy as np

    manifest = sharded_manifest(path)
    arrays: Dict[str, Any] = {}
    for key, entry in manifest["entries"].items():
        shape = tuple(entry["shape"])
        dtype = _np_dtype(entry["dtype"])
        out = np.empty(shape, dtype)
        for s in entry["shards"]:
            idx = tuple(slice(a, b) for a, b in s["index"])
            sub_shape = tuple(b - a for a, b in s["index"])
            with open(os.path.join(path, s["file"]), "rb") as f:
                data = np.frombuffer(f.read(), dtype=dtype)
            out[idx] = data.reshape(sub_shape)
        arrays[key] = out

    if target is not None:
        import jax

        flat, treedef = jax.tree_util.tree_flatten_with_path(target)
        leaves = []
        for key_path, _ in flat:
            key = _shard_key(key_path)
            if key not in arrays:
                raise KeyError(
                    f"sharded checkpoint {path} has no leaf {key!r} "
                    f"(has: {sorted(arrays)[:8]}...)")
            leaves.append(arrays[key])
        result = jax.tree_util.tree_unflatten(treedef, leaves)
    else:
        result: Dict[str, Any] = {}
        for key, arr in arrays.items():
            node = result
            parts = key.split("/")
            for part in parts[:-1]:
                node = node.setdefault(part, {})
            node[parts[-1]] = arr

    if shardings is not None:
        import jax

        if isinstance(shardings, jax.sharding.Sharding):
            result = jax.tree.map(
                lambda x: jax.device_put(x, shardings), result)
        else:
            result = jax.device_put(result, shardings)
    return result


# --------------------------------------------------------------------------- #
# Keep-N checkpoint bookkeeping
# --------------------------------------------------------------------------- #


class CheckpointManager:
    """Tracks reported checkpoints; keeps best-N by a score attribute
    (reference `air/_internal/checkpoint_manager.py`)."""

    def __init__(self, storage_path: str, num_to_keep: Optional[int] = None,
                 score_attribute: Optional[str] = None, score_order: str = "max"):
        self.storage_path = storage_path
        self.num_to_keep = num_to_keep
        self.score_attribute = score_attribute
        self.score_order = score_order
        self._entries = []  # list of (score, index, path, metrics)
        self._index = 0
        os.makedirs(storage_path, exist_ok=True)

    def register(self, checkpoint: Checkpoint, metrics: Dict[str, Any]) -> str:
        self._index += 1
        dest = os.path.join(self.storage_path, f"checkpoint_{self._index:06d}")
        checkpoint.to_directory(dest)
        with open(os.path.join(dest, "metrics.json"), "w") as f:
            json.dump({k: v for k, v in metrics.items()
                       if isinstance(v, (int, float, str, bool))}, f)
        score = metrics.get(self.score_attribute, self._index) \
            if self.score_attribute else self._index
        self._entries.append((score, self._index, dest, metrics))
        self._evict()
        return dest

    def _evict(self):
        if self.num_to_keep is None or len(self._entries) <= self.num_to_keep:
            return
        reverse = self.score_order == "max"
        ranked = sorted(self._entries, key=lambda e: e[0], reverse=reverse)
        keep = ranked[: self.num_to_keep]
        for entry in self._entries:
            if entry not in keep:
                shutil.rmtree(entry[2], ignore_errors=True)
        self._entries = [e for e in self._entries if e in keep]

    def best_checkpoint(self) -> Optional[Checkpoint]:
        if not self._entries:
            return None
        reverse = self.score_order == "max"
        best = sorted(self._entries, key=lambda e: e[0], reverse=reverse)[0]
        return Checkpoint.from_directory(best[2])

    def latest_checkpoint(self) -> Optional[Checkpoint]:
        if not self._entries:
            return None
        return Checkpoint.from_directory(max(self._entries, key=lambda e: e[1])[2])
