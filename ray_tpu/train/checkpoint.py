"""Checkpoint: the interchange unit between Train/Tune/RLlib/Serve.

Equivalent of the reference's AIR `Checkpoint` (`python/ray/air/checkpoint.py:65`
— morphs dict <-> directory <-> URI). TPU-native addition: pytree payloads are
stored via Orbax (`save_pytree`/`restore_pytree`) so sharded jax.Arrays
checkpoint without host-gathering the whole model on one process.
"""

from __future__ import annotations

import json
import os
import pickle
import shutil
import tempfile
import time
from typing import Any, Dict, Optional

_DICT_BLOB = "_ckpt_dict.pkl"


class Checkpoint:
    def __init__(self, data: Optional[Dict[str, Any]] = None,
                 path: Optional[str] = None):
        if (data is None) == (path is None):
            raise ValueError("Checkpoint needs exactly one of data or path")
        self._data = data
        self._path = path

    # -- constructors --------------------------------------------------------

    @classmethod
    def from_dict(cls, data: Dict[str, Any]) -> "Checkpoint":
        return cls(data=dict(data))

    @classmethod
    def from_directory(cls, path: str) -> "Checkpoint":
        return cls(path=os.path.abspath(path))

    @classmethod
    def from_uri(cls, uri: str) -> "Checkpoint":
        """Materialize from a URI: file:// maps directly; cloud schemes
        (gs://, s3://, memory://) download through the pluggable storage
        backends (reference `air/checkpoint.py:65` from_uri)."""
        if uri.startswith("file://"):
            return cls.from_directory(uri[len("file://"):])
        from ray_tpu.train import storage

        local = tempfile.mkdtemp(prefix="rtpu_ckpt_dl_")
        storage.download_dir(uri, local)
        return cls.from_directory(local)

    @classmethod
    def from_pytree(cls, tree: Any, path: Optional[str] = None) -> "Checkpoint":
        path = path or tempfile.mkdtemp(prefix="rtpu_ckpt_")
        save_pytree(os.path.join(path, "pytree"), tree)
        return cls.from_directory(path)

    # -- views ---------------------------------------------------------------

    def to_dict(self) -> Dict[str, Any]:
        if self._data is not None:
            return dict(self._data)
        blob = os.path.join(self._path, _DICT_BLOB)
        if os.path.exists(blob):
            with open(blob, "rb") as f:
                return pickle.load(f)
        raise ValueError(
            f"Directory checkpoint at {self._path} has no dict payload; "
            "use to_directory()/get_pytree()")

    def to_directory(self, path: Optional[str] = None) -> str:
        path = path or tempfile.mkdtemp(prefix="rtpu_ckpt_")
        os.makedirs(path, exist_ok=True)
        if self._data is not None:
            with open(os.path.join(path, _DICT_BLOB), "wb") as f:
                pickle.dump(self._data, f)
        elif os.path.abspath(self._path) != os.path.abspath(path):
            shutil.copytree(self._path, path, dirs_exist_ok=True)
        return path

    def get_pytree(self, target: Any = None) -> Any:
        assert self._path, "pytree checkpoints are directory-backed"
        return restore_pytree(os.path.join(self._path, "pytree"), target)

    def to_uri(self, uri: str) -> str:
        """Persist to a URI and return it; cloud schemes upload through
        the storage backends (on TPU pods local disk dies with the VM —
        durable checkpoints go through here)."""
        if uri.startswith("file://"):
            self.to_directory(uri[len("file://"):])
            return uri
        from ray_tpu.train import storage

        if self._path is not None:
            local = self._path
        else:
            local = self.to_directory()
        storage.upload_dir(local, uri)
        return uri

    @property
    def uri(self) -> Optional[str]:
        return f"file://{self._path}" if self._path else None

    @property
    def path(self) -> Optional[str]:
        return self._path

    def __repr__(self):
        kind = "dict" if self._data is not None else f"dir:{self._path}"
        return f"Checkpoint({kind})"

    def __reduce__(self):
        # Dict checkpoints travel by value; directory checkpoints by path
        # (the path must be reachable by the receiver — same host or shared fs).
        return (Checkpoint, (self._data, self._path))


# --------------------------------------------------------------------------- #
# Orbax-backed pytree persistence (sharded-array aware)
# --------------------------------------------------------------------------- #


def save_pytree(path: str, tree: Any):
    """Save a jax pytree with orbax; falls back to pickle for plain trees."""
    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    try:
        import orbax.checkpoint as ocp

        ckptr = ocp.PyTreeCheckpointer()
        ckptr.save(os.path.abspath(path), tree, force=True)
    except Exception:
        with open(path + ".pkl", "wb") as f:
            pickle.dump(tree, f)


def restore_pytree(path: str, target: Any = None) -> Any:
    pkl = path + ".pkl"
    if os.path.exists(pkl):
        with open(pkl, "rb") as f:
            return pickle.load(f)
    import orbax.checkpoint as ocp

    ckptr = ocp.PyTreeCheckpointer()
    if target is not None:
        return ckptr.restore(os.path.abspath(path), item=target)
    try:
        return ckptr.restore(os.path.abspath(path))
    except ValueError:
        # Without a target, arrays need an explicit restore type — ask for
        # host numpy (a worker restoring for inference re-shards or
        # device_puts afterwards itself).
        import jax
        import numpy as _np

        meta = ckptr.metadata(os.path.abspath(path))
        tree = getattr(getattr(meta, "item_metadata", meta), "tree", meta)
        restore_args = jax.tree.map(
            lambda _: ocp.RestoreArgs(restore_type=_np.ndarray), tree)
        return ckptr.restore(os.path.abspath(path),
                             restore_args=restore_args)


def unbox_value_nodes(tree: Any) -> Any:
    """Flax `LogicallyPartitioned`/`Partitioned` boxes serialize through
    orbax as {'value': leaf} subtrees; a targetless restore surfaces them.
    Callers that want plain arrays (inference without a mesh — e.g.
    JaxPredictor) unbox explicitly with this. Only {'value': leaf} dicts
    are collapsed, so unboxed trees pass through unchanged."""
    if isinstance(tree, dict):
        if set(tree.keys()) == {"value"} and not isinstance(
                tree["value"], dict):
            return tree["value"]
        return {k: unbox_value_nodes(v) for k, v in tree.items()}
    return tree


# --------------------------------------------------------------------------- #
# Keep-N checkpoint bookkeeping
# --------------------------------------------------------------------------- #


class CheckpointManager:
    """Tracks reported checkpoints; keeps best-N by a score attribute
    (reference `air/_internal/checkpoint_manager.py`)."""

    def __init__(self, storage_path: str, num_to_keep: Optional[int] = None,
                 score_attribute: Optional[str] = None, score_order: str = "max"):
        self.storage_path = storage_path
        self.num_to_keep = num_to_keep
        self.score_attribute = score_attribute
        self.score_order = score_order
        self._entries = []  # list of (score, index, path, metrics)
        self._index = 0
        os.makedirs(storage_path, exist_ok=True)

    def register(self, checkpoint: Checkpoint, metrics: Dict[str, Any]) -> str:
        self._index += 1
        dest = os.path.join(self.storage_path, f"checkpoint_{self._index:06d}")
        checkpoint.to_directory(dest)
        with open(os.path.join(dest, "metrics.json"), "w") as f:
            json.dump({k: v for k, v in metrics.items()
                       if isinstance(v, (int, float, str, bool))}, f)
        score = metrics.get(self.score_attribute, self._index) \
            if self.score_attribute else self._index
        self._entries.append((score, self._index, dest, metrics))
        self._evict()
        return dest

    def _evict(self):
        if self.num_to_keep is None or len(self._entries) <= self.num_to_keep:
            return
        reverse = self.score_order == "max"
        ranked = sorted(self._entries, key=lambda e: e[0], reverse=reverse)
        keep = ranked[: self.num_to_keep]
        for entry in self._entries:
            if entry not in keep:
                shutil.rmtree(entry[2], ignore_errors=True)
        self._entries = [e for e in self._entries if e in keep]

    def best_checkpoint(self) -> Optional[Checkpoint]:
        if not self._entries:
            return None
        reverse = self.score_order == "max"
        best = sorted(self._entries, key=lambda e: e[0], reverse=reverse)[0]
        return Checkpoint.from_directory(best[2])

    def latest_checkpoint(self) -> Optional[Checkpoint]:
        if not self._entries:
            return None
        return Checkpoint.from_directory(max(self._entries, key=lambda e: e[1])[2])
