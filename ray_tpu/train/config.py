"""Training configuration dataclasses.

Equivalent of the reference's AIR configs (`python/ray/air/config.py`:
RunConfig/ScalingConfig/FailureConfig/CheckpointConfig) with TPU-first
extensions: ScalingConfig speaks pod slices and mesh axes, not GPU counts.
"""

from __future__ import annotations

import os
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional

from ray_tpu.parallel.mesh import MeshSpec


@dataclass
class ScalingConfig:
    """How many workers, with what resources, over what mesh.

    - `num_workers`: training worker processes (one JAX process per TPU host).
    - `use_tpu` + `tpus_per_worker`: grants TPU chips; workers get
      `TPU_VISIBLE_CHIPS`-style isolation.
    - `topology`: pod slice name ("v4-32", "v5e-16") — when set, overrides
      num_workers/tpus_per_worker from the slice's host layout.
    - `mesh`: logical mesh spec laid over all granted chips.
    """

    num_workers: int = 1
    use_tpu: bool = False
    tpus_per_worker: int = 0
    resources_per_worker: Optional[Dict[str, float]] = None
    cpus_per_worker: float = 1.0
    topology: Optional[str] = None
    mesh: Optional[MeshSpec] = None
    placement_strategy: str = "PACK"

    def __post_init__(self):
        if self.topology:
            from ray_tpu.util.accelerators import slice_host_count, slice_chip_count

            self.num_workers = slice_host_count(self.topology)
            self.tpus_per_worker = slice_chip_count(self.topology) // self.num_workers
            self.use_tpu = True
            self.placement_strategy = "STRICT_SPREAD"

    def worker_resources(self) -> Dict[str, float]:
        out = {"CPU": float(self.cpus_per_worker)}
        if self.use_tpu and self.tpus_per_worker:
            out["TPU"] = float(self.tpus_per_worker)
        if self.resources_per_worker:
            out.update(self.resources_per_worker)
        return out

    def as_placement_group_bundles(self) -> List[Dict[str, float]]:
        return [self.worker_resources() for _ in range(self.num_workers)]

    @property
    def total_workers(self) -> int:
        return self.num_workers


@dataclass
class FailureConfig:
    """Retries for the whole worker group (the reference's Train-era
    semantics: restart the group, not partial-elastic — SURVEY.md §5.3)."""

    max_failures: int = 0


@dataclass
class CheckpointConfig:
    num_to_keep: Optional[int] = None
    checkpoint_score_attribute: Optional[str] = None
    checkpoint_score_order: str = "max"
    checkpoint_frequency: int = 0


@dataclass
class RunConfig:
    name: Optional[str] = None
    storage_path: Optional[str] = None
    failure_config: FailureConfig = field(default_factory=FailureConfig)
    checkpoint_config: CheckpointConfig = field(default_factory=CheckpointConfig)
    stop: Optional[Dict[str, Any]] = None
    verbose: int = 1
    callbacks: Optional[List[Any]] = None

    def resolved_storage_path(self) -> str:
        base = self.storage_path or os.path.join(
            os.path.expanduser("~"), "ray_tpu_results")
        return os.path.join(base, self.name) if self.name else base
