"""Framework-integration trainers: HF Transformers, XGBoost, LightGBM.

Equivalent of the reference's wrapper-trainer families
(`python/ray/train/huggingface/transformers/`, `train/xgboost/`,
`train/lightgbm/`): thin, honest adapters that run the external
framework's training loop inside this framework's worker group with
metrics/checkpoints flowing through `train.session.report`.

TPU-first note: these wrappers exist for migration parity — the
TPU-native training path is JaxTrainer (the reference makes the same
split: its TorchTrainer family is the GPU path, GBDT trainers are
CPU-host work). XGBoost/LightGBM aren't bundled in this environment, so
their trainers validate availability at construction with a clear
error.
"""

from __future__ import annotations

import importlib
from typing import Any, Callable, Dict, Optional

from ray_tpu.train.backend import TorchConfig
from ray_tpu.train.trainer import DataParallelTrainer


def _require(module: str, trainer: str):
    try:
        return importlib.import_module(module)
    except ImportError as e:
        raise ImportError(
            f"{trainer} requires the {module!r} package, which is not "
            f"installed in this environment") from e


class TransformersTrainer(DataParallelTrainer):
    """Run a Hugging Face `transformers` training loop on the worker
    group (reference `TransformersTrainer` /
    `huggingface/transformers/_transformers_utils.py`).

    The per-worker loop receives the config and builds its own
    `transformers.Trainer` (or manual loop); under num_workers > 1 the
    torch process group is formed (gloo on CPU hosts) before the loop
    runs, so `transformers`' DDP integration sees a ready
    `torch.distributed`. Use `prepare_trainer` to wire HF's reporting
    into this framework's session.
    """

    def __init__(self, train_loop_per_worker, *, train_loop_config=None,
                 torch_config: Optional[TorchConfig] = None,
                 scaling_config=None, run_config=None, datasets=None,
                 resume_from_checkpoint=None):
        _require("transformers", "TransformersTrainer")
        super().__init__(
            train_loop_per_worker,
            train_loop_config=train_loop_config,
            backend_config=torch_config or TorchConfig(),
            scaling_config=scaling_config, run_config=run_config,
            datasets=datasets,
            resume_from_checkpoint=resume_from_checkpoint)


def prepare_trainer(hf_trainer):
    """Attach a callback to a `transformers.Trainer` that forwards its
    logged metrics to `train.session.report` (reference
    `RayTrainReportCallback`), so Tune/Train see HF progress natively."""
    transformers = _require("transformers", "prepare_trainer")

    from ray_tpu.train import session

    class _ReportCallback(transformers.TrainerCallback):
        def on_log(self, args, state, control, logs=None, **kwargs):
            if logs:
                metrics = {k: v for k, v in logs.items()
                           if isinstance(v, (int, float))}
                metrics.setdefault("step", state.global_step)
                session.report(metrics)

    hf_trainer.add_callback(_ReportCallback())
    return hf_trainer


def _gbdt_training_matrix(label_column: str):
    """Worker side: the 'train' dataset shard as (X, y) arrays."""
    import numpy as np

    from ray_tpu.train import session

    ds = session.get_dataset_shard("train")
    batches = list(ds.iter_batches()) if ds is not None else []
    if not batches:
        raise ValueError(
            "GBDT trainers require a non-empty 'train' dataset "
            "(datasets={'train': ds})")
    X = np.concatenate([
        np.column_stack([v for k, v in b.items() if k != label_column])
        for b in batches])
    y = np.concatenate([b[label_column] for b in batches])
    return X, y


def _xgboost_loop(config):
    import xgboost as xgb

    from ray_tpu.train import session
    from ray_tpu.train.checkpoint import Checkpoint

    X, y = _gbdt_training_matrix(config["label_column"])
    dtrain = xgb.DMatrix(X, label=y)
    results: Dict[str, Any] = {}
    booster = xgb.train(config["params"], dtrain,
                        num_boost_round=config["num_boost_round"],
                        evals=[(dtrain, "train")], evals_result=results)
    final = {k: float(v[-1]) for k, v in results.get("train", {}).items()}
    session.report({"boost_rounds": config["num_boost_round"], **final},
                   checkpoint=Checkpoint.from_dict(
                       {"model": booster.save_raw()}))


def _lightgbm_loop(config):
    import lightgbm as lgb

    from ray_tpu.train import session
    from ray_tpu.train.checkpoint import Checkpoint

    X, y = _gbdt_training_matrix(config["label_column"])
    booster = lgb.train(config["params"], lgb.Dataset(X, label=y),
                        num_boost_round=config["num_boost_round"])
    session.report({"boost_rounds": config["num_boost_round"]},
                   checkpoint=Checkpoint.from_dict(
                       {"model": booster.model_to_string()}))


class _GBDTTrainer(DataParallelTrainer):
    """Shared shape for the boosting trainers: single worker (the GBDT
    libraries multithread internally; the reference distributes via
    xgboost-ray, which has no equivalent here). The train loop is a
    module-level function and every knob rides train_loop_config, so
    workers never receive a pickled trainer object (with the full
    driver-side datasets inside)."""

    _module = ""
    _name = ""
    _loop_fn: Callable = None

    def __init__(self, *, params: Dict[str, Any],
                 label_column: str = "label",
                 num_boost_round: int = 10,
                 datasets=None, scaling_config=None, run_config=None,
                 resume_from_checkpoint=None):
        _require(self._module, self._name)
        if not datasets or "train" not in datasets:
            raise ValueError(
                f"{self._name} requires datasets={{'train': ...}}")
        super().__init__(
            type(self)._loop_fn,
            train_loop_config={"params": dict(params),
                               "label_column": label_column,
                               "num_boost_round": num_boost_round},
            backend_config=None,
            scaling_config=scaling_config, run_config=run_config,
            datasets=datasets,
            resume_from_checkpoint=resume_from_checkpoint)


class XGBoostTrainer(_GBDTTrainer):
    """Reference `train/xgboost/xgboost_trainer.py`: boosts on the
    worker from the 'train' dataset shard, reporting eval metrics per
    round through the session."""

    _module = "xgboost"
    _name = "XGBoostTrainer"
    _loop_fn = staticmethod(_xgboost_loop)


class LightGBMTrainer(_GBDTTrainer):
    """Reference `train/lightgbm/lightgbm_trainer.py`."""

    _module = "lightgbm"
    _name = "LightGBMTrainer"
    _loop_fn = staticmethod(_lightgbm_loop)
