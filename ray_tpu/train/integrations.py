"""Framework-integration trainers: HF Transformers, XGBoost, LightGBM.

Equivalent of the reference's wrapper-trainer families
(`python/ray/train/huggingface/transformers/`, `train/xgboost/`,
`train/lightgbm/`): thin, honest adapters that run the external
framework's training loop inside this framework's worker group with
metrics/checkpoints flowing through `train.session.report`.

TPU-first note: these wrappers exist for migration parity — the
TPU-native training path is JaxTrainer (the reference makes the same
split: its TorchTrainer family is the GPU path, GBDT trainers are
CPU-host work). XGBoost/LightGBM aren't bundled in this environment, so
their trainers validate availability at construction with a clear
error.
"""

from __future__ import annotations

import importlib
from typing import Any, Callable, Dict, Optional

from ray_tpu.train.backend import TorchConfig
from ray_tpu.train.trainer import DataParallelTrainer


def _require(module: str, trainer: str):
    try:
        return importlib.import_module(module)
    except ImportError as e:
        raise ImportError(
            f"{trainer} requires the {module!r} package, which is not "
            f"installed in this environment") from e


class TransformersTrainer(DataParallelTrainer):
    """Run a Hugging Face `transformers` training loop on the worker
    group (reference `TransformersTrainer` /
    `huggingface/transformers/_transformers_utils.py`).

    The per-worker loop receives the config and builds its own
    `transformers.Trainer` (or manual loop); under num_workers > 1 the
    torch process group is formed (gloo on CPU hosts) before the loop
    runs, so `transformers`' DDP integration sees a ready
    `torch.distributed`. Use `prepare_trainer` to wire HF's reporting
    into this framework's session.
    """

    def __init__(self, train_loop_per_worker, *, train_loop_config=None,
                 torch_config: Optional[TorchConfig] = None,
                 scaling_config=None, run_config=None, datasets=None,
                 resume_from_checkpoint=None):
        _require("transformers", "TransformersTrainer")
        super().__init__(
            train_loop_per_worker,
            train_loop_config=train_loop_config,
            backend_config=torch_config or TorchConfig(),
            scaling_config=scaling_config, run_config=run_config,
            datasets=datasets,
            resume_from_checkpoint=resume_from_checkpoint)


def prepare_trainer(hf_trainer):
    """Attach a callback to a `transformers.Trainer` that forwards its
    logged metrics to `train.session.report` (reference
    `RayTrainReportCallback`), so Tune/Train see HF progress natively."""
    transformers = _require("transformers", "prepare_trainer")

    from ray_tpu.train import session

    class _ReportCallback(transformers.TrainerCallback):
        def on_log(self, args, state, control, logs=None, **kwargs):
            if logs:
                metrics = {k: v for k, v in logs.items()
                           if isinstance(v, (int, float))}
                metrics.setdefault("step", state.global_step)
                session.report(metrics)

    hf_trainer.add_callback(_ReportCallback())
    return hf_trainer


class _GBDTTrainer(DataParallelTrainer):
    """Shared shape for the boosting trainers: single worker (the GBDT
    libraries multithread internally; the reference distributes via
    xgboost-ray which has no equivalent here), params + train_fn."""

    _module = ""
    _name = ""

    def __init__(self, *, params: Dict[str, Any],
                 train_fn: Optional[Callable] = None,
                 label_column: str = "label",
                 num_boost_round: int = 10,
                 datasets=None, scaling_config=None, run_config=None,
                 resume_from_checkpoint=None):
        _require(self._module, self._name)
        self._params = dict(params)
        self._label_column = label_column
        self._num_boost_round = num_boost_round
        self._user_train_fn = train_fn
        super().__init__(
            self._loop,
            train_loop_config={},
            backend_config=None,
            scaling_config=scaling_config, run_config=run_config,
            datasets=datasets,
            resume_from_checkpoint=resume_from_checkpoint)

    def _loop(self, config):
        raise NotImplementedError


class XGBoostTrainer(_GBDTTrainer):
    """Reference `train/xgboost/xgboost_trainer.py`: boosts on the
    worker from the 'train' dataset shard, reporting eval metrics per
    round through the session."""

    _module = "xgboost"
    _name = "XGBoostTrainer"

    def _loop(self, config):
        import numpy as np
        import xgboost as xgb

        from ray_tpu.train import session

        ds = session.get_dataset_shard("train")
        batches = list(ds.iter_batches()) if ds is not None else []
        X = np.concatenate([
            np.column_stack([v for k, v in b.items()
                             if k != self._label_column])
            for b in batches])
        y = np.concatenate([b[self._label_column] for b in batches])
        dtrain = xgb.DMatrix(X, label=y)
        results: Dict[str, Any] = {}
        booster = xgb.train(self._params, dtrain,
                            num_boost_round=self._num_boost_round,
                            evals=[(dtrain, "train")],
                            evals_result=results)
        final = {k: float(v[-1])
                 for k, v in results.get("train", {}).items()}
        from ray_tpu.train.checkpoint import Checkpoint

        session.report({"boost_rounds": self._num_boost_round, **final},
                       checkpoint=Checkpoint.from_dict(
                           {"model": booster.save_raw()}))


class LightGBMTrainer(_GBDTTrainer):
    """Reference `train/lightgbm/lightgbm_trainer.py`."""

    _module = "lightgbm"
    _name = "LightGBMTrainer"

    def _loop(self, config):
        import lightgbm as lgb
        import numpy as np

        from ray_tpu.train import session

        ds = session.get_dataset_shard("train")
        batches = list(ds.iter_batches()) if ds is not None else []
        X = np.concatenate([
            np.column_stack([v for k, v in b.items()
                             if k != self._label_column])
            for b in batches])
        y = np.concatenate([b[self._label_column] for b in batches])
        train_set = lgb.Dataset(X, label=y)
        booster = lgb.train(self._params, train_set,
                            num_boost_round=self._num_boost_round)
        from ray_tpu.train.checkpoint import Checkpoint

        session.report({"boost_rounds": self._num_boost_round},
                       checkpoint=Checkpoint.from_dict(
                           {"model": booster.model_to_string()}))
