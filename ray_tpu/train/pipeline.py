"""Pipeline-parallel training: stage programs, 1F1B schedule, transports.

The multi-axis training fast path (ISSUE 20). A llama is partitioned
into `pp` stage submodules (`ray_tpu.models.llama.LlamaStage`) and each
stage compiles THREE small programs instead of one monolithic step:

- `fwd(params, x) -> y` — forward to the stage boundary;
- `bwd(params, x, gy) -> (gparams[, gx])` — VJP with recompute-in-
  backward (the forward is re-traced INSIDE the backward jit, so no
  residual tensors cross the stage boundary — only activations forward
  and activation-grads backward);
- the LAST stage fuses loss forward + backward into one
  `fwdbwd(params, x, targets) -> (loss, gparams[, gx])`.

Composition is bitwise-exact in f32: splitting the model across jit
boundaries and chaining per-stage VJPs reproduces the monolithic
`jax.value_and_grad` bit for bit (tests/test_train_pipeline.py proves
it), so a pipeline run IS the single-chip run, reordered.

Two schedules drive the stages over `m` microbatches:

- `"1f1b"` — one-forward-one-backward: stage `s` runs
  `min(pp - 1 - s, m)` warmup forwards, then alternates fwd/bwd in the
  steady state, then drains. Analytic bubble `(pp-1)/(m+pp-1)`.
- `"sequential"` — each microbatch round-trips the whole pipe before
  the next starts (the A/B baseline: same arithmetic, maximal bubble).

Both accumulate gradients in MICROBATCH order on every stage, so their
results are bitwise-identical — the schedule changes only the overlap.

Stage boundaries move over a transport: `LocalPipeTransport` (queues,
one process, threads — the test/bench harness) or
`CollectivePipeTransport` (the collective plane's p2p send/recv — one
worker process per stage, posts overlapped via `isend` on background
threads). Per-stage busy/wall accounting reports the measured
`bubble_frac` next to the analytic bound.

`make_pipeline_train_fn` packages the whole thing as a
`train_loop_per_worker` for a WorkerGroup run: world_size == pp, each
rank drives one stage, every step checkpoints the stage's disjoint
subtree (`save_sharded_pytree(own_replicated=True)`), and a gang
restart at a DIFFERENT world size restores bit-exact from the merged
manifest at the new (tp, pp) width — elastic resharded training.
"""

from __future__ import annotations

import os
import queue
import threading
import time
from collections import deque
from dataclasses import dataclass
from typing import Any, Callable, Dict, List, Optional, Sequence

__all__ = [
    "StagePrograms",
    "StageStats",
    "StageRunResult",
    "LocalPipeTransport",
    "CollectivePipeTransport",
    "token_xent",
    "tiny_pipeline_config",
    "build_stage_programs",
    "split_microbatches",
    "seeded_batch",
    "run_stage",
    "run_pipeline_step",
    "LocalPipelineTrainer",
    "analytic_bubble",
    "stage_state_template",
    "save_pipeline_stage",
    "restore_pipeline_stage",
    "make_pipeline_train_fn",
]

SCHEDULES = ("1f1b", "sequential")


def token_xent(logits, targets):
    """Mean next-token cross entropy (log-softmax in f32)."""
    import jax
    import jax.numpy as jnp

    logp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
    nll = -jnp.take_along_axis(logp, targets[..., None], axis=-1)[..., 0]
    return jnp.mean(nll)


def tiny_pipeline_config(**overrides):
    """f32 toy llama for parity tests/benches: f32 end to end because
    bf16 breaks the bitwise stage-composition guarantee (cross-boundary
    fusion changes rounding); big enough for 2 stages."""
    import jax.numpy as jnp

    from ray_tpu.models.llama import LlamaConfig

    kw = dict(vocab_size=64, n_embd=32, n_layer=2, n_head=4, n_kv_head=2,
              intermediate=64, n_positions=64, dtype=jnp.float32,
              param_dtype=jnp.float32, use_flash=False)
    kw.update(overrides)
    return LlamaConfig(**kw)


def analytic_bubble(pp: int, m: int) -> float:
    """Ideal 1F1B pipeline bubble fraction: (p-1)/(m+p-1)."""
    return (pp - 1) / (m + pp - 1) if pp > 1 else 0.0


# --------------------------------------------------------------------------- #
# Stage programs
# --------------------------------------------------------------------------- #


@dataclass
class StagePrograms:
    """The jitted programs one pipeline stage runs.

    Exactly one of {fwd+bwd, fwdbwd} is populated per position: non-last
    stages get the split pair, the last stage gets the fused
    loss-forward+backward (pp == 1 is first AND last: a single fused
    program over the whole model). `accum`/`scale` are the shared
    microbatch gradient-accumulation jits — leafwise, so the SAME
    arithmetic lands on every (tp, pp) regrouping of the tree."""

    cfg: Any
    stage: int
    pp: int
    module: Any
    fwd: Optional[Callable] = None
    bwd: Optional[Callable] = None
    fwdbwd: Optional[Callable] = None
    accum: Callable = None
    scale: Callable = None

    @property
    def is_first(self) -> bool:
        return self.stage == 0

    @property
    def is_last(self) -> bool:
        return self.stage == self.pp - 1

    def compile_counters(self) -> Dict[str, Any]:
        """Named jitted fns for tests/conftest.assert_compiles_once —
        the zero-per-step-recompile acceptance check."""
        out = {}
        for name in ("fwd", "bwd", "fwdbwd", "accum", "scale"):
            fn = getattr(self, name)
            if fn is not None:
                out[f"s{self.stage}.{name}"] = fn
        return out


def build_stage_programs(cfg, stage: int, pp: int) -> StagePrograms:
    """Compile-on-first-call programs for `stage` of a `pp`-deep llama
    pipeline. Recompute-in-backward: `bwd`/`fwdbwd` re-run the forward
    inside their own jit via `jax.vjp`, so the only tensors crossing
    stage boundaries are activations (forward) and their grads
    (backward) — nothing else is stashed between programs."""
    import jax
    import jax.numpy as jnp

    from ray_tpu.models.llama import LlamaStage

    module = LlamaStage(cfg, stage=stage, pp=pp)

    def apply(p, x):
        return module.apply({"params": p}, x)

    progs = StagePrograms(cfg=cfg, stage=stage, pp=pp, module=module)
    first, last = stage == 0, stage == pp - 1

    if last:
        if first:  # pp == 1: whole model, ids in, no gx out
            def fwdbwd(p, ids, targets):
                def lf(pp_):
                    return token_xent(apply(pp_, ids), targets)
                loss, vjp = jax.vjp(lf, p)
                (gp,) = vjp(jnp.ones_like(loss))
                return loss, gp
        else:
            def fwdbwd(p, x, targets):
                def lf(pp_, xx):
                    return token_xent(apply(pp_, xx), targets)
                loss, vjp = jax.vjp(lf, p, x)
                gp, gx = vjp(jnp.ones_like(loss))
                return loss, gp, gx
        progs.fwdbwd = jax.jit(fwdbwd)
    else:
        progs.fwd = jax.jit(apply)
        if first:  # ids are integer — non-differentiable input, no gx
            def bwd(p, ids, gy):
                _, vjp = jax.vjp(lambda pp_: apply(pp_, ids), p)
                (gp,) = vjp(gy)
                return gp
        else:
            def bwd(p, x, gy):
                _, vjp = jax.vjp(apply, p, x)
                return vjp(gy)  # (gparams, gx)
        progs.bwd = jax.jit(bwd)

    progs.accum = jax.jit(
        lambda a, b: jax.tree.map(jnp.add, a, b))
    progs.scale = jax.jit(
        lambda t, c: jax.tree.map(lambda x: x * c, t))
    return progs


def split_microbatches(batch, m: int) -> List[Any]:
    """Split the leading (batch) dim into `m` equal microbatches."""
    n = batch.shape[0]
    if m < 1 or n % m:
        raise ValueError(f"batch dim {n} not divisible into {m} microbatches")
    k = n // m
    return [batch[i * k:(i + 1) * k] for i in range(m)]


def seeded_batch(seed: int, step: int, batch: int, seq: int, vocab: int):
    """Deterministic (ids, targets) for a step — both sides of an
    elastic-restart A/B and every rank of a gang derive the SAME data
    from (seed, step), so resumes stay bit-comparable without shipping
    batches around."""
    import numpy as np

    rng = np.random.default_rng(np.uint64((seed + 1) * 1_000_003 + step))
    ids = rng.integers(0, vocab, (batch, seq), dtype=np.int64).astype("int32")
    tg = rng.integers(0, vocab, (batch, seq), dtype=np.int64).astype("int32")
    return ids, tg


# --------------------------------------------------------------------------- #
# Transports
# --------------------------------------------------------------------------- #


class LocalPipeTransport:
    """In-process stage links: one FIFO per directed edge and kind
    ("act" forward, "grad" backward). The thread-driver harness."""

    def __init__(self, pp: int, timeout_s: float = 300.0):
        self._timeout = timeout_s
        self._q: Dict[tuple, "queue.Queue"] = {}
        for s in range(pp - 1):
            self._q[(s, s + 1, "act")] = queue.Queue()
            self._q[(s + 1, s, "grad")] = queue.Queue()

    def send(self, src: int, dst: int, kind: str, value) -> None:
        self._q[(src, dst, kind)].put(value)

    def recv(self, src: int, dst: int, kind: str):
        try:
            return self._q[(src, dst, kind)].get(timeout=self._timeout)
        except queue.Empty:
            raise TimeoutError(
                f"pipeline edge {src}->{dst} [{kind}] starved for "
                f"{self._timeout}s — peer stage died or deadlocked")

    def flush(self) -> None:
        pass


class CollectivePipeTransport:
    """Stage links over the collective plane's p2p channels: stage index
    == group rank, kinds map to tags. Sends go out as `isend` so the
    store write + GCS post overlap the next microbatch's compute; the
    p2p ack window (collective_p2p_ack_window) is the flow control.
    `flush()` joins every outstanding post and re-raises the first
    error — call it at step boundaries."""

    def __init__(self, group):
        self.group = group
        self._handles: List[Any] = []

    def send(self, src: int, dst: int, kind: str, value) -> None:
        import numpy as np

        assert src == self.group.rank, (src, self.group.rank)
        # Host copy: stage boundaries serialize as plain numpy (jit on
        # the far side re-ingests without retracing).
        payload = np.asarray(value)
        self._handles.append(self.group.isend(payload, dst, tag=kind))
        if len(self._handles) >= 32:  # bound handle growth mid-step
            self._handles.pop(0).wait()

    def recv(self, src: int, dst: int, kind: str):
        assert dst == self.group.rank, (dst, self.group.rank)
        return self.group.recv(src, tag=kind)

    def flush(self) -> None:
        handles, self._handles = self._handles, []
        for h in handles:
            h.wait()


# --------------------------------------------------------------------------- #
# Schedules
# --------------------------------------------------------------------------- #


@dataclass
class StageStats:
    """Busy-vs-wall accounting for one stage over one step. `busy_s` is
    time inside jitted programs (device compute, blocked to
    completion); everything else in `wall_s` is bubble + transport."""

    stage: int
    pp: int
    m: int
    schedule: str
    fwd_calls: int = 0
    bwd_calls: int = 0
    busy_s: float = 0.0
    wall_s: float = 0.0

    @property
    def bubble_frac(self) -> float:
        return max(0.0, 1.0 - self.busy_s / self.wall_s) if self.wall_s \
            else 0.0

    @property
    def analytic_bubble_frac(self) -> float:
        return analytic_bubble(self.pp, self.m)

    def as_dict(self) -> Dict[str, Any]:
        return {"stage": self.stage, "pp": self.pp, "m": self.m,
                "schedule": self.schedule, "fwd_calls": self.fwd_calls,
                "bwd_calls": self.bwd_calls,
                "busy_s": round(self.busy_s, 6),
                "wall_s": round(self.wall_s, 6),
                "bubble_frac": round(self.bubble_frac, 4),
                "analytic_bubble_frac": round(self.analytic_bubble_frac, 4)}


@dataclass
class StageRunResult:
    gsum: Any                      # microbatch-summed grads (NOT yet /m)
    loss_sum: Any                  # last stage only (jnp scalar), else None
    stats: StageStats = None


def run_stage(programs: StagePrograms, params, transport, m: int,
              inputs: Optional[Sequence] = None,
              targets: Optional[Sequence] = None,
              schedule: str = "1f1b") -> StageRunResult:
    """Drive ONE stage through one step of `m` microbatches.

    The same loop implements both schedules — only the warmup depth
    differs. With `warmup = min(pp-1-stage, m)` forwards in flight
    before the first backward, the steady state is one-forward-one-
    backward (1F1B); with `warmup = 0` every iteration forwards one
    microbatch and then BLOCKS on its gradient, which serializes the
    whole pipe per microbatch (the sequential A/B). Backward order is
    microbatch order either way, so gradients are bitwise-identical
    across schedules.

    `inputs` (stage 0) and `targets` (last stage) are per-microbatch
    lists; interior boundaries arrive over `transport`.
    """
    import jax
    import jax.numpy as jnp

    if schedule not in SCHEDULES:
        raise ValueError(f"unknown schedule {schedule!r} (use {SCHEDULES})")
    s, pp = programs.stage, programs.pp
    first, last = programs.is_first, programs.is_last
    if first and (inputs is None or len(inputs) != m):
        raise ValueError(f"stage 0 needs {m} input microbatches")
    if last and (targets is None or len(targets) != m):
        raise ValueError(f"last stage needs {m} target microbatches")

    stats = StageStats(stage=s, pp=pp, m=m, schedule=schedule)
    state = {"gsum": None, "loss": None, "fwd": 0, "bwd": 0}
    stash: deque = deque()          # stage INPUTS awaiting their backward

    def timed(fn, *args):
        t0 = time.perf_counter()
        out = fn(*args)
        jax.block_until_ready(out)
        stats.busy_s += time.perf_counter() - t0
        return out

    def accumulate(g, loss=None):
        state["gsum"] = g if state["gsum"] is None \
            else timed(programs.accum, state["gsum"], g)
        if loss is not None:
            # Scalar add stays OUT of the accum jit: a second tree
            # structure would hold a second cached program and break the
            # one-program-per-counter compile discipline. A lone f32 add
            # is bitwise-identical eager or jitted.
            state["loss"] = loss if state["loss"] is None \
                else state["loss"] + loss

    t_wall = time.perf_counter()
    if last:
        # The last stage is 1F1B by construction: each microbatch fuses
        # its forward and backward, grads stream out immediately.
        for k in range(m):
            if first:               # pp == 1
                loss, gp = timed(programs.fwdbwd, params, inputs[k],
                                 targets[k])
            else:
                x = transport.recv(s - 1, s, "act")
                loss, gp, gx = timed(programs.fwdbwd, params, x, targets[k])
                transport.send(s, s - 1, "grad", gx)
            accumulate(gp, loss)
            state["fwd"] += 1
            state["bwd"] += 1
    else:
        warmup = 0 if schedule == "sequential" else min(pp - 1 - s, m)

        def forward_one():
            k = state["fwd"]
            x = inputs[k] if first else transport.recv(s - 1, s, "act")
            y = timed(programs.fwd, params, x)
            transport.send(s, s + 1, "act", y)
            stash.append(x)
            state["fwd"] += 1

        def backward_one():
            gy = transport.recv(s + 1, s, "grad")
            x = stash.popleft()
            if first:
                gp = timed(programs.bwd, params, x, gy)
            else:
                gp, gx = timed(programs.bwd, params, x, gy)
                transport.send(s, s - 1, "grad", gx)
            accumulate(gp)
            state["bwd"] += 1

        for _ in range(warmup):
            forward_one()
        while state["bwd"] < m:
            if state["fwd"] < m:
                forward_one()
            backward_one()

    stats.wall_s = time.perf_counter() - t_wall
    stats.fwd_calls, stats.bwd_calls = state["fwd"], state["bwd"]
    return StageRunResult(gsum=state["gsum"], loss_sum=state["loss"],
                          stats=stats)


@dataclass
class PipelineStepResult:
    loss: float
    grads: List[Any]               # per-stage mean grads
    stage_stats: List[StageStats]
    makespan_s: float = 0.0

    @property
    def bubble_frac(self) -> float:
        """Pipeline-level bubble over the step makespan: idle area /
        total stage-time area. Per-stage `wall_s` ends when the stage
        drains, so the makespan (slowest stage) is the denominator —
        a stage that finishes early is idle for the remainder."""
        if not self.makespan_s:
            return 0.0
        pp = len(self.stage_stats)
        busy = sum(st.busy_s for st in self.stage_stats)
        return max(0.0, 1.0 - busy / (pp * self.makespan_s))


def run_pipeline_step(programs_list: Sequence[StagePrograms],
                      params_list: Sequence, ids, targets, m: int,
                      schedule: str = "1f1b",
                      transport: Optional[LocalPipeTransport] = None
                      ) -> PipelineStepResult:
    """One training step through an in-process pipeline: `pp` stage
    threads over queue links. XLA releases the GIL inside compute, so
    stage threads genuinely overlap — this is the measurement (and
    test) harness for the schedules; cross-process runs use
    `make_pipeline_train_fn`."""
    import jax.numpy as jnp

    pp = len(programs_list)
    inputs = split_microbatches(ids, m)
    tgts = split_microbatches(targets, m)

    if pp == 1:
        t0 = time.perf_counter()
        res = run_stage(programs_list[0], params_list[0], None, m,
                        inputs=inputs, targets=tgts, schedule=schedule)
        makespan = time.perf_counter() - t0
        inv_m = jnp.float32(1.0 / m)
        grads = [programs_list[0].scale(res.gsum, inv_m)]
        return PipelineStepResult(
            loss=float(res.loss_sum) / m, grads=grads,
            stage_stats=[res.stats], makespan_s=makespan)

    transport = transport or LocalPipeTransport(pp)
    results: List[Optional[StageRunResult]] = [None] * pp
    errors: List[BaseException] = []
    start = threading.Barrier(pp + 1)

    def drive(si: int):
        try:
            start.wait()
            results[si] = run_stage(
                programs_list[si], params_list[si], transport, m,
                inputs=inputs if si == 0 else None,
                targets=tgts if si == pp - 1 else None,
                schedule=schedule)
        except BaseException as e:  # noqa: BLE001 — re-raised below
            errors.append(e)

    threads = [threading.Thread(target=drive, args=(si,), daemon=True,
                                name=f"pipe-stage-{si}")
               for si in range(pp)]
    for t in threads:
        t.start()
    start.wait()
    t0 = time.perf_counter()
    for t in threads:
        t.join(timeout=600)
    makespan = time.perf_counter() - t0
    if errors:
        raise errors[0]
    if any(r is None for r in results):
        raise TimeoutError("pipeline stage thread never finished")

    inv_m = jnp.float32(1.0 / m)
    grads = [programs_list[si].scale(results[si].gsum, inv_m)
             for si in range(pp)]
    return PipelineStepResult(
        loss=float(results[pp - 1].loss_sum) / m, grads=grads,
        stage_stats=[r.stats for r in results], makespan_s=makespan)


# --------------------------------------------------------------------------- #
# In-process trainer (tests / bench)
# --------------------------------------------------------------------------- #


class LocalPipelineTrainer:
    """pp-stage llama training in one process: monolithic-seeded init
    (identical initial weights at EVERY pp), per-stage adam (leafwise,
    so updates are bitwise width-invariant), threads + queues for the
    schedule. The A/B harness behind the parity tests and
    bench_sharded's pipeline legs."""

    def __init__(self, cfg, pp: int = 1, num_microbatches: int = 2,
                 lr: float = 1e-2, seed: int = 0, schedule: str = "1f1b",
                 batch: int = 4, seq: int = 16):
        import jax
        import optax

        from ray_tpu.models.llama import Llama, split_stage_params

        self.cfg, self.pp, self.m = cfg, pp, num_microbatches
        self.schedule = schedule
        self.batch, self.seq = batch, seq
        self.optimizer = optax.adam(lr)
        sample = seeded_batch(seed, 0, batch // num_microbatches, seq,
                              cfg.vocab_size)[0]
        full = Llama(cfg).init(jax.random.PRNGKey(seed), sample)["params"]
        self.params = list(split_stage_params(full, cfg, pp))
        self.programs = [build_stage_programs(cfg, s, pp) for s in range(pp)]
        self.opt_states = [self.optimizer.init(p) for p in self.params]
        # One update jit PER STAGE: stage trees are different structures,
        # and one shared jit would cache pp programs — opaque to the
        # one-program-per-counter compile accounting.
        self._updates = [jax.jit(self._update_impl) for _ in range(pp)]
        self.step_count = 0
        self.last_result: Optional[PipelineStepResult] = None

    def _update_impl(self, params, opt_state, grads):
        import optax

        updates, new_state = self.optimizer.update(grads, opt_state, params)
        return optax.apply_updates(params, updates), new_state

    def train_step(self, ids, targets) -> Dict[str, Any]:
        res = run_pipeline_step(self.programs, self.params, ids, targets,
                                self.m, schedule=self.schedule)
        for s in range(self.pp):
            self.params[s], self.opt_states[s] = self._updates[s](
                self.params[s], self.opt_states[s], res.grads[s])
        self.step_count += 1
        self.last_result = res
        return {"loss": res.loss, "step": self.step_count,
                "bubble_frac": res.bubble_frac,
                "makespan_s": res.makespan_s}

    def merged_params(self):
        from ray_tpu.models.llama import merge_stage_params

        return merge_stage_params(self.params)

    def compile_counters(self) -> Dict[str, Any]:
        out = {f"s{s}.update": u for s, u in enumerate(self._updates)}
        for p in self.programs:
            out.update(p.compile_counters())
        return out


# --------------------------------------------------------------------------- #
# Stage checkpoints (elastic resharding)
# --------------------------------------------------------------------------- #


def stage_state_template(cfg, stage: int, pp: int, optimizer, sample_ids):
    """Shape/dtype template of one stage's {"params", "opt"} subtree —
    built under `jax.eval_shape` (no FLOPs, no memory) at ANY (pp)
    width, which is what lets a restore re-split a checkpoint saved at
    a different width: leaf paths are GLOBAL (layer index, not
    stage-local), so the manifest keys match regardless of pp."""
    import jax

    from ray_tpu.models.llama import Llama, split_stage_params

    model = Llama(cfg)
    full = jax.eval_shape(
        lambda r: model.init(r, sample_ids)["params"], jax.random.PRNGKey(0))
    stage_params = split_stage_params(full, cfg, pp)[stage]
    opt_tpl = jax.eval_shape(optimizer.init, stage_params)
    return {"params": stage_params, "opt": opt_tpl}


def save_pipeline_stage(path: str, stage_state, stage: int, pp: int,
                        step: Optional[int] = None) -> str:
    """Save one stage's disjoint subtree. `own_replicated=True` because
    NO other rank holds this stage's keys — rank 0 owning replicated
    leaves (the SPMD default) would leave interior stages' norm scales
    and adam counts with zero coverage and fail the merge."""
    from ray_tpu.train.checkpoint import save_sharded_pytree

    return save_sharded_pytree(path, stage_state, process_index=stage,
                               process_count=pp,
                               meta={"step": step, "pp": pp},
                               own_replicated=True)


def restore_pipeline_stage(path: str, cfg, stage: int, pp: int, optimizer,
                           sample_ids, mesh=None):
    """Restore ONE stage's subtree at the CURRENT (possibly different)
    width from a merged stage checkpoint — raw-byte assembly, so the
    round trip is bitwise at any (tp, pp) -> (tp', pp'). With a stage
    `mesh`, params land sharded by the llama partition-rule table."""
    import jax
    import jax.numpy as jnp

    from ray_tpu.models.llama import shard_stage_params
    from ray_tpu.train.checkpoint import restore_sharded_pytree

    tpl = stage_state_template(cfg, stage, pp, optimizer, sample_ids)
    state = restore_sharded_pytree(path, target=tpl)
    state = jax.tree.map(jnp.asarray, state)
    if mesh is not None:
        state["params"] = shard_stage_params(state["params"], mesh)
    return state


# --------------------------------------------------------------------------- #
# WorkerGroup train fn (one rank per stage, elastic across restarts)
# --------------------------------------------------------------------------- #


def make_pipeline_train_fn(steps: int = 6, microbatches: int = 2,
                           batch: int = 4, seq: int = 16, lr: float = 1e-2,
                           seed: int = 0, ckpt_dir: Optional[str] = None,
                           tp: int = 1, schedule: str = "1f1b",
                           cfg_overrides: Optional[Dict[str, Any]] = None):
    """A train_loop_per_worker where pp == session.get_world_size():
    rank r drives stage r over the collective p2p plane, data comes
    deterministically from (seed, step), and EVERY step checkpoints the
    stage subtree + merges on rank 0 — so when the gang restarts at a
    different world size (a killed stage, an elastic shrink), the loop
    resumes from the merged manifest re-split at the NEW pp, bit-exact.

    tp > 1 additionally shards each stage's params over an in-process
    ("tp",) mesh by the llama partition-rule table (the multi-axis
    (tp, pp) layout; restore re-shards to whatever tp the new
    incarnation asks for)."""
    if ckpt_dir is None:
        raise ValueError("make_pipeline_train_fn needs a ckpt_dir")
    overrides = dict(cfg_overrides or {})

    def train_fn(config):
        import jax
        import jax.numpy as jnp
        import optax

        from ray_tpu.train import session
        from ray_tpu.train.checkpoint import (
            Checkpoint,
            merge_sharded_manifest,
        )

        world = session.get_world_size()
        rank = session.get_world_rank()
        pp, stage = world, rank
        cfg = tiny_pipeline_config(**overrides)
        optimizer = optax.adam(lr)
        mb = batch // microbatches
        sample = seeded_batch(seed, 0, mb, seq, cfg.vocab_size)[0]

        mesh = None
        if tp > 1:
            from ray_tpu.parallel.mesh import MeshSpec, build_mesh

            devices = jax.devices()
            if len(devices) >= tp:
                mesh = build_mesh(MeshSpec({"tp": tp}),
                                  devices=devices[:tp])

        programs = build_stage_programs(cfg, stage, pp)
        ckpt = session.get_checkpoint()
        if ckpt is not None:
            d = ckpt.to_dict()
            start = int(d["step"]) + 1
            state = restore_pipeline_stage(d["path"], cfg, stage, pp,
                                           optimizer, sample, mesh=mesh)
            params, opt_state = state["params"], state["opt"]
        else:
            start = 0
            from ray_tpu.models.llama import (
                Llama,
                shard_stage_params,
                split_stage_params,
            )

            full = Llama(cfg).init(jax.random.PRNGKey(seed),
                                   sample)["params"]
            params = split_stage_params(full, cfg, pp)[stage]
            if mesh is not None:
                params = shard_stage_params(params, mesh)
            opt_state = optimizer.init(params)

        group = session.get_collective() if world > 1 else None
        transport = CollectivePipeTransport(group) if group is not None \
            else None

        @jax.jit
        def update(p, o, g):
            updates, new_o = optimizer.update(g, o, p)
            return optax.apply_updates(p, updates), new_o

        inv_m = jnp.float32(1.0 / microbatches)
        for step in range(start, steps):
            ids, tg = seeded_batch(seed, step, batch, seq, cfg.vocab_size)
            inputs = split_microbatches(ids, microbatches) if stage == 0 \
                else None
            tgts = split_microbatches(tg, microbatches) \
                if stage == pp - 1 else None
            res = run_stage(programs, params, transport, microbatches,
                            inputs=inputs, targets=tgts, schedule=schedule)
            grads = programs.scale(res.gsum, inv_m)
            params, opt_state = update(params, opt_state, grads)
            if transport is not None:
                transport.flush()

            path = os.path.join(ckpt_dir, f"step_{step:05d}_w{world}")
            save_pipeline_stage(path, {"params": params, "opt": opt_state},
                                stage, pp, step=step)
            if group is not None:
                group.barrier()     # every stage saved before the merge
            metrics = {"step": step, "world": world, **res.stats.as_dict()}
            if res.loss_sum is not None:
                metrics["loss"] = float(res.loss_sum) / microbatches
            if rank == 0:
                if world > 1:
                    merge_sharded_manifest(path, world)
                session.report(metrics, checkpoint=Checkpoint.from_dict(
                    {"path": path, "step": step, "pp": world}))
            else:
                session.report(metrics)
        return {"final_step": steps - 1, "stage": stage, "world": world}

    return train_fn
