"""Per-worker training session: report(), ranks, dataset shards, the mesh.

Equivalent of the reference's `session.report`/`get_dataset_shard`
(`python/ray/air/session.py:43,359`) + `_TrainSession`
(`python/ray/train/_internal/session.py:63`). TPU addition: `get_mesh()`
hands the worker its slice-wide `jax.sharding.Mesh` built by the JaxBackend.
"""

from __future__ import annotations

import queue
import threading
from typing import Any, Dict, Optional

from ray_tpu.train.checkpoint import Checkpoint


class TrainContext:
    def __init__(self, world_rank: int, world_size: int, local_rank: int = 0,
                 local_world_size: int = 1, node_rank: int = 0,
                 trial_name: str = "", experiment_name: str = ""):
        self.world_rank = world_rank
        self.world_size = world_size
        self.local_rank = local_rank
        self.local_world_size = local_world_size
        self.node_rank = node_rank
        self.trial_name = trial_name
        self.experiment_name = experiment_name


class _TrainSession:
    """Lives inside each training worker while the user loop runs."""

    def __init__(self, context: TrainContext,
                 datasets: Optional[Dict[str, Any]] = None,
                 checkpoint: Optional[Checkpoint] = None,
                 mesh=None, collective_factory=None):
        self.context = context
        self.datasets = datasets or {}
        self.loaded_checkpoint = checkpoint
        self.mesh = mesh
        self.result_queue: "queue.Queue" = queue.Queue()
        self.finished = threading.Event()
        self.error: Optional[BaseException] = None
        self.final_return: Any = None
        # Host collective plane (cross-host DDP outside XLA): lazily
        # joined on first use so single-host loops never pay for it.
        self._collective_factory = collective_factory
        self._collective = None
        self._collective_lock = threading.Lock()

    def collective(self):
        """This worker's handle on the run-wide host collective group
        (ray_tpu.collective), joined on first use. None when the session
        runs outside a WorkerGroup (no factory)."""
        with self._collective_lock:
            if self._collective is None and self._collective_factory is not None:
                self._collective = self._collective_factory()
            return self._collective

    def teardown_collective(self):
        with self._collective_lock:
            group, self._collective = self._collective, None
            self._collective_factory = None  # no join can land after this
        if group is not None:
            try:
                group.leave()
            except Exception:  # noqa: BLE001 — teardown is best-effort
                pass

    def report(self, metrics: Dict[str, Any],
               checkpoint: Optional[Checkpoint] = None):
        self.result_queue.put({"metrics": dict(metrics), "checkpoint": checkpoint})

    def get_dataset_shard(self, name: str = "train"):
        ds = self.datasets.get(name)
        if ds is None:
            return None
        # ray_tpu.data shards are pre-split by the trainer (prefetching
        # ShardIterators); plain iterables pass through.
        return ds

    def ingest_stats(self) -> Dict[str, Any]:
        """Per-dataset step-stall accounting from every shard that keeps
        it (ShardIterator): did input ever stall the step?"""
        out: Dict[str, Any] = {}
        for name, ds in self.datasets.items():
            stats_fn = getattr(ds, "ingest_stats", None)
            if stats_fn is not None:
                out[name] = stats_fn()
        return out


_session: Optional[_TrainSession] = None
_session_lock = threading.Lock()


def init_session(session: _TrainSession):
    global _session
    with _session_lock:
        _session = session


def shutdown_session():
    global _session
    with _session_lock:
        _session = None


def get_session() -> _TrainSession:
    if _session is None:
        raise RuntimeError(
            "No training session active: session APIs are only usable inside "
            "a train_loop_per_worker launched by a Trainer.")
    return _session


# Public functional API ------------------------------------------------------


def report(metrics: Dict[str, Any], checkpoint: Optional[Checkpoint] = None):
    get_session().report(metrics, checkpoint)


def get_checkpoint() -> Optional[Checkpoint]:
    return get_session().loaded_checkpoint


def get_dataset_shard(name: str = "train"):
    return get_session().get_dataset_shard(name)


def get_ingest_stats() -> Dict[str, Any]:
    """Step-stall accounting of this worker's dataset shards (per
    dataset: steps, stall_ms_total, stall_frac — see
    ray_tpu/data/streaming/ingest.py). Empty when shards don't track
    ingest (plain iterables)."""
    return get_session().ingest_stats()


def get_context() -> TrainContext:
    return get_session().context


def get_world_rank() -> int:
    return get_session().context.world_rank


def get_world_size() -> int:
    return get_session().context.world_size


def get_local_rank() -> int:
    return get_session().context.local_rank


def get_mesh():
    """The slice-wide jax.sharding.Mesh assembled by the backend (None when
    the trainer was configured without one)."""
    return get_session().mesh


def get_collective():
    """The run-wide host collective group (`ray_tpu.collective`): ring
    allreduce / tree broadcast between the training workers, outside
    compiled programs. Raises when the session has no worker group."""
    group = get_session().collective()
    if group is None:
        raise RuntimeError(
            "No host collective available: this session is not running "
            "under a WorkerGroup (single-process loops have no peers).")
    return group


def sync_gradients(grads, op: str = "mean"):
    """Cross-host data-parallel gradient sync: allreduce a pytree of
    numpy/jax gradients across all training workers over the host
    collective plane (ring reduce-scatter + all-gather through the object
    transfer plane — see docs/COLLECTIVE.md). The DDP seam for loops whose
    collectives are NOT compiled into XLA (separate JAX processes without
    jax.distributed, torch-free CPU loops, DCN-spanning worker groups)."""
    session = get_session()
    if session.context.world_size <= 1:
        return grads
    return get_collective().allreduce(grads, op=op)


def broadcast_params(params, src_rank: int = 0):
    """Broadcast a pytree (initial weights, updated params) from one
    training worker to all others via the transfer plane's tree
    broadcast."""
    session = get_session()
    if session.context.world_size <= 1:
        return params
    return get_collective().broadcast(params, src_rank=src_rank)
