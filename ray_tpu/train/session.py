"""Per-worker training session: report(), ranks, dataset shards, the mesh.

Equivalent of the reference's `session.report`/`get_dataset_shard`
(`python/ray/air/session.py:43,359`) + `_TrainSession`
(`python/ray/train/_internal/session.py:63`). TPU addition: `get_mesh()`
hands the worker its slice-wide `jax.sharding.Mesh` built by the JaxBackend.
"""

from __future__ import annotations

import queue
import threading
from typing import Any, Dict, Optional

from ray_tpu.train.checkpoint import Checkpoint


class TrainContext:
    def __init__(self, world_rank: int, world_size: int, local_rank: int = 0,
                 local_world_size: int = 1, node_rank: int = 0,
                 trial_name: str = "", experiment_name: str = ""):
        self.world_rank = world_rank
        self.world_size = world_size
        self.local_rank = local_rank
        self.local_world_size = local_world_size
        self.node_rank = node_rank
        self.trial_name = trial_name
        self.experiment_name = experiment_name


class _TrainSession:
    """Lives inside each training worker while the user loop runs."""

    def __init__(self, context: TrainContext,
                 datasets: Optional[Dict[str, Any]] = None,
                 checkpoint: Optional[Checkpoint] = None,
                 mesh=None):
        self.context = context
        self.datasets = datasets or {}
        self.loaded_checkpoint = checkpoint
        self.mesh = mesh
        self.result_queue: "queue.Queue" = queue.Queue()
        self.finished = threading.Event()
        self.error: Optional[BaseException] = None
        self.final_return: Any = None

    def report(self, metrics: Dict[str, Any],
               checkpoint: Optional[Checkpoint] = None):
        self.result_queue.put({"metrics": dict(metrics), "checkpoint": checkpoint})

    def get_dataset_shard(self, name: str = "train"):
        ds = self.datasets.get(name)
        if ds is None:
            return None
        # ray_tpu.data DataIterator shards are pre-split by the trainer;
        # plain iterables pass through.
        return ds


_session: Optional[_TrainSession] = None
_session_lock = threading.Lock()


def init_session(session: _TrainSession):
    global _session
    with _session_lock:
        _session = session


def shutdown_session():
    global _session
    with _session_lock:
        _session = None


def get_session() -> _TrainSession:
    if _session is None:
        raise RuntimeError(
            "No training session active: session APIs are only usable inside "
            "a train_loop_per_worker launched by a Trainer.")
    return _session


# Public functional API ------------------------------------------------------


def report(metrics: Dict[str, Any], checkpoint: Optional[Checkpoint] = None):
    get_session().report(metrics, checkpoint)


def get_checkpoint() -> Optional[Checkpoint]:
    return get_session().loaded_checkpoint


def get_dataset_shard(name: str = "train"):
    return get_session().get_dataset_shard(name)


def get_context() -> TrainContext:
    return get_session().context


def get_world_rank() -> int:
    return get_session().context.world_rank


def get_world_size() -> int:
    return get_session().context.world_size


def get_local_rank() -> int:
    return get_session().context.local_rank


def get_mesh():
    """The slice-wide jax.sharding.Mesh assembled by the backend (None when
    the trainer was configured without one)."""
    return get_session().mesh
