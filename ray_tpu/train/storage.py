"""Pluggable URI storage for checkpoints and experiment sync.

Equivalent of the reference's cloud storage seam under AIR/Tune
(`python/ray/air/checkpoint.py:65` dict<->dir<->URI morphs,
`python/ray/tune/syncer.py` experiment sync), built without cloud SDKs:
`gs://` speaks the GCS JSON API and `s3://` speaks SigV4-signed S3 REST
through a pluggable per-scheme `transport`, so on a TPU-VM the only
dependency is the metadata server; tests register a `memory://` backend
or inject a fake transport and verify the exact requests.

On TPU pods this seam is what makes checkpoints durable: local disk dies
with the VM, so Train/Tune persist through here when `storage_path` is a
bucket URI.
"""

from __future__ import annotations

import hashlib
import hmac
import json
import logging
import os
import threading
import time
import urllib.parse
from typing import Callable, Dict, List, Optional, Tuple, Type

logger = logging.getLogger(__name__)

Transport = Callable[..., bytes]  # (method, url, data=None, headers=None)


def parse_uri(uri: str) -> Tuple[str, str, str]:
    """-> (scheme, bucket, path). file:// has bucket ''."""
    parsed = urllib.parse.urlparse(uri)
    if not parsed.scheme:
        raise ValueError(f"not a URI: {uri!r}")
    if parsed.scheme == "file":
        return "file", "", (parsed.netloc + parsed.path)
    return parsed.scheme, parsed.netloc, parsed.path.lstrip("/")


class StorageBackend:
    """Byte-level verbs against one bucket; directory sync is layered on
    top by upload_dir/download_dir."""

    def __init__(self, bucket: str, transport: Optional[Transport] = None):
        self.bucket = bucket
        self.transport = transport or _urllib_transport

    def put(self, path: str, data: bytes) -> None:
        raise NotImplementedError

    def get(self, path: str) -> bytes:
        raise NotImplementedError

    def list(self, prefix: str) -> List[str]:
        raise NotImplementedError

    def delete(self, path: str) -> None:
        raise NotImplementedError

    def exists_prefix(self, prefix: str) -> bool:
        return bool(self.list(prefix))


# --------------------------------------------------------------------------- #
# Registry
# --------------------------------------------------------------------------- #

_BACKENDS: Dict[str, Type[StorageBackend]] = {}
_TRANSPORTS: Dict[str, Transport] = {}
_CACHE: Dict[Tuple[str, str], StorageBackend] = {}
_LOCK = threading.Lock()


def register_backend(scheme: str, backend_cls: Type[StorageBackend]):
    with _LOCK:
        _BACKENDS[scheme] = backend_cls
        _CACHE.clear()


def set_transport(scheme: str, transport: Optional[Transport]):
    """Inject a fake transport for a scheme (tests); None restores real."""
    with _LOCK:
        if transport is None:
            _TRANSPORTS.pop(scheme, None)
        else:
            _TRANSPORTS[scheme] = transport
        _CACHE.clear()


def get_backend(uri: str) -> Tuple[StorageBackend, str]:
    """-> (backend, path-within-bucket) for a non-file URI."""
    scheme, bucket, path = parse_uri(uri)
    with _LOCK:
        cls = _BACKENDS.get(scheme)
        if cls is None:
            raise ValueError(
                f"no storage backend for scheme {scheme!r} "
                f"(registered: {sorted(_BACKENDS)})")
        key = (scheme, bucket)
        backend = _CACHE.get(key)
        if backend is None:
            backend = cls(bucket, transport=_TRANSPORTS.get(scheme))
            _CACHE[key] = backend
    return backend, path


def is_cloud_uri(uri: str) -> bool:
    try:
        scheme, _, _ = parse_uri(uri)
    except ValueError:
        return False
    return scheme != "file"


# --------------------------------------------------------------------------- #
# Directory sync
# --------------------------------------------------------------------------- #


def upload_dir(local_dir: str, uri: str) -> str:
    """Mirror a local directory to the URI prefix (stale remote files under
    the prefix are replaced, not pruned — sync is additive like the
    reference's default syncer)."""
    scheme, _, path = parse_uri(uri)
    if scheme == "file":
        import shutil

        if os.path.abspath(local_dir) != os.path.abspath(path):
            os.makedirs(path, exist_ok=True)
            shutil.copytree(local_dir, path, dirs_exist_ok=True)
        return uri
    backend, prefix = get_backend(uri)
    base = os.path.abspath(local_dir)
    for root, _dirs, files in os.walk(base):
        for f in files:
            full = os.path.join(root, f)
            rel = os.path.relpath(full, base)
            with open(full, "rb") as fh:
                backend.put(_join(prefix, rel), fh.read())
    return uri


def download_dir(uri: str, local_dir: str) -> str:
    scheme, _, path = parse_uri(uri)
    if scheme == "file":
        import shutil

        if os.path.abspath(path) != os.path.abspath(local_dir):
            os.makedirs(local_dir, exist_ok=True)
            shutil.copytree(path, local_dir, dirs_exist_ok=True)
        return local_dir
    backend, prefix = get_backend(uri)
    names = backend.list(prefix)
    if not names:
        raise FileNotFoundError(f"nothing stored under {uri}")
    for name in names:
        rel = name[len(prefix):].lstrip("/") if prefix else name
        dest = os.path.join(local_dir, rel)
        os.makedirs(os.path.dirname(dest) or ".", exist_ok=True)
        with open(dest, "wb") as fh:
            fh.write(backend.get(name))
    return local_dir


def delete_prefix(uri: str) -> None:
    scheme, _, path = parse_uri(uri)
    if scheme == "file":
        import shutil

        shutil.rmtree(path, ignore_errors=True)
        return
    backend, prefix = get_backend(uri)
    for name in backend.list(prefix):
        backend.delete(name)


def uri_exists(uri: str) -> bool:
    scheme, _, path = parse_uri(uri)
    if scheme == "file":
        return os.path.exists(path)
    backend, prefix = get_backend(uri)
    return backend.exists_prefix(prefix)


def _join(prefix: str, rel: str) -> str:
    rel = rel.replace(os.sep, "/")
    return f"{prefix.rstrip('/')}/{rel}" if prefix else rel


# --------------------------------------------------------------------------- #
# Default transport + GCP auth (shared with the autoscaler's TPU provider)
# --------------------------------------------------------------------------- #


def _urllib_transport(method: str, url: str, data: Optional[bytes] = None,
                      headers: Optional[Dict[str, str]] = None) -> bytes:
    import urllib.request

    req = urllib.request.Request(url, data=data, method=method,
                                 headers=headers or {})
    with urllib.request.urlopen(req, timeout=60) as resp:
        return resp.read()


_gcp_token_lock = threading.Lock()
_gcp_token: Dict[str, object] = {"token": None, "expiry": 0.0}


def _gcp_access_token(transport: Transport) -> str:
    with _gcp_token_lock:
        if _gcp_token["token"] and time.time() < _gcp_token["expiry"] - 60:
            return _gcp_token["token"]  # type: ignore[return-value]
    raw = transport(
        "GET",
        "http://metadata.google.internal/computeMetadata/v1/instance/"
        "service-accounts/default/token",
        None, {"Metadata-Flavor": "Google"})
    payload = json.loads(raw)
    with _gcp_token_lock:
        _gcp_token["token"] = payload["access_token"]
        _gcp_token["expiry"] = time.time() + payload.get("expires_in", 3600)
    return payload["access_token"]


class GCSBackend(StorageBackend):
    """gs:// via the GCS JSON API (storage/v1), metadata-server auth."""

    API = "https://storage.googleapis.com"

    def _headers(self) -> Dict[str, str]:
        return {"Authorization":
                f"Bearer {_gcp_access_token(self.transport)}"}

    def put(self, path: str, data: bytes) -> None:
        name = urllib.parse.quote(path, safe="")
        self.transport(
            "POST",
            f"{self.API}/upload/storage/v1/b/{self.bucket}/o"
            f"?uploadType=media&name={name}",
            data, {**self._headers(),
                   "Content-Type": "application/octet-stream"})

    def get(self, path: str) -> bytes:
        name = urllib.parse.quote(path, safe="")
        return self.transport(
            "GET", f"{self.API}/storage/v1/b/{self.bucket}/o/{name}?alt=media",
            None, self._headers())

    def list(self, prefix: str) -> List[str]:
        out: List[str] = []
        page = ""
        while True:
            url = (f"{self.API}/storage/v1/b/{self.bucket}/o"
                   f"?prefix={urllib.parse.quote(prefix, safe='')}" + page)
            resp = json.loads(self.transport("GET", url, None,
                                             self._headers()))
            out.extend(item["name"] for item in resp.get("items", []))
            token = resp.get("nextPageToken")
            if not token:
                return out
            page = f"&pageToken={token}"

    def delete(self, path: str) -> None:
        name = urllib.parse.quote(path, safe="")
        self.transport("DELETE",
                       f"{self.API}/storage/v1/b/{self.bucket}/o/{name}",
                       None, self._headers())


class S3Backend(StorageBackend):
    """s3:// via SigV4-signed REST (env creds, no SDK)."""

    def __init__(self, bucket: str, transport: Optional[Transport] = None):
        super().__init__(bucket, transport)
        self.region = os.environ.get("AWS_REGION", "us-east-1")
        self.endpoint = os.environ.get(
            "AWS_ENDPOINT_URL",
            f"https://{bucket}.s3.{self.region}.amazonaws.com")

    def _sign(self, method: str, path: str, payload: bytes,
              query: str = "") -> Dict[str, str]:
        access = os.environ.get("AWS_ACCESS_KEY_ID", "")
        secret = os.environ.get("AWS_SECRET_ACCESS_KEY", "")
        host = urllib.parse.urlparse(self.endpoint).netloc
        amz_date = time.strftime("%Y%m%dT%H%M%SZ", time.gmtime())
        datestamp = amz_date[:8]
        payload_hash = hashlib.sha256(payload).hexdigest()
        canonical = "\n".join([
            method, "/" + urllib.parse.quote(path), query,
            f"host:{host}\nx-amz-content-sha256:{payload_hash}\n"
            f"x-amz-date:{amz_date}\n",
            "host;x-amz-content-sha256;x-amz-date", payload_hash])
        scope = f"{datestamp}/{self.region}/s3/aws4_request"
        to_sign = "\n".join(["AWS4-HMAC-SHA256", amz_date, scope,
                             hashlib.sha256(canonical.encode()).hexdigest()])

        def _h(key: bytes, msg: str) -> bytes:
            return hmac.new(key, msg.encode(), hashlib.sha256).digest()

        k = _h(_h(_h(_h(("AWS4" + secret).encode(), datestamp),
                     self.region), "s3"), "aws4_request")
        sig = hmac.new(k, to_sign.encode(), hashlib.sha256).hexdigest()
        return {
            "x-amz-date": amz_date,
            "x-amz-content-sha256": payload_hash,
            "Authorization": (
                f"AWS4-HMAC-SHA256 Credential={access}/{scope}, "
                "SignedHeaders=host;x-amz-content-sha256;x-amz-date, "
                f"Signature={sig}"),
        }

    def put(self, path: str, data: bytes) -> None:
        self.transport("PUT", f"{self.endpoint}/{urllib.parse.quote(path)}",
                       data, self._sign("PUT", path, data))

    def get(self, path: str) -> bytes:
        return self.transport(
            "GET", f"{self.endpoint}/{urllib.parse.quote(path)}",
            None, self._sign("GET", path, b""))

    def list(self, prefix: str) -> List[str]:
        import re

        out: List[str] = []
        token = None
        while True:
            # Query params must stay sorted for the SigV4 canonical form.
            parts = [("list-type", "2"),
                     ("prefix", urllib.parse.quote(prefix, safe=""))]
            if token is not None:
                parts.insert(0, ("continuation-token",
                                 urllib.parse.quote(token, safe="")))
            query = "&".join(f"{k}={v}" for k, v in sorted(parts))
            raw = self.transport(
                "GET", f"{self.endpoint}/?{query}", None,
                self._sign("GET", "", b"", query=query)).decode()
            out.extend(re.findall(r"<Key>([^<]+)</Key>", raw))
            if "<IsTruncated>true</IsTruncated>" not in raw:
                return out
            m = re.search(
                r"<NextContinuationToken>([^<]+)</NextContinuationToken>",
                raw)
            if m is None:
                return out  # truncated but no token: avoid spinning
            token = m.group(1)

    def delete(self, path: str) -> None:
        self.transport("DELETE",
                       f"{self.endpoint}/{urllib.parse.quote(path)}",
                       None, self._sign("DELETE", path, b""))


class MemoryBackend(StorageBackend):
    """memory:// — process-global store for tests."""

    _buckets: Dict[str, Dict[str, bytes]] = {}
    _mlock = threading.Lock()

    def _store(self) -> Dict[str, bytes]:
        with self._mlock:
            return self._buckets.setdefault(self.bucket, {})

    def put(self, path: str, data: bytes) -> None:
        self._store()[path] = bytes(data)

    def get(self, path: str) -> bytes:
        return self._store()[path]

    def list(self, prefix: str) -> List[str]:
        return sorted(k for k in self._store() if k.startswith(prefix))

    def delete(self, path: str) -> None:
        self._store().pop(path, None)

    @classmethod
    def clear(cls):
        with cls._mlock:
            cls._buckets.clear()


register_backend("gs", GCSBackend)
register_backend("s3", S3Backend)
register_backend("memory", MemoryBackend)
