"""Trainers: BaseTrainer -> DataParallelTrainer -> JaxTrainer.

Equivalent of the reference's `BaseTrainer.fit` (`python/ray/train/
base_trainer.py:555`) and `DataParallelTrainer` (`data_parallel_trainer.py:56`),
with the Torch/NCCL path replaced by the JaxBackend (SPMD over a mesh). A
Trainer is convertible to a Tune trainable (`as_trainable`) so experiments run
through the Tuner exactly as in the reference.
"""

from __future__ import annotations

import logging
import os
import time
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional

from ray_tpu.train.backend import (Backend, BackendConfig, JaxConfig,
                                   TorchConfig)
from ray_tpu.train.backend_executor import BackendExecutor, TrainingFailedError
from ray_tpu.train.checkpoint import Checkpoint, CheckpointManager
from ray_tpu.train.config import RunConfig, ScalingConfig

logger = logging.getLogger(__name__)


@dataclass
class Result:
    metrics: Dict[str, Any] = field(default_factory=dict)
    checkpoint: Optional[Checkpoint] = None
    best_checkpoint: Optional[Checkpoint] = None
    error: Optional[BaseException] = None
    metrics_history: List[Dict[str, Any]] = field(default_factory=list)
    path: Optional[str] = None


class BaseTrainer:
    def __init__(self, *, scaling_config: Optional[ScalingConfig] = None,
                 run_config: Optional[RunConfig] = None,
                 resume_from_checkpoint: Optional[Checkpoint] = None,
                 datasets: Optional[Dict[str, Any]] = None):
        self.scaling_config = scaling_config or ScalingConfig()
        self.run_config = run_config or RunConfig()
        self.resume_from_checkpoint = resume_from_checkpoint
        self.datasets = datasets or {}

    def training_loop(self) -> Result:
        raise NotImplementedError

    def fit(self) -> Result:
        import ray_tpu

        if not ray_tpu.is_initialized():
            ray_tpu.init()
        return self.training_loop()

    def as_trainable(self):
        """Wrap as a Tune trainable function (reference: base_trainer.py
        constructs a Tuner internally; we expose the seam directly)."""
        trainer = self

        def trainable(config: Dict[str, Any]):
            merged = trainer._with_config_overrides(config)
            result = merged.training_loop()
            if result.error:
                raise result.error
            return result.metrics

        trainable.__name__ = type(self).__name__
        return trainable

    def _with_config_overrides(self, config: Dict[str, Any]) -> "BaseTrainer":
        if config and hasattr(self, "train_loop_config"):
            merged = dict(self.train_loop_config or {})
            merged.update(config)
            self.train_loop_config = merged
        return self


class DataParallelTrainer(BaseTrainer):
    """Runs `train_loop_per_worker` on N workers with a backend-made process
    group; results stream through session.report (reference
    data_parallel_trainer.py:385 training_loop)."""

    _default_backend_config: BackendConfig = BackendConfig()

    def __init__(self, train_loop_per_worker: Callable, *,
                 train_loop_config: Optional[Dict[str, Any]] = None,
                 backend_config: Optional[BackendConfig] = None,
                 scaling_config: Optional[ScalingConfig] = None,
                 run_config: Optional[RunConfig] = None,
                 datasets: Optional[Dict[str, Any]] = None,
                 resume_from_checkpoint: Optional[Checkpoint] = None):
        super().__init__(scaling_config=scaling_config, run_config=run_config,
                         resume_from_checkpoint=resume_from_checkpoint,
                         datasets=datasets)
        self.train_loop_per_worker = train_loop_per_worker
        self.train_loop_config = train_loop_config or {}
        self.backend_config = backend_config or self._default_backend_config

    def _split_datasets(self) -> Optional[List[Dict[str, Any]]]:
        """Per-worker dataset shards: ray_tpu.data Datasets are
        streaming_split and wrapped in prefetching ShardIterators (the
        worker's prefetch thread double-buffers blocks onto its host over
        the transfer plane, with step-stall accounting — see
        ray_tpu/data/streaming/ingest.py); plain lists are round-robin
        sharded; other values are passed through whole."""
        if not self.datasets:
            return None
        n = self.scaling_config.num_workers
        per_worker: List[Dict[str, Any]] = [dict() for _ in range(n)]
        for name, ds in self.datasets.items():
            splits = None
            if hasattr(ds, "streaming_split"):
                from ray_tpu.data.streaming.ingest import ShardIterator

                splits = [ShardIterator(s) for s in ds.streaming_split(n)]
            elif isinstance(ds, (list, tuple)):
                splits = [list(ds[i::n]) for i in range(n)]
            if splits is None:
                for i in range(n):
                    per_worker[i][name] = ds
            else:
                for i in range(n):
                    per_worker[i][name] = splits[i]
        return per_worker

    def training_loop(self) -> Result:
        run_config = self.run_config
        storage = run_config.resolved_storage_path()
        name = run_config.name or f"{type(self).__name__}_{int(time.time())}"
        exp_dir = os.path.join(storage, name)
        ckpt_conf = run_config.checkpoint_config
        manager = CheckpointManager(
            os.path.join(exp_dir, "checkpoints"),
            num_to_keep=ckpt_conf.num_to_keep,
            score_attribute=ckpt_conf.checkpoint_score_attribute,
            score_order=ckpt_conf.checkpoint_score_order,
        )
        executor = BackendExecutor(
            self.backend_config, self.scaling_config,
            max_failures=run_config.failure_config.max_failures)
        executor.start()
        history: List[Dict[str, Any]] = []
        last_metrics: Dict[str, Any] = {}
        last_ckpt: Optional[Checkpoint] = None
        error: Optional[BaseException] = None
        try:
            for round_results in executor.run(
                    self.train_loop_per_worker, self.train_loop_config,
                    checkpoint=self.resume_from_checkpoint,
                    datasets_per_worker=self._split_datasets(),
                    experiment_name=name):
                rank0 = next((r for r in round_results if r["rank"] == 0),
                             round_results[0])
                last_metrics = rank0["metrics"]
                history.append(last_metrics)
                ckpt = rank0.get("checkpoint")
                if ckpt is not None:
                    path = manager.register(ckpt, last_metrics)
                    last_ckpt = Checkpoint.from_directory(path)
                for cb in run_config.callbacks or []:
                    try:
                        cb(last_metrics)
                    except Exception:
                        logger.exception("callback failed")
                if run_config.stop and all(
                        last_metrics.get(k, float("-inf")) >= v
                        for k, v in run_config.stop.items()):
                    logger.info("stop condition met: %s", run_config.stop)
                    break
        except (TrainingFailedError, Exception) as e:  # noqa: BLE001
            error = e
        finally:
            executor.shutdown()
        return Result(metrics=last_metrics, checkpoint=last_ckpt,
                      best_checkpoint=manager.best_checkpoint(),
                      error=error, metrics_history=history, path=exp_dir)

    @classmethod
    def restore(cls, path: str, train_loop_per_worker: Callable, **kwargs):
        """Resume from the latest checkpoint under an experiment dir."""
        ckpt_dir = os.path.join(path, "checkpoints")
        latest = None
        if os.path.isdir(ckpt_dir):
            entries = sorted(os.listdir(ckpt_dir))
            if entries:
                latest = Checkpoint.from_directory(
                    os.path.join(ckpt_dir, entries[-1]))
        return cls(train_loop_per_worker,
                   resume_from_checkpoint=latest, **kwargs)


class JaxTrainer(DataParallelTrainer):
    """The TPU-native TorchTrainer equivalent: one JAX process per host,
    collectives compiled by XLA over ICI (JaxBackend), mesh handed to the
    loop via `session.get_mesh()`. This is the north-star path
    (BASELINE.json: "JaxTrainer ... data-parallel allreduce")."""

    _default_backend_config = JaxConfig()

    def __init__(self, train_loop_per_worker: Callable, *,
                 jax_config: Optional[JaxConfig] = None, **kwargs):
        backend_config = kwargs.pop("backend_config", None) or jax_config \
            or JaxConfig(mesh=(kwargs.get("scaling_config") or ScalingConfig()).mesh)
        super().__init__(train_loop_per_worker,
                         backend_config=backend_config, **kwargs)


class TorchTrainer(DataParallelTrainer):
    """Data-parallel torch training (reference `TorchTrainer`,
    `torch/torch_trainer.py:15`): the worker group forms a
    torch.distributed process group (gloo on CPU hosts) and the user loop
    wraps its model in DistributedDataParallel. On this framework the
    TPU-native path is JaxTrainer; TorchTrainer exists for drop-in
    migration of torch training scripts."""

    _default_backend_config = TorchConfig()

    def __init__(self, train_loop_per_worker, *, train_loop_config=None,
                 torch_config: Optional[TorchConfig] = None,
                 scaling_config=None, run_config=None, datasets=None,
                 resume_from_checkpoint=None):
        super().__init__(
            train_loop_per_worker,
            train_loop_config=train_loop_config,
            backend_config=torch_config or TorchConfig(),
            scaling_config=scaling_config, run_config=run_config,
            datasets=datasets, resume_from_checkpoint=resume_from_checkpoint)
