"""WorkerGroup: N training-worker actors, placement-grouped.

Equivalent of the reference's `python/ray/train/_internal/worker_group.py:100`.
Workers are generic function-executor actors; the JaxBackend and the training
loop both run through `execute*`. TPU workers are placed one per host via a
STRICT_SPREAD placement group (ScalingConfig.topology).
"""

from __future__ import annotations

import logging
import os
import threading
from typing import Any, Callable, Dict, List, Optional

import ray_tpu
from ray_tpu.train.session import (
    TrainContext,
    _TrainSession,
    init_session,
    shutdown_session,
)

logger = logging.getLogger(__name__)


class TrainWorker:
    """Actor hosting one training process (one JAX process per TPU host)."""

    def __init__(self, rank: int, world_size: int, env: Optional[Dict[str, str]] = None):
        self.rank = rank
        self.world_size = world_size
        if env:
            os.environ.update(env)
        from ray_tpu._jax_env import apply_jax_platform_env

        apply_jax_platform_env()
        self._session: Optional[_TrainSession] = None
        self._thread: Optional[threading.Thread] = None

    def execute(self, fn: Callable, *args, **kwargs):
        return fn(*args, **kwargs)

    def ping(self) -> Dict[str, Any]:
        """Liveness probe for the group's death monitor."""
        return {"ok": True, "rank": self.rank}

    def node_info(self):
        import socket

        return {"hostname": socket.gethostname(), "pid": os.getpid(),
                "rank": self.rank}

    # -- training lifecycle --------------------------------------------------

    def start_training(self, train_fn: Callable, config: Dict[str, Any],
                       checkpoint=None, mesh_builder: Optional[Callable] = None,
                       datasets: Optional[Dict[str, Any]] = None,
                       experiment_name: str = "", run_nonce: str = ""):
        assert self._thread is None or not self._thread.is_alive(), \
            "training already running"
        mesh = mesh_builder() if mesh_builder is not None else None
        context = TrainContext(world_rank=self.rank, world_size=self.world_size,
                               experiment_name=experiment_name)
        collective_factory = None
        if self.world_size > 1:
            rank, world = self.rank, self.world_size
            # Run-unique name (nonce from the executor): concurrent runs
            # with the same experiment name can never share a group.
            group_name = (f"train:{experiment_name or 'run'}"
                          f":{run_nonce or 'default'}")

            def collective_factory():
                import ray_tpu
                from ray_tpu import collective as _collective
                from ray_tpu.exceptions import CollectiveError

                try:
                    return _collective.init_collective_group(
                        world, rank, group_name=group_name)
                except CollectiveError:
                    # A crashed previous run left the name broken (its
                    # members died, the record stayed). Clear it — only
                    # if still broken, so a peer's fresh incarnation
                    # survives the race — and join the new epoch.
                    ray_tpu._require_runtime().gcs.call(
                        "collective_destroy",
                        {"name": group_name, "if_broken": True}, timeout=10)
                    return _collective.init_collective_group(
                        world, rank, group_name=group_name)

        session = _TrainSession(context, datasets=datasets, checkpoint=checkpoint,
                                mesh=mesh, collective_factory=collective_factory)
        self._session = session
        init_session(session)

        def run():
            try:
                import inspect

                if config and len(inspect.signature(train_fn).parameters) > 0:
                    session.final_return = train_fn(config)
                elif len(inspect.signature(train_fn).parameters) > 0:
                    session.final_return = train_fn({})
                else:
                    session.final_return = train_fn()
            except BaseException as e:  # noqa: BLE001
                session.error = e
                logger.exception("train loop failed on rank %d", self.rank)
            finally:
                session.finished.set()

        self._thread = threading.Thread(target=run, name="train-loop", daemon=True)
        self._thread.start()
        return True

    def next_result(self, timeout: float = 3600.0):
        """Block until the next session.report() or loop completion."""
        import queue as _q

        session = self._session
        assert session is not None, "training not started"
        while True:
            try:
                item = session.result_queue.get(timeout=0.1)
                return {"done": False, **item}
            except _q.Empty:
                if session.finished.is_set() and session.result_queue.empty():
                    if session.error is not None:
                        from ray_tpu.core import serialization

                        return {"done": True,
                                "error": serialization.serialize_exception(
                                    session.error, "train_loop_per_worker")}
                    return {"done": True, "final": session.final_return}
                timeout -= 0.1
                if timeout <= 0:
                    return {"done": False, "timeout": True}

    def finish(self):
        if self._session is not None:
            self._session.teardown_collective()
        shutdown_session()
        self._session = None
        return True


class WorkerGroup:
    def __init__(self, num_workers: int,
                 resources_per_worker: Optional[Dict[str, float]] = None,
                 placement_strategy: str = "PACK",
                 use_placement_group: bool = True):
        self.num_workers = num_workers
        self._resources_per_worker = dict(
            resources_per_worker or {"CPU": 1.0})
        self._placement_strategy = placement_strategy
        self._use_placement_group = use_placement_group
        # Bumped on every successful (re)creation: consumers key run-scoped
        # names (collective groups) off it so a restarted gang can never
        # collide with its previous incarnation.
        self.incarnation = 0
        self.workers: List[Any] = []
        self._pg = None
        self._dead_rank: Optional[int] = None
        self._monitor = None
        self._create(num_workers)

    @property
    def dead_rank(self) -> Optional[int]:
        return self._dead_rank

    def _create(self, num_workers: int, pg_timeout_s: float = 120.0):
        resources = dict(self._resources_per_worker)
        self.num_workers = num_workers
        self._pg = None
        actor_cls = ray_tpu.remote(TrainWorker)
        options: Dict[str, Any] = {}
        placement_strategy = self._placement_strategy
        use_placement_group = self._use_placement_group
        num_cpus = resources.pop("CPU", 1.0)
        num_tpus = resources.pop("TPU", 0)
        # CPU is a *logical* resource: scale the per-worker request down so
        # the group always fits the cluster (a 2-worker default must work on
        # a 1-CPU bench host). TPU chips are physical and never scaled.
        try:
            total_cpu = ray_tpu.cluster_resources().get("CPU", 0.0)
        except Exception:
            total_cpu = 0.0
        if total_cpu and num_cpus * num_workers > total_cpu:
            fitted = max(0.01, int(total_cpu * 100 / num_workers) / 100)
            logger.warning(
                "ScalingConfig requests %s CPUs x %d workers but the cluster "
                "has %s; scaling the per-worker CPU request to %s.",
                num_cpus, num_workers, total_cpu, fitted)
            num_cpus = fitted
        if use_placement_group and num_workers > 1:
            from ray_tpu.util.placement_group import placement_group
            from ray_tpu.util.scheduling_strategies import (
                PlacementGroupSchedulingStrategy,
            )

            bundle = {"CPU": num_cpus}
            if num_tpus:
                bundle["TPU"] = num_tpus
            bundle.update(resources)
            self._pg = placement_group([dict(bundle)] * num_workers,
                                       strategy=placement_strategy)
            self._pg.ready(timeout=pg_timeout_s)
        self.workers = []
        self._dead_rank = None
        self._monitor = None
        try:
            for rank in range(num_workers):
                opts = dict(options)
                opts["num_cpus"] = num_cpus
                if num_tpus:
                    opts["num_tpus"] = num_tpus
                if resources:
                    opts["resources"] = dict(resources)
                if self._pg is not None:
                    from ray_tpu.util.scheduling_strategies import (
                        PlacementGroupSchedulingStrategy,
                    )

                    opts["scheduling_strategy"] = \
                        PlacementGroupSchedulingStrategy(
                            self._pg, placement_group_bundle_index=rank)
                try:
                    handle = actor_cls.options(**opts).remote(
                        rank, num_workers)
                except Exception as e:
                    raise RuntimeError(
                        f"creating train worker rank {rank}/{num_workers} "
                        f"failed: {type(e).__name__}: {e}") from e
                self.workers.append(handle)
        except Exception:
            # All-or-nothing (raylint RL009): a mid-gang failure releases
            # every already-created worker AND the placement group's
            # bundles — no leaked reservations, no half-alive gangs.
            self._abort_gang()
            raise
        if num_workers > 1:
            # Group death hook: a dead worker fails the next execute()
            # fast with a rank-attributed error instead of a generic
            # actor error minutes later (gradient sync would otherwise
            # discover it at the collective timeout).
            from ray_tpu.shardgroup import GangMonitor, ReplicaGroup, ShardSpec

            grp = ReplicaGroup(
                f"train-wg-{id(self):x}", ShardSpec(world_size=num_workers),
                None, self.workers,
                [f"rank{r}" for r in range(num_workers)])
            self._monitor = GangMonitor(grp, self._on_worker_death)
        self.incarnation += 1

    def restart(self, num_workers: Optional[int] = None,
                deadline_s: Optional[float] = None) -> int:
        """Gang-native elastic restart: abort the whole gang (every rank
        AND the placement group), then re-create it on a FRESH placement
        group under the recovery deadline — shrinking the world when the
        surviving topology cannot place the full gang (a killed node
        whose replacement never came). Returns the new world size; raises
        a loudly-attributed RuntimeError when no gang of ANY size could
        be formed within the deadline (never hangs in pg.ready)."""
        import time as _time

        from ray_tpu.core.config import GLOBAL_CONFIG

        if deadline_s is None:
            deadline_s = GLOBAL_CONFIG.chaos_recovery_deadline_s or 300.0
        deadline = _time.monotonic() + deadline_s
        self.shutdown()
        n = num_workers if num_workers is not None else self.num_workers
        last_err: Optional[BaseException] = None
        while True:
            remaining = deadline - _time.monotonic()
            if remaining <= 0:
                raise RuntimeError(
                    f"train gang restart stuck: no {n}-worker gang could "
                    f"be formed within the {deadline_s:.0f}s recovery "
                    f"deadline (last error: {last_err})") from last_err
            try:
                self._create(n, pg_timeout_s=min(30.0, remaining))
                logger.info("train gang restarted: world=%d incarnation=%d",
                            n, self.incarnation)
                return n
            except Exception as e:  # noqa: BLE001 — retried under deadline
                last_err = e
                self._abort_gang()
                if n > 1:
                    # Elastic shrink: the full gang no longer places —
                    # try a smaller world (checkpoint restore reshards).
                    logger.warning(
                        "train gang restart at world=%d failed (%s); "
                        "shrinking to %d", n, e, n - 1)
                    n -= 1
                _time.sleep(min(0.5, max(0.0,
                                         deadline - _time.monotonic())))

    def _abort_gang(self):
        for w in self.workers:
            try:
                ray_tpu.kill(w)
            except Exception:  # noqa: BLE001 — never created / dead
                pass
        self.workers = []
        if self._pg is not None:
            from ray_tpu.util.placement_group import remove_placement_group

            try:
                remove_placement_group(self._pg)
            except Exception:  # noqa: BLE001 — already removed
                pass
            self._pg = None

    def _on_worker_death(self, group, rank: int):
        self._dead_rank = rank

    def _check_group_alive(self):
        if self._dead_rank is not None:
            raise RuntimeError(
                f"train worker group lost rank {self._dead_rank}/"
                f"{self.num_workers} — the group must be shut down and "
                "recreated (workers restart as a unit)")

    def __len__(self):
        return self.num_workers

    def execute(self, fn: Callable, *args, **kwargs) -> List[Any]:
        self._check_group_alive()
        return ray_tpu.get([w.execute.remote(fn, *args, **kwargs)
                            for w in self.workers])

    def execute_async(self, fn: Callable, *args, **kwargs):
        self._check_group_alive()
        return [w.execute.remote(fn, *args, **kwargs) for w in self.workers]

    def execute_single(self, rank: int, fn: Callable, *args, **kwargs) -> Any:
        self._check_group_alive()
        return ray_tpu.get(self.workers[rank].execute.remote(fn, *args, **kwargs))

    def shutdown(self):
        if self._monitor is not None:
            self._monitor.stop()
            self._monitor.group._dead = True
            self._monitor = None
        for w in self.workers:
            try:
                ray_tpu.kill(w)
            except Exception:
                pass
        if self._pg is not None:
            from ray_tpu.util.placement_group import remove_placement_group

            try:
                remove_placement_group(self._pg)
            except Exception:
                pass
        self.workers = []
