"""ray_tpu.tune: hyperparameter search over trials-as-actors.

Equivalent of Ray Tune (`python/ray/tune/tuner.py:52,315`): `Tuner.fit`
expands the param space into trials, runs them through the TuneController
with a scheduler (ASHA/PBT/FIFO), checkpoints experiment state, and returns
a ResultGrid. Train trainers plug in via `Trainer.as_trainable()`.

    from ray_tpu import tune

    def trainable(config):
        for step in range(10):
            tune.report({"loss": config["lr"] * step})

    tuner = tune.Tuner(trainable,
                       param_space={"lr": tune.loguniform(1e-4, 1e-1)},
                       tune_config=tune.TuneConfig(num_samples=8,
                                                   metric="loss", mode="min"))
    results = tuner.fit()
"""

from __future__ import annotations

import os
import time
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional

from ray_tpu.train.checkpoint import Checkpoint
from ray_tpu.train.config import RunConfig
from ray_tpu.train.session import get_checkpoint, get_session
from ray_tpu.tune.schedulers import (
    ASHAScheduler,
    FIFOScheduler,
    HyperBandScheduler,
    MedianStoppingRule,
    PB2,
    PopulationBasedTraining,
    TrialScheduler,
)
from ray_tpu.tune.search import (
    BasicVariantGenerator,
    BOHBSearcher,
    Searcher,
    TPESearcher,
    choice,
    grid_search,
    loguniform,
    quniform,
    randint,
    sample_from,
    uniform,
)
from ray_tpu.tune.trial import Trial, TrialStatus
from ray_tpu.tune.tune_controller import TuneController


def report(metrics: Dict[str, Any], *, checkpoint: Optional[Checkpoint] = None):
    """Report metrics (+ optional checkpoint) from inside a trainable."""
    get_session().report(metrics, checkpoint)


@dataclass
class TuneConfig:
    metric: Optional[str] = None
    mode: str = "min"
    num_samples: int = 1
    max_concurrent_trials: int = 0
    scheduler: Optional[TrialScheduler] = None
    # Adaptive search algorithm (e.g. TPESearcher). When set, trials are
    # suggested sequentially as results arrive instead of expanded upfront.
    search_alg: Optional[Searcher] = None
    # Restarts per trial after actor death (from the last checkpoint).
    max_failures: int = 0
    seed: Optional[int] = None


@dataclass
class Result:
    metrics: Dict[str, Any]
    config: Dict[str, Any]
    checkpoint: Optional[Checkpoint]
    error: Optional[str]
    trial_id: str
    metrics_history: List[Dict[str, Any]] = field(default_factory=list)


class ResultGrid:
    def __init__(self, trials: List[Trial], metric: Optional[str], mode: str):
        self._trials = trials
        self._metric = metric
        self._mode = mode

    def __len__(self):
        return len(self._trials)

    def __iter__(self):
        return iter(self.results)

    def __getitem__(self, i):
        return self.results[i]

    @property
    def results(self) -> List[Result]:
        return [Result(
            metrics=t.last_result, config=t.config,
            checkpoint=Checkpoint.from_directory(t.checkpoint_path)
            if t.checkpoint_path else None,
            error=t.error, trial_id=t.trial_id,
            metrics_history=t.metrics_history) for t in self._trials]

    @property
    def errors(self) -> List[str]:
        return [t.error for t in self._trials if t.error]

    def get_best_result(self, metric: Optional[str] = None,
                        mode: Optional[str] = None) -> Result:
        metric = metric or self._metric
        mode = mode or self._mode
        if metric is None:
            raise ValueError("metric required (set TuneConfig.metric)")
        scored = [r for r in self.results if metric in r.metrics]
        if not scored:
            raise ValueError(f"no trial reported metric {metric!r}")
        return (max if mode == "max" else min)(
            scored, key=lambda r: r.metrics[metric])

    def get_dataframe(self):
        import pandas as pd

        rows = []
        for r in self.results:
            row = {"trial_id": r.trial_id, **{f"config/{k}": v
                                              for k, v in r.config.items()}}
            row.update(r.metrics)
            rows.append(row)
        return pd.DataFrame(rows)


class Tuner:
    def __init__(self, trainable: Callable, *,
                 param_space: Optional[Dict[str, Any]] = None,
                 tune_config: Optional[TuneConfig] = None,
                 run_config: Optional[RunConfig] = None,
                 _trials: Optional[List[Trial]] = None):
        if hasattr(trainable, "as_trainable"):
            trainable = trainable.as_trainable()
        self._trainable = trainable
        self._param_space = param_space or {}
        self._tune_config = tune_config or TuneConfig()
        self._run_config = run_config or RunConfig()
        self._restored_trials = _trials

    def _experiment_name(self) -> str:
        return self._run_config.name or \
            f"{getattr(self._trainable, '__name__', 'trainable')}_{int(time.time())}"

    def _experiment_dir(self) -> str:
        name = self._experiment_name()
        base = self._run_config.storage_path or os.path.join(
            os.path.expanduser("~"), "ray_tpu_results")
        from ray_tpu.train import storage

        if storage.is_cloud_uri(base):
            # Cloud storage_path: work locally, sync to the bucket
            # (reference tune/syncer.py; _sync_uri consumed by fit()).
            self._sync_uri = f"{base.rstrip('/')}/{name}"
            return os.path.join(os.path.expanduser("~"),
                                ".cache", "ray_tpu", "tune_sync", name)
        self._sync_uri = None
        return os.path.join(base, name)

    def fit(self) -> ResultGrid:
        import ray_tpu

        if not ray_tpu.is_initialized():
            ray_tpu.init()
        tc = self._tune_config
        if self._restored_trials is not None:
            trials = self._restored_trials
        elif tc.search_alg is not None:
            trials = []  # the searcher proposes them as capacity frees
        else:
            configs = BasicVariantGenerator(
                self._param_space, tc.num_samples, tc.seed).generate()
            trials = [Trial(config=c) for c in configs]
        experiment_dir = self._experiment_dir()
        controller = TuneController(
            self._trainable, trials,
            scheduler=tc.scheduler,
            max_concurrent=tc.max_concurrent_trials,
            experiment_dir=experiment_dir,
            stop=self._run_config.stop,
            metric=tc.metric, mode=tc.mode,
            searcher=tc.search_alg,
            num_samples=tc.num_samples if tc.search_alg is not None else None,
            max_failures=tc.max_failures,
            sync_uri=getattr(self, "_sync_uri", None),
        )
        controller.run()
        return ResultGrid(trials, tc.metric, tc.mode)

    @classmethod
    def restore(cls, path: str, trainable: Callable,
                tune_config: Optional[TuneConfig] = None) -> "Tuner":
        """Resume an interrupted experiment from its directory — or from
        a bucket URI (the cloud copy written by experiment sync), which is
        downloaded into the local working dir and re-synced on fit()."""
        from ray_tpu.train import storage

        name = os.path.basename(path.rstrip("/"))
        if storage.is_cloud_uri(path):
            local = os.path.join(os.path.expanduser("~"),
                                 ".cache", "ray_tpu", "tune_sync", name)
            storage.download_dir(path, local)
            trials = TuneController.load_trials(local)
            # Checkpoint paths were recorded on the machine that synced;
            # remap them into the freshly-downloaded tree.
            for t in trials:
                cp = getattr(t, "checkpoint_path", None)
                if cp:
                    cand = os.path.join(local, t.trial_id,
                                        os.path.basename(cp.rstrip("/")))
                    if os.path.isdir(cand):
                        t.checkpoint_path = cand
            run_config = RunConfig(
                name=name,
                storage_path=path.rstrip("/")[: -len(name) - 1])
        else:
            trials = TuneController.load_trials(path)
            run_config = RunConfig(name=name,
                                   storage_path=os.path.dirname(
                                       path.rstrip("/")))
        return cls(trainable, tune_config=tune_config, run_config=run_config,
                   _trials=trials)

    @staticmethod
    def can_restore(path: str) -> bool:
        from ray_tpu.train import storage

        if storage.is_cloud_uri(path):
            return storage.uri_exists(f"{path.rstrip('/')}/tuner.pkl")
        return os.path.exists(os.path.join(path, "tuner.pkl"))


__all__ = [
    "Tuner", "TuneConfig", "Result", "ResultGrid", "report",
    "Trial", "TrialStatus", "TrialScheduler", "FIFOScheduler",
    "ASHAScheduler", "PopulationBasedTraining", "HyperBandScheduler",
    "MedianStoppingRule",
    "grid_search", "choice", "uniform", "loguniform", "randint", "quniform",
    "sample_from", "get_checkpoint", "Searcher", "TPESearcher",
    "BOHBSearcher", "PB2",
]
