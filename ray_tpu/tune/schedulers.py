"""Trial schedulers: FIFO, ASHA, PBT.

Equivalent of the reference's `python/ray/tune/schedulers/`:
`async_hyperband.py` (ASHA — rung-quantile early stopping without
synchronized brackets) and `pbt.py` (exploit top quantile's checkpoint +
perturb config). Decisions are returned from `on_trial_result`; the
controller enforces them.
"""

from __future__ import annotations

import math
import random
from typing import Any, Dict, List, Optional

from ray_tpu.tune.trial import Trial


class TrialScheduler:
    CONTINUE = "CONTINUE"
    STOP = "STOP"

    def on_trial_result(self, trial: Trial, result: Dict[str, Any]) -> str:
        return self.CONTINUE

    def on_trial_complete(self, trial: Trial):
        pass

    def choose_trial_to_run(self, pending: List[Trial]) -> Optional[Trial]:
        return pending[0] if pending else None


class FIFOScheduler(TrialScheduler):
    pass


class ASHAScheduler(TrialScheduler):
    """Async successive halving (reference `async_hyperband.py`).

    Rungs at r, r*eta, r*eta^2, ... up to max_t; a trial reaching a rung is
    stopped unless it is in the top 1/eta of results recorded at that rung.
    """

    def __init__(self, metric: str = "loss", mode: str = "min",
                 time_attr: str = "training_iteration",
                 max_t: int = 100, grace_period: int = 1,
                 reduction_factor: float = 4):
        assert mode in ("min", "max")
        self.metric = metric
        self.mode = mode
        self.time_attr = time_attr
        self.max_t = max_t
        self.grace_period = grace_period
        self.eta = reduction_factor
        # rung value -> list of recorded metric values
        self.rungs: Dict[int, List[float]] = {}
        milestones = []
        t = grace_period
        while t < max_t:
            milestones.append(int(t))
            t *= self.eta
        self.milestones = milestones

    def on_trial_result(self, trial: Trial, result: Dict[str, Any]) -> str:
        t = result.get(self.time_attr, trial.num_results)
        value = result.get(self.metric)
        if value is None:
            return self.CONTINUE
        if t >= self.max_t:
            return self.STOP
        for rung in self.milestones:
            if t == rung or (t > rung and not self._recorded(trial, rung)):
                recorded = self.rungs.setdefault(rung, [])
                recorded.append(float(value))
                trial.last_result.setdefault("_asha_rungs", []).append(rung)
                if not self._in_top_fraction(float(value), recorded):
                    return self.STOP
        return self.CONTINUE

    def _recorded(self, trial: Trial, rung: int) -> bool:
        return rung in trial.last_result.get("_asha_rungs", [])

    def _in_top_fraction(self, value: float, recorded: List[float]) -> bool:
        if len(recorded) < self.eta:
            return True  # not enough data to cut
        ranked = sorted(recorded, reverse=(self.mode == "max"))
        k = max(1, int(len(ranked) / self.eta))
        cutoff = ranked[k - 1]
        return value >= cutoff if self.mode == "max" else value <= cutoff


class MedianStoppingRule(TrialScheduler):
    """Stop a trial whose best result so far is worse than the median of
    the other trials' running averages at the same point (reference
    `schedulers/median_stopping_rule.py`)."""

    def __init__(self, metric: str = "loss", mode: str = "min",
                 time_attr: str = "training_iteration",
                 grace_period: int = 1, min_samples_required: int = 3):
        assert mode in ("min", "max")
        self.metric = metric
        self.mode = mode
        self.time_attr = time_attr
        self.grace_period = grace_period
        self.min_samples = min_samples_required
        # trial_id -> list of metric values (one per result)
        self._histories: Dict[str, List[float]] = {}

    def on_trial_result(self, trial: Trial, result: Dict[str, Any]) -> str:
        value = result.get(self.metric)
        if value is None:
            return self.CONTINUE
        hist = self._histories.setdefault(trial.trial_id, [])
        hist.append(float(value))
        t = result.get(self.time_attr, trial.num_results)
        if t < self.grace_period:
            return self.CONTINUE
        # Running average of every OTHER trial up to this step count.
        others = []
        for tid, h in self._histories.items():
            if tid == trial.trial_id or not h:
                continue
            others.append(sum(h[:len(hist)]) / min(len(h), len(hist)))
        if len(others) < self.min_samples:
            return self.CONTINUE
        median = sorted(others)[len(others) // 2]
        best = max(hist) if self.mode == "max" else min(hist)
        worse = best < median if self.mode == "max" else best > median
        return self.STOP if worse else self.CONTINUE


class HyperBandScheduler(TrialScheduler):
    """Synchronized HyperBand (reference `schedulers/hyperband.py`):
    brackets of successive halving with different (n, r) trade-offs; each
    bracket halves its cohort at its milestones, keeping the top 1/eta.

    Trials are assigned to brackets round-robin at first result; within a
    bracket, halving is enforced asynchronously at each milestone (a trial
    past a milestone stops unless in the bracket's top 1/eta there) — the
    asynchronous-cutoff variant of the synchronized algorithm, which never
    idles a chip waiting for bracket stragglers.
    """

    def __init__(self, metric: str = "loss", mode: str = "min",
                 time_attr: str = "training_iteration",
                 max_t: int = 81, reduction_factor: float = 3):
        assert mode in ("min", "max")
        self.metric = metric
        self.mode = mode
        self.time_attr = time_attr
        self.max_t = max_t
        self.eta = reduction_factor
        s_max = int(math.log(max_t) / math.log(reduction_factor))
        # Bracket s starts at r0 = max_t * eta^-s with milestones up to max_t.
        self._brackets: List[Dict[str, Any]] = []
        for s in range(s_max, -1, -1):
            r0 = max(1, int(max_t * self.eta ** (-s)))
            milestones = []
            t = r0
            while t < max_t:
                milestones.append(int(t))
                t *= self.eta
            self._brackets.append({"milestones": milestones, "rungs": {}})
        self._assignment: Dict[str, int] = {}
        self._next_bracket = 0

    def _bracket_for(self, trial: Trial) -> Dict[str, Any]:
        b = self._assignment.get(trial.trial_id)
        if b is None:
            b = self._next_bracket
            self._assignment[trial.trial_id] = b
            self._next_bracket = (self._next_bracket + 1) % len(self._brackets)
        return self._brackets[b]

    def on_trial_result(self, trial: Trial, result: Dict[str, Any]) -> str:
        value = result.get(self.metric)
        if value is None:
            return self.CONTINUE
        t = result.get(self.time_attr, trial.num_results)
        if t >= self.max_t:
            return self.STOP
        bracket = self._bracket_for(trial)
        seen = trial.last_result.setdefault("_hb_rungs", [])
        # Record only at the HIGHEST newly-crossed milestone: appending one
        # late value to every skipped rung would compare it against peers'
        # genuinely-early values and systematically favor coarse reporters.
        crossed = [r for r in bracket["milestones"]
                   if t >= r and r not in seen]
        if crossed:
            rung = crossed[-1]
            recorded = bracket["rungs"].setdefault(rung, [])
            recorded.append(float(value))
            seen.extend(crossed)  # skipped rungs count as passed, unscored
            if len(recorded) >= self.eta and \
                    not self._in_top_fraction(float(value), recorded):
                return self.STOP
        return self.CONTINUE

    def _in_top_fraction(self, value: float, recorded: List[float]) -> bool:
        ranked = sorted(recorded, reverse=(self.mode == "max"))
        k = max(1, int(len(ranked) / self.eta))
        cutoff = ranked[k - 1]
        return value >= cutoff if self.mode == "max" else value <= cutoff


class PopulationBasedTraining(TrialScheduler):
    """PBT (reference `pbt.py`): every `perturbation_interval` results, a
    bottom-quantile trial is stopped and respawned from a top-quantile
    trial's checkpoint with a perturbed config. The controller performs the
    respawn when it sees the EXPLOIT decision."""

    EXPLOIT = "EXPLOIT"

    def __init__(self, metric: str = "loss", mode: str = "min",
                 perturbation_interval: int = 4,
                 hyperparam_mutations: Optional[Dict[str, Any]] = None,
                 quantile_fraction: float = 0.25,
                 seed: Optional[int] = None):
        self.metric = metric
        self.mode = mode
        self.interval = perturbation_interval
        self.mutations = hyperparam_mutations or {}
        self.quantile = quantile_fraction
        self._rng = random.Random(seed)
        self._trials: Dict[str, Trial] = {}

    def on_trial_result(self, trial: Trial, result: Dict[str, Any]) -> str:
        self._trials[trial.trial_id] = trial
        if trial.num_results % self.interval != 0:
            return self.CONTINUE
        value = result.get(self.metric)
        if value is None:
            return self.CONTINUE
        scored = [(t.last_result.get(self.metric), t)
                  for t in self._trials.values()
                  if t.last_result.get(self.metric) is not None]
        if len(scored) < 2:
            return self.CONTINUE
        scored.sort(key=lambda x: x[0], reverse=(self.mode == "max"))
        k = max(1, int(len(scored) * self.quantile))
        bottom_ids = {t.trial_id for _, t in scored[-k:]}
        if trial.trial_id in bottom_ids:
            return self.EXPLOIT
        return self.CONTINUE

    def exploit_target(self, trial: Trial) -> Optional[Trial]:
        scored = [(t.last_result.get(self.metric), t)
                  for t in self._trials.values()
                  if t.last_result.get(self.metric) is not None
                  and t.trial_id != trial.trial_id]
        if not scored:
            return None
        scored.sort(key=lambda x: x[0], reverse=(self.mode == "max"))
        k = max(1, int(len(scored) * self.quantile))
        return self._rng.choice([t for _, t in scored[:k]])

    def perturb(self, config: Dict[str, Any]) -> Dict[str, Any]:
        from ray_tpu.tune.search import Domain

        out = dict(config)
        for key, mutation in self.mutations.items():
            if isinstance(mutation, list):
                out[key] = self._rng.choice(mutation)
            elif isinstance(mutation, Domain):
                out[key] = mutation.sample(self._rng)
            elif callable(mutation):
                out[key] = mutation()
            elif key in out and isinstance(out[key], (int, float)):
                factor = self._rng.choice([0.8, 1.2])
                out[key] = out[key] * factor
        return out


class _GP:
    """Minimal squared-exponential Gaussian process (numpy only) — the
    reference's PB2 leans on the external GPy package
    (`tune/schedulers/pb2_utils.py`); this is the self-contained core it
    actually needs: fit on normalized inputs, predict mean/std."""

    def __init__(self, lengthscale: float = 0.3, noise: float = 1e-3):
        self.ls = lengthscale
        self.noise = noise
        self._X = self._alpha = self._L = None
        self._y_mean = 0.0
        self._y_std = 1.0

    @staticmethod
    def _sq_dists(A, B):
        import numpy as np

        return ((A[:, None, :] - B[None, :, :]) ** 2).sum(-1)

    def _k(self, A, B):
        import numpy as np

        return np.exp(-0.5 * self._sq_dists(A, B) / self.ls ** 2)

    def fit(self, X, y):
        import numpy as np

        X = np.asarray(X, float)
        y = np.asarray(y, float)
        self._y_mean = float(y.mean())
        self._y_std = float(y.std()) or 1.0
        yn = (y - self._y_mean) / self._y_std
        K = self._k(X, X) + self.noise * np.eye(len(X))
        self._L = np.linalg.cholesky(K)
        self._alpha = np.linalg.solve(
            self._L.T, np.linalg.solve(self._L, yn))
        self._X = X
        return self

    def predict(self, Xs):
        import numpy as np

        Xs = np.asarray(Xs, float)
        Ks = self._k(Xs, self._X)
        mu = Ks @ self._alpha
        v = np.linalg.solve(self._L, Ks.T)
        var = np.clip(1.0 - (v ** 2).sum(0), 1e-12, None)
        return (mu * self._y_std + self._y_mean,
                np.sqrt(var) * self._y_std)


class PB2(PopulationBasedTraining):
    """Population Based Bandits (reference `tune/schedulers/pb2.py`):
    PBT where the explore step is a GP-UCB suggestion over continuous
    hyperparameter bounds instead of a random perturbation — markedly
    more sample-efficient with small populations.

    ``hyperparam_bounds`` maps each tuned key to ``(lower, upper)``.
    Each perturbation interval records (current hyperparams -> score
    improvement over the interval); exploit-triggered explores fit the
    GP on that data and pick the candidate maximizing mean + kappa * std
    within bounds.
    """

    def __init__(self, metric: str = "loss", mode: str = "min",
                 perturbation_interval: int = 4,
                 hyperparam_bounds: Optional[Dict[str, Any]] = None,
                 quantile_fraction: float = 0.25,
                 kappa: float = 1.5,
                 seed: Optional[int] = None):
        if not hyperparam_bounds:
            raise ValueError("PB2 requires hyperparam_bounds: "
                             "{key: (lower, upper), ...}")
        super().__init__(metric=metric, mode=mode,
                         perturbation_interval=perturbation_interval,
                         hyperparam_mutations={},
                         quantile_fraction=quantile_fraction, seed=seed)
        self.bounds = {k: (float(lo), float(hi))
                       for k, (lo, hi) in hyperparam_bounds.items()}
        self.kappa = kappa
        # (normalized hyperparam vector, score delta over one interval)
        self._data: List[Any] = []
        self._last_score: Dict[str, float] = {}

    def on_trial_result(self, trial: Trial, result: Dict[str, Any]) -> str:
        decision = super().on_trial_result(trial, result)
        if trial.num_results % self.interval == 0:
            value = result.get(self.metric)
            if value is not None:
                prev = self._last_score.get(trial.trial_id)
                if prev is not None:
                    delta = (value - prev if self.mode == "max"
                             else prev - value)
                    self._data.append(
                        (self._normalize(trial.config), float(delta)))
                self._last_score[trial.trial_id] = float(value)
        if decision == self.EXPLOIT:
            # The trial is about to adopt a top trial's checkpoint: the
            # next interval's score jump measures the weight copy, not
            # the new hyperparams — dropping the baseline keeps that
            # contaminated delta out of the GP's training data.
            self._last_score.pop(trial.trial_id, None)
        return decision

    def _normalize(self, config: Dict[str, Any]) -> List[float]:
        out = []
        for k, (lo, hi) in self.bounds.items():
            v = float(config.get(k, lo))
            out.append((v - lo) / (hi - lo) if hi > lo else 0.0)
        return out

    def perturb(self, config: Dict[str, Any]) -> Dict[str, Any]:
        import numpy as np

        out = dict(config)
        keys = list(self.bounds)
        if len(self._data) < 4:
            # Cold start: uniform exploration inside bounds.
            for k in keys:
                lo, hi = self.bounds[k]
                out[k] = self._rng.uniform(lo, hi)
            return out
        X = [x for x, _ in self._data[-64:]]
        y = [d for _, d in self._data[-64:]]
        try:
            gp = _GP().fit(X, y)
        except np.linalg.LinAlgError:
            for k in keys:
                lo, hi = self.bounds[k]
                out[k] = self._rng.uniform(lo, hi)
            return out
        rng = np.random.default_rng(self._rng.randrange(1 << 30))
        cands = rng.random((64, len(keys)))
        mu, sd = gp.predict(cands)
        best = cands[int(np.argmax(mu + self.kappa * sd))]
        for k, x in zip(keys, best):
            lo, hi = self.bounds[k]
            out[k] = lo + float(x) * (hi - lo)
        return out
