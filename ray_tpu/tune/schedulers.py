"""Trial schedulers: FIFO, ASHA, PBT.

Equivalent of the reference's `python/ray/tune/schedulers/`:
`async_hyperband.py` (ASHA — rung-quantile early stopping without
synchronized brackets) and `pbt.py` (exploit top quantile's checkpoint +
perturb config). Decisions are returned from `on_trial_result`; the
controller enforces them.
"""

from __future__ import annotations

import math
import random
from typing import Any, Dict, List, Optional

from ray_tpu.tune.trial import Trial


class TrialScheduler:
    CONTINUE = "CONTINUE"
    STOP = "STOP"

    def on_trial_result(self, trial: Trial, result: Dict[str, Any]) -> str:
        return self.CONTINUE

    def on_trial_complete(self, trial: Trial):
        pass

    def choose_trial_to_run(self, pending: List[Trial]) -> Optional[Trial]:
        return pending[0] if pending else None


class FIFOScheduler(TrialScheduler):
    pass


class ASHAScheduler(TrialScheduler):
    """Async successive halving (reference `async_hyperband.py`).

    Rungs at r, r*eta, r*eta^2, ... up to max_t; a trial reaching a rung is
    stopped unless it is in the top 1/eta of results recorded at that rung.
    """

    def __init__(self, metric: str = "loss", mode: str = "min",
                 time_attr: str = "training_iteration",
                 max_t: int = 100, grace_period: int = 1,
                 reduction_factor: float = 4):
        assert mode in ("min", "max")
        self.metric = metric
        self.mode = mode
        self.time_attr = time_attr
        self.max_t = max_t
        self.grace_period = grace_period
        self.eta = reduction_factor
        # rung value -> list of recorded metric values
        self.rungs: Dict[int, List[float]] = {}
        milestones = []
        t = grace_period
        while t < max_t:
            milestones.append(int(t))
            t *= self.eta
        self.milestones = milestones

    def on_trial_result(self, trial: Trial, result: Dict[str, Any]) -> str:
        t = result.get(self.time_attr, trial.num_results)
        value = result.get(self.metric)
        if value is None:
            return self.CONTINUE
        if t >= self.max_t:
            return self.STOP
        for rung in self.milestones:
            if t == rung or (t > rung and not self._recorded(trial, rung)):
                recorded = self.rungs.setdefault(rung, [])
                recorded.append(float(value))
                trial.last_result.setdefault("_asha_rungs", []).append(rung)
                if not self._in_top_fraction(float(value), recorded):
                    return self.STOP
        return self.CONTINUE

    def _recorded(self, trial: Trial, rung: int) -> bool:
        return rung in trial.last_result.get("_asha_rungs", [])

    def _in_top_fraction(self, value: float, recorded: List[float]) -> bool:
        if len(recorded) < self.eta:
            return True  # not enough data to cut
        ranked = sorted(recorded, reverse=(self.mode == "max"))
        k = max(1, int(len(ranked) / self.eta))
        cutoff = ranked[k - 1]
        return value >= cutoff if self.mode == "max" else value <= cutoff


class PopulationBasedTraining(TrialScheduler):
    """PBT (reference `pbt.py`): every `perturbation_interval` results, a
    bottom-quantile trial is stopped and respawned from a top-quantile
    trial's checkpoint with a perturbed config. The controller performs the
    respawn when it sees the EXPLOIT decision."""

    EXPLOIT = "EXPLOIT"

    def __init__(self, metric: str = "loss", mode: str = "min",
                 perturbation_interval: int = 4,
                 hyperparam_mutations: Optional[Dict[str, Any]] = None,
                 quantile_fraction: float = 0.25,
                 seed: Optional[int] = None):
        self.metric = metric
        self.mode = mode
        self.interval = perturbation_interval
        self.mutations = hyperparam_mutations or {}
        self.quantile = quantile_fraction
        self._rng = random.Random(seed)
        self._trials: Dict[str, Trial] = {}

    def on_trial_result(self, trial: Trial, result: Dict[str, Any]) -> str:
        self._trials[trial.trial_id] = trial
        if trial.num_results % self.interval != 0:
            return self.CONTINUE
        value = result.get(self.metric)
        if value is None:
            return self.CONTINUE
        scored = [(t.last_result.get(self.metric), t)
                  for t in self._trials.values()
                  if t.last_result.get(self.metric) is not None]
        if len(scored) < 2:
            return self.CONTINUE
        scored.sort(key=lambda x: x[0], reverse=(self.mode == "max"))
        k = max(1, int(len(scored) * self.quantile))
        bottom_ids = {t.trial_id for _, t in scored[-k:]}
        if trial.trial_id in bottom_ids:
            return self.EXPLOIT
        return self.CONTINUE

    def exploit_target(self, trial: Trial) -> Optional[Trial]:
        scored = [(t.last_result.get(self.metric), t)
                  for t in self._trials.values()
                  if t.last_result.get(self.metric) is not None
                  and t.trial_id != trial.trial_id]
        if not scored:
            return None
        scored.sort(key=lambda x: x[0], reverse=(self.mode == "max"))
        k = max(1, int(len(scored) * self.quantile))
        return self._rng.choice([t for _, t in scored[:k]])

    def perturb(self, config: Dict[str, Any]) -> Dict[str, Any]:
        from ray_tpu.tune.search import Domain

        out = dict(config)
        for key, mutation in self.mutations.items():
            if isinstance(mutation, list):
                out[key] = self._rng.choice(mutation)
            elif isinstance(mutation, Domain):
                out[key] = mutation.sample(self._rng)
            elif callable(mutation):
                out[key] = mutation()
            elif key in out and isinstance(out[key], (int, float)):
                factor = self._rng.choice([0.8, 1.2])
                out[key] = out[key] * factor
        return out
