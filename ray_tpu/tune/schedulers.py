"""Trial schedulers: FIFO, ASHA, PBT.

Equivalent of the reference's `python/ray/tune/schedulers/`:
`async_hyperband.py` (ASHA — rung-quantile early stopping without
synchronized brackets) and `pbt.py` (exploit top quantile's checkpoint +
perturb config). Decisions are returned from `on_trial_result`; the
controller enforces them.
"""

from __future__ import annotations

import math
import random
from typing import Any, Dict, List, Optional

from ray_tpu.tune.trial import Trial


class TrialScheduler:
    CONTINUE = "CONTINUE"
    STOP = "STOP"

    def on_trial_result(self, trial: Trial, result: Dict[str, Any]) -> str:
        return self.CONTINUE

    def on_trial_complete(self, trial: Trial):
        pass

    def choose_trial_to_run(self, pending: List[Trial]) -> Optional[Trial]:
        return pending[0] if pending else None


class FIFOScheduler(TrialScheduler):
    pass


class ASHAScheduler(TrialScheduler):
    """Async successive halving (reference `async_hyperband.py`).

    Rungs at r, r*eta, r*eta^2, ... up to max_t; a trial reaching a rung is
    stopped unless it is in the top 1/eta of results recorded at that rung.
    """

    def __init__(self, metric: str = "loss", mode: str = "min",
                 time_attr: str = "training_iteration",
                 max_t: int = 100, grace_period: int = 1,
                 reduction_factor: float = 4):
        assert mode in ("min", "max")
        self.metric = metric
        self.mode = mode
        self.time_attr = time_attr
        self.max_t = max_t
        self.grace_period = grace_period
        self.eta = reduction_factor
        # rung value -> list of recorded metric values
        self.rungs: Dict[int, List[float]] = {}
        milestones = []
        t = grace_period
        while t < max_t:
            milestones.append(int(t))
            t *= self.eta
        self.milestones = milestones

    def on_trial_result(self, trial: Trial, result: Dict[str, Any]) -> str:
        t = result.get(self.time_attr, trial.num_results)
        value = result.get(self.metric)
        if value is None:
            return self.CONTINUE
        if t >= self.max_t:
            return self.STOP
        for rung in self.milestones:
            if t == rung or (t > rung and not self._recorded(trial, rung)):
                recorded = self.rungs.setdefault(rung, [])
                recorded.append(float(value))
                trial.last_result.setdefault("_asha_rungs", []).append(rung)
                if not self._in_top_fraction(float(value), recorded):
                    return self.STOP
        return self.CONTINUE

    def _recorded(self, trial: Trial, rung: int) -> bool:
        return rung in trial.last_result.get("_asha_rungs", [])

    def _in_top_fraction(self, value: float, recorded: List[float]) -> bool:
        if len(recorded) < self.eta:
            return True  # not enough data to cut
        ranked = sorted(recorded, reverse=(self.mode == "max"))
        k = max(1, int(len(ranked) / self.eta))
        cutoff = ranked[k - 1]
        return value >= cutoff if self.mode == "max" else value <= cutoff


class MedianStoppingRule(TrialScheduler):
    """Stop a trial whose best result so far is worse than the median of
    the other trials' running averages at the same point (reference
    `schedulers/median_stopping_rule.py`)."""

    def __init__(self, metric: str = "loss", mode: str = "min",
                 time_attr: str = "training_iteration",
                 grace_period: int = 1, min_samples_required: int = 3):
        assert mode in ("min", "max")
        self.metric = metric
        self.mode = mode
        self.time_attr = time_attr
        self.grace_period = grace_period
        self.min_samples = min_samples_required
        # trial_id -> list of metric values (one per result)
        self._histories: Dict[str, List[float]] = {}

    def on_trial_result(self, trial: Trial, result: Dict[str, Any]) -> str:
        value = result.get(self.metric)
        if value is None:
            return self.CONTINUE
        hist = self._histories.setdefault(trial.trial_id, [])
        hist.append(float(value))
        t = result.get(self.time_attr, trial.num_results)
        if t < self.grace_period:
            return self.CONTINUE
        # Running average of every OTHER trial up to this step count.
        others = []
        for tid, h in self._histories.items():
            if tid == trial.trial_id or not h:
                continue
            others.append(sum(h[:len(hist)]) / min(len(h), len(hist)))
        if len(others) < self.min_samples:
            return self.CONTINUE
        median = sorted(others)[len(others) // 2]
        best = max(hist) if self.mode == "max" else min(hist)
        worse = best < median if self.mode == "max" else best > median
        return self.STOP if worse else self.CONTINUE


class HyperBandScheduler(TrialScheduler):
    """Synchronized HyperBand (reference `schedulers/hyperband.py`):
    brackets of successive halving with different (n, r) trade-offs; each
    bracket halves its cohort at its milestones, keeping the top 1/eta.

    Trials are assigned to brackets round-robin at first result; within a
    bracket, halving is enforced asynchronously at each milestone (a trial
    past a milestone stops unless in the bracket's top 1/eta there) — the
    asynchronous-cutoff variant of the synchronized algorithm, which never
    idles a chip waiting for bracket stragglers.
    """

    def __init__(self, metric: str = "loss", mode: str = "min",
                 time_attr: str = "training_iteration",
                 max_t: int = 81, reduction_factor: float = 3):
        assert mode in ("min", "max")
        self.metric = metric
        self.mode = mode
        self.time_attr = time_attr
        self.max_t = max_t
        self.eta = reduction_factor
        s_max = int(math.log(max_t) / math.log(reduction_factor))
        # Bracket s starts at r0 = max_t * eta^-s with milestones up to max_t.
        self._brackets: List[Dict[str, Any]] = []
        for s in range(s_max, -1, -1):
            r0 = max(1, int(max_t * self.eta ** (-s)))
            milestones = []
            t = r0
            while t < max_t:
                milestones.append(int(t))
                t *= self.eta
            self._brackets.append({"milestones": milestones, "rungs": {}})
        self._assignment: Dict[str, int] = {}
        self._next_bracket = 0

    def _bracket_for(self, trial: Trial) -> Dict[str, Any]:
        b = self._assignment.get(trial.trial_id)
        if b is None:
            b = self._next_bracket
            self._assignment[trial.trial_id] = b
            self._next_bracket = (self._next_bracket + 1) % len(self._brackets)
        return self._brackets[b]

    def on_trial_result(self, trial: Trial, result: Dict[str, Any]) -> str:
        value = result.get(self.metric)
        if value is None:
            return self.CONTINUE
        t = result.get(self.time_attr, trial.num_results)
        if t >= self.max_t:
            return self.STOP
        bracket = self._bracket_for(trial)
        seen = trial.last_result.setdefault("_hb_rungs", [])
        # Record only at the HIGHEST newly-crossed milestone: appending one
        # late value to every skipped rung would compare it against peers'
        # genuinely-early values and systematically favor coarse reporters.
        crossed = [r for r in bracket["milestones"]
                   if t >= r and r not in seen]
        if crossed:
            rung = crossed[-1]
            recorded = bracket["rungs"].setdefault(rung, [])
            recorded.append(float(value))
            seen.extend(crossed)  # skipped rungs count as passed, unscored
            if len(recorded) >= self.eta and \
                    not self._in_top_fraction(float(value), recorded):
                return self.STOP
        return self.CONTINUE

    def _in_top_fraction(self, value: float, recorded: List[float]) -> bool:
        ranked = sorted(recorded, reverse=(self.mode == "max"))
        k = max(1, int(len(ranked) / self.eta))
        cutoff = ranked[k - 1]
        return value >= cutoff if self.mode == "max" else value <= cutoff


class PopulationBasedTraining(TrialScheduler):
    """PBT (reference `pbt.py`): every `perturbation_interval` results, a
    bottom-quantile trial is stopped and respawned from a top-quantile
    trial's checkpoint with a perturbed config. The controller performs the
    respawn when it sees the EXPLOIT decision."""

    EXPLOIT = "EXPLOIT"

    def __init__(self, metric: str = "loss", mode: str = "min",
                 perturbation_interval: int = 4,
                 hyperparam_mutations: Optional[Dict[str, Any]] = None,
                 quantile_fraction: float = 0.25,
                 seed: Optional[int] = None):
        self.metric = metric
        self.mode = mode
        self.interval = perturbation_interval
        self.mutations = hyperparam_mutations or {}
        self.quantile = quantile_fraction
        self._rng = random.Random(seed)
        self._trials: Dict[str, Trial] = {}

    def on_trial_result(self, trial: Trial, result: Dict[str, Any]) -> str:
        self._trials[trial.trial_id] = trial
        if trial.num_results % self.interval != 0:
            return self.CONTINUE
        value = result.get(self.metric)
        if value is None:
            return self.CONTINUE
        scored = [(t.last_result.get(self.metric), t)
                  for t in self._trials.values()
                  if t.last_result.get(self.metric) is not None]
        if len(scored) < 2:
            return self.CONTINUE
        scored.sort(key=lambda x: x[0], reverse=(self.mode == "max"))
        k = max(1, int(len(scored) * self.quantile))
        bottom_ids = {t.trial_id for _, t in scored[-k:]}
        if trial.trial_id in bottom_ids:
            return self.EXPLOIT
        return self.CONTINUE

    def exploit_target(self, trial: Trial) -> Optional[Trial]:
        scored = [(t.last_result.get(self.metric), t)
                  for t in self._trials.values()
                  if t.last_result.get(self.metric) is not None
                  and t.trial_id != trial.trial_id]
        if not scored:
            return None
        scored.sort(key=lambda x: x[0], reverse=(self.mode == "max"))
        k = max(1, int(len(scored) * self.quantile))
        return self._rng.choice([t for _, t in scored[:k]])

    def perturb(self, config: Dict[str, Any]) -> Dict[str, Any]:
        from ray_tpu.tune.search import Domain

        out = dict(config)
        for key, mutation in self.mutations.items():
            if isinstance(mutation, list):
                out[key] = self._rng.choice(mutation)
            elif isinstance(mutation, Domain):
                out[key] = mutation.sample(self._rng)
            elif callable(mutation):
                out[key] = mutation()
            elif key in out and isinstance(out[key], (int, float)):
                factor = self._rng.choice([0.8, 1.2])
                out[key] = out[key] * factor
        return out
