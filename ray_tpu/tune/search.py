"""Search spaces + trial variant generation.

Equivalent of the reference's `python/ray/tune/search/sample.py` domains and
`BasicVariantGenerator` (`tune/search/basic_variant.py`): grid_search entries
are expanded into the cross product; sampling domains draw per trial.
"""

from __future__ import annotations

import itertools
import random
from dataclasses import dataclass
from typing import Any, Callable, Dict, List, Optional


class Domain:
    def sample(self, rng: random.Random) -> Any:
        raise NotImplementedError


@dataclass
class Categorical(Domain):
    categories: List[Any]

    def sample(self, rng):
        return rng.choice(self.categories)


@dataclass
class Uniform(Domain):
    lower: float
    upper: float

    def sample(self, rng):
        return rng.uniform(self.lower, self.upper)


@dataclass
class LogUniform(Domain):
    lower: float
    upper: float

    def sample(self, rng):
        import math

        return math.exp(rng.uniform(math.log(self.lower), math.log(self.upper)))


@dataclass
class Randint(Domain):
    lower: int
    upper: int

    def sample(self, rng):
        return rng.randrange(self.lower, self.upper)


@dataclass
class QUniform(Domain):
    lower: float
    upper: float
    q: float

    def sample(self, rng):
        v = rng.uniform(self.lower, self.upper)
        return round(v / self.q) * self.q


@dataclass
class FunctionDomain(Domain):
    fn: Callable[[], Any]

    def sample(self, rng):
        return self.fn()


class GridSearch:
    def __init__(self, values: List[Any]):
        self.values = list(values)


# Public constructors (reference `tune.grid_search`, `tune.choice`, ...)

def grid_search(values: List[Any]) -> GridSearch:
    return GridSearch(values)


def choice(categories: List[Any]) -> Categorical:
    return Categorical(list(categories))


def uniform(lower: float, upper: float) -> Uniform:
    return Uniform(lower, upper)


def loguniform(lower: float, upper: float) -> LogUniform:
    return LogUniform(lower, upper)


def randint(lower: int, upper: int) -> Randint:
    return Randint(lower, upper)


def quniform(lower: float, upper: float, q: float) -> QUniform:
    return QUniform(lower, upper, q)


def sample_from(fn: Callable[[], Any]) -> FunctionDomain:
    return FunctionDomain(fn)


class Searcher:
    """Adaptive search algorithm: suggests configs one at a time as trial
    results arrive (reference `tune/search/searcher.py` Searcher)."""

    def suggest(self) -> Optional[Dict[str, Any]]:
        raise NotImplementedError

    def on_trial_complete(self, config: Dict[str, Any],
                          score: Optional[float]) -> None:
        pass


class TPESearcher(Searcher):
    """Tree-structured Parzen Estimator over independent dimensions
    (reference's HyperOpt/Optuna integration niche, self-contained: the
    external libraries aren't available here).

    After `n_initial` random trials, observations split into good (top
    `gamma` fraction) and bad; numeric dimensions sample candidates from a
    kernel density over the good values and keep the candidate maximizing
    the good/bad density ratio; categorical dimensions sample
    proportionally to smoothed good-counts.
    """

    def __init__(self, param_space: Dict[str, Any], metric: str,
                 mode: str = "min", n_initial: int = 8, gamma: float = 0.25,
                 n_candidates: int = 24, exploration: float = 0.1,
                 seed: Optional[int] = None):
        for k, v in param_space.items():
            if isinstance(v, GridSearch):
                raise ValueError(
                    f"TPESearcher does not accept grid_search ({k!r}); "
                    "use BasicVariantGenerator for grids")
        self.param_space = param_space
        self.metric = metric
        self.mode = mode
        self.n_initial = n_initial
        self.gamma = gamma
        self.n_candidates = n_candidates
        # Fraction of suggestions drawn uniformly even after the model
        # kicks in: pure exploitation of a sparse KDE can fixate on a
        # boundary and starve the model of fresh observations.
        self.exploration = exploration
        self._rng = random.Random(seed)
        self._history: List[Any] = []  # (config, score) with score not None

    # ------------------------------------------------------------ feedback

    def on_trial_complete(self, config, score):
        if score is None:
            return
        self._history.append((config, float(score)))

    # ----------------------------------------------------------- suggestion

    def _model_history(self) -> List[Any]:
        """Observations the KDE models (subclasses pick a fidelity)."""
        return self._history

    def suggest(self) -> Dict[str, Any]:
        hist = self._model_history()
        if len(hist) < self.n_initial \
                or self._rng.random() < self.exploration:
            return {k: (v.sample(self._rng) if isinstance(v, Domain) else v)
                    for k, v in self.param_space.items()}
        ordered = sorted(hist, key=lambda cs: cs[1],
                         reverse=(self.mode == "max"))
        # At least two good points once possible: a single-point "good"
        # KDE gets bandwidth = the whole span and models nothing.
        n_good = max(2 if len(ordered) >= 4 else 1,
                     int(len(ordered) * self.gamma))
        good = [c for c, _ in ordered[:n_good]]
        bad = [c for c, _ in ordered[n_good:]] or good
        out: Dict[str, Any] = {}
        for k, dom in self.param_space.items():
            if not isinstance(dom, Domain):
                out[k] = dom
            elif isinstance(dom, Categorical):
                out[k] = self._suggest_categorical(k, dom, good)
            elif isinstance(dom, FunctionDomain):
                out[k] = dom.sample(self._rng)  # opaque: no model possible
            else:
                out[k] = self._suggest_numeric(k, dom, good, bad)
        return out

    def _suggest_categorical(self, key, dom: Categorical, good):
        counts = {c: 1.0 for c in dom.categories}  # +1 smoothing prior
        for cfg in good:
            if cfg.get(key) in counts:
                counts[cfg[key]] += 1.0
        total = sum(counts.values())
        r = self._rng.uniform(0, total)
        acc = 0.0
        for c, w in counts.items():
            acc += w
            if r <= acc:
                return c
        return dom.categories[-1]

    def _suggest_numeric(self, key, dom: Domain, good, bad):
        import math

        log_scale = isinstance(dom, LogUniform)

        def to_x(v):
            return math.log(v) if log_scale else float(v)

        lo, hi = to_x(dom.lower), to_x(dom.upper)
        span = hi - lo
        gx = [to_x(c[key]) for c in good if key in c]
        bx = [to_x(c[key]) for c in bad if key in c]
        if not gx:
            return dom.sample(self._rng)
        bw = max(span / max(len(gx), 1) ** 0.5, 1e-12)

        def density(x, pts):
            if not pts:
                return 1.0 / span
            return sum(math.exp(-0.5 * ((x - p) / bw) ** 2)
                       for p in pts) / (len(pts) * bw)

        best_x, best_ratio = None, -1.0
        for _ in range(self.n_candidates):
            center = self._rng.choice(gx)
            x = min(max(self._rng.gauss(center, bw), lo), hi)
            ratio = density(x, gx) / (density(x, bx) + 1e-12)
            if ratio > best_ratio:
                best_x, best_ratio = x, ratio
        v = math.exp(best_x) if log_scale else best_x
        if isinstance(dom, Randint):
            return min(max(int(round(v)), dom.lower), dom.upper - 1)
        if isinstance(dom, QUniform):
            return round(v / dom.q) * dom.q
        return v


class BOHBSearcher(TPESearcher):
    """BOHB's Bayesian half, self-contained (reference wraps the external
    `hpbandster` package via `tune/search/bohb/bohb_search.py`; that
    library isn't available here).

    Observations are kept per fidelity (the result's
    ``training_iteration``, fed through ``on_result``); the KDE models
    the LARGEST budget that has at least ``n_initial`` observations —
    BOHB's rule — so early low-fidelity scores guide sampling until
    enough full-budget results exist, then the model sharpens. Pair with
    ``HyperBandScheduler`` for the bracketed early stopping half
    (reference pairs TuneBOHB with HyperBandForBOHB).
    """

    def __init__(self, param_space: Dict[str, Any], metric: str,
                 mode: str = "min", n_initial: int = 6, gamma: float = 0.25,
                 n_candidates: int = 24, seed: Optional[int] = None):
        super().__init__(param_space, metric, mode, n_initial=n_initial,
                         gamma=gamma, n_candidates=n_candidates, seed=seed)
        # budget -> {config key -> (config, score)}: one entry per
        # distinct config per budget, latest score wins — replayed
        # iterations (checkpoint restores, exploit restarts) must not
        # double-weight a config in the KDE or inflate a budget past
        # n_initial with duplicates.
        self._by_budget: Dict[int, Dict[str, Any]] = {}

    def on_result(self, config: Dict[str, Any], result: Dict[str, Any]):
        score = result.get(self.metric)
        if score is None:
            return
        budget = int(result.get("training_iteration", 1))
        key = repr(sorted(config.items(), key=lambda kv: kv[0]))
        self._by_budget.setdefault(budget, {})[key] = (
            dict(config), float(score))

    def _model_history(self) -> List[Any]:
        for budget in sorted(self._by_budget, reverse=True):
            obs = list(self._by_budget[budget].values())
            if len(obs) >= self.n_initial:
                return obs
        return self._history


class BasicVariantGenerator:
    """Expands grid_search cross products x num_samples; samples domains."""

    def __init__(self, param_space: Dict[str, Any], num_samples: int = 1,
                 seed: Optional[int] = None):
        self.param_space = param_space
        self.num_samples = num_samples
        self._rng = random.Random(seed)

    def generate(self) -> List[Dict[str, Any]]:
        grid_keys = [k for k, v in self.param_space.items()
                     if isinstance(v, GridSearch)]
        grid_values = [self.param_space[k].values for k in grid_keys]
        combos = list(itertools.product(*grid_values)) if grid_keys else [()]
        configs: List[Dict[str, Any]] = []
        for _ in range(self.num_samples):
            for combo in combos:
                cfg: Dict[str, Any] = {}
                for k, v in self.param_space.items():
                    if isinstance(v, GridSearch):
                        cfg[k] = combo[grid_keys.index(k)]
                    elif isinstance(v, Domain):
                        cfg[k] = v.sample(self._rng)
                    else:
                        cfg[k] = v
                configs.append(cfg)
        return configs
