"""Search spaces + trial variant generation.

Equivalent of the reference's `python/ray/tune/search/sample.py` domains and
`BasicVariantGenerator` (`tune/search/basic_variant.py`): grid_search entries
are expanded into the cross product; sampling domains draw per trial.
"""

from __future__ import annotations

import itertools
import random
from dataclasses import dataclass
from typing import Any, Callable, Dict, List, Optional


class Domain:
    def sample(self, rng: random.Random) -> Any:
        raise NotImplementedError


@dataclass
class Categorical(Domain):
    categories: List[Any]

    def sample(self, rng):
        return rng.choice(self.categories)


@dataclass
class Uniform(Domain):
    lower: float
    upper: float

    def sample(self, rng):
        return rng.uniform(self.lower, self.upper)


@dataclass
class LogUniform(Domain):
    lower: float
    upper: float

    def sample(self, rng):
        import math

        return math.exp(rng.uniform(math.log(self.lower), math.log(self.upper)))


@dataclass
class Randint(Domain):
    lower: int
    upper: int

    def sample(self, rng):
        return rng.randrange(self.lower, self.upper)


@dataclass
class QUniform(Domain):
    lower: float
    upper: float
    q: float

    def sample(self, rng):
        v = rng.uniform(self.lower, self.upper)
        return round(v / self.q) * self.q


@dataclass
class FunctionDomain(Domain):
    fn: Callable[[], Any]

    def sample(self, rng):
        return self.fn()


class GridSearch:
    def __init__(self, values: List[Any]):
        self.values = list(values)


# Public constructors (reference `tune.grid_search`, `tune.choice`, ...)

def grid_search(values: List[Any]) -> GridSearch:
    return GridSearch(values)


def choice(categories: List[Any]) -> Categorical:
    return Categorical(list(categories))


def uniform(lower: float, upper: float) -> Uniform:
    return Uniform(lower, upper)


def loguniform(lower: float, upper: float) -> LogUniform:
    return LogUniform(lower, upper)


def randint(lower: int, upper: int) -> Randint:
    return Randint(lower, upper)


def quniform(lower: float, upper: float, q: float) -> QUniform:
    return QUniform(lower, upper, q)


def sample_from(fn: Callable[[], Any]) -> FunctionDomain:
    return FunctionDomain(fn)


class BasicVariantGenerator:
    """Expands grid_search cross products x num_samples; samples domains."""

    def __init__(self, param_space: Dict[str, Any], num_samples: int = 1,
                 seed: Optional[int] = None):
        self.param_space = param_space
        self.num_samples = num_samples
        self._rng = random.Random(seed)

    def generate(self) -> List[Dict[str, Any]]:
        grid_keys = [k for k, v in self.param_space.items()
                     if isinstance(v, GridSearch)]
        grid_values = [self.param_space[k].values for k in grid_keys]
        combos = list(itertools.product(*grid_values)) if grid_keys else [()]
        configs: List[Dict[str, Any]] = []
        for _ in range(self.num_samples):
            for combo in combos:
                cfg: Dict[str, Any] = {}
                for k, v in self.param_space.items():
                    if isinstance(v, GridSearch):
                        cfg[k] = combo[grid_keys.index(k)]
                    elif isinstance(v, Domain):
                        cfg[k] = v.sample(self._rng)
                    else:
                        cfg[k] = v
                configs.append(cfg)
        return configs
