"""Trial bookkeeping (reference `python/ray/tune/experiment/trial.py`)."""

from __future__ import annotations

import time
import uuid
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional


class TrialStatus:
    PENDING = "PENDING"
    RUNNING = "RUNNING"
    PAUSED = "PAUSED"
    TERMINATED = "TERMINATED"
    ERROR = "ERROR"


@dataclass
class Trial:
    config: Dict[str, Any]
    trial_id: str = field(default_factory=lambda: uuid.uuid4().hex[:8])
    status: str = TrialStatus.PENDING
    last_result: Dict[str, Any] = field(default_factory=dict)
    metrics_history: List[Dict[str, Any]] = field(default_factory=list)
    error: Optional[str] = None
    checkpoint_path: Optional[str] = None
    num_results: int = 0
    num_failures: int = 0   # actor-death restarts consumed
    start_time: float = 0.0
    runtime_s: float = 0.0

    @property
    def is_finished(self) -> bool:
        return self.status in (TrialStatus.TERMINATED, TrialStatus.ERROR)

    def state(self) -> Dict[str, Any]:
        return {
            "trial_id": self.trial_id,
            "config": self.config,
            "status": self.status,
            "last_result": self.last_result,
            "metrics_history": self.metrics_history,
            "error": self.error,
            "checkpoint_path": self.checkpoint_path,
            "num_results": self.num_results,
            "num_failures": self.num_failures,
        }

    @staticmethod
    def from_state(state: Dict[str, Any]) -> "Trial":
        t = Trial(config=state["config"], trial_id=state["trial_id"])
        t.status = state["status"]
        t.last_result = state.get("last_result", {})
        t.metrics_history = state.get("metrics_history", [])
        t.error = state.get("error")
        t.checkpoint_path = state.get("checkpoint_path")
        t.num_results = state.get("num_results", 0)
        t.num_failures = state.get("num_failures", 0)
        return t
