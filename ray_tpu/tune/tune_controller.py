"""TuneController: trials as actors, scheduler decisions, experiment state.

Equivalent of the reference's `TrialRunner`/`TuneController`
(`python/ray/tune/execution/trial_runner.py:1189`, `tune_controller.py`) and
`RayTrialExecutor` (`ray_trial_executor.py:188`), collapsed: trials run in
dedicated actors (same report-queue protocol as Train's workers), the
controller multiplexes `next_result` futures with `ray_tpu.wait`, applies
scheduler decisions (ASHA stop, PBT exploit), persists experiment state
after every event, and restores mid-experiment.
"""

from __future__ import annotations

import logging
import os
import pickle
import threading
import time
from typing import Any, Callable, Dict, List, Optional

import ray_tpu
from ray_tpu.train.checkpoint import Checkpoint
from ray_tpu.tune.schedulers import (
    PopulationBasedTraining,
    TrialScheduler,
)
from ray_tpu.tune.trial import Trial, TrialStatus

logger = logging.getLogger(__name__)


class _TrialActor:
    """Runs one trial's function in a thread; results stream via a queue
    (the TrainWorker protocol, `ray_tpu/train/worker_group.py`)."""

    def __init__(self):
        self._session = None
        self._thread = None

    def run(self, fn: Callable, config: Dict[str, Any],
            checkpoint_path: Optional[str], trial_id: str):
        from ray_tpu._jax_env import apply_jax_platform_env

        apply_jax_platform_env()
        from ray_tpu.train.session import TrainContext, _TrainSession, init_session

        checkpoint = Checkpoint.from_directory(checkpoint_path) \
            if checkpoint_path else None
        context = TrainContext(world_rank=0, world_size=1, trial_name=trial_id)
        session = _TrainSession(context, checkpoint=checkpoint)
        self._session = session
        init_session(session)

        def target():
            try:
                import inspect

                if len(inspect.signature(fn).parameters) > 0:
                    session.final_return = fn(config)
                else:
                    session.final_return = fn()
            except BaseException as e:  # noqa: BLE001
                session.error = e
            finally:
                session.finished.set()

        self._thread = threading.Thread(target=target, daemon=True)
        self._thread.start()
        return True

    def next_result(self, timeout: float = 600.0):
        import queue as _q

        session = self._session
        deadline = time.monotonic() + timeout
        while True:
            try:
                item = session.result_queue.get(timeout=0.1)
                return {"done": False, **item}
            except _q.Empty:
                if session.finished.is_set() and session.result_queue.empty():
                    if session.error is not None:
                        from ray_tpu.core import serialization

                        return {"done": True,
                                "error": serialization.serialize_exception(
                                    session.error, "trainable")}
                    return {"done": True, "final": session.final_return}
                if time.monotonic() > deadline:
                    return {"done": False, "timeout": True}


class TuneController:
    def __init__(self, trainable: Callable, trials: List[Trial],
                 scheduler: Optional[TrialScheduler] = None,
                 max_concurrent: int = 0,
                 experiment_dir: str = ".",
                 stop: Optional[Dict[str, Any]] = None,
                 metric: Optional[str] = None,
                 mode: str = "min",
                 resources_per_trial: Optional[Dict[str, float]] = None,
                 searcher: Optional[Any] = None,
                 num_samples: Optional[int] = None,
                 max_failures: int = 0,
                 sync_uri: Optional[str] = None,
                 sync_period_s: float = 5.0):
        self.trainable = trainable
        self.trials = trials
        self.scheduler = scheduler or TrialScheduler()
        self.stop = stop or {}
        self.metric = metric
        self.mode = mode
        # Adaptive search: the searcher proposes new trials as capacity
        # frees, informed by completed results, up to num_samples total.
        self.searcher = searcher
        self.num_samples = num_samples or len(trials)
        self._created = len(trials)
        # Trial fault tolerance: a trial whose ACTOR dies (node failure,
        # OOM kill) restarts from its last checkpoint up to max_failures
        # times (reference FailureConfig.max_failures).
        self.max_failures = max_failures
        self.experiment_dir = experiment_dir
        self.resources_per_trial = resources_per_trial or {}
        if max_concurrent <= 0:
            try:
                max_concurrent = max(
                    1, int(ray_tpu.cluster_resources().get("CPU", 2)))
            except Exception:
                max_concurrent = 2
        self.max_concurrent = max_concurrent
        os.makedirs(experiment_dir, exist_ok=True)
        # Cloud experiment sync (reference tune/syncer.py): the local
        # experiment dir mirrors to a bucket URI, throttled, plus a final
        # sync when the run ends — on TPU pods the local dir dies with
        # the VM, the bucket copy is what Tuner.restore() reads.
        self.sync_uri = sync_uri
        self.sync_period_s = sync_period_s
        self._last_sync = 0.0
        self._actors: Dict[str, Any] = {}          # trial_id -> actor handle
        self._inflight: Dict[Any, Trial] = {}      # next_result ref -> trial

    # ------------------------------------------------------------- main loop

    def _more_to_create(self) -> bool:
        return self.searcher is not None and self._created < self.num_samples

    def run(self) -> List[Trial]:
        while not all(t.is_finished for t in self.trials) \
                or self._more_to_create():
            self._start_pending()
            if not self._inflight:
                # PENDING covers a just-restarted trial whose relaunch the
                # next pass will attempt — breaking here would strand it.
                if any(t.status in (TrialStatus.RUNNING, TrialStatus.PENDING)
                       for t in self.trials):
                    time.sleep(0.05)
                    continue
                break
            refs = list(self._inflight.keys())
            ready, _ = ray_tpu.wait(refs, num_returns=1, timeout=5.0)
            for ref in ready:
                trial = self._inflight.pop(ref)
                try:
                    res = ray_tpu.get(ref)
                except Exception as e:  # actor died
                    self._maybe_restart(trial, f"trial actor died: {e}")
                    continue
                self._handle_result(trial, res)
            self.save()
        self.save(final=True)
        return self.trials

    def _start_pending(self):
        running = sum(1 for t in self.trials if t.status == TrialStatus.RUNNING)
        pending = [t for t in self.trials if t.status == TrialStatus.PENDING]
        while running < self.max_concurrent:
            if not pending and self._more_to_create():
                config = self.searcher.suggest()
                if config is None:
                    break
                trial = Trial(config=config)
                self.trials.append(trial)
                pending.append(trial)
                self._created += 1
            if not pending:
                break
            trial = self.scheduler.choose_trial_to_run(pending)
            if trial is None:
                break
            pending.remove(trial)
            self._launch(trial)
            if trial.status == TrialStatus.RUNNING:
                running += 1  # failed launches don't consume concurrency

    def _launch(self, trial: Trial):
        opts: Dict[str, Any] = {}
        if self.resources_per_trial:
            res = dict(self.resources_per_trial)
            if "CPU" in res:
                opts["num_cpus"] = res.pop("CPU")
            if "TPU" in res:
                opts["num_tpus"] = res.pop("TPU")
            if res:
                opts["resources"] = res
        actor_cls = ray_tpu.remote(_TrialActor)
        try:
            actor = actor_cls.options(**opts).remote() if opts \
                else actor_cls.remote()
            self._actors[trial.trial_id] = actor
            trial.start_time = time.time()
            ray_tpu.get(actor.run.remote(self.trainable, trial.config,
                                         trial.checkpoint_path,
                                         trial.trial_id))
            ref = actor.next_result.remote()
        except Exception as e:  # noqa: BLE001 — a fast-dying trainable can
            # take the actor down before run() even acknowledges (or between
            # the ack and the first next_result submission); same restart
            # budget as a mid-trial death.
            self._maybe_restart(trial, f"trial failed during launch: {e}")
            return
        trial.status = TrialStatus.RUNNING
        self._inflight[ref] = trial

    def _maybe_restart(self, trial: Trial, msg: str):
        if trial.num_failures < self.max_failures:
            trial.num_failures += 1
            logger.warning(
                "trial %s died (%s); restarting from %s (failure %d/%d)",
                trial.trial_id, msg, trial.checkpoint_path,
                trial.num_failures, self.max_failures)
            self._cleanup_actor(trial, kill=True)
            trial.status = TrialStatus.PENDING
        else:
            self._fail_trial(trial, msg)

    def _handle_result(self, trial: Trial, res: Dict[str, Any]):
        actor = self._actors.get(trial.trial_id)
        if res.get("done"):
            if res.get("error") is not None:
                from ray_tpu.core import serialization

                err = serialization.deserialize_exception(res["error"])
                self._fail_trial(trial, repr(err))
            else:
                final = res.get("final")
                if isinstance(final, dict):
                    trial.last_result.update(final)
                    trial.metrics_history.append(dict(final))
                trial.status = TrialStatus.TERMINATED
                trial.runtime_s = time.time() - trial.start_time
                self.scheduler.on_trial_complete(trial)
                self._notify_searcher(trial)
            self._cleanup_actor(trial)
            return
        if res.get("timeout"):
            self._inflight[actor.next_result.remote()] = trial
            return
        # A reported (metrics, checkpoint) pair.
        metrics = dict(res.get("metrics") or {})
        trial.num_results += 1
        metrics.setdefault("training_iteration", trial.num_results)
        ckpt = res.get("checkpoint")
        if ckpt is not None:
            path = os.path.join(self.experiment_dir, trial.trial_id,
                                f"checkpoint_{trial.num_results:06d}")
            ckpt.to_directory(path)
            trial.checkpoint_path = path
        trial.last_result.update(metrics)
        trial.metrics_history.append(metrics)
        if self.searcher is not None and hasattr(self.searcher, "on_result"):
            # Fidelity-aware searchers (BOHB) model intermediate results
            # at their budget (training_iteration), not just final scores.
            try:
                self.searcher.on_result(trial.config, metrics)
            except Exception:
                logger.exception("searcher on_result failed")
        decision = self.scheduler.on_trial_result(trial, metrics)
        if self._stop_condition_met(metrics):
            decision = TrialScheduler.STOP
        if decision == TrialScheduler.STOP:
            trial.status = TrialStatus.TERMINATED
            trial.runtime_s = time.time() - trial.start_time
            self.scheduler.on_trial_complete(trial)
            self._notify_searcher(trial)
            self._cleanup_actor(trial, kill=True)
        elif decision == PopulationBasedTraining.EXPLOIT and \
                isinstance(self.scheduler, PopulationBasedTraining):
            self._exploit(trial)
        else:
            self._inflight[actor.next_result.remote()] = trial

    def _exploit(self, trial: Trial):
        """PBT: restart this trial from a top-quantile trial's checkpoint
        with a perturbed config."""
        sched: PopulationBasedTraining = self.scheduler
        target = sched.exploit_target(trial)
        if target is None or target.checkpoint_path is None:
            self._inflight[self._actors[trial.trial_id].next_result.remote()] = trial
            return
        logger.info("PBT exploit: trial %s <- %s", trial.trial_id,
                    target.trial_id)
        self._cleanup_actor(trial, kill=True)
        trial.config = sched.perturb(target.config)
        trial.checkpoint_path = target.checkpoint_path
        trial.status = TrialStatus.PENDING

    def _stop_condition_met(self, metrics: Dict[str, Any]) -> bool:
        for key, bound in self.stop.items():
            v = metrics.get(key)
            if v is None:
                continue
            if key == "training_iteration" or self.mode == "max":
                if v >= bound:
                    return True
            elif v <= bound:
                return True
        return False

    def _notify_searcher(self, trial: Trial):
        if self.searcher is None:
            return
        score = trial.last_result.get(self.metric) if self.metric else None
        try:
            self.searcher.on_trial_complete(trial.config, score)
        except Exception:
            logger.exception("searcher on_trial_complete failed")

    def _fail_trial(self, trial: Trial, msg: str):
        trial.status = TrialStatus.ERROR
        trial.error = msg
        trial.runtime_s = time.time() - trial.start_time
        self._cleanup_actor(trial, kill=True)

    def _cleanup_actor(self, trial: Trial, kill: bool = False):
        actor = self._actors.pop(trial.trial_id, None)
        doomed = [r for r, t in self._inflight.items() if t is trial]
        for r in doomed:
            self._inflight.pop(r, None)
        if actor is not None and kill:
            try:
                ray_tpu.kill(actor)
            except Exception:
                pass

    # ------------------------------------------------------ experiment state

    def save(self, final: bool = False):
        state = {"trials": [t.state() for t in self.trials],
                 "metric": self.metric, "mode": self.mode}
        path = os.path.join(self.experiment_dir, "tuner.pkl")
        tmp = path + ".tmp"
        with open(tmp, "wb") as f:
            pickle.dump(state, f)
        os.replace(tmp, path)
        self._maybe_sync(final)

    def _maybe_sync(self, final: bool):
        if not self.sync_uri:
            return
        now = time.time()
        if not final and now - self._last_sync < self.sync_period_s:
            return
        self._last_sync = now
        from ray_tpu.train import storage

        attempts = 3 if final else 1
        for i in range(attempts):
            try:
                storage.upload_dir(self.experiment_dir, self.sync_uri)
                return
            except Exception:  # noqa: BLE001 — results are already safe
                # in experiment_dir; a failed upload must not turn a
                # completed run into a raise out of fit().
                logger.warning("experiment sync to %s failed (attempt "
                               "%d/%d)", self.sync_uri, i + 1, attempts,
                               exc_info=True)
                time.sleep(1.0 * (i + 1))

    @staticmethod
    def load_trials(experiment_dir: str) -> List[Trial]:
        path = os.path.join(experiment_dir, "tuner.pkl")
        with open(path, "rb") as f:
            state = pickle.load(f)
        trials = [Trial.from_state(s) for s in state["trials"]]
        # Trials that were mid-flight resume from their last checkpoint.
        for t in trials:
            if t.status in (TrialStatus.RUNNING, TrialStatus.PAUSED):
                t.status = TrialStatus.PENDING
        return trials
