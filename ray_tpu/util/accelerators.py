"""TPU accelerator & pod-slice topology registry.

The reference's `python/ray/util/accelerators/accelerators.py` enumerates GPU
types and has NO TPU entry; TPU topology awareness is the net-new first-class
capability here (SURVEY.md §7): generation → chips/host, hosts per slice
topology, and ICI axis shapes used by `ray_tpu.parallel.mesh` to lay device
meshes over slices.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Dict, Optional, Tuple

# Accelerator type constants (custom resource names)
TPU_V2 = "TPU-V2"
TPU_V3 = "TPU-V3"
TPU_V4 = "TPU-V4"
TPU_V5E = "TPU-V5E"
TPU_V5P = "TPU-V5P"
TPU_V6E = "TPU-V6E"

NVIDIA_TESLA_V100 = "V100"
NVIDIA_TESLA_T4 = "T4"
NVIDIA_TESLA_A100 = "A100"
NVIDIA_A10G = "A10G"
NVIDIA_H100 = "H100"


@dataclass(frozen=True)
class TpuGeneration:
    name: str
    chips_per_host: int
    cores_per_chip: int
    hbm_gb_per_chip: float
    # Max ICI torus shape of a full pod (chips)
    pod_shape: Tuple[int, ...]
    megacore: bool = False


TPU_GENERATIONS: Dict[str, TpuGeneration] = {
    "v2": TpuGeneration("v2", 4, 2, 8, (4, 4, 2)),
    "v3": TpuGeneration("v3", 4, 2, 16, (8, 8, 4)),
    "v4": TpuGeneration("v4", 4, 2, 32, (8, 8, 8), megacore=True),
    "v5e": TpuGeneration("v5e", 4, 1, 16, (16, 16, 1)),
    "v5p": TpuGeneration("v5p", 4, 2, 95, (16, 16, 12), megacore=True),
    "v6e": TpuGeneration("v6e", 4, 1, 32, (16, 16, 1)),
}


def parse_slice(slice_name: str) -> Tuple[str, int]:
    """'v4-32' -> ('v4', 32 cores) ; returns (generation, total cores)."""
    gen, _, cores = slice_name.partition("-")
    gen = gen.lower().lstrip("tpu").lstrip("_") or gen.lower()
    if gen not in TPU_GENERATIONS:
        raise ValueError(f"Unknown TPU generation in '{slice_name}'")
    return gen, int(cores)


def slice_chip_count(slice_name: str) -> int:
    gen, cores = parse_slice(slice_name)
    g = TPU_GENERATIONS[gen]
    return cores // g.cores_per_chip


def slice_host_count(slice_name: str) -> int:
    gen, _ = parse_slice(slice_name)
    g = TPU_GENERATIONS[gen]
    return max(1, slice_chip_count(slice_name) // g.chips_per_host)


def slice_bundles(slice_name: str, cpus_per_host: float = 1.0):
    """Placement-group bundles for one pod slice: one bundle per TPU host.

    Feed to `placement_group(..., strategy='STRICT_SPREAD')` so each bundle
    lands on a distinct host — the JaxBackend then runs one JAX process per
    bundle and forms the ICI mesh.
    """
    gen, _ = parse_slice(slice_name)
    g = TPU_GENERATIONS[gen]
    hosts = slice_host_count(slice_name)
    chips = min(g.chips_per_host, slice_chip_count(slice_name))
    return [{"CPU": cpus_per_host, "TPU": float(chips)} for _ in range(hosts)]


def detect_local_generation() -> Optional[str]:
    """Best-effort generation detection from TPU runtime env vars."""
    import os

    accel = os.environ.get("TPU_ACCELERATOR_TYPE")  # e.g. "v4-8"
    if accel:
        try:
            return parse_slice(accel)[0]
        except ValueError:
            return None
    return None
