"""ActorPool: load-balance a stream of work items over a fixed set of actors.

API surface matches the reference utility (`python/ray/util/actor_pool.py:8`);
the implementation is built around per-item ``_Slot`` records rather than
parallel index maps: every submission gets a slot with a monotonically
increasing sequence number, slots move backlog -> running -> harvested, and
the two consumption orders (submission order vs completion order) are just
two ways of picking the next slot to harvest.
"""

from __future__ import annotations

import collections
from dataclasses import dataclass
from typing import Any, Callable, Iterable, Iterator, List, Optional


@dataclass
class _Slot:
    seq: int
    ref: Any  # in-flight object ref
    actor: Any


class ActorPool:
    def __init__(self, actors: List[Any]):
        self._free: collections.deque = collections.deque(actors)
        self._backlog: collections.deque = collections.deque()  # (fn, arg)
        self._running: dict = {}  # ref -> _Slot
        self._slots: dict = {}  # seq -> _Slot, until harvested
        self._submitted = 0  # total slots ever created
        self._harvest_seq = 0  # next seq get_next() will return

    # -- submission ----------------------------------------------------- #

    def submit(self, fn: Callable[[Any, Any], Any], value: Any) -> None:
        """Schedule ``fn(actor, value)`` on the next free actor (or queue it)."""
        if not self._free:
            self._backlog.append((fn, value))
            return
        actor = self._free.popleft()
        slot = _Slot(seq=self._submitted, ref=fn(actor, value), actor=actor)
        self._submitted += 1
        self._running[slot.ref] = slot
        self._slots[slot.seq] = slot

    def _recycle(self, slot: _Slot) -> None:
        self._running.pop(slot.ref, None)
        self._slots.pop(slot.seq, None)
        self._free.append(slot.actor)
        if self._backlog:
            fn, value = self._backlog.popleft()
            self.submit(fn, value)

    # -- harvesting ----------------------------------------------------- #

    def has_next(self) -> bool:
        return bool(self._slots) or bool(self._backlog)

    def get_next(self, timeout: Optional[float] = None) -> Any:
        """Block for the result of the oldest unharvested submission.

        A timeout leaves the slot unharvested (retry with another
        get_next); a task error consumes the slot and re-raises.
        """
        import ray_tpu
        from ray_tpu.exceptions import GetTimeoutError

        if self._harvest_seq >= self._submitted:
            raise StopIteration("No more results to get")
        slot = self._slots[self._harvest_seq]
        try:
            value = ray_tpu.get(slot.ref, timeout=timeout)
        except (GetTimeoutError, TimeoutError):
            raise
        except Exception:
            self._harvest_seq += 1
            self._recycle(slot)
            raise
        self._harvest_seq += 1
        self._recycle(slot)
        return value

    def get_next_unordered(self, timeout: Optional[float] = None) -> Any:
        """Block for whichever in-flight submission finishes first."""
        import ray_tpu

        if not self._running:
            raise StopIteration("No more results to get")
        ready, _ = ray_tpu.wait(list(self._running), num_returns=1,
                                timeout=timeout)
        if not ready:
            raise TimeoutError("get_next_unordered timed out")
        slot = self._running[ready[0]]
        value = ray_tpu.get(slot.ref)
        self._recycle(slot)
        return value

    # -- bulk helpers --------------------------------------------------- #

    def map(self, fn: Callable, values: Iterable[Any]) -> Iterator[Any]:
        for v in values:
            self.submit(fn, v)
        while self.has_next():
            yield self.get_next()

    def map_unordered(self, fn: Callable, values: Iterable[Any]) -> Iterator[Any]:
        for v in values:
            self.submit(fn, v)
        while self.has_next():
            yield self.get_next_unordered()

    # -- direct actor management ---------------------------------------- #

    def has_free(self) -> bool:
        return bool(self._free)

    def pop_idle(self) -> Optional[Any]:
        return self._free.pop() if self._free else None

    def push(self, actor: Any) -> None:
        self._free.append(actor)
