"""Host-level collective ops — compatibility shim over `ray_tpu.collective`.

Historically this module WAS the collective implementation: a star-topology
rendezvous actor that round-tripped every payload, fully pickled, through
one process (O(world_size × bytes) through a single actor). The real plane
now lives in `ray_tpu.collective` — ring allreduce / tree broadcast over
the pipelined object-transfer plane, GCS-backed membership with
rank-attributed death aborts (docs/COLLECTIVE.md). The module-level API
below delegates there.

The star implementation is retained as ``backend="star"`` (and the
`_RendezvousActor` class) for A/B benchmarking — bench.py's
collective microbench measures ring vs star — and for tiny host-side
rendezvous where one actor is genuinely enough.
"""

from __future__ import annotations

import threading
from typing import Any, Dict, List, Optional

import numpy as np

from ray_tpu.collective.buffer import tree_index as _tree_index_impl

_REDUCE_OPS = {
    "sum": lambda xs: _tree_reduce(xs, np.add),
    "product": lambda xs: _tree_reduce(xs, np.multiply),
    "min": lambda xs: _tree_reduce(xs, np.minimum),
    "max": lambda xs: _tree_reduce(xs, np.maximum),
}


def _tree_reduce(xs: List[Any], op):
    out = xs[0]
    for x in xs[1:]:
        out = _tree_map2(op, out, x)
    return out


def _tree_map2(op, a, b):
    if isinstance(a, dict):
        return {k: _tree_map2(op, a[k], b[k]) for k in a}
    if isinstance(a, (list, tuple)):
        return type(a)(_tree_map2(op, x, y) for x, y in zip(a, b))
    return op(np.asarray(a), np.asarray(b))


def _tree_index(x, rank: int, world: int):
    """Row-slice every leaf for reducescatter; raises ValueError when a
    leading dimension does not divide world_size (the old code silently
    dropped the remainder rows)."""
    return _tree_index_impl(x, rank, world)


class _RendezvousActor:
    """Barrier + gather/reduce/broadcast state machine for one group.

    Per-key state is refcounted by fetches: every member fetches each
    result exactly once, so the slot (result + event) is deleted when the
    world_size'th fetch drains it — long-lived groups no longer grow
    unboundedly."""

    def __init__(self, world_size: int):
        self.world_size = world_size
        self._round: Dict[str, Dict[int, Any]] = {}
        self._results: Dict[str, Any] = {}
        self._fetches: Dict[str, int] = {}
        self._lock = threading.Lock()
        self._events: Dict[str, threading.Event] = {}

    def get_world_size(self) -> int:
        """Attach-time validation hook: a namesake group with a different
        world_size must raise at init, not hang every rank."""
        return self.world_size

    def _event(self, key: str) -> threading.Event:
        with self._lock:
            return self._events.setdefault(key, threading.Event())

    def contribute(self, key: str, rank: int, value: Any, op: Optional[str]):
        with self._lock:
            slot = self._round.setdefault(key, {})
            slot[rank] = value
            done = len(slot) == self.world_size
            if done:
                vals = [slot[r] for r in sorted(slot)]
                if op is None:
                    self._results[key] = vals                # allgather
                else:
                    self._results[key] = _REDUCE_OPS[op](vals)
                del self._round[key]
        if done:
            self._event(key).set()
        return True

    def fetch(self, key: str, timeout: float = 300.0):
        if not self._event(key).wait(timeout):
            raise TimeoutError(f"collective '{key}' timed out "
                               f"(world_size={self.world_size})")
        with self._lock:
            result = self._results[key]
            self._fetches[key] = self._fetches.get(key, 0) + 1
            if self._fetches[key] >= self.world_size:
                # Drained: every member has its copy — delete the slot so
                # a long-lived group's memory stays bounded.
                del self._results[key]
                del self._fetches[key]
                self._events.pop(key, None)
            return result

    def reset(self):
        with self._lock:
            self._round.clear()
            self._results.clear()
            self._fetches.clear()
            self._events.clear()


class StarCollectiveGroup:
    """Legacy star topology: every op round-trips through one rendezvous
    actor. Kept for A/B measurement against the ring plane and as a
    minimal dependency-free fallback."""

    def __init__(self, name: str, world_size: int, rank: int):
        import ray_tpu

        self.name = name
        self.world_size = world_size
        self.rank = rank
        self._actor = ray_tpu.remote(_RendezvousActor).options(
            name=f"rtpu_collective_{name}", get_if_exists=True,
            max_concurrency=max(8, world_size * 2), num_cpus=0,
            lifetime="detached").remote(world_size)
        # get_if_exists may have attached to a pre-existing namesake actor:
        # a mismatched world_size would deadlock every op (the barrier
        # count never completes) — validate now and fail loudly.
        existing = ray_tpu.get(self._actor.get_world_size.remote())
        if existing != world_size:
            raise ValueError(
                f"collective group '{name}' already exists with "
                f"world_size={existing}; attach requested "
                f"world_size={world_size}. destroy_collective_group() it "
                "first (or pick another name).")
        self._seq = 0

    def _next_key(self, tag: str) -> str:
        self._seq += 1
        return f"{tag}:{self._seq}"

    def _exchange(self, tag: str, value: Any, op: Optional[str]):
        import ray_tpu

        key = self._next_key(tag)
        ray_tpu.get(self._actor.contribute.remote(key, self.rank, value, op))
        return ray_tpu.get(self._actor.fetch.remote(key))

    def allreduce(self, value: Any, op: str = "sum"):
        return self._exchange("ar", value, op)

    def allgather(self, value: Any) -> List[Any]:
        return self._exchange("ag", value, None)

    def broadcast(self, value: Any, src_rank: int = 0):
        vals = self._exchange("bc", value if self.rank == src_rank else None, None)
        return vals[src_rank]

    def reducescatter(self, value: Any, op: str = "sum"):
        full = self._exchange("rs", value, op)
        return _tree_index(full, self.rank, self.world_size)

    def barrier(self):
        self._exchange("barrier", None, None)

    def destroy(self):
        import ray_tpu

        try:
            ray_tpu.kill(self._actor)
        except Exception:
            pass

    def leave(self):  # API parity with the ring plane
        pass


# Backwards-compatible alias: `CollectiveGroup` from this module used to be
# the star implementation; the canonical CollectiveGroup now lives in
# ray_tpu.collective.
CollectiveGroup = StarCollectiveGroup

_groups: Dict[str, Any] = {}


def init_collective_group(world_size: int, rank: int,
                          group_name: str = "default",
                          backend: str = "ring"):
    """Join a host collective group.

    backend="ring" (default): the `ray_tpu.collective` plane — ring
    allreduce / tree broadcast over the object-transfer plane, GCS
    membership, CollectiveError on member death.
    backend="star": the legacy single-actor rendezvous.
    """
    if backend == "ring":
        import ray_tpu.collective as _collective

        group = _collective.init_collective_group(world_size, rank,
                                                  group_name=group_name)
    elif backend == "star":
        group = StarCollectiveGroup(group_name, world_size, rank)
    else:
        raise ValueError(f"unknown collective backend {backend!r} "
                         "(expected 'ring' or 'star')")
    _groups[group_name] = group
    return group


def get_group(group_name: str = "default"):
    if group_name not in _groups:
        raise ValueError(f"collective group '{group_name}' not initialized")
    return _groups[group_name]


def allreduce(value, group_name: str = "default", op: str = "sum"):
    return get_group(group_name).allreduce(value, op)


def allgather(value, group_name: str = "default"):
    return get_group(group_name).allgather(value)


def broadcast(value, src_rank: int = 0, group_name: str = "default"):
    return get_group(group_name).broadcast(value, src_rank)


def reducescatter(value, group_name: str = "default", op: str = "sum"):
    return get_group(group_name).reducescatter(value, op)


def barrier(group_name: str = "default"):
    get_group(group_name).barrier()


def destroy_collective_group(group_name: str = "default"):
    group = _groups.pop(group_name, None)
    if group is not None:
        group.destroy()
