"""Host-level collective ops between actors.

Equivalent of `python/ray/util/collective/collective.py` (:40 GroupManager,
:120 init_collective_group, :258 allreduce) — but with no NCCL/Gloo layer:

- **Device-side collectives** (the hot path) live *inside* XLA programs:
  `jax.lax.psum/...` over a mesh axis, compiled to ICI/DCN transfers. See
  `ray_tpu.parallel`. A "collective group" maps to a named JAX mesh, not a
  communicator object (SURVEY.md §5.8).
- **This module** is the host-RAM fallback for control-plane data (metric
  reduction, weight broadcast between actor groups, rendezvous): CPU
  reductions via a rendezvous actor, exchanging numpy through the object
  store (zero-copy shm on one host).
"""

from __future__ import annotations

import threading
from typing import Any, Dict, List, Optional

import numpy as np

_REDUCE_OPS = {
    "sum": lambda xs: _tree_reduce(xs, np.add),
    "product": lambda xs: _tree_reduce(xs, np.multiply),
    "min": lambda xs: _tree_reduce(xs, np.minimum),
    "max": lambda xs: _tree_reduce(xs, np.maximum),
}


def _tree_reduce(xs: List[Any], op):
    out = xs[0]
    for x in xs[1:]:
        out = _tree_map2(op, out, x)
    return out


def _tree_map2(op, a, b):
    if isinstance(a, dict):
        return {k: _tree_map2(op, a[k], b[k]) for k in a}
    if isinstance(a, (list, tuple)):
        return type(a)(_tree_map2(op, x, y) for x, y in zip(a, b))
    return op(np.asarray(a), np.asarray(b))


class _RendezvousActor:
    """Barrier + gather/reduce/broadcast state machine for one group."""

    def __init__(self, world_size: int):
        self.world_size = world_size
        self._round: Dict[str, Dict[int, Any]] = {}
        self._results: Dict[str, Any] = {}
        self._lock = threading.Lock()
        self._events: Dict[str, threading.Event] = {}

    def _event(self, key: str) -> threading.Event:
        with self._lock:
            return self._events.setdefault(key, threading.Event())

    def contribute(self, key: str, rank: int, value: Any, op: Optional[str]):
        with self._lock:
            slot = self._round.setdefault(key, {})
            slot[rank] = value
            done = len(slot) == self.world_size
            if done:
                vals = [slot[r] for r in sorted(slot)]
                if op is None:
                    self._results[key] = vals                # allgather
                else:
                    self._results[key] = _REDUCE_OPS[op](vals)
                del self._round[key]
        if done:
            self._event(key).set()
        return True

    def fetch(self, key: str, timeout: float = 300.0):
        if not self._event(key).wait(timeout):
            raise TimeoutError(f"collective '{key}' timed out "
                               f"(world_size={self.world_size})")
        with self._lock:
            return self._results[key]

    def reset(self):
        with self._lock:
            self._round.clear()
            self._results.clear()
            self._events.clear()


class CollectiveGroup:
    """Handle used by each member actor/process."""

    def __init__(self, name: str, world_size: int, rank: int):
        import ray_tpu

        self.name = name
        self.world_size = world_size
        self.rank = rank
        self._actor = ray_tpu.remote(_RendezvousActor).options(
            name=f"rtpu_collective_{name}", get_if_exists=True,
            max_concurrency=max(8, world_size * 2), num_cpus=0,
            lifetime="detached").remote(world_size)
        self._seq = 0

    def _next_key(self, tag: str) -> str:
        self._seq += 1
        return f"{tag}:{self._seq}"

    def _exchange(self, tag: str, value: Any, op: Optional[str]):
        import ray_tpu

        key = self._next_key(tag)
        ray_tpu.get(self._actor.contribute.remote(key, self.rank, value, op))
        return ray_tpu.get(self._actor.fetch.remote(key))

    def allreduce(self, value: Any, op: str = "sum"):
        return self._exchange("ar", value, op)

    def allgather(self, value: Any) -> List[Any]:
        return self._exchange("ag", value, None)

    def broadcast(self, value: Any, src_rank: int = 0):
        vals = self._exchange("bc", value if self.rank == src_rank else None, None)
        return vals[src_rank]

    def reducescatter(self, value: Any, op: str = "sum"):
        full = self._exchange("rs", value, op)
        return _tree_index(full, self.rank, self.world_size)

    def barrier(self):
        self._exchange("barrier", None, None)

    def destroy(self):
        import ray_tpu

        try:
            ray_tpu.kill(self._actor)
        except Exception:
            pass


def _tree_index(x, rank: int, world: int):
    if isinstance(x, dict):
        return {k: _tree_index(v, rank, world) for k, v in x.items()}
    if isinstance(x, (list, tuple)):
        return type(x)(_tree_index(v, rank, world) for v in x)
    arr = np.asarray(x)
    chunk = arr.shape[0] // world
    return arr[rank * chunk:(rank + 1) * chunk]


_groups: Dict[str, CollectiveGroup] = {}


def init_collective_group(world_size: int, rank: int,
                          group_name: str = "default") -> CollectiveGroup:
    group = CollectiveGroup(group_name, world_size, rank)
    _groups[group_name] = group
    return group


def get_group(group_name: str = "default") -> CollectiveGroup:
    if group_name not in _groups:
        raise ValueError(f"collective group '{group_name}' not initialized")
    return _groups[group_name]


def allreduce(value, group_name: str = "default", op: str = "sum"):
    return get_group(group_name).allreduce(value, op)


def allgather(value, group_name: str = "default"):
    return get_group(group_name).allgather(value)


def broadcast(value, src_rank: int = 0, group_name: str = "default"):
    return get_group(group_name).broadcast(value, src_rank)


def reducescatter(value, group_name: str = "default", op: str = "sum"):
    return get_group(group_name).reducescatter(value, op)


def barrier(group_name: str = "default"):
    get_group(group_name).barrier()


def destroy_collective_group(group_name: str = "default"):
    group = _groups.pop(group_name, None)
    if group is not None:
        group.destroy()
