"""ParallelIterator: sharded lazy iterators over actors.

Equivalent of the reference's `python/ray/util/iter.py:132`
(`ParallelIterator` / `ParallelIteratorWorker` :1136 — the base of RLlib's
old RolloutWorker): a logical iterator split into shards, each shard a
chain of local transforms hosted by one actor; `gather_sync`/`gather_async`
pull batches back to the driver either round-robin or completion-order.
"""

from __future__ import annotations

import builtins
from typing import Any, Callable, Iterable, Iterator, List, Optional


class _ShardWorker:
    """Actor hosting one shard: a base iterable + transform chain."""

    def __init__(self, items: List[Any], transforms: List[tuple]):
        self._items = items
        self._transforms = transforms
        self._it: Optional[Iterator] = None

    def _build(self) -> Iterator:
        it: Iterable = iter(self._items)
        for kind, fn in self._transforms:
            if kind == "for_each":
                it = builtins.map(fn, it)
            elif kind == "filter":
                it = (x for x in it if fn(x))
            elif kind == "flatten":
                it = (y for x in it for y in x)
            elif kind == "batch":
                it = _batched(it, fn)
        return iter(it)

    def reset(self):
        self._it = self._build()
        return True

    def next_batch(self, n: int) -> List[Any]:
        """Up to n items; empty list = exhausted."""
        if self._it is None:
            self.reset()
        out = []
        try:
            for _ in range(n):
                out.append(next(self._it))
        except StopIteration:
            pass
        return out


def _batched(it: Iterator, n: int) -> Iterator[List[Any]]:
    buf: List[Any] = []
    for x in it:
        buf.append(x)
        if len(buf) == n:
            yield buf
            buf = []
    if buf:
        yield buf


class ParallelIterator:
    """Declarative sharded iterator; transforms stay lazy until gathered.

    Internally a list of (shard_items, transform_chain) segments: a union
    is just segment concatenation (each side keeps its own chain), so
    nothing materializes until a gather spawns the shard actors."""

    def __init__(self, shards: Optional[List[List[Any]]] = None,
                 transforms: Optional[List[tuple]] = None,
                 segments: Optional[List[tuple]] = None):
        if segments is not None:
            self._segments = list(segments)
        else:
            t = list(transforms or [])
            self._segments = [(s, t) for s in (shards or [])]

    # ----------------------------------------------------------- transforms

    def _with_transform(self, step: tuple) -> "ParallelIterator":
        return ParallelIterator(segments=[
            (items, chain + [step]) for items, chain in self._segments])

    def for_each(self, fn: Callable[[Any], Any]) -> "ParallelIterator":
        return self._with_transform(("for_each", fn))

    def filter(self, fn: Callable[[Any], bool]) -> "ParallelIterator":
        return self._with_transform(("filter", fn))

    def flatten(self) -> "ParallelIterator":
        return self._with_transform(("flatten", None))

    def batch(self, n: int) -> "ParallelIterator":
        return self._with_transform(("batch", n))

    def union(self, other: "ParallelIterator") -> "ParallelIterator":
        return ParallelIterator(
            segments=self._segments + other._segments)

    def num_shards(self) -> int:
        return len(self._segments)

    # ------------------------------------------------------------- gathering

    def _spawn(self) -> List[Any]:
        import ray_tpu

        actor_cls = ray_tpu.remote(_ShardWorker)
        workers = [actor_cls.options(num_cpus=0.1).remote(items, chain)
                   for items, chain in self._segments]
        ray_tpu.get([w.reset.remote() for w in workers])
        return workers

    def gather_sync(self, batch: int = 32) -> Iterator[Any]:
        """Round-robin over shards, in shard order within each round."""
        import ray_tpu

        workers = self._spawn()
        try:
            live = list(workers)
            while live:
                refs = [w.next_batch.remote(batch) for w in live]
                next_live = []
                for w, ref in zip(live, refs):
                    got = ray_tpu.get(ref)
                    if got:
                        next_live.append(w)
                        yield from got
                live = next_live
        finally:
            for w in workers:
                try:
                    ray_tpu.kill(w)
                except Exception:  # noqa: BLE001
                    pass

    def gather_async(self, batch: int = 32) -> Iterator[Any]:
        """Completion-order gathering: whichever shard finishes its batch
        first is consumed (and re-pumped) first."""
        import ray_tpu

        workers = self._spawn()
        try:
            inflight = {w.next_batch.remote(batch): w for w in workers}
            while inflight:
                ready, _ = ray_tpu.wait(list(inflight), num_returns=1)
                w = inflight.pop(ready[0])
                got = ray_tpu.get(ready[0])
                if got:
                    inflight[w.next_batch.remote(batch)] = w
                    yield from got
        finally:
            for w in workers:
                try:
                    ray_tpu.kill(w)
                except Exception:  # noqa: BLE001
                    pass

    def take(self, n: int) -> List[Any]:
        out = []
        for x in self.gather_sync():
            out.append(x)
            if len(out) >= n:
                break
        return out

    def show(self, n: int = 20):
        for x in self.take(n):
            print(x)

    def __repr__(self):
        steps = max((len(c) for _, c in self._segments), default=0)
        return (f"ParallelIterator[{len(self._segments)} shards, "
                f"{steps} transforms]")


def from_items(items: List[Any], num_shards: int = 2) -> ParallelIterator:
    shards: List[List[Any]] = [[] for _ in range(num_shards)]
    for i, x in enumerate(items):
        shards[i % num_shards].append(x)
    return ParallelIterator(shards)


def from_range(n: int, num_shards: int = 2) -> ParallelIterator:
    return from_items(list(range(n)), num_shards)


def from_iterators(generators: List[Iterable]) -> ParallelIterator:
    return ParallelIterator([list(g) for g in generators])
