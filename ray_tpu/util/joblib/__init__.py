"""joblib backend: `register_ray()` + `parallel_backend("ray")`.

Equivalent of the reference's `python/ray/util/joblib/`: joblib.Parallel
batches (scikit-learn's parallelism) execute as framework tasks, so an
unmodified `GridSearchCV(n_jobs=-1)` fans out over the cluster.
"""

from __future__ import annotations

from typing import Any, Callable, List


def register_ray() -> None:
    """Register the 'ray' joblib backend (reference register_ray)."""
    from joblib.parallel import register_parallel_backend

    register_parallel_backend("ray", _RayBackend)


try:
    from joblib._parallel_backends import MultiprocessingBackend
except Exception:  # pragma: no cover — joblib absent/renamed internals
    MultiprocessingBackend = object  # type: ignore[misc,assignment]


class _RayBackend(MultiprocessingBackend):
    """Each joblib batch (a list of zero-arg callables) runs as one task."""

    supports_timeout = True

    def configure(self, n_jobs: int = 1, parallel: Any = None,
                  prefer: Any = None, require: Any = None, **kwargs) -> int:
        import ray_tpu

        if not ray_tpu.is_initialized():
            ray_tpu.init()
        n_jobs = self.effective_n_jobs(n_jobs)
        self.parallel = parallel
        return n_jobs

    def effective_n_jobs(self, n_jobs: int) -> int:
        import ray_tpu

        cpus = max(1, int(ray_tpu.cluster_resources().get("CPU", 1)))
        if n_jobs is None or n_jobs == 1:
            return 1
        if n_jobs < 0:
            return cpus
        return min(n_jobs, cpus)

    def apply_async(self, func: Callable[[], List[Any]], callback=None):
        import ray_tpu

        @ray_tpu.remote
        def run_batch(f):
            return f()

        ref = run_batch.remote(func)
        return _RayResult(ref, callback)

    # joblib >= 1.3 dispatches through submit(); same contract: the
    # callback receives the result value (or the exception) directly.
    def submit(self, func, callback=None):
        return self.apply_async(func, callback=callback)

    def retrieve_result_callback(self, out):
        if isinstance(out, BaseException):
            raise out
        return out

    def terminate(self):
        pass

    def abort_everything(self, ensure_ready: bool = True):
        if ensure_ready:
            self.configure(n_jobs=self.parallel.n_jobs,
                           parallel=self.parallel)


class _RayResult:
    def __init__(self, ref, callback):
        self._ref = ref
        self._callback = callback
        if callback is not None:
            import threading

            threading.Thread(target=self._notify, daemon=True).start()

    def _notify(self):
        import ray_tpu

        try:
            out = ray_tpu.get(self._ref)
        except BaseException as e:  # noqa: BLE001 — delivered to joblib
            out = e
        self._callback(out)

    def get(self, timeout=None):
        import ray_tpu

        return ray_tpu.get(self._ref, timeout=timeout)
