"""Lock-order witness: a TSAN-style sanitizer for the threaded control
plane (SURVEY §5.2).

The reference ships TSAN/ASAN build configs for its C++ core
(`bazel --config=tsan`, `src/ray/...` race tests); this runtime's control
plane is Python threads + locks, where the classic failure mode is not a
data race (the GIL serializes byte-code) but a LOCK-ORDER INVERSION:
thread 1 takes A then B, thread 2 takes B then A, and the cluster hangs
under load timing that no unit test reproduces.

`install()` monkeypatches `threading.Lock`/`RLock` so every lock created
afterwards is a witness proxy. Each acquire records the per-thread held
stack and adds edges held→acquiring to a global lock-order graph; the
first edge that closes a cycle is reported with the creation and
acquisition sites of every lock on the cycle. Detection is ORDER-based:
it fires on the inversion pattern even when the interleaving never
actually deadlocks, which is what makes it useful in tests.

Also provides a hang watchdog: acquires that block longer than
``watchdog_s`` dump all thread stacks to stderr once (the moral
equivalent of the reference's blocked-finisher checks).

Usage (tests/test_race_harness.py drives both):

    from ray_tpu.util import lock_witness
    lock_witness.install()          # BEFORE creating the locks of interest
    ... run workload ...
    assert lock_witness.report().cycles == []

Scope notes: locks created before install() (module-level registries) are
not instrumented; `threading.Condition` instruments transparently when
handed an instrumented (R)Lock. Overhead is a dict update per acquire —
fine for tests, not meant for production hot paths.
"""

from __future__ import annotations

import faulthandler
import sys
import threading
import traceback
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Set, Tuple

_real_lock = threading.Lock
_real_rlock = threading.RLock

_state_lock = _real_lock()
_installed = False
_watchdog_s: Optional[float] = None

# Lock-order graph over live witness locks: edges id(a) -> set of id(b)
# observed acquired while a was held. Sites kept for reporting.
_edges: Dict[int, Set[int]] = {}
_edge_sites: Dict[Tuple[int, int], str] = {}
_lock_sites: Dict[int, str] = {}
_cycles: List[str] = []
_held = threading.local()


@dataclass
class Report:
    cycles: List[str] = field(default_factory=list)
    locks_tracked: int = 0
    edges: int = 0


def _caller_site(depth: int = 2) -> str:
    frame = sys._getframe(depth)
    # Skip witness frames so the site names user code.
    while frame is not None and __file__ in (frame.f_code.co_filename or ""):
        frame = frame.f_back
    if frame is None:
        return "<unknown>"
    return f"{frame.f_code.co_filename}:{frame.f_lineno}"


def _held_stack() -> list:
    stack = getattr(_held, "stack", None)
    if stack is None:
        stack = _held.stack = []
    return stack


def _find_cycle(start: int, target: int) -> Optional[List[int]]:
    """Path target ->* start in the edge graph (so adding start->target
    closes a cycle)."""
    path: List[int] = [target]
    seen = {target}

    def dfs(node: int) -> Optional[List[int]]:
        if node == start:
            return path[:]
        for nxt in _edges.get(node, ()):
            if nxt in seen:
                continue
            seen.add(nxt)
            path.append(nxt)
            found = dfs(nxt)
            if found is not None:
                return found
            path.pop()
        return None

    return dfs(target)


def _record_acquire(lock_id: int):
    stack = _held_stack()
    if not stack:
        return
    me = threading.get_ident()
    with _state_lock:
        for held_id in stack:
            if held_id == lock_id:
                continue
            edge = (held_id, lock_id)
            if lock_id in _edges.setdefault(held_id, set()):
                continue
            # New edge: does the reverse path exist? (cycle check BEFORE
            # inserting, so the report shows the closing edge.)
            cycle = _find_cycle(held_id, lock_id)
            _edges[held_id].add(lock_id)
            _edge_sites[edge] = _caller_site(3)
            if cycle is not None:
                names = " -> ".join(
                    _lock_sites.get(l, "?") for l in [held_id] + cycle)
                msg = (f"lock-order inversion (thread {me}): "
                       f"{names} -> back to first; closing acquisition at "
                       f"{_edge_sites[edge]}")
                _cycles.append(msg)


_wid_counter = iter(range(1, 1 << 62))


class _WitnessBase:
    def __init__(self, inner):
        self._inner = inner
        # Monotonic key, NOT id(self): CPython reuses freed addresses, so
        # an id-keyed graph would let a new lock inherit a dead lock's
        # edges and report phantom inversions between locks that never
        # coexisted.
        self._wid = next(_wid_counter)
        with _state_lock:
            _lock_sites[self._wid] = _caller_site(3)

    def acquire(self, blocking: bool = True, timeout: float = -1):
        if blocking and _watchdog_s is not None and timeout == -1:
            got = self._inner.acquire(True, _watchdog_s)
            if not got:
                sys.stderr.write(
                    f"[lock_witness] acquire blocked >{_watchdog_s}s at "
                    f"{_caller_site(2)} (lock from "
                    f"{_lock_sites.get(self._wid)}); thread dump:\n")
                faulthandler.dump_traceback()
                got = self._inner.acquire(True, -1 if timeout == -1 else timeout)
        else:
            got = self._inner.acquire(blocking, timeout)
        if got:
            _record_acquire(self._wid)
            _held_stack().append(self._wid)
        return got

    def release(self):
        stack = _held_stack()
        # Remove the most recent occurrence (locks may release out of
        # LIFO order; witnesses tolerate it).
        for i in range(len(stack) - 1, -1, -1):
            if stack[i] == self._wid:
                del stack[i]
                break
        else:
            # This thread never recorded the acquire. Silently releasing
            # would leave the acquirer's held-stack stale forever: every
            # lock it takes from now on grows phantom order edges, which
            # can both invent and MASK real inversions. Raise before
            # touching the inner lock so the discipline violation names
            # its site instead of corrupting the witness. Deliberate
            # tradeoff: a library using a plain Lock as a legal
            # cross-thread handoff would deadlock its owner here instead
            # of proceeding — but releasing first would unlock while the
            # owner's held-stack still lists the lock, recreating the
            # exact corruption this raise exists to prevent. No such
            # handoff exists under the sanitizer today, and the acquire
            # watchdog dumps all threads if one ever appears.
            raise RuntimeError(
                f"lock_witness: release() of lock created at "
                f"{_lock_sites.get(self._wid, '?')} by thread "
                f"{threading.get_ident()}, which never acquired it "
                f"(cross-thread release or double release)")
        self._inner.release()

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *exc):
        self.release()
        return False

    def locked(self):
        return self._inner.locked()

    # Condition support: forward RLock internals.
    def __getattr__(self, name):
        return getattr(self._inner, name)


class _WitnessLock(_WitnessBase):
    def __init__(self):
        super().__init__(_real_lock())


class _WitnessRLock(_WitnessBase):
    def __init__(self):
        super().__init__(_real_rlock())

    def _is_owned(self):
        return self._inner._is_owned()

    def _release_save(self):
        # Condition.wait releases the lock: clear our held marks for every
        # recursion level so the wait doesn't hold a phantom edge source.
        stack = _held_stack()
        while self._wid in stack:
            stack.remove(self._wid)
        return self._inner._release_save()

    def _acquire_restore(self, state):
        self._inner._acquire_restore(state)
        _held_stack().append(self._wid)


def install(watchdog_s: Optional[float] = None):
    """Patch threading.Lock/RLock with witness proxies. Idempotent."""
    global _installed, _watchdog_s
    with _state_lock:
        if _installed:
            _watchdog_s = watchdog_s if watchdog_s is not None else _watchdog_s
            return
        _installed = True
        _watchdog_s = watchdog_s
    threading.Lock = _WitnessLock  # type: ignore[misc]
    threading.RLock = _WitnessRLock  # type: ignore[misc]


def uninstall():
    global _installed
    with _state_lock:
        if not _installed:
            return
        _installed = False
    threading.Lock = _real_lock  # type: ignore[misc]
    threading.RLock = _real_rlock  # type: ignore[misc]


def reset():
    with _state_lock:
        _edges.clear()
        _edge_sites.clear()
        _cycles.clear()


def discard_cycles(site_substring: str) -> int:
    """Drop recorded cycles whose report mentions `site_substring` in any
    lock/acquisition site. For test fixtures that deliberately create
    inversions with synthetic locks: discarding by the test file's name
    removes exactly their evidence while keeping anything recorded from
    real control-plane locks, so a session-wide sanitizer gate stays
    sound. Returns the number discarded."""
    with _state_lock:
        kept = [c for c in _cycles if site_substring not in c]
        dropped = len(_cycles) - len(kept)
        _cycles[:] = kept
        return dropped


def report() -> Report:
    with _state_lock:
        return Report(cycles=list(_cycles),
                      locks_tracked=len(_lock_sites),
                      edges=sum(len(v) for v in _edges.values()))
