"""User-facing metrics API + process-local registry.

Equivalent of the reference's `ray.util.metrics` (`python/ray/util/metrics.py`)
backed by its native stats layer (`src/ray/stats/metric.h:103`,
`metrics_agent.py:375`). Redesigned for this runtime: each process keeps a
lock-protected registry; the CoreRuntime flushes snapshots to the GCS on a
short period; the GCS aggregates per-process series and renders Prometheus
text exposition (served by the dashboard's /metrics route).
"""

from __future__ import annotations

import threading
import time
from typing import Dict, List, Optional, Sequence, Tuple

_TagKey = Tuple[Tuple[str, str], ...]


class _Registry:
    def __init__(self):
        self._lock = threading.Lock()
        self._metrics: Dict[str, "Metric"] = {}

    def register(self, metric: "Metric") -> "Optional[Metric]":
        """Register `metric`; if a same-name same-type metric already
        exists, KEEP it and return it so the new instance adopts its
        series — re-constructing a metric (e.g. a re-created deployment)
        must not silently reset the accumulated time series."""
        with self._lock:
            existing = self._metrics.get(metric.name)
            if existing is not None:
                if type(existing) is not type(metric):
                    raise ValueError(
                        f"metric {metric.name!r} already registered as "
                        f"{type(existing).__name__}")
                return existing
            self._metrics[metric.name] = metric
            return None

    def snapshot(self) -> List[dict]:
        with self._lock:
            return [m._snapshot() for m in self._metrics.values()]


GLOBAL_REGISTRY = _Registry()


def _tag_tuple(tags: Optional[Dict[str, str]],
               default: Dict[str, str]) -> _TagKey:
    merged = dict(default)
    if tags:
        merged.update(tags)
    return tuple(sorted(merged.items()))


class Metric:
    """Base: named, tagged, per-process time series."""

    kind = "untyped"

    def __init__(self, name: str, description: str = "",
                 tag_keys: Sequence[str] = ()):
        if not name or any(c in name for c in " \n\t"):
            raise ValueError(f"invalid metric name {name!r}")
        self.name = name
        self.description = description
        self.tag_keys = tuple(tag_keys)
        self._default_tags: Dict[str, str] = {}
        self._lock = threading.Lock()
        self._series: Dict[_TagKey, float] = {}
        existing = GLOBAL_REGISTRY.register(self)
        if existing is not None:
            self._adopt(existing)

    def _adopt(self, existing: "Metric"):
        """Share state with the registry's canonical instance: increments
        on this (re-constructed) metric land in the existing series."""
        self._lock = existing._lock
        self._series = existing._series

    def set_default_tags(self, tags: Dict[str, str]) -> "Metric":
        self._default_tags = dict(tags)
        return self

    def _snapshot(self) -> dict:
        with self._lock:
            return {"name": self.name, "kind": self.kind,
                    "description": self.description,
                    "series": [(list(k), v) for k, v in self._series.items()]}


class Counter(Metric):
    """Monotonically increasing count (reference metrics.Counter)."""

    kind = "counter"

    def inc(self, value: float = 1.0, tags: Optional[Dict[str, str]] = None):
        if value < 0:
            raise ValueError("Counter.inc value must be >= 0")
        key = _tag_tuple(tags, self._default_tags)
        with self._lock:
            self._series[key] = self._series.get(key, 0.0) + value


class Gauge(Metric):
    """Point-in-time value (reference metrics.Gauge)."""

    kind = "gauge"

    def set(self, value: float, tags: Optional[Dict[str, str]] = None):
        key = _tag_tuple(tags, self._default_tags)
        with self._lock:
            self._series[key] = float(value)


class Histogram(Metric):
    """Bucketed distribution (reference metrics.Histogram): cumulative
    bucket counts + sum + count, Prometheus-style."""

    kind = "histogram"

    def __init__(self, name: str, description: str = "",
                 boundaries: Sequence[float] = (), tag_keys: Sequence[str] = ()):
        if not boundaries or list(boundaries) != sorted(boundaries):
            raise ValueError("Histogram needs sorted, non-empty boundaries")
        self.boundaries = tuple(float(b) for b in boundaries)
        # Before register (in super().__init__) — the flusher thread may
        # snapshot the registry the instant the metric appears in it.
        self._hist: Dict[_TagKey, dict] = {}
        super().__init__(name, description, tag_keys)

    def _adopt(self, existing: "Metric"):
        if getattr(existing, "boundaries", None) != self.boundaries:
            raise ValueError(
                f"histogram {self.name!r} already registered with "
                f"boundaries {existing.boundaries}")
        super()._adopt(existing)
        self._hist = existing._hist

    def observe(self, value: float, tags: Optional[Dict[str, str]] = None):
        key = _tag_tuple(tags, self._default_tags)
        with self._lock:
            h = self._hist.get(key)
            if h is None:
                h = self._hist[key] = {
                    "buckets": [0] * (len(self.boundaries) + 1),
                    "sum": 0.0, "count": 0}
            idx = len(self.boundaries)
            for i, b in enumerate(self.boundaries):
                if value <= b:
                    idx = i
                    break
            h["buckets"][idx] += 1
            h["sum"] += value
            h["count"] += 1

    def _snapshot(self) -> dict:
        with self._lock:
            return {"name": self.name, "kind": self.kind,
                    "description": self.description,
                    "boundaries": list(self.boundaries),
                    "series": [(list(k), dict(v, buckets=list(v["buckets"])))
                               for k, v in self._hist.items()]}


# --------------------------------------------------------------------------- #
# Prometheus text exposition (rendered GCS-side from aggregated snapshots)
# --------------------------------------------------------------------------- #


def _fmt_tags(pairs: List) -> str:
    if not pairs:
        return ""
    inner = ",".join(f'{k}="{v}"' for k, v in pairs)
    return "{" + inner + "}"


def render_prometheus(snapshots: Dict[str, List[dict]]) -> str:
    """snapshots: reporter id -> list of metric snapshot dicts. Series from
    different reporters get a `proc` tag so they never collide."""
    by_name: Dict[str, List[Tuple[str, dict]]] = {}
    for proc, metrics in snapshots.items():
        for m in metrics:
            by_name.setdefault(m["name"], []).append((proc, m))
    out: List[str] = []
    for name in sorted(by_name):
        entries = by_name[name]
        kind = entries[0][1]["kind"]
        desc = entries[0][1]["description"]
        prom = name.replace(".", "_").replace("-", "_")
        if desc:
            out.append(f"# HELP {prom} {desc}")
        out.append(f"# TYPE {prom} "
                   f"{'histogram' if kind == 'histogram' else kind}")
        for proc, m in entries:
            for pairs, value in m["series"]:
                tags = list(pairs) + [("proc", proc)]
                if kind == "histogram":
                    bounds = m["boundaries"]
                    cum = 0
                    for i, b in enumerate(bounds):
                        cum += value["buckets"][i]
                        out.append(f"{prom}_bucket"
                                   f"{_fmt_tags(tags + [('le', b)])} {cum}")
                    total = cum + value["buckets"][len(bounds)]
                    out.append(f"{prom}_bucket"
                               f"{_fmt_tags(tags + [('le', '+Inf')])} {total}")
                    out.append(f"{prom}_sum{_fmt_tags(tags)} {value['sum']}")
                    out.append(f"{prom}_count{_fmt_tags(tags)} "
                               f"{value['count']}")
                else:
                    out.append(f"{prom}{_fmt_tags(tags)} {value}")
    return "\n".join(out) + "\n"


# --------------------------------------------------------------------------- #
# Background flusher: pushes this process's registry to the GCS
# --------------------------------------------------------------------------- #


class MetricsPusher:
    """Flushes this process's metric registry AND its tracing flight
    recorder to the GCS on one cadence (one RPC carries both — the
    tracing plane piggybacks here instead of adding its own thread).

    `node` is the owning node's hex id when known: the GCS uses it to
    expire this reporter's snapshot the moment the node dies, instead of
    serving a ghost series from /metrics forever."""

    def __init__(self, gcs_client, reporter_id: str, period_s: float = 2.0,
                 node: "Optional[str]" = None):
        self._gcs = gcs_client
        self._id = reporter_id
        self._period = period_s
        self._node = node
        self._stop = threading.Event()
        self._thread = threading.Thread(target=self._loop,
                                        name="metrics-push", daemon=True)

    def start(self):
        self._thread.start()

    def _loop(self):
        while not self._stop.wait(self._period):
            self.flush()

    def flush(self):
        from ray_tpu.observability import tracing

        spans, dropped = tracing.drain_for_flush()
        try:
            snap = GLOBAL_REGISTRY.snapshot()
            if not snap and not spans and not dropped:
                return
            payload = {"reporter": self._id, "metrics": snap,
                       "ts": time.time(), "period_s": self._period,
                       "node": self._node}
            if spans or dropped:
                payload["spans"] = spans
                payload["spans_dropped"] = dropped
            self._gcs.call("metrics_report", payload, timeout=5)
        except Exception:  # noqa: BLE001 — metrics are best-effort, and a
            # single bad snapshot must not kill the flusher thread.
            # Metrics re-snapshot next period, but the DRAINED spans
            # would be gone: put them (and their drop count) back so a
            # GCS hiccup delays trace delivery instead of losing it.
            tracing.RECORDER.restore(spans, dropped)

    def stop(self):
        self._stop.set()
        self.flush()
