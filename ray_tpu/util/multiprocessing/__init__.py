"""Drop-in `multiprocessing.Pool` on top of ray_tpu tasks.

Equivalent of the reference's `python/ray/util/multiprocessing/pool.py:520`:
the same Pool surface (apply/apply_async/map/map_async/starmap/imap/
imap_unordered, context manager, close/terminate/join), with work units
submitted as framework tasks so a pool transparently spans every node in
the cluster instead of one host's forks.
"""

from ray_tpu.util.multiprocessing.pool import AsyncResult, Pool

__all__ = ["Pool", "AsyncResult"]
