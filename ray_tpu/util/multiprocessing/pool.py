"""Pool implementation: chunked, windowed task submission over the core.

`processes` really bounds concurrency: every Pool method pushes its chunk
tasks through a window of at most `processes` unresolved refs (submit as
slots free), so a Pool(2) over an 8-CPU cluster runs 2 chunks at a time —
the contract callers limiting a rate-limited API or memory-heavy fn rely
on.
"""

from __future__ import annotations

import multiprocessing
import threading
from typing import Any, Callable, Iterable, Iterator, List, Optional


def _prime(thunks: List[Callable[[], Any]], slots) -> tuple:
    """Submit as many thunks as free slots allow; returns (inflight, i)."""
    inflight = {}
    i = 0
    while i < len(thunks) and slots.acquire(blocking=False):
        inflight[thunks[i]()] = i
        i += 1
    return inflight, i


class _WindowedIter:
    """Iterator over thunk results bounded by the POOL-wide slot semaphore
    (shared across concurrent map/imap calls, like multiprocessing.Pool's
    fixed worker count); yields (index, value_or_exception) in COMPLETION
    order. A real object rather than a generator so eagerly-primed slots
    are released even if the caller never iterates (__del__/close)."""

    def __init__(self, thunks: List[Callable[[], Any]], slots,
                 primed: tuple = None):
        self._thunks = thunks
        self._slots = slots
        self._inflight, self._i = primed if primed is not None \
            else _prime(thunks, slots)
        self._closed = False

    def __iter__(self):
        return self

    def __next__(self):
        import ray_tpu

        thunks, slots = self._thunks, self._slots
        if self._closed or (self._i >= len(thunks) and not self._inflight):
            self.close()
            raise StopIteration
        while self._i < len(thunks) and slots.acquire(blocking=False):
            self._inflight[thunks[self._i]()] = self._i
            self._i += 1
        if not self._inflight:
            # Another call holds every slot: block for one.
            slots.acquire()
            self._inflight[thunks[self._i]()] = self._i
            self._i += 1
        ready, _ = ray_tpu.wait(list(self._inflight), num_returns=1)
        idx = self._inflight.pop(ready[0])
        slots.release()
        try:
            return idx, ray_tpu.get(ready[0])
        except BaseException as e:  # noqa: BLE001 — delivered to caller
            return idx, _Failure(e)

    def close(self):
        if not self._closed:
            self._closed = True
            inflight, self._inflight = self._inflight, {}
            if inflight:
                # The abandoned chunks are still executing remotely: their
                # slots free only as each chunk resolves, so the pool-wide
                # `processes` bound holds even across discarded iterators.
                slots = self._slots

                def reap(refs=list(inflight)):
                    import ray_tpu

                    while refs:
                        ready, refs = ray_tpu.wait(refs, num_returns=1)
                        for _ in ready:
                            slots.release()

                threading.Thread(target=reap, daemon=True).start()

    def __del__(self):
        self.close()


def _windowed(thunks: List[Callable[[], Any]], slots,
              primed: tuple = None) -> Iterator[tuple]:
    return _WindowedIter(thunks, slots, primed)


class _Failure:
    def __init__(self, error: BaseException):
        self.error = error


class AsyncResult:
    """Handle for apply_async/map_async (mirrors multiprocessing's)."""

    def __init__(self, thunks: List[Callable[[], Any]], single: bool,
                 slots, callback: Optional[Callable] = None,
                 error_callback: Optional[Callable] = None):
        self._thunks = thunks
        self._single = single
        self._slots = slots
        self._callback = callback
        self._error_callback = error_callback
        self._value: Any = None
        self._error: Optional[BaseException] = None
        self._done = threading.Event()
        threading.Thread(target=self._collect, daemon=True).start()

    def _collect(self):
        try:
            chunks: List[Any] = [None] * len(self._thunks)
            for idx, val in _windowed(self._thunks, self._slots):
                if isinstance(val, _Failure):
                    raise val.error
                chunks[idx] = val
            out: List[Any] = []
            for chunk in chunks:
                out.extend(chunk)
            self._value = out[0] if self._single else out
            if self._callback is not None:
                try:
                    self._callback(self._value)
                except Exception:  # noqa: BLE001 — user callback
                    pass
        except BaseException as e:  # noqa: BLE001
            self._error = e
            if self._error_callback is not None:
                try:
                    self._error_callback(e)
                except Exception:  # noqa: BLE001
                    pass
        finally:
            self._done.set()

    def get(self, timeout: Optional[float] = None):
        if not self._done.wait(timeout):
            # The drop-in contract: multiprocessing.TimeoutError (a
            # ProcessError subclass), not the builtin.
            raise multiprocessing.TimeoutError("result not ready")
        if self._error is not None:
            raise self._error
        return self._value

    def wait(self, timeout: Optional[float] = None):
        self._done.wait(timeout)

    def ready(self) -> bool:
        return self._done.is_set()

    def successful(self) -> bool:
        if not self._done.is_set():
            raise ValueError("result not ready")
        return self._error is None


def _run_chunk(fn, chunk, mode):
    if mode == "star":
        return [fn(*args) for args in chunk]
    if mode == "call":
        return [fn(*args, **kwds) for args, kwds in chunk]
    return [fn(x) for x in chunk]


class Pool:
    """Task-backed process pool spanning the cluster; at most `processes`
    chunk tasks run concurrently."""

    def __init__(self, processes: Optional[int] = None,
                 initializer: Optional[Callable] = None,
                 initargs: tuple = (), ray_address: Optional[str] = None):
        import ray_tpu

        if not ray_tpu.is_initialized():
            ray_tpu.init(address=ray_address)
        if processes is None:
            processes = max(1, int(ray_tpu.cluster_resources().get("CPU", 1)))
        self._processes = processes
        self._slots = threading.Semaphore(processes)  # pool-wide window
        self._closed = False
        # Pools don't own workers, so the initializer runs prepended to
        # every chunk's task (cheap; mirrors reference semantics closely
        # enough for setup-style initializers).
        self._initializer = initializer
        self._initargs = initargs
        self._results: List[AsyncResult] = []
        self._remote_chunk = ray_tpu.remote(self._make_runner())

    def _make_runner(self):
        initializer, initargs = self._initializer, self._initargs

        def run_chunk(fn, chunk, mode):
            if initializer is not None:
                initializer(*initargs)
            return _run_chunk(fn, chunk, mode)

        return run_chunk

    # ------------------------------------------------------------------ api

    def _check_open(self):
        if self._closed:
            raise ValueError("Pool not running")

    def _chunks(self, iterable: Iterable, chunksize: Optional[int]
                ) -> List[list]:
        items = list(iterable)
        if chunksize is None:
            chunksize = max(1, len(items) // (self._processes * 4) or 1)
        return [items[i:i + chunksize]
                for i in range(0, len(items), chunksize)]

    def _thunks(self, fn, chunks: List[list], mode: str
                ) -> List[Callable[[], Any]]:
        return [
            (lambda c=c: self._remote_chunk.remote(fn, c, mode))
            for c in chunks
        ]

    def apply(self, fn: Callable, args: tuple = (),
              kwds: Optional[dict] = None):
        return self.apply_async(fn, args, kwds).get()

    def apply_async(self, fn: Callable, args: tuple = (),
                    kwds: Optional[dict] = None,
                    callback: Optional[Callable] = None,
                    error_callback: Optional[Callable] = None) -> AsyncResult:
        self._check_open()
        thunks = self._thunks(fn, [[(tuple(args), kwds or {})]], "call")
        res = AsyncResult(thunks, single=True, slots=self._slots,
                          callback=callback, error_callback=error_callback)
        self._track(res)
        return res

    def map(self, fn: Callable, iterable: Iterable,
            chunksize: Optional[int] = None) -> List[Any]:
        return self.map_async(fn, iterable, chunksize).get()

    def map_async(self, fn: Callable, iterable: Iterable,
                  chunksize: Optional[int] = None,
                  callback: Optional[Callable] = None,
                  error_callback: Optional[Callable] = None) -> AsyncResult:
        self._check_open()
        thunks = self._thunks(fn, self._chunks(iterable, chunksize), "map")
        res = AsyncResult(thunks, single=False, slots=self._slots,
                          callback=callback, error_callback=error_callback)
        self._track(res)
        return res

    def _track(self, res: AsyncResult):
        # join() waits on outstanding results; prune finished ones here so
        # a long-lived pool doesn't pin every past map()'s materialized
        # values until close().
        self._results = [r for r in self._results if not r.ready()]
        self._results.append(res)

    def starmap(self, fn: Callable, iterable: Iterable[tuple],
                chunksize: Optional[int] = None) -> List[Any]:
        self._check_open()
        thunks = self._thunks(fn, self._chunks(iterable, chunksize), "star")
        return AsyncResult(thunks, single=False,
                           slots=self._slots).get()

    def imap(self, fn: Callable, iterable: Iterable,
             chunksize: int = 1) -> Iterator[Any]:
        """Ordered lazy iteration; windowed submission."""
        self._check_open()
        thunks = self._thunks(fn, self._chunks(iterable, chunksize), "map")
        # Work starts NOW, not at first next() (mp semantics); the iterator
        # object owns the primed slots, so discarding it releases them.
        win = _windowed(thunks, self._slots, _prime(thunks, self._slots))

        def gen():
            buffered = {}
            emit = 0
            for idx, val in win:
                if isinstance(val, _Failure):
                    raise val.error
                buffered[idx] = val
                while emit in buffered:
                    for v in buffered.pop(emit):
                        yield v
                    emit += 1

        return gen()

    def imap_unordered(self, fn: Callable, iterable: Iterable,
                       chunksize: int = 1) -> Iterator[Any]:
        """Completion-order iteration; windowed submission."""
        self._check_open()
        thunks = self._thunks(fn, self._chunks(iterable, chunksize), "map")
        win = _windowed(thunks, self._slots, _prime(thunks, self._slots))

        def gen():
            for _idx, val in win:
                if isinstance(val, _Failure):
                    raise val.error
                for v in val:
                    yield v

        return gen()

    # -------------------------------------------------------------- lifecycle

    def close(self):
        self._closed = True

    def terminate(self):
        self._closed = True

    def join(self):
        if not self._closed:
            raise ValueError("Pool is still running")
        for res in self._results:
            res.wait()
        self._results = []

    def __enter__(self) -> "Pool":
        return self

    def __exit__(self, *exc):
        self.terminate()
