"""Pool implementation: chunked task submission over the core runtime."""

from __future__ import annotations

import itertools
import threading
from typing import Any, Callable, Iterable, Iterator, List, Optional


class AsyncResult:
    """Handle for apply_async/map_async (mirrors multiprocessing's)."""

    def __init__(self, refs: List[Any], single: bool,
                 callback: Optional[Callable] = None,
                 error_callback: Optional[Callable] = None):
        self._refs = refs
        self._single = single
        self._callback = callback
        self._error_callback = error_callback
        self._value: Any = None
        self._error: Optional[BaseException] = None
        self._done = threading.Event()
        threading.Thread(target=self._collect, daemon=True).start()

    def _collect(self):
        import ray_tpu

        try:
            values = ray_tpu.get(self._refs)
            out: List[Any] = []
            for chunk in values:
                out.extend(chunk)
            self._value = out[0] if self._single else out
            if self._callback is not None:
                try:
                    self._callback(self._value)
                except Exception:  # noqa: BLE001 — user callback
                    pass
        except BaseException as e:  # noqa: BLE001
            self._error = e
            if self._error_callback is not None:
                try:
                    self._error_callback(e)
                except Exception:  # noqa: BLE001
                    pass
        finally:
            self._done.set()

    def get(self, timeout: Optional[float] = None):
        if not self._done.wait(timeout):
            raise TimeoutError("result not ready")
        if self._error is not None:
            raise self._error
        return self._value

    def wait(self, timeout: Optional[float] = None):
        self._done.wait(timeout)

    def ready(self) -> bool:
        return self._done.is_set()

    def successful(self) -> bool:
        if not self._done.is_set():
            raise ValueError("result not ready")
        return self._error is None


def _run_chunk(fn, chunk, mode):
    if mode == "star":
        return [fn(*args) for args in chunk]
    if mode == "call":
        return [fn(*args, **kwds) for args, kwds in chunk]
    return [fn(x) for x in chunk]


class Pool:
    """Task-backed process pool: `processes` bounds concurrency via the
    scheduler's CPU accounting, not a fixed set of forked children."""

    def __init__(self, processes: Optional[int] = None,
                 initializer: Optional[Callable] = None,
                 initargs: tuple = (), ray_address: Optional[str] = None):
        import ray_tpu

        if not ray_tpu.is_initialized():
            ray_tpu.init(address=ray_address)
        if processes is None:
            processes = max(1, int(ray_tpu.cluster_resources().get("CPU", 1)))
        self._processes = processes
        self._closed = False
        # Pools don't own workers, so the initializer runs prepended to
        # every chunk's task (cheap; mirrors reference semantics closely
        # enough for setup-style initializers).
        self._initializer = initializer
        self._initargs = initargs
        self._remote_chunk = ray_tpu.remote(self._make_runner())

    def _make_runner(self):
        initializer, initargs = self._initializer, self._initargs

        def run_chunk(fn, chunk, mode):
            if initializer is not None:
                initializer(*initargs)
            return _run_chunk(fn, chunk, mode)

        return run_chunk

    # ------------------------------------------------------------------ api

    def _check_open(self):
        if self._closed:
            raise ValueError("Pool not running")

    def _chunks(self, iterable: Iterable, chunksize: Optional[int]
                ) -> List[list]:
        items = list(iterable)
        if chunksize is None:
            chunksize = max(1, len(items) // (self._processes * 4) or 1)
        return [items[i:i + chunksize]
                for i in range(0, len(items), chunksize)]

    def apply(self, fn: Callable, args: tuple = (), kwds: Optional[dict] = None):
        return self.apply_async(fn, args, kwds).get()

    def apply_async(self, fn: Callable, args: tuple = (),
                    kwds: Optional[dict] = None,
                    callback: Optional[Callable] = None,
                    error_callback: Optional[Callable] = None) -> AsyncResult:
        self._check_open()
        ref = self._remote_chunk.remote(fn, [(tuple(args), kwds or {})],
                                        "call")
        return AsyncResult([ref], single=True, callback=callback,
                           error_callback=error_callback)

    def map(self, fn: Callable, iterable: Iterable,
            chunksize: Optional[int] = None) -> List[Any]:
        return self.map_async(fn, iterable, chunksize).get()

    def map_async(self, fn: Callable, iterable: Iterable,
                  chunksize: Optional[int] = None,
                  callback: Optional[Callable] = None,
                  error_callback: Optional[Callable] = None) -> AsyncResult:
        self._check_open()
        refs = [self._remote_chunk.remote(fn, c, "map")
                for c in self._chunks(iterable, chunksize)]
        return AsyncResult(refs, single=False, callback=callback,
                           error_callback=error_callback)

    def starmap(self, fn: Callable, iterable: Iterable[tuple],
                chunksize: Optional[int] = None) -> List[Any]:
        self._check_open()
        refs = [self._remote_chunk.remote(fn, c, "star")
                for c in self._chunks(iterable, chunksize)]
        return AsyncResult(refs, single=False).get()

    def imap(self, fn: Callable, iterable: Iterable,
             chunksize: int = 1) -> Iterator[Any]:
        """Ordered lazy iteration; chunks resolve as they finish."""
        self._check_open()
        import ray_tpu

        refs = [self._remote_chunk.remote(fn, c, "map")
                for c in self._chunks(iterable, chunksize)]

        def gen():
            for ref in refs:
                for v in ray_tpu.get(ref):
                    yield v

        return gen()

    def imap_unordered(self, fn: Callable, iterable: Iterable,
                       chunksize: int = 1) -> Iterator[Any]:
        """Completion-order iteration."""
        self._check_open()
        import ray_tpu

        refs = [self._remote_chunk.remote(fn, c, "map")
                for c in self._chunks(iterable, chunksize)]

        def gen():
            pending = list(refs)
            while pending:
                ready, pending = ray_tpu.wait(pending, num_returns=1)
                for v in ray_tpu.get(ready[0]):
                    yield v

        return gen()

    # -------------------------------------------------------------- lifecycle

    def close(self):
        self._closed = True

    def terminate(self):
        self._closed = True

    def join(self):
        if not self._closed:
            raise ValueError("Pool is still running")

    def __enter__(self) -> "Pool":
        return self

    def __exit__(self, *exc):
        self.terminate()
