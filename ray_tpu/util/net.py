"""Network address helpers shared by the CLI, debugger, and backends."""

from __future__ import annotations

import socket


def primary_ip() -> str:
    """This machine's primary interface IP — the address peers can dial.

    UDP-connect route lookup (no packet is sent), with hostname-resolution
    and loopback fallbacks for isolated machines.
    """
    try:
        s = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
        try:
            s.connect(("8.8.8.8", 80))
            return s.getsockname()[0]
        finally:
            s.close()
    except OSError:
        try:
            return socket.gethostbyname(socket.gethostname())
        except OSError:
            return "127.0.0.1"
