"""Placement groups: atomic gang reservation of resource bundles.

Equivalent of `python/ray/util/placement_group.py` (:34 `PlacementGroup`,
:137 `placement_group()`): bundles are reserved across raylets via the GCS
prepare/commit 2PC and become `{resource}_group_{index}_{pgid}` resources
tasks/actors consume through `PlacementGroupSchedulingStrategy`.

TPU note: a bundle of `{"TPU": 4}` is one TPU host; a STRICT_SPREAD group of
N such bundles is a pod slice's host set — the unit JaxBackend builds its
`jax.distributed` process group over.
"""

from __future__ import annotations

import time
from typing import Dict, List, Optional

from ray_tpu.core.common import PlacementGroupInfo, PlacementStrategy
from ray_tpu.core.ids import PlacementGroupID
from ray_tpu.exceptions import GetTimeoutError, PlacementGroupUnschedulableError


class PlacementGroup:
    def __init__(self, pg_id: PlacementGroupID, bundles: List[Dict[str, float]],
                 strategy: str):
        self.id = pg_id
        self.bundles = bundles
        self.strategy = strategy
        self._bundle_nodes: Optional[Dict[int, str]] = None

    def _fetch(self):
        import ray_tpu

        runtime = ray_tpu._require_runtime()
        return runtime.gcs.call("get_placement_group", {"pg_id": self.id})

    def ready(self, timeout: float = 60.0) -> "PlacementGroup":
        """Block until all bundles are committed."""
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            info = self._fetch()
            if info.get("known"):
                if info["state"] == "CREATED":
                    self._bundle_nodes = {
                        i: n.hex() for i, n in info["bundle_locations"].items()}
                    return self
                if info["state"] in ("INFEASIBLE", "REMOVED"):
                    raise PlacementGroupUnschedulableError(
                        f"placement group {self.id.hex()[:12]} is {info['state']}")
            time.sleep(0.05)
        raise GetTimeoutError(
            f"placement group {self.id.hex()[:12]} not ready in {timeout}s")

    def wait(self, timeout_seconds: float = 60.0) -> bool:
        try:
            self.ready(timeout=timeout_seconds)
            return True
        except (GetTimeoutError, PlacementGroupUnschedulableError):
            return False

    def _bundle_node_hex(self, index: int) -> str:
        if self._bundle_nodes is None:
            self.ready()
        if index < 0:
            # Wildcard: any bundle's node; pick bundle 0's for affinity.
            return self._bundle_nodes[min(self._bundle_nodes)]
        return self._bundle_nodes[index]

    @property
    def bundle_specs(self) -> List[Dict[str, float]]:
        return self.bundles

    def __reduce__(self):
        return (PlacementGroup, (self.id, self.bundles, self.strategy))


def placement_group(bundles: List[Dict[str, float]], strategy: str = "PACK",
                    name: Optional[str] = None,
                    lifetime: Optional[str] = None) -> PlacementGroup:
    import ray_tpu

    runtime = ray_tpu._require_runtime()
    if not bundles or any(not b for b in bundles):
        raise ValueError("bundles must be a non-empty list of non-empty dicts")
    pg_id = PlacementGroupID.of(runtime.job_id)
    info = PlacementGroupInfo(
        pg_id=pg_id,
        bundles=[{k: float(v) for k, v in b.items()} for b in bundles],
        strategy=PlacementStrategy(strategy),
        name=name,
        job_id=runtime.job_id,
        lifetime=lifetime,
    )
    runtime.gcs.call("create_placement_group", {"pg": info})
    return PlacementGroup(pg_id, info.bundles, strategy)


def remove_placement_group(pg: PlacementGroup):
    import ray_tpu

    runtime = ray_tpu._require_runtime()
    runtime.gcs.call("remove_placement_group", {"pg_id": pg.id})


def get_placement_group(name: str) -> PlacementGroup:
    """Look up a live placement group by name (reference
    `ray.util.get_placement_group`)."""
    import ray_tpu

    runtime = ray_tpu._require_runtime()
    resp = runtime.gcs.call("get_named_placement_group", {"name": name})
    if not resp.get("found"):
        raise ValueError(f"Failed to look up placement group {name!r}. "
                         "It was either not created or was removed.")
    return PlacementGroup(resp["pg_id"], resp["bundles"], resp["strategy"])


def placement_group_table(pg: Optional[PlacementGroup] = None):
    import ray_tpu

    runtime = ray_tpu._require_runtime()
    if pg is not None:
        info = runtime.gcs.call("get_placement_group", {"pg_id": pg.id})
        return {pg.id.hex(): info}
    return {}
