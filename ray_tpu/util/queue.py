"""Distributed FIFO queue backed by an actor.

Equivalent of `python/ray/util/queue.py:20` (`Queue` over `_QueueActor`).
"""

from __future__ import annotations

import asyncio
from typing import Any, List, Optional


class Empty(Exception):
    pass


class Full(Exception):
    pass


class _QueueActor:
    def __init__(self, maxsize: int = 0):
        self.q: asyncio.Queue = asyncio.Queue(maxsize=maxsize)

    async def put(self, item, timeout: Optional[float] = None):
        try:
            await asyncio.wait_for(self.q.put(item), timeout)
            return True
        except asyncio.TimeoutError:
            return False

    async def get(self, timeout: Optional[float] = None):
        try:
            return True, await asyncio.wait_for(self.q.get(), timeout)
        except asyncio.TimeoutError:
            return False, None

    async def put_nowait(self, item):
        try:
            self.q.put_nowait(item)
            return True
        except asyncio.QueueFull:
            return False

    async def get_nowait(self):
        try:
            return True, self.q.get_nowait()
        except asyncio.QueueEmpty:
            return False, None

    async def qsize(self):
        return self.q.qsize()

    async def empty(self):
        return self.q.empty()

    async def full(self):
        return self.q.full()


class Queue:
    def __init__(self, maxsize: int = 0, actor_options: Optional[dict] = None):
        import ray_tpu

        opts = dict(actor_options or {})
        opts.setdefault("max_concurrency", 64)
        opts.setdefault("num_cpus", 0.1)
        self.actor = ray_tpu.remote(_QueueActor).options(**opts).remote(maxsize)

    def put(self, item: Any, block: bool = True, timeout: Optional[float] = None):
        import ray_tpu

        if not block:
            if not ray_tpu.get(self.actor.put_nowait.remote(item)):
                raise Full()
            return
        if not ray_tpu.get(self.actor.put.remote(item, timeout)):
            raise Full()

    def get(self, block: bool = True, timeout: Optional[float] = None) -> Any:
        import ray_tpu

        if not block:
            ok, item = ray_tpu.get(self.actor.get_nowait.remote())
        else:
            ok, item = ray_tpu.get(self.actor.get.remote(timeout))
        if not ok:
            raise Empty()
        return item

    def put_nowait(self, item: Any):
        self.put(item, block=False)

    def get_nowait(self) -> Any:
        return self.get(block=False)

    def qsize(self) -> int:
        import ray_tpu

        return ray_tpu.get(self.actor.qsize.remote())

    def empty(self) -> bool:
        import ray_tpu

        return ray_tpu.get(self.actor.empty.remote())

    def full(self) -> bool:
        import ray_tpu

        return ray_tpu.get(self.actor.full.remote())

    def put_async(self, item: Any):
        return self.actor.put.remote(item, None)

    def get_async(self):
        return self.actor.get.remote(None)

    def shutdown(self):
        import ray_tpu

        ray_tpu.kill(self.actor)
