"""Remote pdb: breakpoints inside tasks/actors, attached from the CLI.

Equivalent of the reference's `python/ray/util/rpdb.py` (`ray debug`): a
worker hitting `set_trace()` opens a TCP listener, advertises itself in
the GCS KV, and blocks in a socket-backed Pdb until a debugger client
attaches (`python -m ray_tpu debug`) or the wait times out. Post-mortem
via `post_mortem()` in an except block.
"""

from __future__ import annotations

import json
import os
import pdb
import socket
import sys
import time
from typing import Any, Dict, List, Optional

_KV_PREFIX = "__rpdb__:"


class _SocketIO:
    """File-like stdin/stdout over one accepted connection."""

    def __init__(self, conn: socket.socket):
        self._conn = conn
        self._rfile = conn.makefile("r")
        self._wfile = conn.makefile("w")

    def readline(self):
        return self._rfile.readline()

    def read(self, n):
        return self._rfile.read(n)

    def write(self, data):
        self._wfile.write(data)
        return len(data)

    def flush(self):
        try:
            self._wfile.flush()
        except Exception:  # noqa: BLE001 — client went away mid-session
            pass

    def close(self):
        for f in (self._rfile, self._wfile, self._conn):
            try:
                f.close()
            except Exception:  # noqa: BLE001
                pass


class _RemotePdb(pdb.Pdb):
    def __init__(self, io: _SocketIO):
        super().__init__(stdin=io, stdout=io)
        self.use_rawinput = False
        self.prompt = "(ray_tpu-pdb) "
        self._io = io

    # continue/quit end the remote session: stop tracing BEFORE control
    # returns to the worker (otherwise the next traced call lands the
    # debugger inside this module's own cleanup code).
    def do_continue(self, arg):
        self.clear_all_breaks()
        self.set_continue()
        self._io.close()
        return 1

    do_c = do_cont = do_continue

    def do_quit(self, arg):
        self.clear_all_breaks()
        self.set_continue()  # quit must not kill the worker: continue
        self._io.close()
        return 1

    do_q = do_exit = do_quit

    def do_EOF(self, arg):
        # Client disconnected: detach-and-continue. The inherited handler
        # would raise BdbQuit into the traced task, killing it.
        return self.do_continue(arg)


def _bind_host() -> str:
    """Debugger listeners bind localhost by default: an attached pdb is
    arbitrary code execution, so exposing it beyond the node requires the
    explicit ``RAY_TPU_DEBUGGER_EXTERNAL=1`` opt-in (mirroring the
    reference's RAY_DEBUGGER_EXTERNAL, `python/ray/util/rpdb.py`)."""
    if os.environ.get("RAY_TPU_DEBUGGER_EXTERNAL", "") in ("1", "true"):
        return "0.0.0.0"
    return "127.0.0.1"


def _node_ip() -> str:
    """This node's address as seen by the rest of the cluster: the raylet
    address workers were launched with, else a best-effort local IP."""
    addr = os.environ.get("RAY_TPU_RAYLET_ADDRESS", "")
    if ":" in addr:
        host = addr.rsplit(":", 1)[0]
        if host not in ("", "0.0.0.0"):
            return host
    from ray_tpu.util.net import primary_ip

    return primary_ip()


def _advertise(entry: Dict[str, Any]) -> Optional[str]:
    try:
        import ray_tpu

        runtime = ray_tpu._require_runtime()
        key = f"{_KV_PREFIX}{entry['id']}"
        runtime.gcs.call("kv_put", {"key": key,
                                    "value": json.dumps(entry).encode()})
        return key
    except Exception:  # noqa: BLE001 — no cluster: local-only breakpoint
        return None


def _unadvertise(key: Optional[str]) -> None:
    if key is None:
        return
    try:
        import ray_tpu

        ray_tpu._require_runtime().gcs.call("kv_del", {"key": key})
    except Exception:  # noqa: BLE001
        pass


def set_trace(frame=None, timeout_s: float = 300.0):
    """Block in a remote pdb session at the caller's frame.

    Advertises `host:port` in the GCS KV so `python -m ray_tpu debug` can
    list and attach; gives up (and continues execution) after `timeout_s`
    with no client.
    """
    listener = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
    listener.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
    bind_host = _bind_host()
    listener.bind((bind_host, 0))
    listener.listen(1)
    port = listener.getsockname()[1]
    host = _node_ip() if bind_host == "0.0.0.0" else "127.0.0.1"
    frame = frame or sys._getframe().f_back
    entry = {
        "id": f"{os.getpid()}-{port}",
        "host": host,
        "port": port,
        "pid": os.getpid(),
        "filename": frame.f_code.co_filename,
        "lineno": frame.f_lineno,
        "function": frame.f_code.co_name,
        "ts": time.time(),
    }
    key = _advertise(entry)
    print(f"ray_tpu debugger waiting on {host}:{port} "
          f"({entry['filename']}:{entry['lineno']}) — attach with "
          "`python -m ray_tpu debug`", file=sys.stderr, flush=True)
    listener.settimeout(timeout_s)
    try:
        conn, _ = listener.accept()
    except socket.timeout:
        print("ray_tpu debugger: no client attached; continuing",
              file=sys.stderr)
        return
    finally:
        _unadvertise(key)
        listener.close()
    io = _SocketIO(conn)
    # Last statement on purpose: the first trace event after this call
    # must land in the CALLER's frame, not in cleanup code here.
    _RemotePdb(io).set_trace(frame)


def post_mortem(tb=None, timeout_s: float = 300.0):
    """Debug an exception's traceback remotely (call in an except block)."""
    if tb is None:
        tb = sys.exc_info()[2]
    if tb is None:
        raise ValueError("no traceback to debug")
    listener = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
    listener.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
    bind_host = _bind_host()
    listener.bind((bind_host, 0))
    listener.listen(1)
    port = listener.getsockname()[1]
    host = _node_ip() if bind_host == "0.0.0.0" else "127.0.0.1"
    entry = {"id": f"{os.getpid()}-{port}", "host": host, "port": port,
             "pid": os.getpid(), "filename": "<post-mortem>", "lineno": 0,
             "function": "post_mortem", "ts": time.time()}
    key = _advertise(entry)
    print(f"ray_tpu post-mortem waiting on {host}:{port}",
          file=sys.stderr, flush=True)
    listener.settimeout(timeout_s)
    try:
        conn, _ = listener.accept()
    except socket.timeout:
        return
    finally:
        _unadvertise(key)
        listener.close()
    io = _SocketIO(conn)
    try:
        _RemotePdb(io).interaction(None, tb)
    finally:
        io.close()


# --------------------------------------------------------------------------- #
# Client side (CLI)
# --------------------------------------------------------------------------- #


def list_breakpoints() -> List[Dict[str, Any]]:
    import ray_tpu

    runtime = ray_tpu._require_runtime()
    keys = runtime.gcs.call("kv_keys", {"prefix": _KV_PREFIX})["keys"]
    out = []
    for k in keys:
        try:
            v = runtime.gcs.call("kv_get", {"key": k})["value"]
            if v:
                out.append(json.loads(v))
        except Exception:  # noqa: BLE001
            pass
    return sorted(out, key=lambda e: e["ts"])


def attach(entry: Dict[str, Any], stdin=None, stdout=None) -> None:
    """Bridge this terminal to the advertised pdb session."""
    import threading

    stdin = stdin or sys.stdin
    stdout = stdout or sys.stdout
    conn = socket.create_connection((entry["host"], entry["port"]),
                                    timeout=10)

    def pump_out():
        # Byte-wise pump: the pdb prompt has no trailing newline, so a
        # line-based reader would never show it to an interactive user.
        while True:
            try:
                data = conn.recv(4096)
            except OSError:
                break
            if not data:
                break
            stdout.write(data.decode(errors="replace"))
            stdout.flush()

    t = threading.Thread(target=pump_out, daemon=True)
    t.start()
    session_ended = False
    try:
        for line in stdin:
            try:
                conn.sendall(line.encode() if isinstance(line, str)
                             else line)
            except OSError:  # server ended the session already
                session_ended = True
                break
            if line.strip() in ("c", "cont", "continue",
                                "q", "quit", "exit"):
                session_ended = True
                break
            if not t.is_alive():  # server closed: stop reading stdin
                session_ended = True
                break
    finally:
        if session_ended:
            # Server is ending the session: drain its last responses
            # before closing (closing first races them away).
            t.join(timeout=5)
        # stdin-EOF without a terminator: close NOW — the server is still
        # waiting for commands, and our close triggers its do_EOF detach.
        try:
            conn.close()
        except Exception:  # noqa: BLE001
            pass
        t.join(timeout=2)
