"""Scheduling strategies (reference: `python/ray/util/scheduling_strategies.py`)."""

from __future__ import annotations

from typing import Optional

from ray_tpu.core.common import SchedulingStrategy


class DefaultSchedulingStrategy(SchedulingStrategy):
    """Hybrid policy: pack locally under threshold, then best remote node."""


class SpreadSchedulingStrategy(SchedulingStrategy):
    """Round-robin among feasible nodes (the "SPREAD" strategy)."""


class NodeAffinitySchedulingStrategy(SchedulingStrategy):
    def __init__(self, node_id: str, soft: bool = False):
        self.node_id = node_id
        self.soft = soft


class PlacementGroupSchedulingStrategy(SchedulingStrategy):
    def __init__(
        self,
        placement_group,
        placement_group_bundle_index: int = -1,
        placement_group_capture_child_tasks: bool = False,
    ):
        self.placement_group = placement_group
        self.placement_group_bundle_index = placement_group_bundle_index
        self.placement_group_capture_child_tasks = placement_group_capture_child_tasks
