"""Workflows: durable DAG execution with exactly-once step checkpointing.

Equivalent of the reference's workflow layer (`python/ray/workflow/api.py`:
run/resume/get_output, step checkpointing in `workflow_storage`): a DAG
built with `.bind()` runs step by step; each step's result is persisted to
the workflow's storage directory the moment it completes, so a crashed or
interrupted workflow resumes from its last finished step instead of
recomputing.

    @ray_tpu.remote
    def fetch(): ...
    @ray_tpu.remote
    def train(data): ...

    wf = train.bind(fetch.bind())
    result = workflow.run(wf, workflow_id="nightly")
    # later, after a crash mid-run:
    result = workflow.resume("nightly")

Step identity: deterministic ids from DAG structure (topological position
+ task name), so the same DAG shape maps onto the same checkpoints.
"""

from __future__ import annotations

import os
import pickle
import time
from typing import Any, Dict, List, Optional

import ray_tpu
from ray_tpu.dag import DAGNode, FunctionNode

__all__ = ["run", "run_async", "resume", "get_output", "get_status",
           "list_all", "delete", "WorkflowStatus"]


class WorkflowStatus:
    RUNNING = "RUNNING"
    SUCCESSFUL = "SUCCESSFUL"
    FAILED = "FAILED"
    RESUMABLE = "RESUMABLE"


def _storage_root() -> str:
    return os.environ.get("RAY_TPU_WORKFLOW_DIR") or os.path.join(
        os.path.expanduser("~"), "ray_tpu_workflows")


def _wf_dir(workflow_id: str) -> str:
    return os.path.join(_storage_root(), workflow_id)


def _step_ids(dag: FunctionNode) -> Dict[int, str]:
    """Deterministic id per node: depth-first position + task name."""
    ids: Dict[int, str] = {}
    counter = [0]

    def walk(node: DAGNode):
        if id(node) in ids or not isinstance(node, FunctionNode):
            return
        for child in node._children():
            walk(child)
        ids[id(node)] = f"step_{counter[0]:04d}_{node.name}"
        counter[0] += 1

    walk(dag)
    return ids


def _atomic_pickle(path: str, obj: Any):
    tmp = path + ".tmp"
    with open(tmp, "wb") as f:
        pickle.dump(obj, f)
    os.replace(tmp, path)


class _WorkflowRun:
    def __init__(self, workflow_id: str, dag: FunctionNode):
        self.workflow_id = workflow_id
        self.dag = dag
        self.dir = _wf_dir(workflow_id)
        os.makedirs(self.dir, exist_ok=True)
        self.step_ids = _step_ids(dag)

    # -------------------------------------------------------------- state

    def _meta(self) -> Dict[str, Any]:
        path = os.path.join(self.dir, "meta.pkl")
        if os.path.exists(path):
            with open(path, "rb") as f:
                return pickle.load(f)
        return {}

    def _set_status(self, status: str, error: Optional[str] = None):
        meta = self._meta()
        meta.update({"status": status, "error": error,
                     "updated_at": time.time()})
        meta.setdefault("created_at", time.time())
        _atomic_pickle(os.path.join(self.dir, "meta.pkl"), meta)

    def _step_path(self, step_id: str) -> str:
        return os.path.join(self.dir, f"{step_id}.pkl")

    # ---------------------------------------------------------- execution

    def execute(self) -> Any:
        self._set_status(WorkflowStatus.RUNNING)
        try:
            result = self._run_node(self.dag)
            _atomic_pickle(os.path.join(self.dir, "output.pkl"), result)
            self._set_status(WorkflowStatus.SUCCESSFUL)
            return result
        except BaseException as e:
            self._set_status(WorkflowStatus.RESUMABLE, error=repr(e))
            raise

    def _run_node(self, node: DAGNode) -> Any:
        if not isinstance(node, FunctionNode):
            raise TypeError(
                "workflows run task DAGs (fn.bind(...)); InputNode-"
                "parameterized DAGs need their inputs bound first")
        step_id = self.step_ids[id(node)]
        path = self._step_path(step_id)
        if os.path.exists(path):  # checkpointed: skip re-execution
            with open(path, "rb") as f:
                return pickle.load(f)
        args = [self._run_arg(a) for a in node._args]
        kwargs = {k: self._run_arg(v) for k, v in node._kwargs.items()}
        fn = node._fn.options(**node._options) if node._options else node._fn
        value = ray_tpu.get(fn.remote(*args, **kwargs))
        _atomic_pickle(path, value)
        return value

    def _run_arg(self, arg: Any) -> Any:
        if isinstance(arg, DAGNode):
            return self._run_node(arg)
        if isinstance(arg, (list, tuple)):
            return type(arg)(self._run_arg(a) for a in arg)
        if isinstance(arg, dict):
            return {k: self._run_arg(v) for k, v in arg.items()}
        return arg


# ------------------------------------------------------------------- API #


def run(dag: FunctionNode, *, workflow_id: Optional[str] = None) -> Any:
    """Execute a DAG durably; returns the final result. Raises on step
    failure, leaving the workflow RESUMABLE."""
    from ray_tpu.core import serialization

    workflow_id = workflow_id or f"wf_{int(time.time() * 1000):x}"
    runner = _WorkflowRun(workflow_id, dag)
    # cloudpickle: DAGs close over user functions/lambdas that plain
    # pickle cannot carry across a restart.
    blob = serialization.dumps(dag)
    tmp = os.path.join(runner.dir, "dag.bin.tmp")
    with open(tmp, "wb") as f:
        f.write(blob)
    os.replace(tmp, os.path.join(runner.dir, "dag.bin"))
    return runner.execute()


def run_async(dag: FunctionNode, *, workflow_id: Optional[str] = None):
    """Run in a background thread; returns (workflow_id, thread)."""
    import threading

    workflow_id = workflow_id or f"wf_{int(time.time() * 1000):x}"
    t = threading.Thread(target=run, args=(dag,),
                         kwargs={"workflow_id": workflow_id}, daemon=True)
    t.start()
    return workflow_id, t


def resume(workflow_id: str) -> Any:
    """Re-run a failed/interrupted workflow; completed steps are loaded
    from their checkpoints, not re-executed."""
    from ray_tpu.core import serialization

    dag_path = os.path.join(_wf_dir(workflow_id), "dag.bin")
    if not os.path.exists(dag_path):
        raise ValueError(f"no workflow {workflow_id!r}")
    with open(dag_path, "rb") as f:
        dag = serialization.loads(f.read())
    return _WorkflowRun(workflow_id, dag).execute()


def get_output(workflow_id: str) -> Any:
    path = os.path.join(_wf_dir(workflow_id), "output.pkl")
    if not os.path.exists(path):
        raise ValueError(f"workflow {workflow_id!r} has no output "
                         f"(status={get_status(workflow_id)})")
    with open(path, "rb") as f:
        return pickle.load(f)


def get_status(workflow_id: str) -> Optional[str]:
    meta_path = os.path.join(_wf_dir(workflow_id), "meta.pkl")
    if not os.path.exists(meta_path):
        return None
    with open(meta_path, "rb") as f:
        return pickle.load(f).get("status")


def list_all(status_filter: Optional[str] = None) -> List[tuple]:
    root = _storage_root()
    if not os.path.isdir(root):
        return []
    out = []
    for wid in sorted(os.listdir(root)):
        status = get_status(wid)
        if status is not None and \
                (status_filter is None or status == status_filter):
            out.append((wid, status))
    return out


def delete(workflow_id: str):
    import shutil

    shutil.rmtree(_wf_dir(workflow_id), ignore_errors=True)
