"""Cross-language gateway: msgpack-speaking entry point for non-Python
clients (the C++ user API in `cpp/`).

Equivalent surface to the reference's cross-language support (C++/Java
user APIs binding the same core — `cpp/include/ray/api.h`,
`java/runtime/.../RayNativeRuntime.java`) re-designed for this runtime's
shape: instead of embedding a native CoreWorker in every foreign-language
process, a Python-side gateway exposes the public API over raw-msgpack
RPC methods (`RpcServer.register_raw`), and foreign clients stay thin —
a socket, the 12-byte frame header, and a msgpack codec. Cross-language
VALUES are msgpack-encoded (the reference uses msgpack for its XLANG
serialization format too, `python/ray/_private/serialization.py`), which
bounds them to plain data: numbers, strings, binary, lists, maps.

Wire protocol (shared with `ray_tpu/core/rpc.py` framing):

    [4B LE total][4B LE envlen][msgpack env {i,k,m}][msgpack payload]

Methods (payload -> response payload, all msgpack maps):
    xlang_ping      {}                                  -> {ok: true}
    xlang_kv_put    {ns, key(bin), value(bin)}          -> {ok}
    xlang_kv_get    {ns, key(bin)}                      -> {value(bin)|nil}
    xlang_put       {value}                             -> {id(hex str)}
    xlang_get       {id, timeout?}                      -> {value}
    xlang_free      {id}                                -> {freed(bool)}
    xlang_call      {fn "module:attr", args, kwargs,
                     mode: "sync"|"submit", timeout?}   -> {value}|{id}
    xlang_actor_call{name, namespace?, method, args,
                     kwargs, timeout?}                  -> {value}

Errors come back as the RPC envelope's `e` field (ValueError on the
client). Python sees cross-language objects as the decoded msgpack value
(a dict/list/str/int/bytes), so `ray_tpu.get` on an id a C++ client put
just works, and vice versa for plain-data Python objects.
"""

from __future__ import annotations

import importlib
import logging
import threading
from typing import Any, Dict, Optional

import msgpack

logger = logging.getLogger(__name__)

GATEWAY_KV_NS = "xlang"
GATEWAY_KV_KEY = b"gateway_address"

_lock = threading.Lock()
_server = None


def _pack(obj: Any) -> bytes:
    return msgpack.packb(obj, use_bin_type=True)


def _unpack(payload: bytes) -> Any:
    return msgpack.unpackb(payload, raw=False, strict_map_key=False)


def _check_xlang_value(value: Any):
    """Raise if a value cannot cross the language boundary (msgpack-able
    plain data only — mirrors the reference's XLANG format limits)."""
    try:
        return _pack(value)
    except Exception as e:
        raise TypeError(
            f"value of type {type(value).__name__} is not cross-language "
            f"serializable (msgpack plain data only): {e}") from None


def _value_response(value: Any) -> bytes:
    """Encode {"value": value} reusing the validation pack — a large
    result is serialized once, not once to check and again to respond.
    Layout: fixmap(1) + fixstr(5) "value" + <packed value>."""
    return b"\x81\xa5value" + _check_xlang_value(value)


class XlangGateway:
    """Raw-msgpack handlers bound to a driver runtime."""

    def __init__(self, runtime):
        self._runtime = runtime
        # Objects whose ids crossed the language boundary: a foreign
        # client holds ids, not ObjectRefs, so nothing on the Python side
        # would keep the objects alive — the gateway pins them until the
        # client frees them (xlang_free) or the gateway stops. Without
        # this, a submit-mode task result is refcount-freed the moment
        # the handler returns and the client's later xlang_get polls a
        # dead object forever.
        self._held: Dict[str, Any] = {}
        self._held_lock = threading.Lock()

    def _hold(self, ref):
        with self._held_lock:
            self._held[ref.hex()] = ref

    # Handler helpers -------------------------------------------------

    def _resolve_fn(self, ref: str):
        """'pkg.mod:attr' (or 'pkg.mod.attr') -> callable. Only module
        attributes — cross-language calls are by name, like the reference's
        function descriptors (module + name), never by pickled code."""
        if ":" in ref:
            mod_name, _, attr = ref.partition(":")
        else:
            mod_name, _, attr = ref.rpartition(".")
        if not mod_name:
            raise ValueError(f"function reference {ref!r} must be "
                             "'module:attr' or 'module.attr'")
        fn = importlib.import_module(mod_name)
        for part in attr.split("."):
            fn = getattr(fn, part)
        if not callable(fn):
            raise TypeError(f"{ref!r} resolved to non-callable {type(fn)}")
        return fn

    # Handlers (conn, payload bytes) -> response bytes ----------------

    def ping(self, conn, payload: bytes) -> bytes:
        return _pack({"ok": True})

    def kv_put(self, conn, payload: bytes) -> bytes:
        req = _unpack(payload)
        self._runtime.gcs.call("kv_put", {
            "namespace": req.get("ns") or "xlang-user",
            "key": bytes(req["key"]),
            "value": bytes(req["value"]),
            "overwrite": True,
        })
        return _pack({"ok": True})

    def kv_get(self, conn, payload: bytes) -> bytes:
        req = _unpack(payload)
        resp = self._runtime.gcs.call("kv_get", {
            "namespace": req.get("ns") or "xlang-user",
            "key": bytes(req["key"]),
        })
        return _pack({"value": resp.get("value")})

    def put(self, conn, payload: bytes) -> bytes:
        from ray_tpu.object_ref import ObjectRef

        req = _unpack(payload)
        oid = self._runtime.put(req["value"])
        self._hold(ObjectRef(oid))
        return _pack({"id": oid.hex()})

    def free(self, conn, payload: bytes) -> bytes:
        req = _unpack(payload)
        with self._held_lock:
            dropped = self._held.pop(req["id"], None) is not None
        return _pack({"freed": dropped})

    def get(self, conn, payload: bytes) -> bytes:
        from ray_tpu.core.ids import ObjectID

        req = _unpack(payload)
        oid = ObjectID.from_hex(req["id"])
        value = self._runtime.get([oid], timeout=req.get("timeout"))[0]
        return _value_response(value)

    def call(self, conn, payload: bytes) -> bytes:
        import ray_tpu

        req = _unpack(payload)
        fn = self._resolve_fn(req["fn"])
        remote_fn = ray_tpu.remote(fn)
        ref = remote_fn.remote(*(req.get("args") or []),
                               **(req.get("kwargs") or {}))
        if req.get("mode") == "submit":
            self._hold(ref)
            return _pack({"id": ref.hex()})
        value = self._runtime.get([ref.object_id],
                                  timeout=req.get("timeout", 60))[0]
        return _value_response(value)

    def actor_call(self, conn, payload: bytes) -> bytes:
        import ray_tpu

        req = _unpack(payload)
        handle = ray_tpu.get_actor(req["name"],
                                   namespace=req.get("namespace"))
        method = getattr(handle, req["method"])
        ref = method.remote(*(req.get("args") or []),
                            **(req.get("kwargs") or {}))
        value = self._runtime.get([ref.object_id],
                                  timeout=req.get("timeout", 60))[0]
        return _value_response(value)


def start_gateway(host: str = "127.0.0.1", port: int = 0) -> str:
    """Start (idempotently) the cross-language gateway on this driver and
    publish its address to the GCS KV (`xlang/gateway_address`) so foreign
    clients can be pointed at the cluster. Returns the gateway address."""
    global _server
    import ray_tpu
    from ray_tpu.core.rpc import RpcServer

    runtime = ray_tpu._require_runtime()
    with _lock:
        if _server is not None:
            return _server.address
        gw = XlangGateway(runtime)
        server = RpcServer(host=host, port=port, name="xlang-gateway")
        # Callers are out-of-tree non-Python clients (cpp/): RL014's
        # reference scan cannot see them, so each registration carries
        # the dead-endpoint waiver explicitly.
        server.register_raw(  # raylint: disable=RL014 — cpp client
            "xlang_ping", gw.ping)
        server.register_raw(  # raylint: disable=RL014 — cpp client
            "xlang_kv_put", gw.kv_put)
        server.register_raw(  # raylint: disable=RL014 — cpp client
            "xlang_kv_get", gw.kv_get)
        server.register_raw(  # raylint: disable=RL014 — cpp client
            "xlang_put", gw.put)
        server.register_raw(  # raylint: disable=RL014 — cpp client
            "xlang_free", gw.free)
        server.register_raw(  # raylint: disable=RL014 — cpp client
            "xlang_get", gw.get)
        server.register_raw(  # raylint: disable=RL014 — cpp client
            "xlang_call", gw.call)
        server.register_raw(  # raylint: disable=RL014 — cpp client
            "xlang_actor_call", gw.actor_call)
        server.start()
        _server = server
    try:
        runtime.gcs.call("kv_put", {"namespace": GATEWAY_KV_NS,
                                    "key": GATEWAY_KV_KEY,
                                    "value": server.address.encode(),
                                    "overwrite": True})
    except Exception:  # noqa: BLE001 — discovery is best-effort
        logger.warning("failed to publish xlang gateway address",
                       exc_info=True)
    logger.info("xlang gateway listening on %s", server.address)
    return server.address


def stop_gateway():
    global _server
    with _lock:
        if _server is not None:
            _server.stop()
            _server = None
