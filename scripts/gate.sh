#!/usr/bin/env bash
# Pre-snapshot gate: everything here must pass before an end-of-round commit.
# (Round-2 postmortem: the snapshot was committed with a failing test and a
# kernel that could not lower on TPU — this script makes that impossible.)
#
# Usage: scripts/gate.sh [--full]
#   default: full pytest + quick bench + 8-device multichip dryrun
#   --full:  additionally runs the non-quick bench (real TPU, ~5 min)

set -uo pipefail
cd "$(dirname "$0")/.."
FAIL=0

step() {
  echo "=== gate: $1"
  shift
  if ! "$@"; then
    echo "!!! gate FAILED: $1"
    FAIL=1
  fi
}

# Incremental raylint: per-file results cached under .raylint_cache/
# keyed by content hash (an absent or stale cache is the cold-run
# fallback — same findings, just slower). The cold leg runs against a
# THROWAWAY cache dir so the printed cold/warm ratio is honest on every
# gate, not only the first (the persistent cache would otherwise make
# both legs warm); --timings keeps a slow rule visible before it bloats
# this step. The unused-suppression audit rides along so a stale
# `# raylint: disable=` comment fails the gate too.
step "raylint (incremental + suppression audit)" bash -c '
  coldcache=$(mktemp -d)
  t0=$(date +%s%N)
  python -m ray_tpu.analysis ray_tpu/ --incremental --cache-dir "$coldcache" \
      --timings --report-unused-suppressions || exit 1
  t1=$(date +%s%N)
  python -m ray_tpu.analysis ray_tpu/ --incremental --cache-dir "$coldcache" \
      || exit 1
  t2=$(date +%s%N)
  rm -rf "$coldcache"
  # Refresh the persistent cache too (steady-state warm for local runs).
  python -m ray_tpu.analysis ray_tpu/ --incremental >/dev/null 2>&1
  cold_ms=$(( (t1 - t0) / 1000000 )); warm_ms=$(( (t2 - t1) / 1000000 ))
  ratio=$(( warm_ms * 100 / (cold_ms > 0 ? cold_ms : 1) ))
  echo "raylint wall: cold ${cold_ms}ms, warm ${warm_ms}ms (${ratio}% of cold)"
  # Acceptance bound: the warm incremental run (per-file results cached,
  # project rules re-joined over cached summaries — now including the
  # RL020-RL024 dataflow extracts) must stay under 25% of cold.
  if (( ratio >= 25 )); then
    echo "raylint warm run is ${ratio}% of cold (must be <25%)"
    exit 1
  fi
'
step "pytest tests/" python -m pytest tests/ -q
# Seeded chaos smoke: ONE node kill under light serve load, deterministic
# seed, <60s — zero hangs + bounded recovery asserted (exit nonzero on
# either). The full bench_chaos (Poisson serve + training loop under the
# whole schedule) stays a bench-only run.
step "chaos smoke (seeded, 1 node kill)" \
  env JAX_PLATFORMS=cpu python bench.py --chaos-smoke
# Ingest smoke: one seeded node kill MID-SHUFFLE (the node holding the
# most blocks), <60s — the epoch must complete with recomputed blocks
# >= 1 (the fault destroyed state the pipeline needed) and bounded by
# the victim's resident count, HangWatchdog-clean, zero unsealed
# buffers (exit nonzero on any hang/unbounded-recompute/leak).
step "ingest smoke (seeded node kill mid-shuffle)" \
  env JAX_PLATFORMS=cpu python bench.py --ingest-smoke
# Inference smoke: prefix-cache A/B over one seeded shared-prefix trace
# plus spec-decode quick runs, <60s — hard asserts on ZERO recompiles
# (prefill/decode/draft/propose/verify), ZERO leaked blocks on every
# arm, a nonzero radix hit rate, and the target-as-draft acceptance
# upper bound (exit nonzero on any invariant breach).
step "inference smoke (prefix cache + spec decode)" \
  env JAX_PLATFORMS=cpu python bench.py --inference-smoke
# Query smoke: sort/groupby/join through the windowed shuffle on a
# 3-node cluster, <60s — row-identity verified inline, the driver's sort
# footprint bounded by the key sample, and the locality-routing A/B must
# show the routed arm moving strictly fewer cross-node bytes (socket
# path forced; exit nonzero on any invariant breach).
step "query smoke (exchange operators + locality A/B)" \
  env JAX_PLATFORMS=cpu python bench.py --query-smoke
# Job-tier smoke: cold vs forge-template submit->first-task (warm must
# be >=2x faster), 3 concurrent tenant jobs with distinct runtime envs
# on one cluster, then the cleanup invariants — zero orphan job
# processes via /proc cmdline scan (driver mark + cold-worker argv
# diff) and num_unsealed 0 (exit nonzero on any breach).
step "jobs smoke (submission plane + env forge + tenants)" \
  env JAX_PLATFORMS=cpu python bench.py --jobs-smoke
# Sharded smoke: pp=2 pipeline parity + seeded kill-a-stage resume, <60s —
# hard asserts on step-for-step BITWISE parity with pp=1 (zero per-step
# recompiles via compile counters), the 1F1B bubble fraction strictly
# below the sequential schedule's, an ingest-fed run with bounded
# stall_frac, and a checkpoint-gated stage kill whose elastic resharded
# resume is bitwise-equal to the unkilled run at the same step (exit
# nonzero on any invariant breach). Makespan speedup stays a soft flag
# (`sharded_regressed`) — on small hosts XLA intra-op threading hands the
# sequential schedule every core per op, so wall-clock is noise-bound.
step "sharded smoke (pp=2 parity + kill-a-stage resume)" \
  env JAX_PLATFORMS=cpu python bench.py --sharded-smoke
# 100-node envelope smoke: placement at width + one seeded node kill with
# AUTOSCALER-driven replacement, bounded — zero hangs, zero lost tasks,
# lease-cache invalidation asserted (no stale-lease double execution).
step "envelope100 smoke (100 nodes, autoscaled kill)" \
  env JAX_PLATFORMS=cpu python bench.py --envelope100-smoke
step "multichip dryrun (8 virtual devices)" \
  env JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
  python __graft_entry__.py 8

if [[ "${1:-}" == "--full" ]]; then
  BENCH_OUT=$(mktemp)
  step "bench.py (full, real chip)" \
    bash -c "set -o pipefail; python bench.py | tee '$BENCH_OUT'"
  # The full run must prove the Pallas kernels actually engaged on the chip
  # (a silently-disabled kernel otherwise publishes XLA numbers as flash).
  step "pallas engaged on chip" grep -q '"pallas_engaged": true' "$BENCH_OUT"
  rm -f "$BENCH_OUT"
else
  step "bench.py --quick" python bench.py --quick
fi

if [[ $FAIL -ne 0 ]]; then
  echo "GATE: FAILED"
  exit 1
fi
echo "GATE: OK"
