"""Test harness config.

JAX runs on an 8-device virtual CPU platform (mirrors how the reference
exercises multi-node logic on one machine via `cluster_utils.Cluster`); env
must be set before the first jax import anywhere in the process.
"""

import os

os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8").strip()
os.environ.setdefault("JAX_ENABLE_X64", "0")
# Worker subprocesses: sitecustomize may force an accelerator platform at
# interpreter start; this framework knob re-pins them to CPU (see
# ray_tpu/_jax_env.py).
os.environ["RAY_TPU_JAX_PLATFORM"] = "cpu"

# Worker subprocesses must resolve functions defined in test modules (pytest
# puts tests/ on the driver's sys.path; spawned workers inherit PYTHONPATH).
_tests_dir = os.path.dirname(os.path.abspath(__file__))
_pp = os.environ.get("PYTHONPATH", "")
if _tests_dir not in _pp.split(os.pathsep):
    os.environ["PYTHONPATH"] = (
        _tests_dir + (os.pathsep + _pp if _pp else ""))

# The container's sitecustomize may import jax and register a TPU plugin
# before conftest runs; flip the already-imported config to CPU (backends
# aren't initialized yet at collection time, so this still takes effect).
import sys  # noqa: E402

if "jax" in sys.modules:
    import jax

    jax.config.update("jax_platforms", "cpu")

import pytest  # noqa: E402

# Opt-in cluster-wide sanitizer run: RAY_TPU_LOCK_WITNESS=1 installs the
# lock-order witness (with hang watchdog) BEFORE any cluster fixture
# creates a lock, so every tier-1 test doubles as a race-detection pass.
# The session teardown below then fails the run on any recorded cycle.
WITNESS_ENABLED = os.environ.get("RAY_TPU_LOCK_WITNESS") == "1"
if WITNESS_ENABLED:
    from ray_tpu.util import lock_witness

    lock_witness.install(watchdog_s=float(
        os.environ.get("RAY_TPU_LOCK_WITNESS_WATCHDOG", "60")))


@pytest.fixture(scope="session", autouse=True)
def _lock_witness_session_gate():
    yield
    if WITNESS_ENABLED:
        from ray_tpu.util import lock_witness

        rep = lock_witness.report()
        assert rep.cycles == [], (
            "lock-order cycles recorded during the suite:\n"
            + "\n".join(rep.cycles))


@pytest.fixture(scope="session")
def multi_device_workers():
    """Multi-device CPU meshes in WORKER subprocesses.

    The XLA_FLAGS export above runs at conftest import — before any jax
    import and before any cluster exists — so every worker subprocess
    (cold execs inherit os.environ; forge forks inherit the template's
    env, and the template is spawned before XLA init) sees an 8-device
    CPU platform. Tests that build tp meshes inside replicas/rank actors
    take this fixture as their explicit dependency on that guarantee;
    it asserts the flag is still exported and returns the device count.
    """
    flags = os.environ.get("XLA_FLAGS", "")
    marker = "xla_force_host_platform_device_count="
    assert marker in flags, (
        "XLA_FLAGS lost the forced device count — worker meshes would "
        f"be single-device: {flags!r}")
    count = flags.split(marker, 1)[1].split()[0]
    return int(count)


@pytest.fixture(scope="module")
def ray_start_shared():
    """Module-scoped cluster: fast, shared across a module's tests.

    Teardown only shuts down the cluster THIS fixture created: the runtime is
    a process-global, and a late-running finalizer from another module must
    not tear down its successor's cluster.
    """
    import ray_tpu

    ray_tpu.shutdown()
    ray_tpu.init(num_cpus=4)
    created = ray_tpu._global_runtime
    yield
    if ray_tpu._global_runtime is created:
        ray_tpu.shutdown()


@pytest.fixture()
def ray_start_regular():
    """Function-scoped fresh cluster for tests that mutate cluster state."""
    import ray_tpu

    ray_tpu.shutdown()
    ray_tpu.init(num_cpus=4)
    created = ray_tpu._global_runtime
    yield
    if ray_tpu._global_runtime is created:
        ray_tpu.shutdown()


def assert_compiles_once(source, *counters, context=None):
    """The compile-once discipline, shared across the JAX test surface
    (the dynamic complement of raylint's RL020/RL024 static checks).

    Two forms:

    - ``assert_compiles_once(jitted_fn)`` — the callable's trace cache
      holds exactly ONE compiled program (``_cache_size()``);
    - ``assert_compiles_once(stats, "prefill_compiles", ...)`` — each
      named counter in a stats/metrics dict is exactly 1.

    `context` is included in the failure message (engine name, arm
    label) so parametrized sweeps stay diagnosable.
    """
    if not isinstance(source, dict):
        n = source._cache_size()
        assert n == 1, (context, "trace cache holds", n, "programs")
        return
    assert counters, "name the counters to check on a stats dict"
    for key in counters:
        assert source.get(key) == 1, (context, key, source)


def pytest_configure(config):
    config.addinivalue_line(
        "markers",
        "slow: long-running perf comparisons excluded from the tier-1 "
        "budget (run explicitly or via bench.py)")
