"""Importable serve app for schema tests."""
from ray_tpu import serve


@serve.deployment
class Doubler:
    def __call__(self, x):
        return 2 * x


@serve.deployment
class Pipeline:
    def __init__(self, inner, bonus):
        self.inner = inner
        self.bonus = bonus

    def __call__(self, x):
        import ray_tpu

        return ray_tpu.get(self.inner.remote(x)) + self.bonus


app = Pipeline.bind(Doubler.bind(), 5)
