"""Actor restart on worker death (max_restarts), isolated cluster."""

import time

import ray_tpu


def test_actor_restart(ray_start_regular):
    @ray_tpu.remote(max_restarts=1)
    class Phoenix:
        def __init__(self):
            self.n = 0

        def pid(self):
            import os

            return os.getpid()

        def die(self):
            import os

            os._exit(1)

    p = Phoenix.remote()
    pid1 = ray_tpu.get(p.pid.remote())
    try:
        ray_tpu.get(p.die.remote())
    except Exception:
        pass
    # Wait for restart
    deadline = time.time() + 30
    pid2 = None
    while time.time() < deadline:
        try:
            pid2 = ray_tpu.get(p.pid.remote())
            break
        except Exception:
            time.sleep(0.3)
    assert pid2 is not None and pid2 != pid1


def test_actor_max_restarts_config_default(ray_start_regular):
    """Regression for the RL015 knob-drift pass: the declared
    `actor_max_restarts` knob is the default an actor WITHOUT an
    explicit max_restarts option gets (same contract task_max_retries
    already had for tasks)."""
    from ray_tpu.core.config import GLOBAL_CONFIG

    GLOBAL_CONFIG.actor_max_restarts = 1
    try:
        @ray_tpu.remote
        class Phoenix:
            def pid(self):
                import os

                return os.getpid()

            def die(self):
                import os

                os._exit(1)

        p = Phoenix.remote()
        pid1 = ray_tpu.get(p.pid.remote())
        try:
            ray_tpu.get(p.die.remote())
        except Exception:
            pass
        deadline = time.time() + 30
        pid2 = None
        while time.time() < deadline:
            try:
                pid2 = ray_tpu.get(p.pid.remote())
                break
            except Exception:
                time.sleep(0.3)
        assert pid2 is not None and pid2 != pid1, \
            "knob-derived max_restarts did not restart the actor"
    finally:
        GLOBAL_CONFIG._overrides.pop("actor_max_restarts", None)


def test_list_named_actors_uses_runtime_namespace(ray_start_regular):
    """state.list_named_actors() with no namespace must list the CURRENT
    runtime namespace (get_actor's resolution), not the GCS literal
    "default"."""
    from ray_tpu import state

    ray_tpu.shutdown()
    ray_tpu.init(num_cpus=2, namespace="ns1")
    try:
        @ray_tpu.remote
        class Holder:
            def ok(self):
                return True

        h = Holder.options(name="ns_holder").remote()
        assert ray_tpu.get(h.ok.remote())
        assert "ns_holder" in {e["name"] for e in state.list_named_actors()}
        assert state.list_named_actors(namespace="default") == []
        every = {(e["namespace"], e["name"])
                 for e in state.list_named_actors(all_namespaces=True)}
        assert ("ns1", "ns_holder") in every
    finally:
        ray_tpu.shutdown()


def test_actor_restart_during_inflight_call(ray_start_regular):
    """Kill the actor's worker process while a call is EXECUTING: the
    caller must see ActorDiedError (or a successful retry) within a
    bound — never a hang."""
    import ray_tpu
    from ray_tpu.exceptions import RayActorError

    @ray_tpu.remote(max_restarts=1)
    class Slow:
        def pid(self):
            import os

            return os.getpid()

        def slow_echo(self, x):
            import time as _t

            _t.sleep(3.0)
            return x

    a = Slow.remote()
    pid1 = ray_tpu.get(a.pid.remote())
    ref = a.slow_echo.remote(41)
    time.sleep(0.5)  # the call is now executing inside the worker
    import os as _os
    import signal as _signal

    _os.kill(pid1, _signal.SIGKILL)  # crash, not graceful
    t0 = time.time()
    try:
        out = ray_tpu.get(ref, timeout=30)
        assert out == 41  # a successful retry is acceptable
    except RayActorError:
        pass  # the documented outcome for in-flight calls
    assert time.time() - t0 < 30, "in-flight call hung past its bound"

    # The restarted incarnation serves fresh calls.
    deadline = time.time() + 30
    pid2 = None
    while time.time() < deadline:
        try:
            pid2 = ray_tpu.get(a.pid.remote(), timeout=5)
            break
        except Exception:
            time.sleep(0.3)
    assert pid2 is not None and pid2 != pid1


def test_restart_hook_and_exhaustion_semantics(ray_start_regular):
    """__ray_restart__ state-restore hook: never called on first
    creation, called with the incarnation count on each restart, and
    once restarts are exhausted the actor is terminally DEAD — callers
    get ActorDiedError, no further incarnation (and no hook) ever runs."""
    import ray_tpu
    from ray_tpu.exceptions import RayActorError

    @ray_tpu.remote(max_restarts=1)
    class Phoenix:
        def __init__(self):
            self.restored_from = 0  # 0 = fresh __init__, no hook ran

        def __ray_restart__(self, restart_count):
            self.restored_from = restart_count

        def state(self):
            import os

            return {"restored_from": self.restored_from,
                    "pid": os.getpid()}

        def die(self):
            import os

            os._exit(1)

    p = Phoenix.remote()
    first = ray_tpu.get(p.state.remote())
    assert first["restored_from"] == 0, "hook must not run on creation"

    try:
        ray_tpu.get(p.die.remote())
    except Exception:
        pass
    deadline = time.time() + 30
    second = None
    while time.time() < deadline:
        try:
            second = ray_tpu.get(p.state.remote(), timeout=5)
            break
        except Exception:
            time.sleep(0.3)
    assert second is not None, "actor never restarted"
    assert second["pid"] != first["pid"]
    assert second["restored_from"] == 1, \
        "state-restore hook must run with the incarnation count"

    # Exhaust restarts: the second death is terminal.
    try:
        ray_tpu.get(p.die.remote())
    except Exception:
        pass
    deadline = time.time() + 30
    while time.time() < deadline:
        try:
            ray_tpu.get(p.state.remote(), timeout=5)
            time.sleep(0.3)  # still alive? (shouldn't restart again)
        except RayActorError:
            break  # terminal death observed
        except Exception:
            time.sleep(0.3)
    else:
        raise AssertionError("exhausted actor never reported DEAD")
    # And it STAYS dead: fresh calls keep failing with the death error.
    try:
        ray_tpu.get(p.state.remote(), timeout=10)
        raise AssertionError("call to a restart-exhausted actor succeeded")
    except RayActorError:
        pass
