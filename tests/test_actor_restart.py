"""Actor restart on worker death (max_restarts), isolated cluster."""

import time

import ray_tpu


def test_actor_restart(ray_start_regular):
    @ray_tpu.remote(max_restarts=1)
    class Phoenix:
        def __init__(self):
            self.n = 0

        def pid(self):
            import os

            return os.getpid()

        def die(self):
            import os

            os._exit(1)

    p = Phoenix.remote()
    pid1 = ray_tpu.get(p.pid.remote())
    try:
        ray_tpu.get(p.die.remote())
    except Exception:
        pass
    # Wait for restart
    deadline = time.time() + 30
    pid2 = None
    while time.time() < deadline:
        try:
            pid2 = ray_tpu.get(p.pid.remote())
            break
        except Exception:
            time.sleep(0.3)
    assert pid2 is not None and pid2 != pid1
