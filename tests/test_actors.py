"""Actor API: lifecycle, ordering, named actors, async actors, failures.

Mirrors the reference's `python/ray/tests/test_actor.py` coverage.
"""

import asyncio
import time

import pytest

import ray_tpu
from ray_tpu.exceptions import RayActorError


@ray_tpu.remote
class Counter:
    def __init__(self, start=0):
        self.v = start

    def inc(self, by=1):
        self.v += by
        return self.v

    def get(self):
        return self.v

    def fail(self):
        raise RuntimeError("actor method failed")


def test_basic_actor(ray_start_shared):
    c = Counter.remote()
    assert ray_tpu.get(c.inc.remote()) == 1
    assert ray_tpu.get(c.inc.remote(10)) == 11


def test_actor_constructor_args(ray_start_shared):
    c = Counter.remote(start=100)
    assert ray_tpu.get(c.get.remote()) == 100


def test_actor_method_ordering(ray_start_shared):
    c = Counter.remote()
    refs = [c.inc.remote() for _ in range(50)]
    assert ray_tpu.get(refs) == list(range(1, 51))


def test_actor_method_error(ray_start_shared):
    c = Counter.remote()
    with pytest.raises(RuntimeError):
        ray_tpu.get(c.fail.remote())
    # actor survives method errors
    assert ray_tpu.get(c.inc.remote()) == 1


def test_actor_init_error(ray_start_shared):
    @ray_tpu.remote
    class Bad:
        def __init__(self):
            raise ValueError("bad init")

        def ping(self):
            return "pong"

    b = Bad.remote()
    with pytest.raises((ValueError, RayActorError)):
        ray_tpu.get(b.ping.remote())


def test_named_actor(ray_start_shared):
    c = Counter.options(name="counter_x").remote(5)
    ray_tpu.get(c.inc.remote())
    h = ray_tpu.get_actor("counter_x")
    assert ray_tpu.get(h.get.remote()) == 6
    with pytest.raises(ValueError):
        ray_tpu.get_actor("no_such_actor")


def test_list_named_actors(ray_start_shared):
    # Regression for the RL014 pass: the GCS `list_named_actors`
    # endpoint now has a real consumer (ray_tpu.state).
    from ray_tpu import state

    Counter.options(name="counter_lna").remote(0)
    names = {e["name"] for e in state.list_named_actors()}
    assert "counter_lna" in names
    every = state.list_named_actors(all_namespaces=True)
    assert {"namespace", "name"} <= set(every[0])


def test_get_if_exists(ray_start_shared):
    a = Counter.options(name="gie", get_if_exists=True).remote(1)
    b = Counter.options(name="gie", get_if_exists=True).remote(1)
    ray_tpu.get(a.inc.remote())
    assert ray_tpu.get(b.get.remote()) == 2  # same actor


def test_kill_actor(ray_start_shared):
    c = Counter.remote()
    assert ray_tpu.get(c.inc.remote()) == 1
    ray_tpu.kill(c)
    time.sleep(0.3)
    with pytest.raises(RayActorError):
        ray_tpu.get(c.inc.remote())


def test_handle_passing(ray_start_shared):
    c = Counter.remote()

    @ray_tpu.remote
    def bump(handle):
        return ray_tpu.get(handle.inc.remote())

    assert ray_tpu.get(bump.remote(c)) == 1
    assert ray_tpu.get(c.get.remote()) == 1


def test_async_actor(ray_start_shared):
    @ray_tpu.remote
    class AsyncWorker:
        async def work(self, t, tag):
            await asyncio.sleep(t)
            return tag

    a = AsyncWorker.options(max_concurrency=4).remote()
    ray_tpu.get(a.work.remote(0, -1))  # warm up (creation excluded)
    t0 = time.time()
    refs = [a.work.remote(1.0, i) for i in range(4)]
    out = ray_tpu.get(refs)
    elapsed = time.time() - t0
    assert sorted(out) == [0, 1, 2, 3]
    # concurrent, not serial (4 x 1.0s serial would be >= 4s)
    assert elapsed < 3.0


def test_threaded_actor_concurrency(ray_start_shared):
    @ray_tpu.remote
    class Slow:
        def work(self, t):
            time.sleep(t)
            return t

    s = Slow.options(max_concurrency=3).remote()
    ray_tpu.get(s.work.remote(0))  # warm up (actor creation excluded)
    t0 = time.time()
    ray_tpu.get([s.work.remote(1.0) for _ in range(3)])
    # concurrent, not serial (3 x 1.0s serial would be >= 3s)
    assert time.time() - t0 < 2.5
