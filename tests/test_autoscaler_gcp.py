"""GCE TPU-VM node provider: REST bodies + fake-cloud autoscaler e2e.

Reference: `python/ray/autoscaler/_private/gcp/node_provider.py` (request
shape) and `_private/fake_multi_node/node_provider.py` (fake-cloud e2e
pattern).
"""

import os
import subprocess
import sys
import time

import pytest

import ray_tpu
from ray_tpu.autoscaler import (
    AutoscalerConfig,
    FakeTPUTransport,
    GCETPUConfig,
    GCETPUNodeProvider,
    StandardAutoscaler,
)
from ray_tpu.autoscaler.gcp import (
    CLUSTER_LABEL,
    TYPE_LABEL,
    SubprocessFakeTPUTransport,
)
from ray_tpu.cluster_utils import Cluster


def _config(head_address="10.0.0.2:6379", **kw):
    return GCETPUConfig(project="proj-1", zone="us-central2-b",
                        cluster_name="rtpu", head_address=head_address,
                        accelerator_type="v5litepod-4", **kw)


def test_create_node_request_body():
    transport = FakeTPUTransport()
    provider = GCETPUNodeProvider(_config(), transport=transport)
    handle = provider.create_node({"CPU": 8, "TPU": 4})
    assert handle.name.startswith("rtpu-worker-")

    (call,) = transport.calls
    assert call["method"] == "POST"
    assert call["url"].startswith(
        "https://tpu.googleapis.com/v2/projects/proj-1/locations/"
        "us-central2-b/nodes?nodeId=rtpu-worker-")
    body = call["body"]
    assert body["acceleratorType"] == "v5litepod-4"
    assert body["runtimeVersion"] == "tpu-ubuntu2204-base"
    assert body["labels"][CLUSTER_LABEL] == "rtpu"
    assert body["labels"][TYPE_LABEL] == "worker"
    script = body["metadata"]["startup-script"]
    assert "10.0.0.2:6379" in script        # workers join the head
    assert handle.name in script            # and self-label for idle mapping
    assert body["schedulingConfig"] == {"preemptible": False}


def test_terminate_and_list_requests():
    transport = FakeTPUTransport()
    provider = GCETPUNodeProvider(_config(), transport=transport)
    handle = provider.create_node({})
    nodes = provider.non_terminated_nodes()
    assert [n.name for n in nodes] == [handle.name]
    provider.terminate_node(handle)
    assert provider.non_terminated_nodes() == []

    methods = [c["method"] for c in transport.calls]
    assert methods == ["POST", "GET", "DELETE", "GET"]
    del_call = transport.calls[2]
    assert del_call["url"].endswith(f"/nodes/{handle.name}")
    list_call = transport.calls[1]
    assert f"filter=labels.{CLUSTER_LABEL}=rtpu" in list_call["url"]


def test_provider_adopts_preexisting_nodes():
    """A restarted autoscaler re-discovers VMs it didn't create this
    process (reference: provider state is the cloud, not memory)."""
    transport = FakeTPUTransport()
    p1 = GCETPUNodeProvider(_config(), transport=transport)
    handle = p1.create_node({})
    p2 = GCETPUNodeProvider(_config(), transport=transport)
    adopted = p2.non_terminated_nodes()
    assert [n.name for n in adopted] == [handle.name]


def test_node_resources_for_accelerator_type():
    provider = GCETPUNodeProvider(_config(), transport=FakeTPUTransport())
    assert provider.node_resources_for() == {"CPU": 32.0, "TPU": 4.0}


def test_startup_script_joins_real_node(tmp_path):
    """The provider's startup script — the exact command a real TPU VM
    boots with — is EXECUTED in a subprocess and must daemonize a worker
    that joins the head's GCS (this is the command-exists regression
    guard: a typo'd CLI would fail here, not in production)."""
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = {"RAY_TPU_TMPDIR": str(tmp_path),
           "PYTHONPATH": repo + os.pathsep + os.environ.get("PYTHONPATH", "")}
    head = subprocess.run(
        [sys.executable, "-m", "ray_tpu", "start", "--head",
         "--num-cpus", "1"],
        env={**os.environ, **env}, cwd="/tmp", capture_output=True,
        text=True, timeout=90)
    assert head.returncode == 0, head.stderr
    address = head.stdout.split("started at ")[1].split()[0]
    try:
        transport = SubprocessFakeTPUTransport(env=env)
        provider = GCETPUNodeProvider(_config(head_address=address),
                                      transport=transport)
        handle = provider.create_node(provider.node_resources_for())
        nodes = provider.non_terminated_nodes()
        assert [n.name for n in nodes] == [handle.name]

        # The joined node is visible to the GCS with the startup script's
        # self-label, and resolve_node_id maps VM -> ray node through it.
        probe = (
            "import json, time, ray_tpu\n"
            f"ray_tpu.init(address={address!r})\n"
            "for _ in range(120):\n"
            "    alive = [n for n in ray_tpu.nodes() if n['Alive']]\n"
            "    if len(alive) == 2: break\n"
            "    time.sleep(0.25)\n"
            "print(json.dumps([\n"
            "    {'id': n['NodeID'], 'labels': n.get('Labels', {})}\n"
            "    for n in alive]))\n")
        out = subprocess.run(
            [sys.executable, "-c", probe], env={**os.environ, **env},
            cwd="/tmp", capture_output=True, text=True, timeout=120)
        assert out.returncode == 0, (out.stdout, out.stderr)
        import json as _json

        entries = _json.loads(out.stdout.strip().splitlines()[-1])
        assert len(entries) == 2, entries
        view = {e["id"]: {"labels": e["labels"]} for e in entries}
        # A fresh handle (no cached node_id) must resolve through the
        # tpu-vm-name label the startup script registered — the real API
        # returns no ray_node_id, so the label is the only mapping.
        from ray_tpu.autoscaler.gcp import TPUNodeHandle

        fresh = TPUNodeHandle(name=handle.name)
        assert provider.resolve_node_id(fresh, view) is not None
        assert provider.resolve_node_id(handle, view) is not None

        provider.terminate_node(handle)
        assert provider.non_terminated_nodes() == []
    finally:
        subprocess.run(
            [sys.executable, "-m", "ray_tpu", "stop", "--force"],
            env={**os.environ, **env}, cwd="/tmp", capture_output=True,
            timeout=30)


def test_fake_cloud_autoscaler_end_to_end():
    """Demand -> TPU-VM create calls -> fake VMs join as raylets -> work
    runs -> idle -> TPU-VM delete calls."""
    ray_tpu.shutdown()
    cluster = Cluster(initialize_head=True, head_node_args={"num_cpus": 1})
    autoscaler = None
    try:
        cluster.connect()
        transport = FakeTPUTransport(cluster=cluster, cpus_per_vm=2)
        provider = GCETPUNodeProvider(_config(), transport=transport)
        autoscaler = StandardAutoscaler(
            cluster.gcs_address, provider,
            AutoscalerConfig(min_workers=0, max_workers=2,
                             node_resources={"CPU": 2},
                             idle_timeout_s=3.0, launch_grace_s=15.0,
                             update_period_s=0.5))
        autoscaler.start()

        @ray_tpu.remote(num_cpus=2)
        def work(i):
            time.sleep(0.3)
            return i

        out = ray_tpu.get([work.remote(i) for i in range(6)], timeout=120)
        assert out == list(range(6))
        assert autoscaler.num_launches >= 1
        creates = [c for c in transport.calls if c["method"] == "POST"]
        assert creates, "no TPU-VM create request issued"
        assert all(c["body"]["acceleratorType"] == "v5litepod-4"
                   for c in creates)

        # Idle: VMs deleted through the API.
        deadline = time.monotonic() + 60
        while provider.non_terminated_nodes() and \
                time.monotonic() < deadline:
            time.sleep(0.5)
        assert not provider.non_terminated_nodes(), "idle TPU VMs not reaped"
        deletes = [c for c in transport.calls if c["method"] == "DELETE"]
        assert len(deletes) >= 1
    finally:
        if autoscaler is not None:
            autoscaler.stop()
        cluster.shutdown()
