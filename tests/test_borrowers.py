"""Borrower protocol: refs passed inside values survive the owner's frame.

Reference: `src/ray/core_worker/reference_count.h:61,494-500`
(AddBorrowerAddress / WaitForRefRemoved). This framework's redesign is
GCS-mediated: a process deserializing a ref registers itself in the
directory entry's borrower set; the owner's free only marks the entry
pending until the set empties.
"""

import gc
import time

import numpy as np
import pytest

import ray_tpu


@pytest.fixture()
def ray_borrow():
    ray_tpu.shutdown()
    ray_tpu.init(num_cpus=2)
    yield
    ray_tpu.shutdown()


def _entry_known(oid) -> bool:
    rt = ray_tpu._require_runtime()
    return bool(rt.gcs.call("object_locations_get",
                            {"object_id": oid})["known"])


def _wait_for(pred, timeout=20):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if pred():
            return True
        time.sleep(0.2)
    return pred()


def test_borrowed_ref_survives_owner_drop(ray_borrow):
    """An actor stores a ref it received nested in an argument; the owner
    drops every local ref; the object must survive until the actor drops
    it — then the deferred free must actually run."""

    @ray_tpu.remote
    class Holder:
        def __init__(self):
            self.ref = None

        def store(self, container):
            self.ref = container[0]
            return True

        def read(self):
            return float(ray_tpu.get(self.ref).sum())

        def drop(self):
            self.ref = None
            gc.collect()
            return True

    h = Holder.remote()
    # Large enough for the shm store (not inline), so the free is real.
    data = np.ones(300_000)
    ref = ray_tpu.put(data)
    oid = ref.object_id
    assert ray_tpu.get(h.store.remote([ref]), timeout=60)

    # Owner drops its last local reference.
    del ref
    gc.collect()
    time.sleep(2.5)  # free buffer flushes after 1 s

    # The directory entry survives (borrowed), and the actor can still
    # read the object.
    assert _entry_known(oid), "borrowed object was freed under the holder"
    assert ray_tpu.get(h.read.remote(), timeout=60) == 300_000.0

    # Inverse: the borrower drops — the pending free must now fire.
    assert ray_tpu.get(h.drop.remote(), timeout=60)
    assert _wait_for(lambda: not _entry_known(oid)), \
        "object leaked after the last borrower dropped it"


def test_unborrowed_free_still_prompt(ray_borrow):
    """No borrowers: the owner's free removes the entry as before."""
    ref = ray_tpu.put(np.ones(300_000))
    oid = ref.object_id
    assert _entry_known(oid)
    del ref
    gc.collect()
    assert _wait_for(lambda: not _entry_known(oid))


def test_borrower_registered_in_gcs_entry(ray_borrow):
    """The borrower set is visible server-side while the task holds it."""

    @ray_tpu.remote
    class Holder:
        def __init__(self):
            self.ref = None

        def store(self, container):
            self.ref = container[0]
            return True

    h = Holder.remote()
    ref = ray_tpu.put(np.ones(300_000))
    assert ray_tpu.get(h.store.remote([ref]), timeout=60)
    gcs = ray_tpu._global_node.gcs
    with gcs._lock:
        entry = gcs.objects.get(ref.object_id)
    assert entry is not None and entry.get("borrowers"), \
        "actor never registered as a borrower"


def test_nested_ref_resolvable_inside_task(ray_borrow):
    """A task receiving a nested ref can get() it (visibility + pin)."""

    @ray_tpu.remote
    def consume(container):
        return float(ray_tpu.get(container["k"]).sum())

    ref = ray_tpu.put(np.ones(50_000))
    assert ray_tpu.get(consume.remote({"k": ref}), timeout=60) == 50_000.0
