"""Data/Tune breadth + task cancellation + runtime_env env_vars.

Mirrors reference coverage for actor-pool map_batches
(`test_actor_pool_map_operator.py`), limit/sort, adaptive search, and
`ray.cancel` (`test_cancel.py`).
"""

import time

import numpy as np
import pytest

import ray_tpu


# --------------------------------------------------------------------------- #
# Data: actor-pool map_batches, limit, sort
# --------------------------------------------------------------------------- #


class AddState:
    """Stateful UDF: expensive setup once per actor, not per block."""

    def __init__(self, offset):
        import os

        self.offset = offset
        self.pid = os.getpid()

    def __call__(self, batch):
        batch["id"] = batch["id"] + self.offset
        batch["pid"] = np.full(len(batch["id"]), self.pid)
        return batch


def test_map_batches_actor_pool(ray_start_shared):
    from ray_tpu import data
    from ray_tpu.data import ActorPoolStrategy

    ds = data.range(200, parallelism=8).map_batches(
        AddState, compute=ActorPoolStrategy(size=2),
        fn_constructor_args=(1000,))
    rows = ds.take_all()
    assert sorted(r["id"] for r in rows) == list(range(1000, 1200))
    # Exactly pool-size distinct actor processes did the work.
    assert len({r["pid"] for r in rows}) <= 2


def test_map_batches_actor_pool_chains_with_tasks(ray_start_shared):
    from ray_tpu import data
    from ray_tpu.data import ActorPoolStrategy

    ds = (data.range(100, parallelism=4)
          .map_batches(AddState, compute=ActorPoolStrategy(size=1),
                       fn_constructor_args=(0,))
          .filter(lambda r: r["id"] % 2 == 0))
    assert ds.count() == 50


def test_map_batches_class_requires_actor_strategy_fn_check(ray_start_shared):
    from ray_tpu import data
    from ray_tpu.data import ActorPoolStrategy

    with pytest.raises(ValueError):
        data.range(10).map_batches(lambda b: b,
                                   compute=ActorPoolStrategy(size=1))


def test_limit_and_sort(ray_start_shared):
    from ray_tpu import data

    assert data.range(1000, parallelism=10).limit(7).take_all() == [
        {"id": i} for i in range(7)]
    ds = data.from_items([{"v": x} for x in [5, 1, 4, 2, 3]])
    assert [r["v"] for r in ds.sort(key="v").take_all()] == [1, 2, 3, 4, 5]
    assert [r["v"] for r in ds.sort(key="v", descending=True).take_all()] == \
        [5, 4, 3, 2, 1]


# --------------------------------------------------------------------------- #
# Tune: TPE searcher
# --------------------------------------------------------------------------- #


def test_tpe_searcher_suggests_and_improves():
    from ray_tpu.tune.search import TPESearcher, loguniform, uniform

    space = {"x": uniform(-5, 5), "lr": loguniform(1e-4, 1e-1), "fixed": 7}
    s = TPESearcher(space, metric="loss", mode="min", n_initial=6, seed=0)
    # Quadratic bowl at x=2: feed results, expect later suggestions near 2.
    for _ in range(30):
        cfg = s.suggest()
        assert -5 <= cfg["x"] <= 5 and cfg["fixed"] == 7
        s.on_trial_complete(cfg, (cfg["x"] - 2.0) ** 2)
    late = [s.suggest()["x"] for _ in range(10)]
    assert abs(np.median(late) - 2.0) < 1.5, late


def test_tpe_searcher_rejects_grid():
    from ray_tpu.tune.search import TPESearcher, grid_search

    with pytest.raises(ValueError):
        TPESearcher({"a": grid_search([1, 2])}, metric="m")


def test_tuner_with_tpe_search(ray_start_shared, tmp_path):
    from ray_tpu import tune

    def trainable(config):
        tune.report({"score": (config["x"] - 3.0) ** 2})

    tuner = tune.Tuner(
        trainable,
        param_space={"x": tune.uniform(0, 10)},
        tune_config=tune.TuneConfig(
            metric="score", mode="min", num_samples=12,
            max_concurrent_trials=3,
            search_alg=tune.TPESearcher({"x": tune.uniform(0, 10)},
                                        metric="score", mode="min",
                                        n_initial=5, seed=0)),
        run_config=tune.RunConfig(name="tpe_test", storage_path=str(tmp_path))
        if hasattr(tune, "RunConfig") else None,
    )
    results = tuner.fit()
    assert len(results) == 12
    best = results.get_best_result()
    assert best.metrics["score"] < 4.0  # better than random-ish


# --------------------------------------------------------------------------- #
# New datasources: tfrecords, sql, images
# --------------------------------------------------------------------------- #


def test_tfrecords_roundtrip(ray_start_shared, tmp_path):
    from ray_tpu import data
    from ray_tpu.data.datasource import write_tfrecords

    path = str(tmp_path / "recs.tfrecord")
    payloads = [b"alpha", b"beta", bytes(range(256))]
    write_tfrecords([{"data": p} for p in payloads], path)
    rows = data.read_tfrecords(path).take_all()
    assert [r["data"] for r in rows] == payloads
    # CRC validation catches corruption.
    blob = bytearray(open(path, "rb").read())
    blob[14] ^= 0xFF  # flip a data byte
    bad = str(tmp_path / "bad.tfrecord")
    open(bad, "wb").write(bytes(blob))
    from ray_tpu.data.datasource import read_tfrecord_file

    with pytest.raises(ValueError):
        read_tfrecord_file(bad)


def test_read_sql(ray_start_shared, tmp_path):
    import sqlite3

    from ray_tpu import data

    db = str(tmp_path / "t.db")
    conn = sqlite3.connect(db)
    conn.execute("CREATE TABLE items (id INTEGER, name TEXT)")
    conn.executemany("INSERT INTO items VALUES (?, ?)",
                     [(1, "a"), (2, "b"), (3, "c")])
    conn.commit()
    conn.close()
    ds = data.read_sql("SELECT id, name FROM items ORDER BY id",
                       lambda: sqlite3.connect(db))
    rows = ds.take_all()
    assert [int(r["id"]) for r in rows] == [1, 2, 3]
    assert [str(r["name"]) for r in rows] == ["a", "b", "c"]


def test_read_images(ray_start_shared, tmp_path):
    from PIL import Image

    from ray_tpu import data

    for i in range(3):
        Image.new("RGB", (16 + i, 16), (i * 10, 0, 0)).save(
            str(tmp_path / f"img{i}.png"))
    rows = data.read_images(str(tmp_path), size=(8, 8)).take_all()
    assert len(rows) == 3
    assert all(r["image"].shape == (8, 8, 3) for r in rows)


# --------------------------------------------------------------------------- #
# Tune trial fault tolerance
# --------------------------------------------------------------------------- #


def test_tune_trial_restarts_after_actor_death(ray_start_regular, tmp_path):
    import os as _os

    from ray_tpu import tune

    marker = str(tmp_path / "died_once")

    def trainable(config):
        from ray_tpu.train.checkpoint import Checkpoint

        start = 0
        ckpt = tune.get_checkpoint()
        if ckpt is not None:
            start = int(open(_os.path.join(ckpt.path, "step")).read())
        for step in range(start, 6):
            d = str(tmp_path / f"ck{step}")
            _os.makedirs(d, exist_ok=True)
            open(_os.path.join(d, "step"), "w").write(str(step + 1))
            tune.report({"step": step},
                        checkpoint=Checkpoint.from_directory(d))
            if step == 2 and not _os.path.exists(marker):
                open(marker, "w").write("x")
                _os.kill(_os.getpid(), 9)  # simulate node/OOM kill

    tuner = tune.Tuner(
        trainable,
        tune_config=tune.TuneConfig(metric="step", mode="max",
                                    num_samples=1, max_failures=1),
        run_config=tune.RunConfig(name="ft", storage_path=str(tmp_path)),
    )
    results = tuner.fit()
    best = results.get_best_result()
    assert best.error is None
    assert best.metrics["step"] == 5  # finished after restart
    assert _os.path.exists(marker)


# --------------------------------------------------------------------------- #
# cancel + runtime_env
# --------------------------------------------------------------------------- #


def test_cancel_queued_task(ray_start_regular):
    from ray_tpu.exceptions import TaskCancelledError

    @ray_tpu.remote
    def blocked():
        return 1

    ref = blocked.options(num_cpus=99).remote()  # never schedulable
    time.sleep(0.3)
    ray_tpu.cancel(ref)
    with pytest.raises(TaskCancelledError):
        ray_tpu.get(ref, timeout=30)


def test_cancel_running_task(ray_start_regular):
    from ray_tpu.exceptions import TaskCancelledError

    @ray_tpu.remote
    def sleeper():
        time.sleep(60)
        return "finished"

    ref = sleeper.remote()
    time.sleep(3.0)  # let it start executing
    ray_tpu.cancel(ref)
    t0 = time.monotonic()
    with pytest.raises(TaskCancelledError):
        ray_tpu.get(ref, timeout=30)
    assert time.monotonic() - t0 < 25, "cancel did not interrupt the sleep"


def test_cancel_running_task_force(ray_start_regular):
    from ray_tpu.exceptions import TaskCancelledError

    @ray_tpu.remote
    def stubborn():
        while True:
            time.sleep(1)

    ref = stubborn.remote()
    time.sleep(3.0)
    ray_tpu.cancel(ref, force=True)
    with pytest.raises(TaskCancelledError):
        ray_tpu.get(ref, timeout=30)


def test_runtime_env_env_vars(ray_start_regular):
    @ray_tpu.remote
    def read_env():
        import os

        return os.environ.get("MY_RUNTIME_FLAG")

    val = ray_tpu.get(read_env.options(
        runtime_env={"env_vars": {"MY_RUNTIME_FLAG": "on"}}).remote(),
        timeout=60)
    assert val == "on"
    # A task without the env gets a worker without it.
    assert ray_tpu.get(read_env.remote(), timeout=60) is None

# --------------------------------------------------------------------------- #
# remote debugger (reference util/rpdb.py / `ray debug`)
# --------------------------------------------------------------------------- #


def test_rpdb_breakpoint_attach_inspect_continue(ray_start_regular):
    """A task blocks at set_trace, advertises in KV, a client attaches,
    inspects a local, continues, and the task completes."""
    import io as _io
    import threading
    import time as _time

    from ray_tpu.util import rpdb

    @ray_tpu.remote
    def buggy(x):
        from ray_tpu.util import rpdb as _rpdb

        secret = x * 10
        _rpdb.set_trace(timeout_s=60)
        return secret

    ref = buggy.remote(7)
    deadline = _time.time() + 30
    entries = []
    while _time.time() < deadline and not entries:
        entries = rpdb.list_breakpoints()
        _time.sleep(0.2)
    assert entries, "breakpoint never advertised"
    assert entries[0]["function"] == "buggy"

    out = _io.StringIO()
    rpdb.attach(entries[0], stdin=_io.StringIO("p secret\nc\n"), stdout=out)
    assert ray_tpu.get(ref, timeout=30) == 70
    assert "70" in out.getvalue()
    # The breakpoint unregisters after the session.
    assert not rpdb.list_breakpoints()


def test_runtime_env_working_dir_and_py_modules(ray_start_regular, tmp_path):
    """working_dir/py_modules package to content-addressed KV blobs; a
    worker with that env chdirs into the extracted dir and can import the
    shipped module (reference runtime_env working_dir/py_modules)."""
    import os as _os

    wd = tmp_path / "appdir"
    wd.mkdir()
    (wd / "data.txt").write_text("shipped-data")
    mod = tmp_path / "shipped_pkg"
    mod.mkdir()
    (mod / "__init__.py").write_text("MAGIC = 'xyzzy'\n")

    @ray_tpu.remote
    def probe():
        import shipped_pkg  # only importable via the shipped py_module

        return (open("data.txt").read(), shipped_pkg.MAGIC,
                _os.path.basename(_os.getcwd()) != "appdir")

    ref = probe.options(runtime_env={
        "working_dir": str(wd),
        "py_modules": [str(mod)],
    }).remote()
    data, magic, _ = ray_tpu.get(ref, timeout=60)
    assert data == "shipped-data"
    assert magic == "xyzzy"

    # A plain task (no runtime_env) does NOT see the working_dir.
    @ray_tpu.remote
    def plain():
        return _os.path.exists("data.txt")

    assert ray_tpu.get(plain.remote(), timeout=60) is False


def test_internal_kv(ray_start_regular):
    from ray_tpu.experimental import internal_kv as kv

    assert kv._internal_kv_initialized()
    assert kv._internal_kv_put("k1", b"v1") is False  # fresh key
    assert kv._internal_kv_put("k1", b"v2") is True   # existed
    assert kv._internal_kv_get("k1") == b"v2"
    assert kv._internal_kv_put("k1", b"v3", overwrite=False) is True
    assert kv._internal_kv_get("k1") == b"v2"  # not overwritten
    kv._internal_kv_put("k2", b"x")
    assert set(kv._internal_kv_list("k")) >= {b"k1", b"k2"}
    assert kv._internal_kv_exists("k2")
    assert kv._internal_kv_del("k1") == 1
    assert kv._internal_kv_get("k1") is None
    assert kv._internal_kv_del("k", del_by_prefix=True) >= 1


def test_internal_kv_mixed_key_types(ray_start_regular):
    """str and bytes keys interoperate: the GCS normalizes both to bytes,
    so prefix scans never hit a startswith type mismatch (ADVICE r3)."""
    import ray_tpu
    from ray_tpu.experimental import internal_kv as kv

    gcs = ray_tpu._require_runtime().gcs
    # rpdb-style str key straight through the raw GCS API:
    gcs.call("kv_put", {"key": "__mix__:a", "value": b"1"})
    kv._internal_kv_put(b"__mix__:b", b"2")
    # str-prefix scan over a namespace holding both str- and bytes-born keys
    keys = gcs.call("kv_keys", {"prefix": "__mix__:"})["keys"]
    assert set(keys) == {b"__mix__:a", b"__mix__:b"}
    # str key fetches the value written under the same str key
    assert gcs.call("kv_get", {"key": "__mix__:a"})["value"] == b"1"
    # bytes-prefix delete takes out both
    assert kv._internal_kv_del(b"__mix__:", del_by_prefix=True) == 2
