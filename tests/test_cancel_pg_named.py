"""Round-4 contract holes: actor-task cancellation, named placement-group
lookup, DQN Learner-interface conformance.

Reference: `ray.cancel` on actor tasks (core_worker cancellation for
queued/async actor tasks), `ray.util.get_placement_group`, and RLlib's
single-update-path Learner contract (`rllib/core/learner/learner.py:645`).
"""

import time

import pytest

import ray_tpu
from ray_tpu.exceptions import TaskCancelledError


@pytest.fixture()
def ray2():
    ray_tpu.shutdown()
    ray_tpu.init(num_cpus=2)
    yield
    ray_tpu.shutdown()


def test_cancel_queued_actor_task(ray2):
    @ray_tpu.remote
    class Slow:
        def block(self, t):
            time.sleep(t)
            return "done"

        def quick(self):
            return "quick"

    a = Slow.remote()
    blocker = a.block.remote(8)
    time.sleep(0.5)           # blocker occupies the single method thread
    queued = a.block.remote(8)
    ray_tpu.cancel(queued)    # still queued behind blocker -> cancels
    with pytest.raises(TaskCancelledError):
        ray_tpu.get(queued, timeout=30)
    # The actor survives and keeps serving.
    assert ray_tpu.get(blocker, timeout=30) == "done"
    assert ray_tpu.get(a.quick.remote(), timeout=30) == "quick"


def test_cancel_running_async_actor_task(ray2):
    @ray_tpu.remote
    class AsyncActor:
        async def sleeper(self):
            import asyncio

            await asyncio.sleep(60)
            return "done"

        async def quick(self):
            return "q"

    a = AsyncActor.options(max_concurrency=2).remote()
    ref = a.sleeper.remote()
    time.sleep(1.0)           # let it reach the await
    ray_tpu.cancel(ref)
    with pytest.raises(TaskCancelledError):
        ray_tpu.get(ref, timeout=30)
    assert ray_tpu.get(a.quick.remote(), timeout=30) == "q"


def test_cancel_actor_task_force_rejected(ray2):
    @ray_tpu.remote
    class A:
        def f(self):
            time.sleep(5)

    a = A.remote()
    ref = a.f.remote()
    with pytest.raises(ValueError, match="force"):
        ray_tpu.cancel(ref, force=True)


def test_named_placement_group_lookup(ray2):
    from ray_tpu.util.placement_group import (
        get_placement_group,
        placement_group,
        remove_placement_group,
    )

    pg = placement_group([{"CPU": 1}], strategy="PACK", name="my_pg")
    pg.ready(timeout=60)
    found = get_placement_group("my_pg")
    assert found.id == pg.id
    assert found.bundles == [{"CPU": 1.0}]
    with pytest.raises(ValueError, match="no_such_pg"):
        get_placement_group("no_such_pg")
    remove_placement_group(pg)
    deadline = time.monotonic() + 20
    while time.monotonic() < deadline:
        try:
            get_placement_group("my_pg")
        except ValueError:
            break
        time.sleep(0.2)
    with pytest.raises(ValueError):
        get_placement_group("my_pg")


def test_dqn_learner_interface_update():
    """DQNLearner satisfies the generic Learner contract: compute_loss is
    real and update() (one update path) trains, staying consistent with
    the target network after sync_target()."""
    import numpy as np

    from ray_tpu.rllib import sample_batch as sb
    from ray_tpu.rllib.dqn import DQNConfig, DQNLearner, QModule
    from ray_tpu.rllib.rl_module import SpecDict

    cfg = DQNConfig(env="CartPole-v1")
    module = QModule(SpecDict(obs_dim=4, n_actions=2), hidden=(32,))
    learner = DQNLearner(module, cfg, seed=0)

    rng = np.random.default_rng(0)
    batch = {
        sb.OBS: rng.normal(size=(32, 4)).astype(np.float32),
        sb.ACTIONS: rng.integers(0, 2, size=32).astype(np.int32),
        sb.REWARDS: rng.normal(size=32).astype(np.float32),
        sb.DONES: np.zeros(32, dtype=np.float32),
        "next_obs": rng.normal(size=(32, 4)).astype(np.float32),
    }
    m1 = learner.update(dict(batch))
    assert "td_loss" in m1 and "grad_norm" in m1
    # Target sync changes the loss surface; the interface path must see it
    # (a stale closure would keep using the old target).
    learner.sync_target()
    m2 = learner.update(dict(batch))
    assert all(isinstance(v, float) for v in m2.values())
    # compute_loss itself is callable per the interface.
    loss, metrics = learner.compute_loss(
        learner.params, {**batch, "_target_net": learner.target_net})
    assert float(loss) >= 0 and "q_mean" in metrics


def test_trace_spans_propagate_through_nested_tasks(ray2):
    """Span propagation (reference tracing_helper.py:35-81): a task
    submitted from inside another task shares its trace_id and records
    the parent's span as parent_span_id in the task events."""

    @ray_tpu.remote
    def child():
        return "c"

    @ray_tpu.remote
    def parent():
        return ray_tpu.get(child.remote(), timeout=60)

    assert ray_tpu.get(parent.remote(), timeout=120) == "c"

    deadline = time.monotonic() + 20
    by_name = {}
    while time.monotonic() < deadline:
        events = ray_tpu.timeline()
        by_name = {}
        for ev in events:
            if ev.get("state") in ("RUNNING", "FINISHED") and \
                    ev.get("trace_id"):
                short = ev["name"].rsplit(".", 1)[-1]
                by_name.setdefault(short, ev)
        if "parent" in by_name and "child" in by_name:
            break
        time.sleep(0.3)
    assert "parent" in by_name and "child" in by_name, sorted(by_name)
    p, c = by_name["parent"], by_name["child"]
    assert c["trace_id"] == p["trace_id"], (p, c)
    assert c["parent_span_id"] == p["span_id"], (p, c)
    assert p.get("parent_span_id") is None  # driver-rooted trace
