"""Chaos plane: deterministic schedules, RPC fault hook, bounded recovery.

The contract under test (docs/FAULT_TOLERANCE.md): same seed => same
injected event log; the RPC fault filter is provably inert when absent;
every fault class recovers within the deadline with a measured MTTR; and
nothing — neither a parked future nor a state-machine transition — is
allowed to wedge silently.
"""

import threading
import time

import pytest

import ray_tpu
from ray_tpu.chaos import (
    ChaosRunner,
    ChaosSchedule,
    HangWatchdog,
    NodeKillInjector,
    RpcFaultInjector,
    TransitionWatch,
    WorkerKillInjector,
)
from ray_tpu.cluster_utils import Cluster
from ray_tpu.core import rpc as rpc_mod
from ray_tpu.core.rpc import (
    ConnectionLost,
    RpcClient,
    RpcServer,
    clear_chaos_filter,
    install_chaos_filter,
)


# ------------------------------------------------------------ determinism


def test_schedule_same_seed_same_event_log():
    kinds = {"node_kill": 3.0, "gcs_restart": 1.0, "rpc_faults": 1.0}
    a = ChaosSchedule(seed=1234, kinds=kinds, period_s=2.0, count=20)
    b = ChaosSchedule(seed=1234, kinds=kinds, period_s=2.0, count=20)
    c = ChaosSchedule(seed=1235, kinds=kinds, period_s=2.0, count=20)
    assert a.signatures() == b.signatures()
    assert a.signatures() != c.signatures()
    # Times are ordered-ish (one per slot) and kinds come from the set.
    assert all(e.kind in kinds for e in a.events)
    assert [e.seq for e in a.events] == list(range(20))


def test_runner_executes_exactly_the_scheduled_log():
    """The runner's executed log IS the schedule — injectors see events
    in order with the scheduled draws (proven without a cluster)."""

    class NullInjector:
        kind = "noop"

        def __init__(self):
            self.seen = []

        def inject(self, event):
            self.seen.append(event.signature())
            return {"ok": True}

        def recovered(self):
            return True

    sched = ChaosSchedule(seed=7, kinds=("noop",), period_s=0.05, count=5)
    inj = NullInjector()
    runner = ChaosRunner(cluster=None, schedule=sched,
                         injectors={"noop": inj}, recovery_deadline_s=5)
    with runner:
        assert runner.wait(timeout=10)
    assert runner.executed_signatures == sched.signatures()
    assert inj.seen == sched.signatures()
    assert runner.faults_injected == 5
    runner.assert_recovered()
    mttr = runner.mttr_by_kind()["noop"]
    assert mttr["count"] == 5 and mttr["max_ms"] < 1000


# ------------------------------------------------------------ rpc faults


@pytest.fixture()
def rpc_pair():
    server = RpcServer(name="chaos-test")
    server.register("echo", lambda conn, data: data)
    server.start()
    client = RpcClient(server.address, name="chaos-test-client")
    yield server, client
    clear_chaos_filter()
    client.close()
    server.stop()


def test_rpc_filter_error_and_clear(rpc_pair):
    _, client = rpc_pair
    assert client.call("echo", 1) == 1
    install_chaos_filter(lambda name, addr, method: "error")
    with pytest.raises(ConnectionLost):
        client.call("echo", 2)
    clear_chaos_filter()
    # Inert again: the connection itself was never closed.
    assert client.call("echo", 3) == 3


def test_rpc_filter_drop_hits_callers_own_timeout(rpc_pair):
    _, client = rpc_pair
    install_chaos_filter(lambda name, addr, method: "drop")
    t0 = time.monotonic()
    with pytest.raises(TimeoutError):
        client.call("echo", 1, timeout=0.4)
    assert 0.3 < time.monotonic() - t0 < 5.0
    clear_chaos_filter()
    assert client.call("echo", 2) == 2


def test_rpc_filter_delay_and_selectivity(rpc_pair):
    _, client = rpc_pair

    def only_echo_delay(name, addr, method):
        return ("delay", 0.3) if method == "echo" else None

    install_chaos_filter(only_echo_delay)
    t0 = time.monotonic()
    assert client.call("echo", 1) == 1
    assert time.monotonic() - t0 >= 0.3
    clear_chaos_filter()


def test_rpc_filter_disabled_path_is_single_guard():
    """Inertness proof at the code level: with no filter installed the
    send path consults ONE module global and nothing else (the bench's
    A-B-A overhead check covers the runtime side)."""
    assert rpc_mod._CHAOS_FILTER is None


def test_rpc_fault_injector_window():
    inj = RpcFaultInjector(fraction=1.0, action="error", window_s=0.2)
    sched = ChaosSchedule(seed=3, kinds=("rpc_faults",), period_s=0.01,
                          count=1)
    inj.inject(sched.events[0])
    assert rpc_mod._CHAOS_FILTER is not None
    assert not inj.recovered()  # window still open
    time.sleep(0.25)
    assert inj.recovered()
    assert rpc_mod._CHAOS_FILTER is None  # filter removed with the window


# ------------------------------------------------------------- watchdog


def test_hang_watchdog_attributes_parked_ops():
    wd = HangWatchdog(limit_s=0.3, poll_s=0.05)
    release = threading.Event()

    def parked():
        with wd.track("test-op"):
            release.wait(5.0)

    t = threading.Thread(target=parked, daemon=True)
    with wd:
        t.start()
        time.sleep(0.8)
    release.set()
    t.join()
    assert wd.hang_count >= 1
    assert "test-op" in wd.hangs[0]
    with pytest.raises(AssertionError):
        wd.assert_no_hangs()


def test_hang_watchdog_quiet_on_bounded_ops():
    wd = HangWatchdog(limit_s=0.5, poll_s=0.05)
    with wd:
        for _ in range(5):
            with wd.track("quick"):
                time.sleep(0.02)
    wd.assert_no_hangs()


# ------------------------------------------------------- transition watch


def test_transition_watch_attribution_and_progress():
    watch = TransitionWatch("test", deadline_s=0.2)
    watch.enter("replica-1", "STARTING")
    watch.enter("replica-1", "STARTING")  # same state: clock keeps running
    assert watch.stuck() == []
    time.sleep(0.3)
    stuck = watch.stuck()
    assert len(stuck) == 1 and stuck[0][0] == "replica-1" \
        and stuck[0][1] == "STARTING"
    # Progress (a NEW state) resets the clock; completion clears it.
    watch.enter("replica-1", "RECOVERING")
    assert watch.stuck() == []
    watch.clear("replica-1")
    time.sleep(0.3)
    assert watch.stuck() == []
    # fail_stuck counts and clears.
    watch.enter("replica-2", "STARTING")
    time.sleep(0.3)
    assert [k for k, _s, _e in watch.fail_stuck()] == ["replica-2"]
    assert watch.stuck_total == 1


def test_transition_watch_disabled_at_zero_deadline():
    watch = TransitionWatch("test", deadline_s=0.0)
    watch.enter("x", "STARTING")
    time.sleep(0.1)
    assert watch.stuck() == []


# ---------------------------------------------------------- chaos e2e


def test_worker_kill_under_actor_load():
    """Worker-kill injector: a restartable actor's worker is SIGKILLed;
    the fault recovers (actor ALIVE again) within the deadline and
    callers never hang."""
    ray_tpu.shutdown()
    cluster = Cluster(initialize_head=True, head_node_args={"num_cpus": 2})
    try:
        cluster.wait_for_nodes()
        cluster.connect()

        @ray_tpu.remote(max_restarts=4)
        class Bumper:
            def __init__(self):
                self.n = 0

            def bump(self):
                self.n += 1
                return self.n

        b = Bumper.remote()
        assert ray_tpu.get(b.bump.remote(), timeout=30) == 1

        sched = ChaosSchedule(seed=11, kinds=("worker_kill",),
                              period_s=0.5, count=1, jitter=0.0)
        runner = ChaosRunner(
            cluster, sched,
            {"worker_kill": WorkerKillInjector(cluster, actors_only=True)},
            recovery_deadline_s=30)
        with HangWatchdog(limit_s=45) as wd:
            with runner:
                assert runner.wait(timeout=60)
                deadline = time.time() + 30
                ok = False
                while time.time() < deadline:
                    try:
                        ray_tpu.get(b.bump.remote(), timeout=5)
                        ok = True
                        break
                    except Exception:
                        time.sleep(0.2)
                assert ok, "actor never served again after worker kill"
        runner.assert_recovered()
        wd.assert_no_hangs()
        assert runner.faults_injected == 1
        assert runner.mttr_by_kind()["worker_kill"]["count"] == 1
    finally:
        cluster.shutdown()


@pytest.mark.slow
def test_node_kill_chaos_with_task_load():
    """Seeded node-kill chaos under retried task load: all results
    correct, every fault recovered with bounded MTTR, executed log
    matches the schedule, zero hangs."""
    ray_tpu.shutdown()
    cluster = Cluster(initialize_head=True, head_node_args={"num_cpus": 1})
    try:
        for _ in range(2):
            cluster.add_node(num_cpus=2, resources={"churn": 2})
        cluster.wait_for_nodes()
        cluster.connect()

        @ray_tpu.remote
        def slow_square(x):
            time.sleep(0.2)
            return x * x

        sched = ChaosSchedule(seed=42, kinds=("node_kill",), period_s=1.5,
                              count=2, jitter=0.2)
        runner = ChaosRunner(
            cluster, sched,
            {"node_kill": NodeKillInjector(
                cluster, replace=True,
                node_args={"num_cpus": 2, "resources": {"churn": 2}})},
            recovery_deadline_s=45)
        opts = {"resources": {"churn": 1}, "max_retries": 8}
        with HangWatchdog(limit_s=90) as wd:
            with runner:
                results = ray_tpu.get(
                    [slow_square.options(**opts).remote(i)
                     for i in range(16)], timeout=120)
                assert runner.wait(timeout=90)
        assert results == [i * i for i in range(16)]
        runner.assert_recovered()
        wd.assert_no_hangs()
        assert runner.executed_signatures == sched.signatures()
        mttr = runner.mttr_by_kind().get("node_kill")
        assert mttr and mttr["count"] >= 1
    finally:
        cluster.shutdown()


def test_train_gang_elastic_restart_resumes_from_checkpoint():
    """Kill a train worker mid-run: the gang aborts and restarts as a
    unit on a fresh placement group, and the loop RESUMES from the last
    reported checkpoint (step continuity, no lost progress beyond the
    checkpoint lag)."""
    from ray_tpu.train import session
    from ray_tpu.train.checkpoint import Checkpoint
    from ray_tpu.train.config import (
        FailureConfig,
        RunConfig,
        ScalingConfig,
    )
    from ray_tpu.train.trainer import DataParallelTrainer

    ray_tpu.shutdown()
    ray_tpu.init(num_cpus=4)
    try:
        def loop(config):
            ckpt = session.get_checkpoint()
            start = ckpt.to_dict()["step"] + 1 if ckpt is not None else 0
            for step in range(start, 10):
                time.sleep(0.2)
                session.report(
                    {"step": step, "start": start,
                     "world": session.get_world_size()},
                    checkpoint=Checkpoint.from_dict({"step": step})
                    if session.get_world_rank() == 0 else None)

        trainer = DataParallelTrainer(
            loop, scaling_config=ScalingConfig(num_workers=2),
            run_config=RunConfig(
                name="chaos_resume_test",
                failure_config=FailureConfig(max_failures=3)))

        def killer():
            time.sleep(1.6)
            rt = ray_tpu._global_runtime
            rt.raylet.call("chaos_kill_worker",
                           {"draw": 1, "actors_only": True})

        threading.Thread(target=killer, daemon=True).start()
        result = trainer.fit()
        assert result.error is None, result.error
        steps = [m["step"] for m in result.metrics_history]
        starts = sorted({m["start"] for m in result.metrics_history})
        assert steps[-1] == 9, steps
        # The run restarted at least once AND resumed from a checkpoint
        # (a non-zero start step), not from scratch.
        assert len(starts) >= 2 and starts[-1] > 0, starts
    finally:
        ray_tpu.shutdown()


@pytest.mark.slow
def test_serve_stuck_transition_fails_loudly():
    """A replica wedged in STARTING past chaos_recovery_deadline_s is
    failed LOUDLY (attributed critical + forced replacement + counter in
    status()) instead of silently spinning."""
    from ray_tpu import serve

    ray_tpu.shutdown()
    ray_tpu.init(num_cpus=4,
                 _system_config={"chaos_recovery_deadline_s": 1.5})
    try:
        @serve.deployment
        class Wedged:
            def __init__(self):
                time.sleep(120)  # never finishes starting

            def __call__(self, x):
                return x

        try:
            serve.run(Wedged.bind(), timeout_s=6)
        except Exception:  # noqa: BLE001 — never becomes ready, expected
            pass
        st = serve.status()
        assert st["Wedged"]["stuck_transitions"] >= 1, st
    finally:
        try:
            serve.shutdown()
        except Exception:  # noqa: BLE001
            pass
        ray_tpu.shutdown()


@pytest.mark.slow
def test_multiplexed_replica_kill_reloads_adapters_no_leaks():
    """ISSUE 11 satellite + ISSUE 16 warm-radix-tree extension: the
    adapter-multiplexed replica joins the chaos victim set WITH a warm
    radix prefix cache. The three adapters share one prompt, so the
    baselines cross-share cached prefix blocks on one arena (KV is
    adapter-invariant under q/o LoRA targeting). Kill the replica; the
    controller respawns it, requests reload each adapter ON DEMAND and
    rebuild the radix tree from scratch (same seeds => token-identical
    outputs, warm or cold), the rebuilt arena holds zero leaked blocks
    beyond the cache's own donations, and recovery stays under the
    deadline."""
    from ray_tpu import serve
    from ray_tpu.inference import LLMServer

    ray_tpu.shutdown()
    ray_tpu.init(num_cpus=8)
    try:
        adapters = {"m-a": {"seed": 11}, "m-b": {"seed": 22},
                    "m-c": {"seed": 33}}
        # block_size 4: the 9-token shared prompt caches 2 full blocks,
        # so adapters b/c hit adapter a's donated prefix.
        handle = serve.run(LLMServer.options(
            name="mux", num_replicas=1,
            max_concurrent_queries=16).bind(
                "tiny", 256, 8, {"block_size": 4, "max_blocks_per_seq": 16},
                adapters))

        def gen(mid, timeout=120):
            return ray_tpu.get(handle.generate.remote(
                {"ids": [1, 2, 3, 4, 5, 6, 7, 8, 9], "max_new_tokens": 6,
                 "model_id": mid}), timeout=timeout)

        baseline = {mid: gen(mid) for mid in adapters}
        pre = ray_tpu.get(handle.metrics.remote(None), timeout=30)
        assert sorted(pre["adapters"]["resident"]) == sorted(adapters)
        # Cross-adapter prefix sharing: the 2nd and 3rd adapters' shared
        # prompt hit the 1st's cached blocks.
        assert pre["prefix_cache"]["hits"] >= 2, pre["prefix_cache"]
        # Drained: the only arena references are the cache's donations.
        assert (pre["kv"]["blocks_in_use"]
                == pre["prefix_cache"]["cached_blocks"] > 0), pre

        # SIGKILL-equivalent: the replica actor dies with 3 resident
        # adapters; the controller's health check replaces it.
        victim = ray_tpu.get_actor("SERVE_REPLICA::mux#0",
                                   namespace="serve")
        ray_tpu.kill(victim)
        t0 = time.perf_counter()
        recovered = None
        with HangWatchdog(limit_s=90) as wd:
            deadline = time.time() + 60
            while time.time() < deadline:
                try:
                    recovered = gen("m-b", timeout=10)
                    break
                except Exception:  # noqa: BLE001 — replica mid-respawn
                    time.sleep(0.25)
        mttr_s = time.perf_counter() - t0
        assert recovered is not None, "replica never served again"
        assert mttr_s < 60.0, f"MTTR {mttr_s:.1f}s exceeds the deadline"
        wd.assert_no_hangs()

        # On-demand reload, token-identical to the pre-crash replica —
        # the first post-crash gen ran against a COLD tree, proving
        # cached and uncached paths emit the same bytes.
        assert recovered == baseline["m-b"]
        for mid in ("m-a", "m-c"):
            assert gen(mid) == baseline[mid], mid
        # Second pass: now the rebuilt tree is warm again — every
        # adapter's generation must hit it and stay bit-identical.
        for mid in adapters:
            assert gen(mid) == baseline[mid], mid
        post = ray_tpu.get(handle.metrics.remote(None), timeout=30)
        # The fresh replica loaded exactly the adapters requested since
        # the crash (on demand — not a bulk restore at spawn).
        assert sorted(post["adapters"]["resident"]) == sorted(adapters)
        assert post["adapters"]["loads"] == 3
        assert post["prefix_cache"]["hits"] >= 3, post["prefix_cache"]
        # Zero leaked arena blocks across the kill/respawn/reload/rewarm
        # cycle: in-use equals exactly the warm tree's donations.
        assert (post["kv"]["blocks_in_use"]
                == post["prefix_cache"]["cached_blocks"] > 0), post["kv"]
        assert post["prefill_compiles"] == 1 and \
            post["decode_compiles"] == 1, post
    finally:
        try:
            serve.shutdown()
        except Exception:  # noqa: BLE001
            pass
        ray_tpu.shutdown()


# ---------------------------------------- task fast path in the victim set


@pytest.mark.slow
def test_node_kill_invalidates_lease_cache():
    """Node death mid-push: every lease cached against the dead node's
    workers is invalidated (the RL012 death hook), in-flight tasks
    re-route to fresh leases within their retry budget, and the
    side-channel execution marks prove no task was lost and no stale
    lease double-pushed one (dup executions <= owner-recorded retries)."""
    import os
    import tempfile

    ray_tpu.shutdown()
    cluster = Cluster(initialize_head=True, head_node_args={"num_cpus": 1})
    mark_file = os.path.join(tempfile.mkdtemp(), "lease_marks")
    try:
        for _ in range(2):
            cluster.add_node(num_cpus=2, resources={"churn": 2})
        cluster.wait_for_nodes()
        cluster.connect()

        @ray_tpu.remote
        def marked(path, idx):
            time.sleep(0.05)
            with open(path, "a") as f:
                f.write(f"{idx}\n")
            return idx

        opts = {"resources": {"churn": 1}, "max_retries": 8}
        # Warm leases on the churn nodes, then keep the pipeline deep so
        # the kill lands while pushes are in flight.
        ray_tpu.get([marked.options(**opts).remote(mark_file, -1 - i)
                     for i in range(4)], timeout=60)
        d = ray_tpu._require_runtime()._direct
        lost_before = d.stats["leases_lost"] + d.stats["leases_swept"]

        refs = [marked.options(**opts).remote(mark_file, i)
                for i in range(60)]
        time.sleep(0.4)  # mid-stream...
        victim = next(r for r in cluster.raylets if not r.is_head)
        cluster.crash_node(victim)
        cluster.add_node(num_cpus=2, resources={"churn": 2})

        with HangWatchdog(limit_s=120) as wd:
            results = ray_tpu.get(refs, timeout=120)
        wd.assert_no_hangs()
        assert results == list(range(60)), "task lost under node kill"
        # The death hook fired for the victim's leases.
        assert d.stats["leases_lost"] + d.stats["leases_swept"] \
            > lost_before, "no cached lease was invalidated by the kill"
        with d._lock:
            for leases in d._leases.values():
                for lease in leases:
                    assert not lease.closed
        # Duplicate executions are owner-accounted retries, never a
        # stale-lease double push.
        counts: dict = {}
        with open(mark_file) as f:
            for line in f:
                if line.strip():
                    idx = int(line)
                    counts[idx] = counts.get(idx, 0) + 1
        rt = ray_tpu._require_runtime()
        retries = sum(rec.attempts for rec in rt._tasks.values()
                      if rec.spec is not None
                      and rec.spec.name.endswith("marked"))
        dup = sum(c - 1 for c in counts.values()
                  if c > 1)
        assert dup <= retries, (
            f"{dup} duplicate executions but only {retries} owner "
            "retries: a stale lease double-pushed")
    finally:
        cluster.shutdown()


def test_pubsub_delta_batch_monotonic_across_gcs_failover():
    """Delta-batched pubsub frames carry a strictly-increasing seq per
    connection; resource churn before, during, and after a GCS failover
    never reorders or replays a batch, and the subscriber's merged view
    converges to the restarted GCS's live resource view."""
    import os
    import tempfile

    ray_tpu.shutdown()
    path = os.path.join(tempfile.mkdtemp(), "gcs_tables.bin")
    cluster = Cluster(initialize_head=True,
                      head_node_args={"num_cpus": 1},
                      gcs_storage_path=path)
    subs = []
    try:
        cluster.wait_for_nodes()

        frames: list = []   # (client_epoch, seq, events)

        def make_subscriber(epoch):
            def on_push(method, data):
                if method == "pubsub_batch":
                    frames.append((epoch, data["seq"], data["events"]))
                elif method == "pubsub":
                    frames.append((epoch, None, [data]))
            cli = RpcClient(cluster.gcs.address,
                            name=f"delta-sub-{epoch}",
                            push_handler=on_push)
            cli.call("subscribe", {"channel": "RESOURCES", "key": b"*"},
                     timeout=10)
            subs.append(cli)
            return cli

        make_subscriber(0)
        # Resource churn: node joins force full-view broadcasts; task
        # load drives per-node deltas.
        added = [cluster.add_node(num_cpus=1, resources={"c": 1})
                 for _ in range(3)]
        cluster.wait_for_nodes()
        deadline = time.monotonic() + 20
        while time.monotonic() < deadline and not any(
                e for _, s, e in frames if s is not None):
            time.sleep(0.1)

        cluster.kill_gcs()
        cluster.restart_gcs()
        # The old connection died with the GCS; a reconnected subscriber
        # is a NEW connection epoch with its own seq stream.
        deadline = time.monotonic() + 30
        while time.monotonic() < deadline:
            try:
                make_subscriber(1)
                break
            except Exception:  # noqa: BLE001 — GCS still restarting
                time.sleep(0.2)
        cluster.add_node(num_cpus=1, resources={"c": 1})
        deadline = time.monotonic() + 20
        while time.monotonic() < deadline and not any(
                ep == 1 and s is not None for ep, s, _ in frames):
            time.sleep(0.1)

        # Monotonicity: per (epoch), batch seqs strictly increase —
        # never reordered, never replayed, across the failover.
        by_epoch: dict = {}
        for ep, seq, _events in frames:
            if seq is None:
                continue
            assert seq > by_epoch.get(ep, 0), (
                f"batch seq regressed in epoch {ep}: {seq} after "
                f"{by_epoch.get(ep)}")
            by_epoch[ep] = seq
        assert by_epoch.get(1), "no delta batch arrived after failover"

        # Convergence: fold every RESOURCES event in arrival order; the
        # merged view must match the restarted GCS's live view.
        view: dict = {}
        for _ep, _seq, events in frames:
            for ev in events:
                msg = ev["message"]
                if "delta" in msg:
                    view.update(msg["delta"])
                else:
                    view = dict(msg)
        deadline = time.monotonic() + 20
        while time.monotonic() < deadline:
            live = cluster.gcs.handle_get_resource_view(None) \
                if hasattr(cluster.gcs, "handle_get_resource_view") \
                else cluster.gcs._resource_view()
            if set(view) >= {k for k, e in live.items() if e.get("alive")}:
                break
            time.sleep(0.2)
        alive = {k for k, e in live.items() if e.get("alive")}
        assert set(view) >= alive, (
            f"subscriber view missing alive nodes: {alive - set(view)}")
    finally:
        for cli in subs:
            try:
                cli.close()
            except Exception:  # noqa: BLE001
                pass
        cluster.shutdown()


# ------------------------------------------------------- job driver kill


def test_driver_kill_detached_survives_next_job_unaffected():
    """Driver-kill schedule for the job tier (docs/JOBS.md cleanup
    contract): SIGKILL a submitted job's driver mid-run; its detached
    actor survives with state, its non-detached actor is reclaimed, and
    a second job submitted DURING the first's cleanup runs its first
    task normally (cleanup never wedges dispatch)."""
    import os
    import signal
    import sys

    from ray_tpu.job_submission import JobStatus, JobSubmissionClient

    ray_tpu.shutdown()
    ray_tpu.init(num_cpus=4)
    client = JobSubmissionClient(ray_tpu._global_runtime.gcs.address)
    try:
        sid = client.submit_job(entrypoint=(
            f"{sys.executable} -c \""
            "import os, time, ray_tpu; ray_tpu.init()\n"
            "@ray_tpu.remote\n"
            "class Keeper:\n"
            "    def __init__(self):\n"
            "        self.n = 0\n"
            "    def bump(self):\n"
            "        self.n += 1\n"
            "        return self.n\n"
            "d = Keeper.options(name='chaos-keeper', "
            "lifetime='detached').remote()\n"
            "e = Keeper.options(name='chaos-eph').remote()\n"
            "ray_tpu.get([d.bump.remote(), e.bump.remote()])\n"
            "print('READY pid=%d' % os.getpid(), flush=True)\n"
            "time.sleep(120)\""))
        # Wait for the driver to report itself, then SIGKILL it — no
        # SIGTERM grace, no atexit: the hardest driver death.
        deadline = time.monotonic() + 60
        pid = None
        while time.monotonic() < deadline and pid is None:
            for line in client.get_job_logs(sid).splitlines():
                if line.startswith("READY pid="):
                    pid = int(line.split("=", 1)[1])
            time.sleep(0.2)
        assert pid is not None, client.get_job_logs(sid)[-500:]
        os.kill(pid, signal.SIGKILL)
        # Second job races the first's cleanup: submit-to-first-task must
        # complete normally while workers/actors of job 1 are torn down.
        sid2 = client.submit_job(entrypoint=(
            f"{sys.executable} -c \""
            "import ray_tpu; ray_tpu.init()\n"
            "@ray_tpu.remote\n"
            "def first():\n"
            "    return 'second-job-task-ran'\n"
            "print(ray_tpu.get(first.remote()))\n"
            "ray_tpu.shutdown()\""))
        deadline = time.monotonic() + 60
        while time.monotonic() < deadline and \
                client.get_job_status(sid2) not in JobStatus.TERMINAL:
            time.sleep(0.25)
        assert client.get_job_status(sid2) == JobStatus.SUCCEEDED, \
            client.get_job_logs(sid2)[-500:]
        assert "second-job-task-ran" in client.get_job_logs(sid2)
        # Job 1 lands FAILED (killed, not stopped by the platform).
        deadline = time.monotonic() + 30
        while time.monotonic() < deadline and \
                client.get_job_status(sid) not in JobStatus.TERMINAL:
            time.sleep(0.25)
        assert client.get_job_status(sid) == JobStatus.FAILED
        # Detached actor survives the driver kill with its state...
        handle = ray_tpu.get_actor("chaos-keeper")
        assert ray_tpu.get(handle.bump.remote(), timeout=30) == 2
        # ...the non-detached one is reclaimed with the job.
        deadline = time.monotonic() + 30
        gone = False
        while time.monotonic() < deadline and not gone:
            try:
                ray_tpu.get_actor("chaos-eph")
                time.sleep(0.25)
            except ValueError:
                gone = True
        assert gone, "non-detached actor outlived its killed driver"
        ray_tpu.kill(handle)
    finally:
        client.close()
        ray_tpu.shutdown()
