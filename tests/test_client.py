"""ray:// client mode: drive the cluster from a process that isn't in it.

Mirrors the reference's Ray Client tests (`ray/util/client/`): the client
process has no raylet and no shared-memory attachment — everything proxies
through the head's client server.
"""

import json
import os
import subprocess
import sys
import textwrap

import ray_tpu

CLIENT_SCRIPT = textwrap.dedent("""
    import json, sys
    import ray_tpu

    ray_tpu.init(address="ray://" + sys.argv[1])

    @ray_tpu.remote
    def square(x):
        return x * x

    @ray_tpu.remote
    class Counter:
        def __init__(self, start):
            self.n = start
        def bump(self, by=1):
            self.n += by
            return self.n

    out = {}
    out["tasks"] = ray_tpu.get([square.remote(i) for i in range(5)])
    big = ray_tpu.put(list(range(50000)))           # forces proxy put path
    out["big_len"] = len(ray_tpu.get(big))
    c = Counter.options(name="cli-counter").remote(10)
    out["bumps"] = [ray_tpu.get(c.bump.remote()) for _ in range(3)]
    again = ray_tpu.get_actor("cli-counter")
    out["named"] = ray_tpu.get(again.bump.remote(5))
    ready, pending = ray_tpu.wait([square.remote(9)], timeout=30)
    out["wait_ready"] = len(ready)
    try:
        ray_tpu.get(square.remote("nope"), timeout=30)
        out["error"] = "MISSED"
    except TypeError:
        out["error"] = "TypeError"
    out["nodes"] = len([n for n in ray_tpu.nodes() if n["Alive"]])
    print("RESULT:" + json.dumps(out))
    ray_tpu.shutdown()
""")


def test_client_mode_end_to_end(ray_start_regular, tmp_path):
    gcs_address = ray_tpu._global_runtime.gcs.address
    script = tmp_path / "client.py"
    script.write_text(CLIENT_SCRIPT)
    env = dict(os.environ)
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env["PYTHONPATH"] = repo + os.pathsep + env.get("PYTHONPATH", "")
    proc = subprocess.run([sys.executable, str(script), gcs_address],
                          capture_output=True, text=True, timeout=240,
                          env=env)
    line = next((ln for ln in proc.stdout.splitlines()
                 if ln.startswith("RESULT:")), None)
    assert line, f"stdout={proc.stdout[-500:]} stderr={proc.stderr[-1500:]}"
    out = json.loads(line[len("RESULT:"):])
    assert out["tasks"] == [0, 1, 4, 9, 16]
    assert out["big_len"] == 50000
    assert out["bumps"] == [11, 12, 13]
    assert out["named"] == 18
    assert out["wait_ready"] == 1
    assert out["error"] == "TypeError"
    assert out["nodes"] >= 1


def test_client_cancel_and_sliced_get(ray_start_regular):
    """cancel() proxies in client mode, and a get longer than one server
    slice still completes (the client loops bounded slices)."""
    import time

    from ray_tpu.client import ClientRuntime
    from ray_tpu.client.server import CLIENT_SERVER_KV_KEY

    addr = ray_tpu._global_runtime.gcs.call(
        "kv_get", {"namespace": "cluster",
                   "key": CLIENT_SERVER_KV_KEY})["value"].decode()
    client = ClientRuntime(addr)
    client._SLICE_S = 1.0  # force multiple slices
    try:
        from ray_tpu.core import serialization
        from ray_tpu.core.common import TaskSpec
        from ray_tpu.core.ids import TaskID

        def nap():
            import time as _t

            _t.sleep(3)
            return "done"

        blob = serialization.dumps(nap)
        fn_id = client.export_function(blob)
        spec = TaskSpec(task_id=TaskID.for_task(client.job_id),
                        job_id=client.job_id, name="nap",
                        function_id=fn_id, function_blob=None,
                        resources={"CPU": 1.0})
        (oid,) = client.submit_task(spec)
        assert client.get([oid], timeout=60) == ["done"]  # > 2 slices
    finally:
        client.shutdown()


def test_client_refs_released_on_disconnect(ray_start_regular):
    """The server registers refs per client and drops them when the client
    connection closes (no leak across client sessions)."""
    from ray_tpu.client import ClientRuntime
    from ray_tpu.client.server import CLIENT_SERVER_KV_KEY

    addr = ray_tpu._global_runtime.gcs.call(
        "kv_get", {"namespace": "cluster",
                   "key": CLIENT_SERVER_KV_KEY})["value"].decode()
    client = ClientRuntime(addr)
    oid = client.put([1, 2, 3])
    assert client.get([oid]) == [[1, 2, 3]]
    server = ray_tpu._global_node.client_server
    assert any(oid.binary() in refs
               for refs in server._client_refs.values())
    client.shutdown()
    import time

    deadline = time.monotonic() + 10
    while time.monotonic() < deadline:
        if not any(oid.binary() in refs
                   for refs in server._client_refs.values()):
            break
        time.sleep(0.2)
    assert not any(oid.binary() in refs
                   for refs in server._client_refs.values())
