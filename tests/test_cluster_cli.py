"""Cluster bring-up from real OS processes via `python -m ray_tpu start`.

Reference: `ray start/stop` (`python/ray/scripts/scripts.py:535,1231`) and
the services layer that runs gcs/raylet as driver-independent processes
(`python/ray/_private/services.py:1280,1353`).
"""

import json
import os
import signal
import subprocess
import sys
import time

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _env(tmpdir):
    env = dict(os.environ)
    env["RAY_TPU_TMPDIR"] = str(tmpdir)
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    return env


def _cli(args, env, timeout=90):
    return subprocess.run(
        [sys.executable, "-m", "ray_tpu", *args], env=env, cwd="/tmp",
        capture_output=True, text=True, timeout=timeout)


def _stop_all(env):
    try:
        _cli(["stop", "--force"], env, timeout=30)
    except Exception:
        pass


@pytest.fixture
def cluster_env(tmp_path):
    env = _env(tmp_path)
    yield env
    _stop_all(env)


def test_two_node_cluster_from_cli_processes(cluster_env):
    """Head + worker as separate daemonized OS processes; a driver
    connects, runs work on both, disconnects, reconnects; `stop` tears
    everything down."""
    env = cluster_env
    head = _cli(["start", "--head", "--num-cpus", "1",
                 "--resources", '{"head_marker": 1}'], env)
    assert head.returncode == 0, head.stderr
    address = head.stdout.split("started at ")[1].split()[0]

    worker = _cli(["start", "--address", address, "--num-cpus", "1",
                   "--resources", '{"worker_marker": 1}',
                   "--labels", "kind=worker-vm"], env)
    assert worker.returncode == 0, worker.stderr

    # The daemons are real detached processes with records on disk.
    base = str(env["RAY_TPU_TMPDIR"])
    recs = []
    for name in os.listdir(os.path.join(base, "daemons")):
        with open(os.path.join(base, "daemons", name)) as f:
            recs.append(json.load(f))
    assert sorted(r["role"] for r in recs) == ["head", "worker"]
    for r in recs:
        os.kill(r["pid"], 0)  # alive

    driver = r"""
import time
import ray_tpu

ray_tpu.init(address="auto")
deadline = time.time() + 30
while time.time() < deadline:
    alive = [n for n in ray_tpu.nodes() if n["Alive"]]
    if len(alive) == 2:
        break
    time.sleep(0.25)
assert len(alive) == 2, alive
assert any(n.get("Labels", {}).get("kind") == "worker-vm" for n in alive)

@ray_tpu.remote(resources={"worker_marker": 0.1})
def on_worker():
    import os
    return os.getpid()

@ray_tpu.remote(resources={"head_marker": 0.1})
def on_head():
    import os
    return os.getpid()

wpid = ray_tpu.get(on_worker.remote(), timeout=60)
hpid = ray_tpu.get(on_head.remote(), timeout=60)
assert wpid != hpid
print("DRIVER_OK", wpid, hpid)
"""
    for attempt in range(2):  # run twice: disconnect must not hurt the cluster
        out = subprocess.run([sys.executable, "-c", driver], env=env,
                             cwd="/tmp", capture_output=True, text=True,
                             timeout=120)
        assert out.returncode == 0, (out.stdout, out.stderr)
        assert "DRIVER_OK" in out.stdout

    stop = _cli(["stop"], env)
    assert stop.returncode == 0, stop.stderr
    assert "stopped 2" in stop.stdout
    for r in recs:
        deadline = time.time() + 10
        while time.time() < deadline:
            try:
                os.kill(r["pid"], 0)
                time.sleep(0.1)
            except ProcessLookupError:
                break
        else:
            pytest.fail(f"daemon {r['pid']} still alive after stop")
    assert not os.path.exists(os.path.join(base, "ray_current_cluster.json"))


def test_head_survives_driver_sigkill(cluster_env):
    """Driver crash (SIGKILL) must not take the cluster down — the head is
    a separate process, unlike an in-process `ray_tpu.init()` node."""
    env = cluster_env
    head = _cli(["start", "--head", "--num-cpus", "1"], env)
    assert head.returncode == 0, head.stderr
    address = head.stdout.split("started at ")[1].split()[0]

    crasher = (
        "import ray_tpu, os, time\n"
        f"ray_tpu.init(address={address!r})\n"
        "print('CONNECTED', flush=True)\n"
        "time.sleep(60)\n")
    proc = subprocess.Popen([sys.executable, "-c", crasher], env=env,
                            cwd="/tmp", stdout=subprocess.PIPE, text=True)
    assert proc.stdout.readline().strip() == "CONNECTED"
    proc.send_signal(signal.SIGKILL)
    proc.wait(timeout=10)

    check = (
        "import ray_tpu\n"
        f"ray_tpu.init(address={address!r})\n"
        "@ray_tpu.remote\n"
        "def f(): return 41 + 1\n"
        "assert ray_tpu.get(f.remote(), timeout=60) == 42\n"
        "print('STILL_UP')\n")
    out = subprocess.run([sys.executable, "-c", check], env=env, cwd="/tmp",
                         capture_output=True, text=True, timeout=120)
    assert out.returncode == 0, (out.stdout, out.stderr)
    assert "STILL_UP" in out.stdout
