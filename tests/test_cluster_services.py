"""Cluster services: metrics export, log streaming, job submission,
autoscaler.

Mirrors the reference's `test_metrics_agent.py`, `test_output.py`
(log_to_driver), `test_job_manager.py`, and `test_autoscaler.py` at the
behavior level.
"""

import sys
import time

import pytest

import ray_tpu


# --------------------------------------------------------------------------- #
# Metrics
# --------------------------------------------------------------------------- #


def test_metrics_api_and_prometheus_render():
    from ray_tpu.util.metrics import (
        Counter,
        Gauge,
        Histogram,
        render_prometheus,
    )

    c = Counter("test_requests_total", "requests", tag_keys=("route",))
    c.inc(2, tags={"route": "/a"})
    c.inc(1, tags={"route": "/b"})
    g = Gauge("test_queue_depth", "depth")
    g.set(7)
    h = Histogram("test_latency_s", "latency", boundaries=[0.1, 1.0])
    h.observe(0.05)
    h.observe(0.5)
    h.observe(5.0)

    snaps = {"proc1": [c._snapshot(), g._snapshot(), h._snapshot()]}
    text = render_prometheus(snaps)
    assert 'test_requests_total{route="/a",proc="proc1"} 2.0' in text
    assert "test_queue_depth" in text and "} 7" in text
    assert 'test_latency_s_bucket' in text
    assert 'le="+Inf"} 3' in text
    assert "test_latency_s_count" in text

    with pytest.raises(ValueError):
        c.inc(-1)
    with pytest.raises(ValueError):
        Histogram("bad_hist", boundaries=[])


def test_metrics_flow_to_gcs(ray_start_regular):
    from ray_tpu.util.metrics import Counter

    @ray_tpu.remote
    def bump():
        from ray_tpu.util.metrics import Counter as C

        c = C("task_side_counter", "from a worker")
        c.inc(5)
        # Force a flush so the test doesn't wait for the 2s period.
        ray_tpu._global_runtime._metrics_pusher.flush()
        return True

    Counter("driver_side_counter", "from the driver").inc(3)
    ray_tpu._global_runtime._metrics_pusher.flush()
    assert ray_tpu.get(bump.remote())

    snap = ray_tpu._global_runtime.gcs.call("metrics_snapshot")
    names = {m["name"] for metrics in snap.values() for m in metrics}
    assert "driver_side_counter" in names
    assert "task_side_counter" in names
    text = ray_tpu._global_runtime.gcs.call("metrics_prometheus")["text"]
    assert "driver_side_counter" in text


# --------------------------------------------------------------------------- #
# Log streaming
# --------------------------------------------------------------------------- #


def test_worker_prints_stream_to_driver(ray_start_regular, capsys):
    @ray_tpu.remote
    def chatty(i):
        print(f"hello-from-task-{i}")
        sys.stdout.flush()
        import ray_tpu as rt

        # Push the batch now instead of waiting for the 0.25s flusher.
        return i

    ray_tpu.get([chatty.remote(i) for i in range(3)])
    deadline = time.monotonic() + 10
    seen = ""
    while time.monotonic() < deadline:
        seen += capsys.readouterr().err
        if all(f"hello-from-task-{i}" in seen for i in range(3)):
            break
        time.sleep(0.2)
    for i in range(3):
        assert f"hello-from-task-{i}" in seen, seen[-500:]
    assert "pid=" in seen  # worker prefix


# --------------------------------------------------------------------------- #
# Job submission
# --------------------------------------------------------------------------- #


def test_job_submission_end_to_end(ray_start_regular):
    from ray_tpu.job_submission import JobStatus, JobSubmissionClient

    address = ray_tpu._global_runtime.gcs.address
    client = JobSubmissionClient(address)

    sid = client.submit_job(
        entrypoint=(
            f"{sys.executable} -c \""
            "import ray_tpu; ray_tpu.init()\n"
            "print('job says hi')\n"
            "ray_tpu.shutdown()\""),
        metadata={"owner": "test"})
    deadline = time.monotonic() + 120
    while time.monotonic() < deadline:
        status = client.get_job_status(sid)
        if status in JobStatus.TERMINAL:
            break
        time.sleep(0.5)
    logs = client.get_job_logs(sid)
    assert status == JobStatus.SUCCEEDED, f"status={status} logs={logs[-800:]}"
    assert "job says hi" in logs
    info = client.get_job_info(sid)
    assert info.metadata["owner"] == "test"
    assert any(j.submission_id == sid for j in client.list_jobs())
    client.close()


def test_job_stop_and_failure_status(ray_start_regular):
    from ray_tpu.job_submission import JobStatus, JobSubmissionClient

    address = ray_tpu._global_runtime.gcs.address
    client = JobSubmissionClient(address)

    fail_id = client.submit_job(
        entrypoint=f"{sys.executable} -c 'raise SystemExit(3)'")
    deadline = time.monotonic() + 60
    while client.get_job_status(fail_id) not in JobStatus.TERMINAL and \
            time.monotonic() < deadline:
        time.sleep(0.2)
    assert client.get_job_status(fail_id) == JobStatus.FAILED

    slow_id = client.submit_job(
        entrypoint=f"{sys.executable} -c 'import time; time.sleep(60)'")
    while client.get_job_status(slow_id) == JobStatus.PENDING and \
            time.monotonic() < deadline:
        time.sleep(0.1)
    assert client.stop_job(slow_id)
    deadline = time.monotonic() + 30
    while client.get_job_status(slow_id) != JobStatus.STOPPED and \
            time.monotonic() < deadline:
        time.sleep(0.2)
    assert client.get_job_status(slow_id) == JobStatus.STOPPED
    assert client.delete_job(slow_id)
    client.close()


def _leaked_pids(mark: str):
    """Pids whose /proc cmdline carries `mark`. The job-manager leak
    tests put the mark INSIDE the `python -c` source so it lands in the
    grandchild's argv — a shell-comment mark dies with the sh wrapper
    and the scan would pass vacuously. (A zombie has an empty cmdline,
    so a killed-but-unreaped process cannot false-positive.)"""
    import os

    pids = []
    for pid in os.listdir("/proc"):
        if not pid.isdigit():
            continue
        try:
            with open(f"/proc/{pid}/cmdline", "rb") as f:
                cmdline = f.read()
        except OSError:
            continue  # exited while scanning
        if mark.encode() in cmdline:
            pids.append(pid)
    return pids


def test_job_manager_shutdown_kills_inflight_spawn(tmp_path):
    """shutdown() racing submit() must never orphan an entrypoint: a job
    still PENDING (spawn in flight on the runner thread) is marked
    STOPPED, and the runner's post-spawn handshake delivers the kill to
    the process group it just created (manager.py _run)."""
    import uuid

    from ray_tpu.job_submission import JobStatus
    from ray_tpu.job_submission.manager import JobManager

    mark = "jmorph_" + uuid.uuid4().hex[:12]
    jm = JobManager(gcs_address="127.0.0.1:1", log_dir=str(tmp_path))
    # First batch gets a head start (likely RUNNING when shutdown lands),
    # second batch is submitted immediately before it (likely still
    # PENDING mid-spawn) — both sides of the race in one pass.
    sids = [jm.submit(f"{sys.executable} -c "
                      f"'import time; time.sleep(45)  # {mark}'")
            for _ in range(2)]
    time.sleep(0.3)
    sids += [jm.submit(f"{sys.executable} -c "
                       f"'import time; time.sleep(45)  # {mark}'")
             for _ in range(2)]
    jm.shutdown()

    deadline = time.monotonic() + 20
    while time.monotonic() < deadline:
        details = [jm.details(s) for s in sids]
        if all(d["status"] == JobStatus.STOPPED and d["end_time"]
               for d in details) and not _leaked_pids(mark):
            break
        time.sleep(0.2)
    details = [jm.details(s) for s in sids]
    assert all(d["status"] == JobStatus.STOPPED for d in details), details
    assert all(d["end_time"] for d in details), details
    assert _leaked_pids(mark) == []


def test_job_manager_shutdown_waits_for_kill_delivery(tmp_path):
    """shutdown() must not return while the off-thread kill handshake is
    still in flight: the caller (GcsServer.stop) exits the process right
    after, and an unjoined daemon killer dies with it — its SIGTERM
    never sent, the entrypoint orphaned. A TERM-trapping driver is the
    worst case: delivery needs the full grace period + SIGKILL."""
    import uuid

    from ray_tpu.job_submission import JobStatus
    from ray_tpu.job_submission.manager import JobManager

    mark = "jmjoin_" + uuid.uuid4().hex[:12]
    jm = JobManager(gcs_address="127.0.0.1:1", log_dir=str(tmp_path))
    sid = jm.submit(
        f'{sys.executable} -c "import signal, time; '
        f'signal.signal(signal.SIGTERM, signal.SIG_IGN); '
        f'time.sleep(60)  # {mark}"')
    deadline = time.monotonic() + 10
    while jm.details(sid)["status"] == JobStatus.PENDING and \
            time.monotonic() < deadline:
        time.sleep(0.05)
    # Give the driver time to install its SIGTERM trap — otherwise the
    # group TERM kills it before the trap exists and the escalation path
    # under test never has to fire.
    time.sleep(1.2)
    jm.shutdown()
    # No grace window here: by the time shutdown() returns, the group
    # must be dead and reaped (killer joined), not merely signaled.
    leaked = _leaked_pids(mark)
    assert leaked == [], f"entrypoint outlived shutdown(): {leaked}"


def test_job_manager_submit_after_shutdown_raises(tmp_path):
    """The GCS RPC server keeps serving submits while it tears down
    (server.stop() runs AFTER job_manager.shutdown()); a submit admitted
    then would spawn after the kill sweep and be orphaned on process
    exit. It must be refused instead."""
    import pytest

    from ray_tpu.job_submission.manager import JobManager

    jm = JobManager(gcs_address="127.0.0.1:1", log_dir=str(tmp_path))
    jm.shutdown()
    with pytest.raises(RuntimeError, match="shut down"):
        jm.submit("echo too-late")


def test_job_manager_stop_escalates_past_sigterm_trap(tmp_path):
    """stop() on an entrypoint that ignores SIGTERM must escalate to a
    group SIGKILL after the grace period — otherwise the driver outlives
    its STOPPED status. The driver is a python GRANDCHILD under the
    sh -c wrapper: the shell dies on TERM, so the escalation must key on
    group liveness, not on the direct child — and the leak scan must
    look for the grandchild's argv (in-code mark), not the shell's."""
    import uuid

    from ray_tpu.job_submission import JobStatus
    from ray_tpu.job_submission.manager import JobManager

    mark = "jmtrap_" + uuid.uuid4().hex[:12]
    jm = JobManager(gcs_address="127.0.0.1:1", log_dir=str(tmp_path))
    sid = jm.submit(
        f'{sys.executable} -c "import signal, time; '
        f'signal.signal(signal.SIGTERM, signal.SIG_IGN); '
        f'time.sleep(60)  # {mark}"')
    deadline = time.monotonic() + 10
    while jm.details(sid)["status"] == JobStatus.PENDING and \
            time.monotonic() < deadline:
        time.sleep(0.05)
    time.sleep(1.2)  # let the driver install its trap before the TERM
    assert jm.stop(sid)

    deadline = time.monotonic() + 15
    while time.monotonic() < deadline:
        d = jm.details(sid)
        if d["status"] == JobStatus.STOPPED and d["end_time"] \
                and not _leaked_pids(mark):
            break
        time.sleep(0.2)
    d = jm.details(sid)
    assert d["status"] == JobStatus.STOPPED, d
    assert d["end_time"], "runner never unparked: SIGKILL escalation missing"
    assert _leaked_pids(mark) == [], "TERM-trapping driver outlived the SIGKILL"


# --------------------------------------------------------------------------- #
# Task events / timeline / CLI
# --------------------------------------------------------------------------- #


def test_timeline_records_and_exports_chrome_trace(ray_start_regular,
                                                   tmp_path):
    import json

    @ray_tpu.remote
    def traced(i):
        time.sleep(0.05)
        return i

    ray_tpu.get([traced.remote(i) for i in range(4)])
    # Events flush with the raylet heartbeat (1s period).
    deadline = time.monotonic() + 15
    events = []
    while time.monotonic() < deadline:
        events = ray_tpu.timeline()
        if sum(1 for e in events if e.get("state") == "FINISHED") >= 4:
            break
        time.sleep(0.3)
    names = {e["name"] for e in events}
    assert any("traced" in n for n in names), names

    out = str(tmp_path / "trace.json")
    ray_tpu.timeline(filename=out)
    trace = json.loads(open(out).read())
    spans = [t for t in trace if "traced" in t["name"]]
    assert len(spans) >= 4
    assert all(t["ph"] == "X" and t["dur"] >= 0 for t in spans)


def test_state_cli(ray_start_regular, capsys):
    import json

    from ray_tpu.scripts.cli import main as cli_main

    @ray_tpu.remote
    def f():
        return 1

    ray_tpu.get(f.remote())
    address = ray_tpu._global_runtime.gcs.address
    cli_main(["--address", address, "status"])
    out = json.loads(capsys.readouterr().out)
    assert out["nodes"] >= 1 and "resources_total" in out
    cli_main(["--address", address, "list", "nodes"])
    nodes = json.loads(capsys.readouterr().out)
    assert any(n["Alive"] for n in nodes)


# --------------------------------------------------------------------------- #
# Dashboard
# --------------------------------------------------------------------------- #


def test_dashboard_routes(ray_start_regular):
    import json
    import urllib.request

    from ray_tpu.util.metrics import Gauge

    info = ray_tpu.init(ignore_reinit_error=True)
    url = info["dashboard_url"]
    assert url, "head node did not start a dashboard"

    Gauge("dash_test_gauge", "x").set(11)
    ray_tpu._global_runtime._metrics_pusher.flush()

    with urllib.request.urlopen(url + "/api/nodes", timeout=10) as r:
        nodes = json.loads(r.read())
    assert any(n["Alive"] for n in nodes)
    with urllib.request.urlopen(url + "/metrics", timeout=10) as r:
        text = r.read().decode()
    assert "dash_test_gauge" in text
    # Structured twin of /metrics (the GCS metrics_snapshot endpoint's
    # consumer, wired by the RL014 dead-endpoint pass).
    with urllib.request.urlopen(url + "/api/metrics", timeout=10) as r:
        snap = json.loads(r.read())
    assert any(m["name"] == "dash_test_gauge"
               for series in snap.values() for m in series)
    with urllib.request.urlopen(url, timeout=10) as r:
        html = r.read().decode()
    assert "ray_tpu cluster" in html
    with urllib.request.urlopen(url + "/api/cluster_resources",
                                timeout=10) as r:
        res = json.loads(r.read())
    assert res  # totals present


# --------------------------------------------------------------------------- #
# Autoscaler
# --------------------------------------------------------------------------- #


def test_autoscaler_scales_up_on_demand_and_down_when_idle():
    from ray_tpu.autoscaler import (
        AutoscalerConfig,
        LocalNodeProvider,
        StandardAutoscaler,
    )
    from ray_tpu.cluster_utils import Cluster

    ray_tpu.shutdown()
    cluster = Cluster(initialize_head=True, head_node_args={"num_cpus": 1})
    autoscaler = None
    try:
        cluster.connect()
        provider = LocalNodeProvider(cluster)
        autoscaler = StandardAutoscaler(
            cluster.gcs_address, provider,
            AutoscalerConfig(min_workers=0, max_workers=2,
                             node_resources={"CPU": 2, "pool": 2},
                             idle_timeout_s=3.0, update_period_s=0.5))
        autoscaler.start()

        @ray_tpu.remote
        def work(i):
            time.sleep(0.3)
            return i

        # Demand the head can never satisfy -> scale-up.
        refs = [work.options(resources={"pool": 1}).remote(i)
                for i in range(8)]
        out = ray_tpu.get(refs, timeout=120)
        assert out == list(range(8))
        assert autoscaler.num_launches >= 1
        assert len(provider.non_terminated_nodes()) >= 1

        # Idle -> scale back down to min_workers.
        deadline = time.monotonic() + 60
        while provider.non_terminated_nodes() and \
                time.monotonic() < deadline:
            time.sleep(0.5)
        assert not provider.non_terminated_nodes(), "idle nodes not reaped"
        assert autoscaler.num_terminations >= 1
    finally:
        if autoscaler is not None:
            autoscaler.stop()
        cluster.shutdown()


def test_request_resources_pins_capacity():
    from ray_tpu.autoscaler import (
        AutoscalerConfig,
        LocalNodeProvider,
        StandardAutoscaler,
        request_resources,
    )
    from ray_tpu.cluster_utils import Cluster

    ray_tpu.shutdown()
    cluster = Cluster(initialize_head=True, head_node_args={"num_cpus": 1})
    autoscaler = None
    try:
        cluster.connect()
        provider = LocalNodeProvider(cluster)
        autoscaler = StandardAutoscaler(
            cluster.gcs_address, provider,
            AutoscalerConfig(min_workers=0, max_workers=3,
                             node_resources={"CPU": 4},
                             idle_timeout_s=300.0, update_period_s=0.5))
        autoscaler.start()
        # Ask for more CPUs than the head has: nodes appear without any
        # queued tasks.
        request_resources(num_cpus=6)
        deadline = time.monotonic() + 60
        while time.monotonic() < deadline:
            total = sum(e["total"].get("CPU", 0) for e in
                        ray_tpu._global_runtime.gcs.call(
                            "get_resource_view").values() if e["alive"])
            if total >= 6:
                break
            time.sleep(0.5)
        assert total >= 6, f"cluster CPU total stuck at {total}"
        # Clearing the request allows (eventual) scale-down; just verify
        # the floor is lifted server-side.
        request_resources(bundles=[])
        resp = ray_tpu._global_runtime.gcs.call("resource_demand")
        assert resp["requests"] == []
    finally:
        if autoscaler is not None:
            autoscaler.stop()
        cluster.shutdown()
