"""Host collective plane (`ray_tpu.collective`): ring/tree collectives over
the object-transfer plane, GCS group membership, rank-attributed aborts.

Most tests drive ranks as THREADS over an in-process multi-node Cluster
(RayletTransport — full GCS control plane + chunked transfer plane, no
worker processes); the runtime-transport path is covered with real rank
actors, and the legacy star path through a real rendezvous actor.
"""

import threading
import time

import numpy as np
import pytest

import ray_tpu
from ray_tpu import collective
from ray_tpu.cluster_utils import Cluster
from ray_tpu.collective import CollectiveGroup, RayletTransport
from ray_tpu.collective.buffer import PackedTree, tree_index
from ray_tpu.core.config import GLOBAL_CONFIG
from ray_tpu.exceptions import CollectiveError
from ray_tpu.util.collective import _RendezvousActor, StarCollectiveGroup

CHUNK = 256 * 1024
STALL_S = 10.0
WORLD = 4


@pytest.fixture()
def collective_cluster():
    """4 raylets, tiny chunks, short stall timeout; no driver session."""
    ray_tpu.shutdown()
    saved = dict(GLOBAL_CONFIG._overrides)
    GLOBAL_CONFIG._overrides.update({
        "object_transfer_chunk_bytes": CHUNK,
        "collective_stall_timeout_s": STALL_S,
        "collective_ring_min_bytes": 64 * 1024,
        "rpc_connect_timeout_s": 2.0,
    })
    cluster = Cluster(initialize_head=True, head_node_args={"num_cpus": 1})
    for _ in range(WORLD - 1):
        cluster.add_node(num_cpus=1)
    cluster.wait_for_nodes()
    try:
        yield cluster
    finally:
        cluster.shutdown()
        GLOBAL_CONFIG._overrides.clear()
        GLOBAL_CONFIG._overrides.update(saved)


def _run_ranks(cluster, fn, world=WORLD, join_s=90.0):
    """fn(rank, group) on one thread per rank; returns (results, errors)."""
    results, errors = [None] * world, [None] * world

    def run(rank):
        try:
            group = CollectiveGroup(
                "t", world, rank,
                transport=RayletTransport(cluster.raylets[rank]))
            try:
                results[rank] = fn(rank, group)
            finally:
                if rank == 0:
                    group.destroy()
                else:
                    group.leave()
        except Exception as e:  # noqa: BLE001 — asserted by callers
            errors[rank] = e

    threads = [threading.Thread(target=run, args=(r,)) for r in range(world)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(join_s)
    return results, errors


def _group_record(cluster, name="t"):
    return cluster.raylets[0].gcs.call("collective_get", {"name": name})


# --------------------------------------------------------------------------- #
# Numeric parity
# --------------------------------------------------------------------------- #


def test_ring_allreduce_matches_numpy_on_pytrees(collective_cluster):
    """Ring allreduce (payload >> ring threshold) of a mixed-dtype pytree
    equals the numpy reference on every rank, for sum/max/mean."""
    rng = np.random.default_rng(7)
    values = [{"w": rng.standard_normal((1000, 200)).astype(np.float32),
               "b": rng.standard_normal(17),
               "step": np.int64(i + 1),
               "nested": [rng.standard_normal(63).astype(np.float32)]}
              for i in range(WORLD)]

    def fn(rank, group):
        return {"sum": group.allreduce(values[rank], op="sum"),
                "max": group.allreduce(values[rank], op="max"),
                "mean": group.allreduce(values[rank], op="mean")}

    results, errors = _run_ranks(collective_cluster, fn)
    assert not any(errors), errors
    want_w = sum(v["w"] for v in values)
    want_b = sum(v["b"] for v in values)
    max_w = np.maximum.reduce([v["w"] for v in values])
    for out in results:
        np.testing.assert_allclose(out["sum"]["w"], want_w, atol=1e-4)
        np.testing.assert_allclose(out["sum"]["b"], want_b, rtol=1e-12)
        assert int(out["sum"]["step"]) == sum(range(1, WORLD + 1))
        np.testing.assert_array_equal(out["max"]["w"], max_w)
        np.testing.assert_allclose(out["mean"]["w"], want_w / WORLD,
                                   atol=1e-4)
        np.testing.assert_allclose(
            out["sum"]["nested"][0],
            sum(v["nested"][0] for v in values), atol=1e-4)
    # Identical results on every rank, bit for bit (they all hold the same
    # reduced segments after the all-gather phase).
    for out in results[1:]:
        np.testing.assert_array_equal(out["sum"]["w"], results[0]["sum"]["w"])


def test_small_payload_inline_path_and_mailbox_drains(collective_cluster):
    """Tiny payloads ride the GCS mailbox inline (fan-in path, no store
    objects); the refcounted mailbox is empty after every op."""
    def fn(rank, group):
        out = group.allreduce({"loss": float(rank), "n": np.int64(rank)})
        # Every allreduce (fan-in included) ends with a group sync, so all
        # takes have drained by the time any rank returns. The barrier
        # below fences the record check against a faster rank's teardown
        # (leave/destroy would GC the record under us).
        rec = _group_record(collective_cluster)
        assert rec["known"] and rec["mailbox_keys"] == 0, rec
        group.barrier()
        return out

    results, errors = _run_ranks(collective_cluster, fn)
    assert not any(errors), errors
    for out in results:
        assert float(out["loss"]) == sum(range(WORLD))
    # Graceful leave of every member GC'd the record.
    assert _group_record(collective_cluster) == {"known": False}


def test_allgather_broadcast_reducescatter(collective_cluster):
    rng = np.random.default_rng(3)
    big = rng.integers(0, 255, size=3 * CHUNK + 123,
                       dtype=np.uint8)  # multi-chunk broadcast payload

    def fn(rank, group):
        gathered = group.allgather({"rank": rank})
        bcast = group.broadcast(big if rank == 2 else None, src_rank=2)
        rows = group.reducescatter(
            np.full((WORLD * 3, 5), float(rank), dtype=np.float64))
        return gathered, bcast, rows

    results, errors = _run_ranks(collective_cluster, fn)
    assert not any(errors), errors
    want_rows = np.full((3, 5), float(sum(range(WORLD))))
    for rank, (gathered, bcast, rows) in enumerate(results):
        assert [g["rank"] for g in gathered] == list(range(WORLD))
        np.testing.assert_array_equal(np.asarray(bcast), big)
        np.testing.assert_array_equal(rows, want_rows)


def test_reducescatter_remainder_raises(collective_cluster):
    """shape[0] % world_size != 0 must raise a clear ValueError, not
    silently drop the remainder rows (regression)."""
    def fn(rank, group):
        with pytest.raises(ValueError, match="not divisible"):
            group.reducescatter(np.ones((WORLD * 3 + 1, 4)))
        return True

    results, errors = _run_ranks(collective_cluster, fn)
    assert not any(errors), errors
    assert all(results)
    # The same validation, directly on the helper the star path shares.
    with pytest.raises(ValueError, match="not divisible"):
        tree_index({"x": np.ones((5, 2))}, rank=0, world=4)


def test_packed_tree_roundtrip_unit():
    """Packing layer alone: mixed dtypes, padding, segment reduce."""
    value = {"a": np.arange(10, dtype=np.float32).reshape(2, 5),
             "b": [np.float64(2.5), np.arange(3, dtype=np.int64)]}
    packed = PackedTree(value, segments=4)
    out = packed.unpack()
    np.testing.assert_array_equal(out["a"], value["a"])
    assert float(out["b"][0]) == 2.5
    np.testing.assert_array_equal(out["b"][1], value["b"][1])
    other = PackedTree(value, segments=4)
    for s in range(4):
        joined = b"".join(bytes(p) for p in other.segment_parts(s))
        packed.reduce_segment(s, joined, np.add)
    doubled = packed.unpack()
    np.testing.assert_array_equal(doubled["a"], value["a"] * 2)


# --------------------------------------------------------------------------- #
# Membership validation
# --------------------------------------------------------------------------- #


def test_world_size_mismatch_raises(collective_cluster):
    cluster = collective_cluster
    CollectiveGroup("m", 4, 0, transport=RayletTransport(cluster.raylets[0]))
    with pytest.raises(ValueError, match="world_size=4"):
        CollectiveGroup("m", 3, 1,
                        transport=RayletTransport(cluster.raylets[1]))


def test_rank_taken_and_rejoin_after_destroy(collective_cluster):
    cluster = collective_cluster
    g0 = CollectiveGroup("m", 4, 0,
                         transport=RayletTransport(cluster.raylets[0]))
    with pytest.raises(ValueError, match="already held"):
        CollectiveGroup("m", 4, 0,
                        transport=RayletTransport(cluster.raylets[1]))
    g0.destroy()
    # Fresh epoch: the name is reusable, even with a different world size.
    g1 = CollectiveGroup("m", 2, 0,
                         transport=RayletTransport(cluster.raylets[1]))
    assert g1.epoch > g0.epoch


# --------------------------------------------------------------------------- #
# Failure semantics
# --------------------------------------------------------------------------- #


def test_member_death_aborts_survivors_with_rank(collective_cluster):
    """Killing one member's node mid-op makes every surviving rank raise a
    CollectiveError naming the dead rank, well inside the stall timeout —
    never a 300s hang."""
    cluster = collective_cluster
    payload = np.ones(2 * CHUNK, dtype=np.float32)
    round_one = threading.Barrier(WORLD, timeout=60)
    errors = [None] * WORLD
    abort_s = [None] * WORLD

    def run(rank):
        try:
            group = CollectiveGroup(
                "d", WORLD, rank,
                transport=RayletTransport(cluster.raylets[rank]))
            group.allreduce(payload)
            round_one.wait()
            if rank == 3:
                return  # goes silent; its raylet is killed below
            t0 = time.monotonic()
            try:
                group.allreduce(payload)
            finally:
                abort_s[rank] = time.monotonic() - t0
        except Exception as e:  # noqa: BLE001
            errors[rank] = e

    threads = [threading.Thread(target=run, args=(r,)) for r in range(WORLD)]
    for t in threads:
        t.start()
    threads[3].join(60)
    time.sleep(0.3)  # survivors are now parked inside round 2
    cluster.remove_node(cluster.raylets[3])
    for t in threads[:3]:
        t.join(60)

    for rank in range(3):
        err = errors[rank]
        assert isinstance(err, CollectiveError), (rank, err)
        assert "rank 3" in str(err), err
        assert 3 in err.dead_ranks, err.dead_ranks
        assert abort_s[rank] < STALL_S, (
            f"rank {rank} took {abort_s[rank]:.1f}s to abort — the death "
            "push did not fire, only the stall timeout would have")


def test_barrier_reusable_across_rounds(collective_cluster):
    """Three barrier rounds on one group, with a straggler each round:
    nobody leaves a barrier before the straggler arrives, and the per-seq
    barrier state is GC'd after each round."""
    crossings = []
    lock = threading.Lock()

    def fn(rank, group):
        for rnd in range(3):
            if rank == rnd:  # a different straggler each round
                time.sleep(0.4)
                with lock:
                    crossings.append(("late", rnd, rank))
            group.barrier()
            with lock:
                crossings.append(("crossed", rnd, rank))
        rec = _group_record(collective_cluster)
        assert rec["pending_barriers"] == 0, rec
        return True

    results, errors = _run_ranks(collective_cluster, fn)
    assert not any(errors), errors
    assert all(results)
    for rnd in range(3):
        late = crossings.index(("late", rnd, rnd))
        first_cross = min(i for i, c in enumerate(crossings)
                          if c[0] == "crossed" and c[1] == rnd)
        assert late < first_cross, (
            f"round {rnd}: a rank crossed the barrier before the "
            f"straggler arrived: {crossings}")


def test_rendezvous_actor_slots_drain_unit():
    """Regression for the unbounded `_results`/`_events` growth: after
    every member fetched a key, its slot is deleted."""
    actor = _RendezvousActor(world_size=2)
    for i in range(5):
        key = f"ar:{i}"
        actor.contribute(key, 0, 1.0, "sum")
        actor.contribute(key, 1, 2.0, "sum")
        assert actor.fetch(key, timeout=5) == actor.fetch(key, timeout=5) == 3.0
    assert actor._results == {}
    assert actor._events == {}
    assert actor._fetches == {}
    assert actor._round == {}


# --------------------------------------------------------------------------- #
# Runtime transport (real rank actors) + star path
# --------------------------------------------------------------------------- #


class _RankActor:
    def __init__(self, rank, world, group_name="actors", backend="ring"):
        from ray_tpu.util.collective import init_collective_group

        self.group = init_collective_group(
            world, rank, group_name=group_name, backend=backend)

    def allreduce_value(self, value):
        return self.group.allreduce(value)

    def allreduce_size(self, n_bytes):
        import numpy as _np

        value = _np.full(max(1, n_bytes // 4), float(self.group.rank + 1),
                         dtype=_np.float32)
        self.group.allreduce(value)
        return True


def test_runtime_transport_actors_and_death(collective_cluster):
    """Worker-process ranks over the runtime transport: results match, and
    killing one member's process aborts the peer with the dead rank —
    membership fate-shares with the worker's GCS connection."""
    cluster = collective_cluster
    cluster.connect()
    actor_cls = ray_tpu.remote(_RankActor)
    a0 = actor_cls.options(max_concurrency=2).remote(0, 2)
    a1 = actor_cls.options(max_concurrency=2).remote(1, 2)
    arr = np.arange(CHUNK, dtype=np.float64)  # > inline, exercises the store
    r0 = a0.allreduce_value.remote({"g": arr})
    r1 = a1.allreduce_value.remote({"g": arr * 2})
    out0, out1 = ray_tpu.get([r0, r1], timeout=120)
    np.testing.assert_allclose(np.asarray(out0["g"]), arr * 3)
    np.testing.assert_allclose(np.asarray(out1["g"]), arr * 3)

    pending = a0.allreduce_value.remote({"g": arr})  # a1 never joins this op
    time.sleep(0.3)
    ray_tpu.kill(a1)
    with pytest.raises(CollectiveError, match="rank 1"):
        ray_tpu.get(pending, timeout=60)


def test_star_attach_validates_world_size(collective_cluster):
    """get_if_exists on a namesake rendezvous actor with a different
    world_size must raise instead of deadlocking every op."""
    cluster = collective_cluster
    cluster.connect()
    group = StarCollectiveGroup("star_ws", 2, 0)
    try:
        with pytest.raises(ValueError, match="world_size=2"):
            StarCollectiveGroup("star_ws", 3, 1)
    finally:
        group.destroy()


@pytest.mark.slow
def test_ring_beats_star_under_modeled_links(collective_cluster):
    """The perf story: a large allreduce between rank actors pinned one
    per node beats the single-actor star rendezvous under a modeled
    per-host link bandwidth (`_chunk_serve_bw_bps` serializes each node's
    chunk egress). The star funnels O(W x bytes) through the hub's one
    link — args in, one result object out per caller — while the ring
    moves 2(W-1)/W x bytes per link, spread over every node."""
    cluster = collective_cluster
    cluster.connect()
    GLOBAL_CONFIG._overrides.update({
        "object_transfer_chunk_bytes": 2 << 20,
        "object_transfer_refetch_location_chunks": 2,
    })
    mb = 64
    actor_cls = ray_tpu.remote(_RankActor)

    def measure(backend):
        # num_cpus=1 on 1-CPU nodes: exactly one rank actor per node.
        ranks = [actor_cls.options(num_cpus=1).remote(
            r, WORLD, group_name=f"perf_{backend}", backend=backend)
            for r in range(WORLD)]
        # Warm-up op outside the timed window (worker spawn, connections);
        # payloads are created rank-locally, like real gradients.
        ray_tpu.get([a.allreduce_size.remote(1024) for a in ranks],
                    timeout=120)
        for raylet in cluster.raylets:
            raylet._chunk_serve_bw_bps = 25e6
        try:
            t0 = time.perf_counter()
            ray_tpu.get([a.allreduce_size.remote(mb << 20) for a in ranks],
                        timeout=300)
            return time.perf_counter() - t0
        finally:
            for raylet in cluster.raylets:
                raylet._chunk_serve_bw_bps = 0.0
            for a in ranks:
                ray_tpu.kill(a)

    star_s = measure("star")
    ring_s = measure("ring")
    # bench.py measures ~2.2x at this size (and is the acceptance gate);
    # the 1.33x floor here absorbs CI jitter. Marked slow: ~30s of
    # modeled-link sleeps is bench territory, not tier-1 budget.
    assert ring_s < star_s * 0.75, (
        f"ring ({ring_s:.2f}s) should beat the star actor "
        f"({star_s:.2f}s) on a {mb} MiB allreduce over 25 MB/s links")
