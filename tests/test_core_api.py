"""Core API: tasks, objects, wait, errors, dependencies, resources.

Mirrors the reference's `python/ray/tests/test_basic.py` coverage.
"""

import time

import numpy as np
import pytest

import ray_tpu
from ray_tpu.exceptions import GetTimeoutError, RayTaskError


@ray_tpu.remote
def add(a, b):
    return a + b


@ray_tpu.remote
def echo(x):
    return x


def test_simple_task(ray_start_shared):
    assert ray_tpu.get(add.remote(1, 2)) == 3


def test_kwargs(ray_start_shared):
    assert ray_tpu.get(add.remote(a=10, b=5)) == 15
    assert ray_tpu.get(add.remote(1, b=2)) == 3


def test_many_tasks(ray_start_shared):
    refs = [add.remote(i, i) for i in range(100)]
    assert ray_tpu.get(refs) == [2 * i for i in range(100)]


def test_put_get(ray_start_shared):
    r = ray_tpu.put({"a": 1})
    assert ray_tpu.get(r) == {"a": 1}


def test_large_object_roundtrip(ray_start_shared):
    x = np.random.rand(512, 512)
    ref = ray_tpu.put(x)
    np.testing.assert_array_equal(ray_tpu.get(ref), x)


def test_large_task_arg_and_return(ray_start_shared):
    x = np.ones((1000, 1000), dtype=np.float32)
    out = ray_tpu.get(echo.remote(x))
    np.testing.assert_array_equal(out, x)


def test_object_ref_dependency(ray_start_shared):
    a = add.remote(1, 1)
    b = add.remote(a, 1)
    c = add.remote(a, b)
    assert ray_tpu.get(c) == 5


def test_put_ref_as_arg(ray_start_shared):
    r = ray_tpu.put(41)
    assert ray_tpu.get(add.remote(r, 1)) == 42


def test_num_returns(ray_start_shared):
    @ray_tpu.remote(num_returns=3)
    def three():
        return 1, 2, 3

    r1, r2, r3 = three.remote()
    assert ray_tpu.get([r1, r2, r3]) == [1, 2, 3]


def test_error_propagation(ray_start_shared):
    @ray_tpu.remote
    def fail():
        raise ZeroDivisionError("zero!")

    with pytest.raises(ZeroDivisionError):
        ray_tpu.get(fail.remote())
    try:
        ray_tpu.get(fail.remote())
    except RayTaskError as e:
        assert "zero!" in e.traceback_str


def test_error_in_dependency_propagates(ray_start_shared):
    @ray_tpu.remote
    def fail():
        raise ValueError("dep fail")

    # passing a failed ref to another task surfaces the error on get of the
    # downstream result
    with pytest.raises(ValueError):
        ray_tpu.get(echo.remote(fail.remote()))


def test_wait(ray_start_shared):
    @ray_tpu.remote
    def sleepy(t):
        time.sleep(t)
        return t

    fast = sleepy.remote(0.01)
    slow = sleepy.remote(1.5)
    ready, pending = ray_tpu.wait([fast, slow], num_returns=1, timeout=1.0)
    assert ready == [fast]
    assert pending == [slow]
    ready2, pending2 = ray_tpu.wait([slow], timeout=5.0)
    assert ready2 == [slow]


def test_get_timeout(ray_start_shared):
    @ray_tpu.remote
    def hang():
        time.sleep(30)

    ref = hang.remote()
    with pytest.raises(GetTimeoutError):
        ray_tpu.get(ref, timeout=0.3)


@pytest.mark.slow  # >10s wall; tier-1 truncation headroom (gate.sh runs full suite)
def test_nested_tasks(ray_start_shared):
    @ray_tpu.remote
    def outer(n):
        return sum(ray_tpu.get([add.remote(i, 1) for i in range(n)]))

    assert ray_tpu.get(outer.remote(4)) == 10


def test_options_name_and_resources(ray_start_shared):
    @ray_tpu.remote(num_cpus=0.5)
    def half():
        return "ok"

    assert ray_tpu.get(half.options(name="renamed").remote()) == "ok"


def test_direct_call_forbidden(ray_start_shared):
    with pytest.raises(TypeError):
        add(1, 2)


def test_cluster_resources(ray_start_shared):
    total = ray_tpu.cluster_resources()
    assert total["CPU"] == 4.0
    nodes = ray_tpu.nodes()
    assert len(nodes) == 1
    assert nodes[0]["Alive"]


def test_ref_pickling_through_task(ray_start_shared):
    # An ObjectRef nested in a structure stays a ref (no auto-resolution),
    # matching the reference semantics for nested refs.
    inner = ray_tpu.put(123)

    @ray_tpu.remote
    def unwrap(d):
        return ray_tpu.get(d["ref"])

    assert ray_tpu.get(unwrap.remote({"ref": inner})) == 123
