"""C++ user API (cpp/) against a live cluster via the xlang gateway.

Covers SURVEY §2.1 N16 (C++ user API) and §2.2 cross-language calls:
the C++ client KVs, puts/gets objects both directions, invokes Python
tasks by module:name, and drives a named Python actor — reference
`cpp/include/ray/api.h` surface, re-shaped as a gateway client (see
ray_tpu/xlang.py module docstring for the design rationale)."""

import os
import shutil
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(scope="module")
def smoke_binary(tmp_path_factory):
    gxx = shutil.which("g++")
    if gxx is None:
        pytest.skip("g++ not available")
    out = tmp_path_factory.mktemp("cppbin") / "smoke"
    subprocess.run(
        [gxx, "-std=c++17", "-O1", "-I", os.path.join(REPO, "cpp", "include"),
         os.path.join(REPO, "cpp", "examples", "smoke.cc"), "-o", str(out)],
        check=True, capture_output=True, text=True)
    return str(out)


def test_cpp_client_end_to_end(smoke_binary, ray_start_regular):
    import ray_tpu
    from ray_tpu import xlang

    # Ensure workers can import tests/xlang_mod.py.
    if REPO not in sys.path:
        sys.path.insert(0, REPO)
    os.environ["PYTHONPATH"] = (
        os.path.join(REPO, "tests") + os.pathsep +
        os.environ.get("PYTHONPATH", ""))

    address = xlang.start_gateway()
    try:
        # Discovery: the gateway address is published in the GCS KV.
        runtime = ray_tpu._require_runtime()
        resp = runtime.gcs.call("kv_get", {"namespace": xlang.GATEWAY_KV_NS,
                                           "key": xlang.GATEWAY_KV_KEY})
        assert resp["value"].decode() == address

        # A named actor the C++ side drives.
        @ray_tpu.remote
        class Counter:
            def __init__(self):
                self.x = 0

            def inc(self, n):
                self.x += n
                return self.x

        counter = Counter.options(name="xlang-counter").remote()
        assert ray_tpu.get(counter.inc.remote(0)) == 0

        # An object the Python side puts, read from C++.
        py_ref = ray_tpu.put({"greeting": "from-python"})

        proc = subprocess.run(
            [smoke_binary, address, py_ref.hex()],
            capture_output=True, text=True, timeout=120,
            env=dict(os.environ))
        assert proc.returncode == 0, (proc.stdout, proc.stderr)
        assert "SMOKE OK" in proc.stdout

        # Cross-language the other way: read the C++ put from Python.
        put_id = next(line.split()[1] for line in proc.stdout.splitlines()
                      if line.startswith("PUT_ID "))
        from ray_tpu.core.ids import ObjectID

        value = runtime.get([ObjectID.from_hex(put_id)], timeout=30)[0]
        assert value["kind"] == "from-cpp"
        assert value["nums"] == [1, 2, 3]

        # And the KV the C++ side wrote.
        resp = runtime.gcs.call("kv_get", {"namespace": "xlang-user",
                                           "key": b"cpp-key"})
        assert resp["value"] == b"cpp-value"
    finally:
        xlang.stop_gateway()
