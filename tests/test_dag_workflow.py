"""DAG API (.bind/.execute) and durable Workflows (run/resume).

Mirrors the reference's `python/ray/dag/tests/` and
`python/ray/workflow/tests/test_basic_workflows.py` behaviors.
"""

import os

import pytest

import ray_tpu
from ray_tpu.dag import InputNode


@ray_tpu.remote
def add(a, b):
    return a + b


@ray_tpu.remote
def double(x):
    return 2 * x


@ray_tpu.remote
def bump_counter(path, value):
    with open(path, "a") as f:
        f.write("x")
    return value


@ray_tpu.remote
def fail_until_flag(path, value):
    if not os.path.exists(path):
        raise RuntimeError("transient failure (flag missing)")
    return value + 1


def test_dag_bind_execute(ray_start_shared):
    dag = add.bind(double.bind(3), double.bind(4))
    assert ray_tpu.get(dag.execute()) == 14


def test_dag_shared_subtree_runs_once(ray_start_shared, tmp_path):
    counter = str(tmp_path / "count")
    shared = bump_counter.bind(counter, 5)
    dag = add.bind(shared, shared)  # diamond: shared node must run once
    assert ray_tpu.get(dag.execute()) == 10
    assert open(counter).read() == "x"


def test_dag_input_node(ray_start_shared):
    with InputNode() as inp:
        dag = add.bind(double.bind(inp), 1)
    assert ray_tpu.get(dag.execute(10)) == 21
    assert ray_tpu.get(dag.execute(0)) == 1


def test_dag_options(ray_start_shared):
    dag = double.options(name="custom").bind(21)
    assert ray_tpu.get(dag.execute()) == 42


def test_workflow_run_and_output(ray_start_shared, tmp_path, monkeypatch):
    from ray_tpu import workflow

    monkeypatch.setenv("RAY_TPU_WORKFLOW_DIR", str(tmp_path))
    dag = add.bind(double.bind(10), 2)
    assert workflow.run(dag, workflow_id="wf1") == 22
    assert workflow.get_status("wf1") == workflow.WorkflowStatus.SUCCESSFUL
    assert workflow.get_output("wf1") == 22
    assert ("wf1", "SUCCESSFUL") in workflow.list_all()


def test_workflow_resume_skips_completed_steps(ray_start_shared, tmp_path,
                                               monkeypatch):
    from ray_tpu import workflow

    monkeypatch.setenv("RAY_TPU_WORKFLOW_DIR", str(tmp_path))
    counter = str(tmp_path / "exec_count")
    flag = str(tmp_path / "flag")

    dag = fail_until_flag.bind(flag, bump_counter.bind(counter, 7))
    with pytest.raises(Exception):
        workflow.run(dag, workflow_id="wf2")
    assert workflow.get_status("wf2") == workflow.WorkflowStatus.RESUMABLE
    assert open(counter).read() == "x"  # first step checkpointed

    open(flag, "w").write("go")
    assert workflow.resume("wf2") == 8
    # The checkpointed first step was NOT re-executed on resume.
    assert open(counter).read() == "x"
    assert workflow.get_status("wf2") == workflow.WorkflowStatus.SUCCESSFUL


def test_workflow_delete(ray_start_shared, tmp_path, monkeypatch):
    from ray_tpu import workflow

    monkeypatch.setenv("RAY_TPU_WORKFLOW_DIR", str(tmp_path))
    workflow.run(double.bind(1), workflow_id="wf3")
    workflow.delete("wf3")
    assert workflow.get_status("wf3") is None
