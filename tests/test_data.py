"""ray_tpu.data: datasets, streaming execution, splits, IO round-trips."""

import os

import numpy as np
import pytest

from ray_tpu import data as rd


def test_range_count_take(ray_start_shared):
    ds = rd.range(1000)
    assert ds.count() == 1000
    rows = ds.take(5)
    assert [r["id"] for r in rows] == [0, 1, 2, 3, 4]


def test_map_filter_pipeline_fuses(ray_start_shared):
    ds = rd.range(100).map(lambda r: {"id": r["id"] * 2}) \
        .filter(lambda r: r["id"] % 4 == 0)
    vals = sorted(r["id"] for r in ds.take_all())
    assert vals == [i * 2 for i in range(100) if (i * 2) % 4 == 0]


def test_map_batches_numpy_format(ray_start_shared):
    ds = rd.range(64).map_batches(lambda b: {"sq": b["id"] ** 2})
    total = ds.sum("sq")
    assert total == sum(i * i for i in range(64))


def test_map_batches_with_batch_size(ray_start_shared):
    seen_sizes = []

    def record(batch):
        return {"n": np.array([len(batch["id"])])}

    ds = rd.range(100, parallelism=1).map_batches(record, batch_size=16)
    sizes = [r["n"] for r in ds.take_all()]
    assert all(s <= 16 for s in sizes)
    assert sum(sizes) > 0


def test_iter_batches_exact_sizes(ray_start_shared):
    ds = rd.range(100)
    batches = list(ds.iter_batches(batch_size=32))
    sizes = [len(b["id"]) for b in batches]
    assert sizes == [32, 32, 32, 4]
    assert sorted(np.concatenate([b["id"] for b in batches]).tolist()) == \
        list(range(100))
    batches = list(ds.iter_batches(batch_size=32, drop_last=True))
    assert [len(b["id"]) for b in batches] == [32, 32, 32]


def test_from_items_and_flat_map(ray_start_shared):
    ds = rd.from_items([1, 2, 3]).flat_map(lambda x: [x, x * 10])
    assert sorted(ds.take_all()) == [1, 2, 3, 10, 20, 30]


def test_repartition_and_union(ray_start_shared):
    ds = rd.range(90).repartition(3)
    assert ds.num_blocks() == 3
    assert ds.count() == 90
    u = rd.from_items([1]).union(rd.from_items([2]), rd.from_items([3]))
    assert sorted(u.take_all()) == [1, 2, 3]


def test_random_shuffle_preserves_rows(ray_start_shared):
    ds = rd.range(200).random_shuffle(seed=7)
    ids = [r["id"] for r in ds.take_all()]
    assert sorted(ids) == list(range(200))
    assert ids != list(range(200))


def test_split_balanced(ray_start_shared):
    parts = rd.range(100, parallelism=4).split(2)
    counts = [p.count() for p in parts]
    assert sum(counts) == 100
    assert all(c > 0 for c in counts)


def test_streaming_split_covers_all_rows(ray_start_shared):
    ds = rd.range(120, parallelism=6)
    it_a, it_b = ds.streaming_split(2)
    rows_a = [r["id"] for r in it_a.iter_rows()]
    rows_b = [r["id"] for r in it_b.iter_rows()]
    assert sorted(rows_a + rows_b) == list(range(120))
    # Second epoch works (re-executes).
    rows_a2 = [r["id"] for r in it_a.iter_rows()]
    rows_b2 = [r["id"] for r in it_b.iter_rows()]
    assert sorted(rows_a2 + rows_b2) == list(range(120))


def test_parquet_roundtrip(ray_start_shared, tmp_path):
    ds = rd.range(50).map_batches(lambda b: {"id": b["id"],
                                             "x": b["id"] * 0.5})
    files = ds.write_parquet(str(tmp_path))
    assert files and all(os.path.exists(f) for f in files)
    back = rd.read_parquet(str(tmp_path))
    assert back.count() == 50
    assert back.sum("id") == sum(range(50))


def test_csv_and_json_roundtrip(ray_start_shared, tmp_path):
    ds = rd.from_items([{"a": i, "b": f"s{i}"} for i in range(10)])
    csv_dir, json_dir = tmp_path / "csv", tmp_path / "json"
    ds.write_csv(str(csv_dir))
    ds.write_json(str(json_dir))
    assert rd.read_csv(str(csv_dir)).count() == 10
    back = rd.read_json(str(json_dir)).take_all()
    assert sorted(r["a"] for r in back) == list(range(10))


def test_text_and_numpy_reads(ray_start_shared, tmp_path):
    p = tmp_path / "f.txt"
    p.write_text("hello\nworld\n\nfoo\n")
    ds = rd.read_text(str(p))
    assert ds.take_all() == ["hello", "world", "foo"]

    npy = tmp_path / "arr.npy"
    np.save(npy, np.arange(12).reshape(3, 4))
    nds = rd.read_numpy(str(npy))
    batch = next(nds.iter_batches(batch_size=10))
    assert batch["item"].shape == (3, 4)


def test_from_numpy_and_mean(ray_start_shared):
    arr = np.arange(100, dtype=np.float64)
    ds = rd.from_numpy(arr, column="x")
    assert ds.mean("x") == pytest.approx(49.5)
    assert ds.min("x") == 0 and ds.max("x") == 99


def test_to_pandas(ray_start_shared):
    df = rd.range(10).to_pandas()
    assert list(df["id"]) == list(range(10))


def test_dataset_feeds_trainer_shards(ray_start_shared, tmp_path):
    """Data -> Train integration: streaming_split shards reach workers via
    session.get_dataset_shard (the reference's north-star ingest path)."""
    from ray_tpu.train import JaxTrainer, RunConfig, ScalingConfig
    from ray_tpu.train.backend import JaxConfig

    import ray_tpu

    @ray_tpu.remote
    class Tally:
        def __init__(self):
            self.total = 0

        def add(self, n):
            self.total += n

        def get(self):
            return self.total

    tally = Tally.options(name="ingest_tally").remote()
    ray_tpu.get(tally.get.remote())  # ensure alive

    def loop(config):
        import ray_tpu
        from ray_tpu.train import session

        shard = session.get_dataset_shard("train")
        seen = 0
        for batch in shard.iter_batches(batch_size=8):
            seen += len(batch["id"])
        t = ray_tpu.get_actor("ingest_tally")
        ray_tpu.get(t.add.remote(seen))
        session.report({"rows": seen})

    result = JaxTrainer(
        loop,
        jax_config=JaxConfig(distributed=False),
        scaling_config=ScalingConfig(num_workers=2),
        run_config=RunConfig(name="ingest", storage_path=str(tmp_path)),
        datasets={"train": rd.range(64, parallelism=4)},
    ).fit()
    assert result.error is None, result.error
    assert ray_tpu.get(tally.get.remote()) == 64


# --------------------------------------------------------------------------- #
# groupby / zip / column ops
# --------------------------------------------------------------------------- #


def test_groupby_aggregations(ray_start_shared):
    ds = rd.from_items([{"g": i % 3, "v": float(i)} for i in range(30)])
    counts = {r["g"]: r["count()"] for r in ds.groupby("g").count().take_all()}
    assert counts == {0: 10, 1: 10, 2: 10}
    sums = {r["g"]: r["sum(v)"] for r in ds.groupby("g").sum("v").take_all()}
    assert sums[0] == sum(float(i) for i in range(0, 30, 3))
    means = {r["g"]: r["mean(v)"]
             for r in ds.groupby("g").mean("v").take_all()}
    assert abs(means[1] - np.mean([i for i in range(30) if i % 3 == 1])) < 1e-9
    mins = {r["g"]: r["min(v)"] for r in ds.groupby("g").min("v").take_all()}
    maxs = {r["g"]: r["max(v)"] for r in ds.groupby("g").max("v").take_all()}
    assert mins == {0: 0.0, 1: 1.0, 2: 2.0}
    assert maxs == {0: 27.0, 1: 28.0, 2: 29.0}
    # Results arrive sorted by key.
    assert [r["g"] for r in ds.groupby("g").count().take_all()] == [0, 1, 2]


def test_groupby_key_function_and_map_groups(ray_start_shared):
    ds = rd.from_items(list(range(20)))
    grouped = ds.groupby(lambda x: x % 2)
    out = grouped.map_groups(lambda rows: {"parity": rows[0] % 2,
                                           "total": sum(rows)})
    rows = sorted(out.take_all(), key=lambda r: r["parity"])
    assert rows == [{"parity": 0, "total": sum(range(0, 20, 2))},
                    {"parity": 1, "total": sum(range(1, 20, 2))}]


def test_zip_merges_rows(ray_start_shared):
    a = rd.from_items([{"x": i} for i in range(5)])
    b = rd.from_items([{"y": 10 * i} for i in range(5)])
    rows = a.zip(b).take_all()
    assert rows[3] == {"x": 3, "y": 30}
    # Collisions get the _1 suffix.
    c = rd.from_items([{"x": -i} for i in range(5)])
    rows = a.zip(c).take_all()
    assert rows[2] == {"x": 2, "x_1": -2}
    # Scalar rows pair into tuples; length mismatch is an error.
    assert rd.from_items([1, 2]).zip(rd.from_items([3, 4])).take_all() \
        == [(1, 3), (2, 4)]
    with pytest.raises(ValueError):
        rd.from_items([1, 2, 3]).zip(rd.from_items([1])).take_all()


def test_column_ops_and_unique(ray_start_shared):
    ds = rd.from_items([{"a": i, "b": i % 4} for i in range(12)])
    with_c = ds.add_column("c", lambda r: r["a"] * 2)
    assert with_c.take(1)[0] == {"a": 0, "b": 0, "c": 0}
    assert with_c.drop_columns(["a", "b"]).take(1) == [{"c": 0}]
    assert with_c.select_columns(["b"]).take(2) == [{"b": 0}, {"b": 1}]
    assert ds.unique("b") == [0, 1, 2, 3]


def test_groupby_none_values(ray_start_shared):
    """None = missing (reference ignore_nulls): sums/means skip Nones but
    count() still counts the rows."""
    ds = rd.from_items([{"g": 1, "v": None}, {"g": 1, "v": 2.0},
                        {"g": 2, "v": None}])
    assert ds.groupby("g").sum("v").take_all() == [
        {"g": 1, "sum(v)": 2.0}, {"g": 2, "sum(v)": None}]
    assert ds.groupby("g").mean("v").take_all() == [
        {"g": 1, "mean(v)": 2.0}, {"g": 2, "mean(v)": None}]
    assert ds.groupby("g").count().take_all() == [
        {"g": 1, "count()": 2}, {"g": 2, "count()": 1}]


def test_push_shuffle_preserves_rows_and_is_seeded(ray_start_shared):
    """random_shuffle runs as the two-stage push shuffle: rows preserved,
    order changed, deterministic per seed, no driver materialization of
    the whole dataset in one block."""
    ds = rd.from_items(list(range(200)))
    a = ds.random_shuffle(seed=7)
    rows_a = a.take_all()
    assert sorted(rows_a) == list(range(200))
    assert rows_a != list(range(200))
    assert a.num_blocks() == ds.num_blocks()  # partitions preserved
    b = ds.random_shuffle(seed=7).take_all()
    assert rows_a == b  # seeded determinism
    c = ds.random_shuffle(seed=8).take_all()
    assert rows_a != c


def test_repartition_shuffle(ray_start_shared):
    ds = rd.from_items(list(range(120)))
    out = ds.repartition(5, shuffle=True)
    assert out.num_blocks() == 5
    assert sorted(out.take_all()) == list(range(120))


def test_map_groups_equal_keys_across_types(ray_start_shared):
    """np.int64(1), 1 and 1.0 are one logical group: the hash partitioner
    must route them to the same partition (regression: pickle-based
    hashing split them)."""
    ds = rd.from_items([{"k": np.int64(1), "v": 1},
                        {"k": 1, "v": 10},
                        {"k": 1.0, "v": 100},
                        {"k": 2, "v": 5}])
    out = ds.groupby(lambda r: r["k"]).map_groups(
        lambda rows: {"k": rows[0]["k"], "total": sum(r["v"] for r in rows)})
    rows = sorted(out.take_all(), key=lambda r: float(r["k"]))
    assert [r["total"] for r in rows] == [111, 5]


def test_dataset_stats_per_op(ray_start_shared):
    """ds.stats() reports per-operator blocks/rows/wall after execution
    (reference Dataset.stats, data/_internal/stats.py)."""
    from ray_tpu import data

    def double(r):
        return {"id": r["id"] * 2}

    ds = data.range(100, parallelism=4).map(double).filter(
        lambda r: r["id"] % 4 == 0)
    out = ds.materialize()
    assert sorted(r["id"] for r in out.take_all())[:3] == [0, 4, 8]
    stats = out.stats()
    assert stats is not None
    names = [op["name"] for op in stats.ops]
    assert any(n.startswith("Map(double)") for n in names), names
    assert any(n.startswith("Filter(") for n in names), names
    read_ops = [op for op in stats.ops if op["index"] == -1]
    assert read_ops and read_ops[0]["blocks"] == 4
    map_op = next(op for op in stats.ops if op["name"] == "Map(double)")
    assert map_op["rows"] == 100 and map_op["blocks"] == 4
    assert "blocks" in repr(stats) and "wall" in repr(stats)


def test_dataset_stats_disabled(ray_start_shared):
    from ray_tpu import data
    from ray_tpu.data.context import DataContext

    ctx = DataContext.get_current()
    ctx.enable_stats = False
    try:
        ds = data.range(10, parallelism=2).map(lambda x: x)
        ds = ds.materialize()
        assert ds.stats() is None
    finally:
        ctx.enable_stats = True
