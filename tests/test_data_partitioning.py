"""Path partitioning + webdataset/mongo datasources.

Reference behavior: `python/ray/data/datasource/partitioning.py`
(Partitioning/PathPartitionParser/PathPartitionFilter on file readers)
and `ray.data.read_webdataset` / `read_mongo`.
"""

import os
import tarfile

import numpy as np
import pytest

from ray_tpu import data


def _write_partitioned_csv(base):
    import pandas as pd

    for year, country, vals in [("2023", "de", [1, 2]),
                                ("2023", "us", [3]),
                                ("2024", "de", [4, 5, 6])]:
        d = os.path.join(base, f"year={year}", f"country={country}")
        os.makedirs(d, exist_ok=True)
        pd.DataFrame({"v": vals}).to_csv(os.path.join(d, "part.csv"),
                                         index=False)


def test_partitioning_parse_hive_and_dir(tmp_path):
    p = data.Partitioning("hive")
    assert p.parse("/x/year=2024/country=de/f.parquet") == {
        "year": "2024", "country": "de"}
    assert p.parse("/plain/path/f.parquet") == {}

    p2 = data.Partitioning("dir", field_names=["year", "country"])
    assert p2.parse("/data/2024/de/f.csv") == {"year": "2024",
                                               "country": "de"}
    with pytest.raises(ValueError, match="field_names"):
        data.Partitioning("dir")
    with pytest.raises(ValueError, match="style"):
        data.Partitioning("banana")


def test_read_csv_hive_partitioned(ray_start_shared, tmp_path):
    base = str(tmp_path / "tbl")
    _write_partitioned_csv(base)
    ds = data.read_csv(base, partitioning=data.Partitioning("hive"))
    rows = ds.take_all()
    assert len(rows) == 6
    assert all({"v", "year", "country"} <= set(r.keys()) for r in rows)
    de_2024 = [r["v"] for r in rows
               if r["year"] == "2024" and r["country"] == "de"]
    assert sorted(de_2024) == [4, 5, 6]


def test_partition_filter_prunes_files(ray_start_shared, tmp_path):
    base = str(tmp_path / "tbl")
    _write_partitioned_csv(base)
    ds = data.read_csv(
        base, partitioning=data.Partitioning("hive"),
        partition_filter=lambda parts: parts.get("year") == "2023")
    rows = ds.take_all()
    assert sorted(r["v"] for r in rows) == [1, 2, 3]
    with pytest.raises(FileNotFoundError, match="partition_filter"):
        data.read_csv(base, partitioning=data.Partitioning("hive"),
                      partition_filter=lambda parts: False)


def test_webdataset_round_trip(ray_start_shared, tmp_path):
    shard_dir = str(tmp_path / "wds")
    rows = [{"__key__": f"{i:04d}", "txt": f"hello {i}", "cls": i,
             "json": {"idx": i}, "flag": bool(i % 2)} for i in range(10)]
    ds = data.from_items(rows, parallelism=2)
    shards = ds.write_webdataset(shard_dir)
    assert len(shards) == 2
    assert all(tarfile.is_tarfile(s) for s in shards)

    back = data.read_webdataset(os.path.join(shard_dir, "*.tar"))
    got = sorted(back.take_all(), key=lambda r: r["__key__"])
    assert len(got) == 10
    assert got[3]["txt"] == "hello 3"
    assert got[3]["cls"] == 3
    assert got[3]["json"] == {"idx": 3}
    assert got[3]["flag"] == b"1"  # bools write as ints (cls-decodable)


def test_read_mongo_gated():
    try:
        import pymongo  # noqa: F401

        pytest.skip("pymongo installed; the import gate cannot fire "
                    "(and no mongod is available to connect to)")
    except ImportError:
        pass
    ds = data.read_mongo("mongodb://localhost", "db", "coll")
    with pytest.raises(Exception, match="pymongo"):
        ds.take_all()
