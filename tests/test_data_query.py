"""Distributed query tier: locality-routed sort/groupby/join over the
streaming plane (ray_tpu/data/query/), per-tenant data budgets, and the
same-host sealed-segment attach fast path.

Row-identity discipline: every operator's output is compared against a
driver-side reference computed from the same input rows — across seeds,
partition counts, and both join strategies — while the driver-resident
state stays bounded (asserted via `last_sort_stats`).
"""

import threading
import time

import numpy as np
import pytest

import ray_tpu
from ray_tpu import data as rd
from ray_tpu.core.config import GLOBAL_CONFIG
from ray_tpu.data.context import DataContext


# --------------------------------------------------------------------------- #
# Distributed sort
# --------------------------------------------------------------------------- #


def _ref_sort(rows, key, descending=False):
    """Driver-side stable reference (what the distributed sort must
    reproduce row-for-row)."""
    keyf = key if callable(key) else (lambda r: r[key])
    return sorted(rows, key=keyf, reverse=descending)


@pytest.mark.parametrize("parallelism", [1, 3, 7])
@pytest.mark.parametrize("seed", [0, 11])
def test_sort_row_identity_across_partition_counts(ray_start_shared,
                                                   parallelism, seed):
    rng = np.random.default_rng(seed)
    rows = [{"k": int(rng.integers(0, 20)), "v": i} for i in range(200)]
    ds = rd.from_items(rows, parallelism=parallelism).sort(key="k")
    got = ds.take_all()
    # Stable: equal keys keep input order — byte-for-byte row identity,
    # not just key order.
    assert got == _ref_sort(rows, "k")


def test_sort_descending_is_stable(ray_start_shared):
    rows = [{"k": i % 5, "v": i} for i in range(100)]
    ds = rd.from_items(rows, parallelism=4).sort(key="k", descending=True)
    assert ds.take_all() == _ref_sort(rows, "k", descending=True)


def test_sort_callable_key_and_plain_values(ray_start_shared):
    vals = [7, 3, 9, 1, 3, 8, 0, 5]
    ds = rd.from_items(vals, parallelism=3).sort(key=lambda x: -x)
    assert ds.take_all() == sorted(vals, reverse=True)
    # Plain comparable values need no key at all.
    assert rd.from_items(vals, parallelism=2).sort().take_all() == \
        sorted(vals)


def test_sort_string_keys_columnar_path(ray_start_shared):
    rows = [{"k": f"key-{i % 7:02d}", "v": i} for i in range(80)]
    ds = rd.from_items(rows, parallelism=4).sort(key="k")
    assert ds.take_all() == _ref_sort(rows, "k")


def test_sort_single_key_and_skew(ray_start_shared):
    # All-equal keys: one range partition swallows everything; output is
    # the input (stability) regardless of boundary degeneracy.
    rows = [{"k": 1, "v": i} for i in range(60)]
    assert rd.from_items(rows, parallelism=4).sort(key="k").take_all() \
        == rows
    # 90% of rows share one key: the skewed partition still sorts
    # correctly and equal keys never split across partitions.
    rng = np.random.default_rng(3)
    skewed = [{"k": 5 if rng.random() < 0.9 else int(rng.integers(0, 100)),
               "v": i} for i in range(300)]
    got = rd.from_items(skewed, parallelism=5).sort(key="k").take_all()
    assert got == _ref_sort(skewed, "k")


def test_sort_empty_dataset(ray_start_shared):
    assert rd.from_items([{"k": 1}]).filter(lambda r: False) \
        .sort(key="k").take_all() == []


def test_sort_driver_sample_bytes_bounded(ray_start_shared):
    """The driver's entire per-row footprint is the boundary sample —
    bounded by `query_sort_sample_rows`, measured and asserted, and the
    output is STILL row-identical (equal keys never split, local sorts
    are stable, so any sample draw yields the same global order)."""
    ctx = DataContext.get_current()
    old = ctx.sort_sample_rows
    try:
        ctx.sort_sample_rows = 32
        rows = [{"k": int(np.random.default_rng(9).integers(0, 50)),
                 "v": i} for i in range(5000)]
        ds = rd.from_items(rows, parallelism=8).sort(key="k")
        got = ds.take_all()
        assert got == _ref_sort(rows, "k")
        stats = ds.last_sort_stats
        assert 0 < stats["sample_rows"] <= 32
        # 32 int keys serialize well under this; 5000 rows would not.
        assert stats["driver_sample_bytes"] < 16 * 1024
        assert ds.last_shuffle_stats["input_blocks"] == 8
    finally:
        ctx.sort_sample_rows = old


def test_sort_chains_with_downstream_transforms(ray_start_shared):
    rows = [{"k": i % 4, "v": i} for i in range(40)]
    ds = rd.from_items(rows, parallelism=4).sort(key="k") \
        .map(lambda r: {"k": r["k"], "v2": r["v"] * 2})
    got = ds.take_all()
    assert got == [{"k": r["k"], "v2": r["v"] * 2}
                   for r in _ref_sort(rows, "k")]


# --------------------------------------------------------------------------- #
# Distributed groupby
# --------------------------------------------------------------------------- #


@pytest.mark.parametrize("parallelism", [1, 4, 9])
def test_groupby_aggregate_matches_reference(ray_start_shared,
                                             parallelism):
    rng = np.random.default_rng(parallelism)
    rows = [{"g": int(rng.integers(0, 12)), "x": float(rng.normal())}
            for _ in range(300)]
    ds = rd.from_items(rows, parallelism=parallelism)
    got = {r["g"]: r for r in ds.groupby("g").sum("x").take_all()}
    keys = sorted({r["g"] for r in rows})
    assert sorted(got) == keys
    for k in keys:
        want = sum(r["x"] for r in rows if r["g"] == k)
        assert got[k]["sum(x)"] == pytest.approx(want)


def test_groupby_multi_aggregate_single_pass(ray_start_shared):
    from ray_tpu.data.query import Count, Max, Mean, Min, Sum

    rows = [{"g": i % 3, "x": i} for i in range(30)]
    out = rd.from_items(rows, parallelism=4).groupby("g").aggregate(
        Count(), Sum("x"), Mean("x"), Min("x"), Max("x")).take_all()
    assert [r["g"] for r in out] == [0, 1, 2]
    for r in out:
        vals = [row["x"] for row in rows if row["g"] == r["g"]]
        assert r["count()"] == len(vals)
        assert r["sum(x)"] == sum(vals)
        assert r["mean(x)"] == pytest.approx(sum(vals) / len(vals))
        assert r["min(x)"] == min(vals)
        assert r["max(x)"] == max(vals)


def test_groupby_custom_aggregate_fn(ray_start_shared):
    from ray_tpu.data.query import AggregateFn

    # Sum of squares as a UDF: init/accumulate/merge/finalize compose
    # through partial pre-aggregation exactly like the built-ins.
    sumsq = AggregateFn(
        init=lambda: 0.0,
        accumulate=lambda s, row: s + row["x"] ** 2,
        merge=lambda a, b: a + b,
        name="sumsq(x)")
    rows = [{"g": i % 4, "x": i} for i in range(40)]
    out = rd.from_items(rows, parallelism=5).groupby("g") \
        .aggregate(sumsq).take_all()
    for r in out:
        want = sum(row["x"] ** 2 for row in rows if row["g"] == r["g"])
        assert r["sumsq(x)"] == pytest.approx(want)


def test_groupby_single_key_and_empty(ray_start_shared):
    rows = [{"g": "only", "x": i} for i in range(25)]
    out = rd.from_items(rows, parallelism=4).groupby("g").count() \
        .take_all()
    assert out == [{"g": "only", "count()": 25}]
    empty = rd.from_items(rows).filter(lambda r: False) \
        .groupby("g").count().take_all()
    assert empty == []


# --------------------------------------------------------------------------- #
# Distributed join
# --------------------------------------------------------------------------- #


def _ref_join(left, right, left_on, right_on, how):
    """Driver-side nested-loop reference with the zip() `_1` collision
    suffix contract."""
    out = []
    rcols = []
    for rrow in right:
        for c in rrow:
            if c not in rcols:
                rcols.append(c)
    for lrow in left:
        matches = [r for r in right if r[right_on] == lrow[left_on]]
        if not matches and how == "left":
            row = dict(lrow)
            for c in rcols:
                if c != right_on:
                    row[c + "_1" if c in lrow else c] = None
            out.append(row)
        for rrow in matches:
            row = dict(lrow)
            for c, v in rrow.items():
                if c == right_on:
                    continue
                row[c + "_1" if c in lrow else c] = v
            out.append(row)
    return out


def _rows_set(rows):
    return sorted(tuple(sorted(r.items())) for r in rows)


@pytest.mark.parametrize("how", ["inner", "left"])
def test_join_hash_and_broadcast_row_identity(ray_start_shared, how):
    left = [{"id": i % 6, "lv": i} for i in range(40)]
    # Duplicate build keys (cartesian per key) + a key with no probe
    # match + a colliding non-key column name.
    right = [{"id": 0, "rv": 100, "lv": -1}, {"id": 0, "rv": 101},
             {"id": 2, "rv": 102}, {"id": 99, "rv": 103}]
    want = _rows_set(_ref_join(left, right, "id", "id", how))
    ctx = DataContext.get_current()
    old = ctx.broadcast_join_bytes
    try:
        lds = rd.from_items(left, parallelism=4)
        rds = rd.from_items(right, parallelism=2)
        ctx.broadcast_join_bytes = 1 << 30
        bds = lds.join(rds, on="id", how=how)
        assert _rows_set(bds.take_all()) == want
        assert bds.last_join_stats["strategy"] == "broadcast"

        ctx.broadcast_join_bytes = 0
        hds = lds.join(rds, on="id", how=how)
        assert _rows_set(hds.take_all()) == want
        assert hds.last_join_stats["strategy"] == "hash"
        assert hds.last_join_stats["left_shuffle"]["input_blocks"] > 0
    finally:
        ctx.broadcast_join_bytes = old


def test_join_build_side_exactly_at_threshold(ray_start_shared):
    """The strategy flips exactly at `query_broadcast_join_bytes`: a
    build side AT the threshold broadcasts, one byte under it forces the
    hash exchange — and both produce identical rows."""
    left = [{"id": i % 8, "lv": i} for i in range(64)]
    right = [{"id": i, "rv": i * 10} for i in range(8)]
    lds = rd.from_items(left, parallelism=4)
    rds = rd.from_items(right, parallelism=2)
    ctx = DataContext.get_current()
    old = ctx.broadcast_join_bytes
    try:
        probe = lds.join(rds, on="id")
        want = _rows_set(probe.take_all())
        build_bytes = probe.last_join_stats["build_bytes"]
        assert build_bytes > 0

        ctx.broadcast_join_bytes = build_bytes
        at = lds.join(rds, on="id")
        assert _rows_set(at.take_all()) == want
        assert at.last_join_stats["strategy"] == "broadcast"

        ctx.broadcast_join_bytes = build_bytes - 1
        under = lds.join(rds, on="id")
        assert _rows_set(under.take_all()) == want
        assert under.last_join_stats["strategy"] == "hash"
    finally:
        ctx.broadcast_join_bytes = old


def test_join_tuple_on_and_validation(ray_start_shared):
    left = [{"lid": i, "a": i * 2} for i in range(6)]
    right = [{"rid": i, "b": i * 3} for i in range(0, 12, 2)]
    out = rd.from_items(left, parallelism=2).join(
        rd.from_items(right, parallelism=2), on=("lid", "rid")) \
        .take_all()
    assert _rows_set(out) == _rows_set(
        _ref_join(left, right, "lid", "rid", "inner"))
    with pytest.raises(ValueError):
        rd.from_items(left).join(rd.from_items(right), on=("lid",))
    with pytest.raises(ValueError):
        rd.from_items(left).join(rd.from_items(right), on="lid",
                                 how="outer")


def test_join_empty_sides(ray_start_shared):
    left = [{"id": i} for i in range(5)]
    none = rd.from_items(left).filter(lambda r: False)
    assert rd.from_items(left).join(none, on="id").take_all() == []
    got = none.join(rd.from_items(left), on="id", how="inner").take_all()
    assert got == []


# --------------------------------------------------------------------------- #
# Per-tenant data budgets
# --------------------------------------------------------------------------- #


@pytest.fixture()
def tenant_cap():
    from ray_tpu.data.streaming.budget import reset_tenant_stats

    ctx = DataContext.get_current()
    old_tenant = ctx.tenant
    GLOBAL_CONFIG._overrides["data_tenant_budget_bytes"] = 100
    reset_tenant_stats()
    try:
        yield ctx
    finally:
        ctx.tenant = old_tenant
        GLOBAL_CONFIG._overrides.pop("data_tenant_budget_bytes", None)
        reset_tenant_stats()


def test_tenant_cap_rejects_with_backpressure(tenant_cap):
    """Admission past the tenant cap is refused (visible in
    `tenant_stats`), spanning BUDGETS: two pipelines of one tenant share
    the cap even though each is under its own pipeline budget."""
    from ray_tpu.data.streaming.budget import ByteBudget, tenant_stats

    tenant_cap.tenant = "tenant-a"
    a, b = ByteBudget(10_000), ByteBudget(10_000)
    assert a.try_acquire("map", 80)
    assert b.try_acquire("map", 15)  # 95 in flight: still under the cap
    assert not b.try_acquire("map", 50)  # would cross 100: refused
    st = tenant_stats()["tenant-a"]
    assert st["rejections"] >= 1
    assert st["bytes_in_flight"] == 95
    # Releasing in ONE budget unblocks the OTHER (same tenant).
    a.release("map", 80)
    assert b.try_acquire("map", 50)
    assert tenant_stats()["tenant-a"]["bytes_in_flight"] == 65


def test_tenant_progress_guarantee_never_deadlocks(tenant_cap):
    """A tenant with nothing in flight is ALWAYS admitted (even over the
    cap) — mirrors the per-op progress guarantee, so one oversized block
    degrades to window-at-a-time instead of wedging the pipeline."""
    from ray_tpu.data.streaming.budget import ByteBudget

    tenant_cap.tenant = "tenant-big"
    b = ByteBudget(10_000)
    assert b.try_acquire("map", 5_000)  # 50x the cap: idle tenant admits
    assert not b.try_acquire("map", 1)  # now it waits like everyone
    b.release("map", 5_000)
    assert b.try_acquire("map", 1)


def test_tenant_blocking_acquire_wakes_on_cross_budget_release(tenant_cap):
    from ray_tpu.data.streaming.budget import ByteBudget

    tenant_cap.tenant = "tenant-w"
    a, b = ByteBudget(10_000), ByteBudget(10_000)
    assert a.try_acquire("map", 90)
    done = []

    def blocked():
        done.append(b.acquire("map", 90, timeout=10.0))

    assert b.try_acquire("map", 5)  # b must have in-flight bytes to wait
    t = threading.Thread(target=blocked, daemon=True)
    t.start()
    time.sleep(0.1)
    a.release("map", 90)  # cross-budget release, observed via the poll
    t.join(timeout=10.0)
    assert done == [True]


def test_tenant_resolution_defaults(tenant_cap, monkeypatch):
    from ray_tpu.data.streaming.budget import ByteBudget

    monkeypatch.delenv("RAY_TPU_JOB_ID", raising=False)
    tenant_cap.tenant = None
    assert ByteBudget(10).tenant == "default"
    monkeypatch.setenv("RAY_TPU_JOB_ID", "job-42")
    assert ByteBudget(10).tenant == "job-42"
    tenant_cap.tenant = "explicit"
    assert ByteBudget(10).tenant == "explicit"


def test_tenant_cap_off_by_default(tenant_cap):
    from ray_tpu.data.streaming.budget import ByteBudget, tenant_stats

    GLOBAL_CONFIG._overrides["data_tenant_budget_bytes"] = 0
    tenant_cap.tenant = "tenant-free"
    b = ByteBudget(10_000)
    assert b.try_acquire("map", 4_000)
    assert b.try_acquire("map", 4_000)  # no cap: only the budget gates
    # Bytes still tracked for observability even with the cap off.
    assert tenant_stats()["tenant-free"]["bytes_in_flight"] == 8_000


# --------------------------------------------------------------------------- #
# Locality-routed split handout
# --------------------------------------------------------------------------- #


def test_iter_shards_locality_hit_accounting(ray_start_shared):
    """Single-node cluster, blocks past the 100 KiB inline threshold:
    every block the coordinator hands out is resident on the consumer's
    node, so the ingest stats must show hits and zero misses — and with
    routing off, the same handouts all count as misses."""
    ctx = DataContext.get_current()
    old = ctx.locality_routing
    try:
        # 4 blocks x 500 rows x 32 float64 = ~128 KiB each: real store
        # residency (inline blocks have no directory entry and would
        # honestly count as misses).
        ds = rd.range_tensor(2000, shape=(32,), parallelism=4) \
            .materialize()
        ctx.locality_routing = True
        shard, = rd.DataIterator(ds).iter_shards(1, prefetch=0)
        rows = sum(len(b["data"]) for b in shard.iter_batches(
            batch_size=500))
        assert rows == 2000
        stats = shard.ingest_stats()
        assert stats["locality_hits"] == 4
        assert stats["locality_misses"] == 0

        ctx.locality_routing = False
        shard2, = rd.DataIterator(ds).iter_shards(1, prefetch=0)
        rows = sum(len(b["data"]) for b in shard2.iter_batches(
            batch_size=500))
        assert rows == 2000
        stats2 = shard2.ingest_stats()
        assert stats2["locality_hits"] == 0
        assert stats2["locality_misses"] == 4
    finally:
        ctx.locality_routing = old


def test_split_coordinator_locality_never_starves(ray_start_shared):
    """Locality reorders the handout but every split still gets blocks
    and every block is handed out exactly once."""
    ds = rd.range_tensor(2000, shape=(32,), parallelism=4).materialize()
    it_a, it_b = ds.streaming_split(2)
    got_a = [b["data"].sum() for b in it_a.iter_batches(batch_size=500)]
    got_b = [b["data"].sum() for b in it_b.iter_batches(batch_size=500)]
    assert len(got_a) + len(got_b) == 4
    la, lb = it_a.locality_stats(), it_b.locality_stats()
    handed = (la["locality_hits"] + la["locality_misses"]
              + lb["locality_hits"] + lb["locality_misses"])
    assert handed == 4


# --------------------------------------------------------------------------- #
# Same-host sealed-segment attach
# --------------------------------------------------------------------------- #

_CHUNK = 128 * 1024


@pytest.fixture()
def attach_cluster():
    """3 raylets on one host, tiny chunks; raylets driven directly."""
    from ray_tpu.cluster_utils import Cluster

    ray_tpu.shutdown()
    saved = dict(GLOBAL_CONFIG._overrides)
    GLOBAL_CONFIG._overrides.update({
        "object_transfer_chunk_bytes": _CHUNK,
        "rpc_connect_timeout_s": 1.0,
    })
    cluster = Cluster(initialize_head=True, head_node_args={"num_cpus": 1})
    for _ in range(2):
        cluster.add_node(num_cpus=1)
    cluster.wait_for_nodes()
    try:
        yield cluster
    finally:
        cluster.shutdown()
        GLOBAL_CONFIG._overrides.clear()
        GLOBAL_CONFIG._overrides.update(saved)


def _seed_object(raylet, n_chunks, seed=0):
    from ray_tpu.core.ids import ObjectID

    oid = ObjectID.from_random()
    payload = np.random.default_rng(seed).integers(
        0, 255, size=n_chunks * _CHUNK, dtype=np.uint8).tobytes()
    raylet.store.put_serialized(oid, [payload])
    raylet.gcs.call("object_location_add",
                    {"object_id": oid, "node_id": raylet.node_id,
                     "size": raylet.store.local_size(oid)}, timeout=10)
    return oid


def _pull(raylet, oid):
    entry = raylet.gcs.call("object_locations_get", {"object_id": oid},
                            timeout=10)
    return raylet._pull_object_pipelined(oid, entry)


def test_same_host_attach_skips_the_socket(attach_cluster):
    """A same-host pull attaches the holder's sealed segment: identical
    bytes, zero chunk RPCs served, no unsealed buffers, counters in the
    raylet debug state."""
    holder, puller = attach_cluster.raylets[:2]
    oid = _seed_object(holder, n_chunks=8)
    assert _pull(puller, oid)
    assert puller.store.get_bytes(oid) == holder.store.get_bytes(oid)
    assert puller._attach_hits == 1
    assert puller._attach_bytes == 8 * _CHUNK
    assert holder._chunk_bytes_served == 0  # zero socket copies
    for r in attach_cluster.raylets:
        assert r.store.stats()["num_unsealed"] == 0
    dbg = puller.handle_debug_state({})["transfer"]
    assert dbg["attach_hits"] == 1
    assert dbg["attach_bytes"] == 8 * _CHUNK


def test_attach_registers_location_for_later_pullers(attach_cluster):
    holder, second, third = attach_cluster.raylets[:3]
    oid = _seed_object(holder, n_chunks=4, seed=1)
    assert _pull(second, oid)
    entry = holder.gcs.call("object_locations_get", {"object_id": oid},
                            timeout=10)
    hexes = {n.hex() if hasattr(n, "hex") else str(n)
             for n in entry["nodes"]}
    assert second.node_id.hex() in hexes  # attach announced the copy
    assert _pull(third, oid)
    assert third.store.get_bytes(oid) == holder.store.get_bytes(oid)


def test_attach_declines_when_knob_off(attach_cluster):
    holder, puller = attach_cluster.raylets[:2]
    GLOBAL_CONFIG._overrides["object_transfer_same_host_attach"] = False
    oid = _seed_object(holder, n_chunks=4, seed=2)
    assert _pull(puller, oid)
    assert puller._attach_hits == 0
    assert holder._chunk_bytes_served == 4 * _CHUNK  # the chunk path ran
    assert puller.store.get_bytes(oid) == holder.store.get_bytes(oid)


def test_attach_declines_when_link_model_armed(attach_cluster):
    """Bench honesty: a holder modeling a network link (serve delay or
    bandwidth cap) or a puller modeling RTT must keep measuring the
    network — attach silently bypassing the model would fake the A/B."""
    holder, puller, other = attach_cluster.raylets[:3]
    holder._chunk_serve_bw_bps = 1e9
    try:
        oid = _seed_object(holder, n_chunks=2, seed=3)
        assert _pull(puller, oid)
        assert puller._attach_hits == 0
    finally:
        holder._chunk_serve_bw_bps = 0.0
    puller._chunk_fetch_delay_s = 0.001
    try:
        oid2 = _seed_object(holder, n_chunks=2, seed=4)
        assert _pull(puller, oid2)
        assert puller._attach_hits == 0
    finally:
        puller._chunk_fetch_delay_s = 0.0
    # With no model armed the same topology attaches.
    oid3 = _seed_object(holder, n_chunks=2, seed=5)
    assert _pull(other, oid3)
    assert other._attach_hits == 1


# --------------------------------------------------------------------------- #
# Chaos: query exchange survives a node kill
# --------------------------------------------------------------------------- #


@pytest.mark.slow  # multi-node cluster + recovery: >10s under load; the
# envelope bench's query leg hard-gates the same scenario at scale
def test_sort_survives_node_kill_mid_exchange():
    """Kill the busiest worker node mid-sort (blocks past the 100 KiB
    inline threshold, so real store state dies with it). The epoch must
    complete with a correctly sorted output, recomputed work bounded by
    the victim's resident blocks + n_parts, and zero hangs."""
    from ray_tpu.chaos import HangWatchdog
    from ray_tpu.cluster_utils import Cluster
    from ray_tpu.data.streaming.lineage import core_reconstructions

    ray_tpu.shutdown()
    # CPU-less head: every task — and so every sorted partition — runs
    # and lives on a worker. The head (driver) survives the kill, but
    # the state it still needs does not.
    cluster = Cluster(initialize_head=True, head_node_args={"num_cpus": 0})
    for _ in range(2):
        cluster.add_node(num_cpus=2)
    cluster.wait_for_nodes()
    cluster.connect()
    try:
        n_parts = 8

        def keyed(batch):
            return {"k": (batch["data"][:, 0].astype(np.int64)) % 50,
                    "data": batch["data"]}

        # Sized so even the per-bucket scatter blocks (~1/8 of a parent
        # block) clear the 100 KiB inline threshold: every intermediate
        # is REAL store state on some node (inline blocks live in the
        # GCS and would shrug off any kill), reduce placement routes to
        # the bucket holders, and the sorted partitions land spread
        # across the workers — so killing the most-loaded worker
        # necessarily destroys output the consumer hasn't pulled yet.
        ds = rd.range_tensor(32000, shape=(40,), parallelism=n_parts) \
            .map_batches(keyed).sort(key="k")
        base = core_reconstructions()
        rows = 0
        last_key = None
        killed = {}
        with HangWatchdog(limit_s=90.0) as wd:
            for i, batch in enumerate(ds.iter_batches(batch_size=2000)):
                rows += len(batch["k"])
                ks = np.asarray(batch["k"])
                assert (np.diff(ks) >= 0).all()  # sorted inside batches
                if last_key is not None:
                    assert ks[0] >= last_key  # ...and across them
                last_key = int(ks[-1])
                if i == 1 and not killed:
                    victim = max(
                        (r for r in cluster.raylets if not r.is_head),
                        key=lambda r: r.store.stats()["num_objects"])
                    killed["resident"] = \
                        victim.store.stats()["num_objects"]
                    cluster.crash_node(victim)
        wd.assert_no_hangs()
        assert rows == 32000
        recomputed = (core_reconstructions() - base) \
            + (ds._lineage.recomputed_blocks if ds._lineage else 0)
        assert recomputed >= 1, "the kill destroyed nothing the sort used"
        bound = max(killed.get("resident", 0), 1) + n_parts
        assert recomputed <= bound, (recomputed, killed)
        for raylet in cluster.raylets:
            assert raylet.store.stats()["num_unsealed"] == 0
    finally:
        try:
            cluster.shutdown()
        except Exception:  # noqa: BLE001 — nodes already churned
            pass
